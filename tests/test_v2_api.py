"""The v2 API dialect (reference python/paddle/v2/: layer DSL ->
Parameters -> trainer.SGD -> events/infer), re-hosted on the TPU stack.
Mirrors the reference's v2 book usage: build layers, create parameters,
train with a batched reader + event handler, test, infer, tar round-trip.
"""

import io

import numpy as np
import pytest

from paddle_tpu import v2 as paddle


@pytest.fixture(autouse=True)
def _fresh_graph():
    paddle.reset()
    yield
    paddle.reset()


def _mnist_like(n=256, dim=64, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim).astype("float32")
    ys = rng.randint(0, classes, size=n)
    xs = centers[ys] + 0.1 * rng.randn(n, dim).astype("float32")
    return xs.astype("float32"), ys.astype("int64")


def _reader(xs, ys):
    def r():
        for x, y in zip(xs, ys):
            yield x, int(y)
    return r


def test_v2_classification_end_to_end():
    """layer DSL + classification_cost + Momentum: cost falls, events
    fire in order, metrics carry classification_error_evaluator."""
    xs, ys = _mnist_like()
    img = paddle.layer.data(name="img",
                            type=paddle.data_type.dense_vector(64))
    hidden = paddle.layer.fc(input=img, size=32,
                             act=paddle.activation.Relu())
    pred = paddle.layer.fc(input=hidden, size=10,
                           act=paddle.activation.Softmax())
    lbl = paddle.layer.data(name="lbl",
                            type=paddle.data_type.integer_value(10))
    cost = paddle.layer.classification_cost(input=pred, label=lbl)

    params = paddle.parameters.create(cost)
    assert any("fc" in n or "w" in n.lower() for n in params.names())

    trainer = paddle.trainer.SGD(
        cost, params,
        paddle.optimizer.Momentum(momentum=0.9, learning_rate=0.1))

    events = []
    costs = []

    def handler(e):
        events.append(type(e).__name__)
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)
            assert "classification_error_evaluator" in e.metrics
            assert 0.0 <= e.metrics["classification_error_evaluator"] <= 1.0

    trainer.train(paddle.batch(_reader(xs, ys), 64), num_passes=4,
                  event_handler=handler)

    assert events[0] == "BeginPass" and events[-1] == "EndPass"
    assert "EndForwardBackward" in events
    assert costs[-1] < costs[0] * 0.7, (costs[0], costs[-1])

    result = trainer.test(paddle.batch(_reader(xs, ys), 64))
    assert result.cost < costs[0]
    assert result.metrics["classification_error_evaluator"] < 0.5

    probs = paddle.infer(output_layer=pred, parameters=params,
                         input=[(x,) for x in xs[:16]])
    assert probs.shape == (16, 10)
    np.testing.assert_allclose(np.sum(probs, axis=1), np.ones(16),
                               rtol=1e-4)
    acc = np.mean(np.argmax(probs, axis=1) == ys[:16])
    assert acc > 0.5


def test_v2_regression_and_tar_roundtrip():
    rng = np.random.RandomState(1)
    w = rng.randn(8, 1).astype("float32")
    xs = rng.randn(512, 8).astype("float32")
    ys = xs @ w + 0.01 * rng.randn(512, 1).astype("float32")

    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(8))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1)
    cost = paddle.layer.mse_cost(input=pred, label=y)

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost, params, paddle.optimizer.Adam(learning_rate=0.1))

    def reader():
        for i in range(512):
            yield xs[i], ys[i]

    trainer.train(paddle.batch(reader, 64), num_passes=20)

    out = paddle.infer(output_layer=pred, parameters=params,
                       input=[(x_,) for x_ in xs[:32]])
    mse = float(np.mean((out - ys[:32]) ** 2))
    assert mse < 0.1, mse

    # tar round-trip (reference parameters.py to_tar/from_tar)
    buf = io.BytesIO()
    trainer.save_parameter_to_tar(buf)
    buf.seek(0)
    loaded = paddle.parameters.Parameters.from_tar(buf)
    for name in params.names():
        np.testing.assert_array_equal(loaded.get(name), params.get(name))

    # mutate, then restore via init_from_tar: inference must match
    params.set(params.names()[0],
               np.zeros_like(params.get(params.names()[0])))
    buf.seek(0)
    params.init_from_tar(buf)
    out2 = paddle.infer(output_layer=pred, parameters=params,
                        input=[(x_,) for x_ in xs[:32]])
    np.testing.assert_allclose(out, out2, rtol=1e-5)


def test_v2_sequence_model():
    """embedding + sequence pooling over integer_value_sequence input
    (the v2 text-classification shape)."""
    rng = np.random.RandomState(2)
    vocab, n = 50, 192
    seqs, labels = [], []
    for _ in range(n):
        L = rng.randint(3, 12)
        s = rng.randint(0, vocab, size=L).tolist()
        labels.append(1 if (7 in s) else 0)
        seqs.append(s)

    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(vocab))
    emb = paddle.layer.embedding(input=words, size=16)
    pooled = paddle.layer.pooling(input=emb,
                                  pooling_type=paddle.pooling.Max())
    pred = paddle.layer.fc(input=pooled, size=2,
                           act=paddle.activation.Softmax())
    lbl = paddle.layer.data(name="lbl",
                            type=paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=pred, label=lbl)

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost, params, paddle.optimizer.Adam(learning_rate=0.05))

    def reader():
        for s, y in zip(seqs, labels):
            yield s, y

    costs = []
    trainer.train(
        paddle.batch(reader, 32), num_passes=8,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.7, (costs[0], costs[-1])


def test_v2_conv_network_and_feeding():
    """networks.simple_img_conv_pool on a flat dense vector + explicit
    feeding order (label column first)."""
    xs, ys = _mnist_like(n=96, dim=64, classes=4, seed=3)

    img = paddle.layer.data(name="pixel",
                            type=paddle.data_type.dense_vector(64))
    conv = paddle.networks.simple_img_conv_pool(
        input=img, filter_size=3, num_filters=4, num_channel=1,
        pool_size=2, pool_stride=2, act=paddle.activation.Relu())
    pred = paddle.layer.fc(input=conv, size=4,
                           act=paddle.activation.Softmax())
    lbl = paddle.layer.data(name="label",
                            type=paddle.data_type.integer_value(4))
    cost = paddle.layer.classification_cost(input=pred, label=lbl)

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost, params, paddle.optimizer.Adam(learning_rate=0.02))

    def reader():  # label first: exercises the feeding map
        for x, y in zip(xs, ys):
            yield int(y), x

    costs = []
    trainer.train(
        paddle.batch(reader, 32), num_passes=4,
        feeding={"pixel": 1, "label": 0},
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0], (costs[0], costs[-1])


def test_v2_batch_drop_last():
    r = paddle.batch(lambda: iter(range(10)), 3)
    assert [len(b) for b in r()] == [3, 3, 3]
    r2 = paddle.batch(lambda: iter(range(10)), 3, drop_last=False)
    assert [len(b) for b in r2()] == [3, 3, 3, 1]


def test_v2_topology_and_parse_network():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    h = paddle.layer.fc(input=x, size=8, act=paddle.activation.Tanh())
    prog = paddle.layer.parse_network(h)
    ops = [op.type for op in prog.global_block().ops]
    assert "mul" in ops and "tanh" in ops

    from paddle_tpu.v2.topology import Topology
    topo = Topology(h)
    assert topo.data_layer_names() == ["x"]
    (name, tp), = topo.data_type()
    assert name == "x" and tp.dim == 4
    d = topo.proto()
    assert isinstance(d, dict) and d.get("blocks")


def test_v2_lstm_network():
    """networks.simple_lstm trains on a toy last-token task."""
    rng = np.random.RandomState(4)
    vocab = 12
    seqs = [rng.randint(0, vocab, size=rng.randint(3, 8)).tolist()
            for _ in range(128)]
    labels = [s[-1] % 2 for s in seqs]

    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(vocab))
    emb = paddle.layer.embedding(input=words, size=8)
    lstm = paddle.networks.simple_lstm(input=emb, size=8)
    last = paddle.layer.last_seq(input=lstm)
    pred = paddle.layer.fc(input=last, size=2,
                           act=paddle.activation.Softmax())
    lbl = paddle.layer.data(name="y", type=paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=pred, label=lbl)

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost, params, paddle.optimizer.Adam(learning_rate=0.05))

    costs = []
    trainer.train(
        paddle.batch(lambda: iter(zip(seqs, labels)), 32), num_passes=6,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.9, (costs[0], costs[-1])


def test_v2_master_client_records_and_save_arbitration(tmp_path):
    """v2.master.client: recordio chunks -> task leases -> next_record
    stream + save-model arbitration (reference v2/master/client.py over
    go/master/service.go)."""
    import paddle_tpu.recordio as recordio
    from paddle_tpu.cloud.master import MasterService

    path = str(tmp_path / "data.recordio")
    with recordio.Writer(path, max_chunk_bytes=64) as w:
        for i in range(20):
            w.write(("rec-%02d" % i).encode())

    svc = MasterService(chunks_per_task=1, timeout=30.0)
    c = paddle.master.client(svc)
    c.set_dataset([path])

    c.paddle_start_get_records(0)
    got = []
    while True:
        rec, err = c.next_record()
        if err != 0:
            assert err == -2  # pass end
            break
        got.append(rec)
    assert sorted(got) == sorted(("rec-%02d" % i).encode()
                                 for i in range(20))

    # save-model arbitration: first trainer wins, second is blocked
    assert c.request_save_model("t0", 60000) == 1
    assert c.request_save_model("t1", 60000) == 0
    c.release()
