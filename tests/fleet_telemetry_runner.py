"""Multi-process fleet telemetry drill (ISSUE 19): members push
MetricDigests over real heartbeat RPC, the master merges them, a
``delay_dispatch`` fault slows ONE member mid-run, and the straggler
alert fires with that member's id — then resolves after the fault
window disarms.

Used two ways:
* ``tools/run_ci.sh`` step 19 drives ``supervise`` from the CLI;
* ``tests/test_fleet_telemetry.py`` wraps the same supervisor in a
  slow-marked test.

Modes (argv):
    member    <workdir> <host_id> <master_addr> [slow]
    supervise <workdir> [members]
"""

import glob
import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# short lease so digest windows (lease/3 heartbeats) are quick; long
# enough that a GC pause or a loaded CI box cannot expire a live member
LEASE_SECONDS = 4.0
# the fault window on the slow member: executor steps [30, 70) each pay
# an extra DELAY_S at dispatch, then the drill disarms by schedule
SLOW_STEPS = tuple(range(30, 70))
DELAY_S = 0.25
PACE_S = 0.04


def _build_mlp():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        pred = fluid.layers.fc(h, size=4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def member(workdir, host_id, master_addr, slow=False):
    """One training member: monitored tiny-MLP step loop, fleet
    telemetry on (digests ride the auto-heartbeat), paced so digest
    windows hold a steady step rate.  Runs until the supervisor drops
    the stop file."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import fault, monitor
    from paddle_tpu.cluster.runtime import ClusterMember
    from paddle_tpu.monitor import aggregate

    monitor.enable(log_dir=os.path.join(workdir, host_id))
    aggregate.enable()
    if slow:
        fault.delay_dispatch(DELAY_S,
                             fault.FaultSchedule(steps=SLOW_STEPS))
    main, startup, loss = _build_mlp()
    stop = os.path.join(workdir, "stop")
    rng = np.random.RandomState(0)
    mem = ClusterMember(master_addr, host_id)
    scope = fluid.Scope()
    try:
        with fluid.scope_guard(scope):
            fluid.Executor(fluid.CPUPlace()).run(startup)
            exe = fluid.Executor(fluid.CPUPlace())
            for _ in range(4000):
                if os.path.exists(stop):
                    break
                feed = {"x": rng.rand(4, 8).astype("float32"),
                        "label": rng.randint(0, 4, (4, 1))
                        .astype("int64")}
                exe.run(main, feed=feed, fetch_list=[loss])
                time.sleep(PACE_S)
    finally:
        mem.leave()
    return 0


def _load_jsonl(log_dir):
    records = []
    for f in sorted(glob.glob(os.path.join(log_dir, "*.jsonl"))
                    + glob.glob(os.path.join(log_dir, "*.jsonl.*"))):
        with open(f) as fh:
            for ln in fh:
                try:
                    records.append(json.loads(ln))
                except ValueError:
                    continue
    return records


def _active_alert(agg, rule):
    for a in agg.fleet_view()["alerts"]:
        if a["rule"] == rule:
            return a
    return None


def _wait(pred, timeout, poll=0.5):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    return None


def supervise(workdir, members=3):
    """The drill: in-process master + aggregator + /metrics endpoint,
    ``members`` subprocess trainers (index 0 slow), asserts the
    acceptance evidence and returns it."""
    from paddle_tpu import monitor
    from paddle_tpu.cloud import MasterServer
    from paddle_tpu.cluster.membership import ClusterMaster
    from paddle_tpu.monitor import aggregate, alerts

    os.makedirs(workdir, exist_ok=True)
    master_logs = os.path.join(workdir, "master")
    monitor.enable(log_dir=master_logs)
    master = ClusterMaster(lease_timeout=LEASE_SECONDS)
    agg = aggregate.FleetAggregator(
        master=master,
        rules=alerts.default_rules(straggler_for_s=1.0,
                                   digest_stale_s=6.0 * LEASE_SECONDS))
    srv = MasterServer(master).start()
    http = monitor.start_http_server(0, monitor.expose_text)
    stop = os.path.join(workdir, "stop")
    procs = []
    t0 = time.monotonic()
    try:
        for i in range(members):
            cmd = [sys.executable, os.path.abspath(__file__), "member",
                   workdir, "m-%d" % i, srv.address]
            if i == 0:
                cmd.append("slow")
            procs.append(subprocess.Popen(
                cmd, env=dict(os.environ, JAX_PLATFORMS="cpu")))

        all_report = _wait(
            lambda: len(agg.fleet_view()["hosts"]) >= members, 120)
        assert all_report, "not all members pushed digests"
        hosts_reporting = len(agg.fleet_view()["hosts"])

        fired = _wait(lambda: _active_alert(agg, "straggler"), 120)
        assert fired, "straggler alert never fired"
        assert fired["member_id"] == "m-0", fired
        fired_after_s = time.monotonic() - t0

        # merged fleet series on the master's own /metrics endpoint
        port = http.server_address[1]
        text = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % port, timeout=10) \
            .read().decode("utf-8")
        assert "fleet_hosts" in text, "no merged fleet series on /metrics"
        fleet_series = sorted({ln.split(None, 1)[0] for ln in
                               text.splitlines()
                               if ln.startswith("fleet_")
                               and not ln.startswith("# ")})

        # the fault schedule disarms itself after step 70: the slow
        # member's windows return in-band and the alert must resolve
        resolved = _wait(
            lambda: _active_alert(agg, "straggler") is None, 180)
        assert resolved, "straggler alert never resolved after disarm"

        open(stop, "w").close()
        for p in procs:
            p.wait(timeout=60)

        recs = _load_jsonl(master_logs)
        alert_recs = [r for r in recs if r.get("event") == "alert"
                      and r.get("rule") == "straggler"]
        states = [r["state"] for r in alert_recs]
        assert "firing" in states and "resolved" in states, states
        assert all(r.get("member_id") == "m-0" for r in alert_recs)
        view = agg.fleet_view()
        evidence = {
            "members": members,
            "straggler_member": "m-0",
            "fired_after_s": round(fired_after_s, 1),
            "alert_jsonl": {"firing": states.count("firing"),
                            "resolved": states.count("resolved")},
            "fleet_series": fleet_series[:12],
            "fleet_view_records": sum(
                1 for r in recs if r.get("event") == "fleet_view"),
            "hosts_reporting": hosts_reporting,
            "goodput_ratio": view["goodput_ratio"],
            "member_rcs": [p.returncode for p in procs],
        }
        assert evidence["fleet_view_records"] >= 1
        assert all(rc == 0 for rc in evidence["member_rcs"]), evidence
        return evidence
    finally:
        open(stop, "w").close()
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.shutdown()
        http.shutdown()
        monitor.disable()
        aggregate.disable()


def main(argv):
    mode = argv[0]
    if mode == "member":
        workdir, host_id, addr = argv[1:4]
        return member(workdir, host_id, addr,
                      slow="slow" in argv[4:])
    if mode == "supervise":
        workdir = argv[1]
        members = int(argv[2]) if len(argv) > 2 else 3
        evidence = supervise(workdir, members=members)
        print(json.dumps(evidence, indent=2, sort_keys=True))
        print("FLEET TELEMETRY OK")
        return 0
    raise SystemExit("unknown mode %r" % mode)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
