"""OpTest: the golden per-op test harness.

Replicates the reference's ``python/paddle/fluid/tests/unittests/op_test.py``
pattern (op_test.py:131): build a one-op program from numpy inputs, check
forward against a numpy oracle (check_output), and check analytic gradients
(program-level append_backward) against numeric central differences
(check_grad, get_numeric_gradient:43) — parameterized over places.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import grad_var_name


class OpTest:
    """Subclass sets: op_type, inputs {slot: ndarray | [(name, ndarray)]},
    attrs {}, outputs {slot: ndarray | [(name, ndarray)]}."""

    op_type = None
    inputs = {}
    attrs = {}
    outputs = {}

    # ------------------------------------------------------------------
    def _canon(self, mapping):
        out = {}
        for slot, v in mapping.items():
            if isinstance(v, (list, tuple)) and v and isinstance(v[0], tuple):
                out[slot] = [(name, np.asarray(a)) for name, a in v]
            elif v is None:
                out[slot] = []
            else:
                out[slot] = [("%s__%s" % (self.op_type, slot), np.asarray(v))]
        return out

    def _build(self, stop_gradient_all=False):
        program = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(program, startup):
            block = program.global_block()
            ins = self._canon(self.inputs)
            outs = self._canon(self.outputs)
            in_map = {}
            feed = {}
            for slot, pairs in ins.items():
                names = []
                for name, arr in pairs:
                    block.create_var(
                        name=name, shape=arr.shape, dtype=arr.dtype,
                        stop_gradient=stop_gradient_all, is_data=True,
                    )
                    feed[name] = arr
                    names.append(name)
                in_map[slot] = names
            out_map = {
                slot: [name for name, _ in pairs]
                for slot, pairs in outs.items()
            }
            block.append_op(
                type=self.op_type, inputs=in_map, outputs=out_map,
                attrs=dict(self.attrs),
            )
        return program, startup, feed, outs

    # ------------------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-4, place=None):
        program, startup, feed, outs = self._build(stop_gradient_all=True)
        exe = fluid.Executor(place or fluid.CPUPlace())
        fetch_names = [n for pairs in outs.values() for n, _ in pairs]
        expected = [a for pairs in outs.values() for _, a in pairs]
        results = exe.run(program, feed=feed, fetch_list=fetch_names)
        for name, got, want in zip(fetch_names, results, expected):
            np.testing.assert_allclose(
                np.asarray(got, dtype=np.float64),
                np.asarray(want, dtype=np.float64),
                atol=atol, rtol=rtol,
                err_msg="output %r mismatch for op %s" % (name, self.op_type),
            )

    # ------------------------------------------------------------------
    def check_grad(self, inputs_to_check, output_name,
                   max_relative_error=0.005, delta=5e-3, place=None,
                   no_grad_set=None):
        """Numeric central-difference d(sum(output))/d(input) vs the
        analytic program gradient (reference op_test.py:check_grad)."""
        program, startup, feed, _ = self._build(stop_gradient_all=False)
        exe = fluid.Executor(place or fluid.CPUPlace())

        # weight the output with a fixed random cotangent so the scalar loss
        # is sensitive to every output element (plain sum is degenerate for
        # e.g. softmax); same trick as the reference's user_defined_grads.
        out_shape = self._canon(self.outputs)
        shape_by_name = {
            n: a.shape for pairs in out_shape.values() for n, a in pairs
        }
        w = np.random.RandomState(99).uniform(
            0.5, 1.5, shape_by_name[output_name]).astype("float32")

        def _append_loss(block):
            block.append_op(
                type="assign_value", outputs={"Out": ["__ct__"]},
                attrs={"shape": list(w.shape), "dtype": "float32",
                       "values": w.reshape(-1).tolist()},
            )
            block.var("__ct__").stop_gradient = True
            block.append_op(
                type="elementwise_mul",
                inputs={"X": [output_name], "Y": ["__ct__"]},
                outputs={"Out": ["__weighted__"]}, attrs={"axis": -1},
            )
            block.append_op(
                type="reduce_sum", inputs={"X": ["__weighted__"]},
                outputs={"Out": ["__loss__"]},
                attrs={"dim": [0], "keep_dim": False, "reduce_all": True},
            )

        with fluid.program_guard(program, startup):
            block = program.global_block()
            _append_loss(block)
            fluid.append_backward(block.var("__loss__"),
                                  no_grad_set=no_grad_set)

        grad_names = [grad_var_name(n) for n in inputs_to_check]
        analytic = exe.run(program, feed=feed, fetch_list=grad_names)

        # numeric gradients on the forward-only program
        fwd_program, fwd_startup, _, _ = self._build(stop_gradient_all=True)
        with fluid.program_guard(fwd_program, fwd_startup):
            _append_loss(fwd_program.global_block())
        exe2 = fluid.Executor(place or fluid.CPUPlace())

        def loss_at(feed_override):
            (val,) = exe2.run(fwd_program, feed=feed_override,
                              fetch_list=["__loss__"])
            return float(np.asarray(val).reshape(-1)[0])

        for name, analytic_grad in zip(inputs_to_check, analytic):
            base = feed[name].astype(np.float64)
            numeric = np.zeros_like(base, dtype=np.float64)
            flat = base.reshape(-1)
            for i in range(flat.size):
                orig = flat[i]
                f = {k: v.copy() for k, v in feed.items()}
                f[name] = base.copy().astype(feed[name].dtype)
                f[name].reshape(-1)[i] = orig + delta
                hi = loss_at(f)
                f[name].reshape(-1)[i] = orig - delta
                lo = loss_at(f)
                numeric.reshape(-1)[i] = (hi - lo) / (2 * delta)
            a = np.asarray(analytic_grad, dtype=np.float64)
            abs_a = np.abs(a).max()
            denom = max(abs_a, np.abs(numeric).max(), 1e-3)
            max_diff = np.abs(a - numeric).max()
            assert max_diff / denom <= max_relative_error, (
                "gradient of %r wrong for op %s: max diff %g (rel %g)\n"
                "analytic=%s\nnumeric=%s"
                % (name, self.op_type, max_diff, max_diff / denom,
                   a.reshape(-1)[:8], numeric.reshape(-1)[:8])
            )
