"""Golden numpy-oracle coverage for op families the model/layer tests
only reach indirectly (the SURVEY §4 OpTest pattern): parameterized
activations, the small-loss family, metric/manipulation stragglers, and
the random-creation ops' distribution contracts.

References: ``activation_op.cc`` (functor family), ``hinge_loss_op.cc``,
``huber_loss_op.cc``, ``log_loss_op.cc``, ``rank_loss_op.cc``,
``margin_rank_loss_op.cc``, ``squared_l2_distance_op.cc``,
``mean_iou_op.cc``, ``multiplex_op.cc``, ``maxout_op.cc``,
``clip_by_norm_op.cc``, ``cumsum_op.cc``, ``arg_max_op.cc``,
``uniform_random_op.cc``, ``gaussian_random_op.cc``,
``truncated_gaussian_random_op.cc``, ``sampling_id_op.cc``.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest


def _x(shape=(4, 7), lo=-3.0, hi=3.0, seed=0):
    rng = np.random.RandomState(seed)
    return (lo + (hi - lo) * rng.rand(*shape)).astype("float32")


def _sig(v):
    return 1.0 / (1.0 + np.exp(-v))


# ---- parameterized activation sweep ---------------------------------------

ACTS = [
    ("logsigmoid", {}, lambda x: np.log(_sig(x)), (-3, 3)),
    ("tanh_shrink", {}, lambda x: x - np.tanh(x), (-3, 3)),
    ("reciprocal", {}, lambda x: 1.0 / x, (0.5, 3)),
    ("sin", {}, np.sin, (-3, 3)),
    ("cos", {}, np.cos, (-3, 3)),
    ("relu6", {"threshold": 6.0}, lambda x: np.clip(x, 0, 6), (-8, 8)),
    ("leaky_relu", {"alpha": 0.1},
     lambda x: np.where(x >= 0, x, 0.1 * x), (-3, 3)),
    ("brelu", {"t_min": -1.0, "t_max": 2.0},
     lambda x: np.clip(x, -1, 2), (-3, 3)),
    ("soft_relu", {"threshold": 40.0}, lambda x: np.log1p(np.exp(x)),
     (-3, 3)),
    ("pow", {"factor": 2.0}, lambda x: x * x, (0.5, 3)),
    ("stanh", {"scale_a": 0.67, "scale_b": 1.7159},
     lambda x: 1.7159 * np.tanh(0.67 * x), (-3, 3)),
    ("hard_sigmoid", {"slope": 0.2, "offset": 0.5},
     lambda x: np.clip(0.2 * x + 0.5, 0, 1), (-5, 5)),
    ("swish", {"beta": 1.5}, lambda x: x * _sig(1.5 * x), (-3, 3)),
    ("thresholded_relu", {"threshold": 1.0},
     lambda x: np.where(x > 1.0, x, 0.0), (-3, 3)),
    ("hard_shrink", {"threshold": 0.5},
     lambda x: np.where(np.abs(x) > 0.5, x, 0.0), (-3, 3)),
    ("softshrink", {"lambda": 0.5},
     lambda x: np.where(x > 0.5, x - 0.5,
                        np.where(x < -0.5, x + 0.5, 0.0)), (-3, 3)),
]


@pytest.mark.parametrize("op_type,attrs,oracle,rng",
                         ACTS, ids=[a[0] for a in ACTS])
def test_activation_forward(op_type, attrs, oracle, rng):
    t = OpTest()
    t.op_type = op_type
    x = _x(lo=rng[0], hi=rng[1])
    t.inputs = {"X": x}
    t.attrs = dict(attrs)
    t.outputs = {"Out": oracle(x).astype("float32")}
    t.check_output(atol=2e-5)


@pytest.mark.parametrize("op_type,attrs",
                         [("swish", {"beta": 1.5}),
                          ("stanh", {"scale_a": 0.67, "scale_b": 1.7159}),
                          ("soft_relu", {"threshold": 40.0}),
                          ("logsigmoid", {})])
def test_activation_grad_smooth(op_type, attrs):
    """Numeric-vs-analytic grads for the smooth parameterized
    activations (kinked ones are covered forward-only: central
    differences straddle the kink)."""
    t = OpTest()
    t.op_type = op_type
    x = _x(shape=(3, 5))
    t.inputs = {"X": x}
    t.attrs = dict(attrs)
    t.outputs = {"Out": np.zeros_like(x)}  # shape only; grad check re-runs fwd
    t.check_grad(["%s__X" % op_type], "%s__Out" % op_type,
                 max_relative_error=5e-3)


# ---- small loss family -----------------------------------------------------

def test_hinge_loss():
    t = OpTest()
    t.op_type = "hinge_loss"
    logits = _x(shape=(6, 1))
    labels = (np.random.RandomState(1).rand(6, 1) > 0.5).astype("float32")
    t.inputs = {"Logits": logits, "Labels": labels}
    t.outputs = {"Loss": np.maximum(
        1 - (2 * labels - 1) * logits, 0).astype("float32")}
    t.check_output()


def test_huber_loss_both_branches():
    t = OpTest()
    t.op_type = "huber_loss"
    x = np.array([[0.0], [0.0], [0.0], [0.0]], "float32")
    y = np.array([[0.3], [-0.4], [2.0], [-3.0]], "float32")
    d = 1.0
    r = y - x
    loss = np.where(np.abs(r) <= d, 0.5 * r * r, d * (np.abs(r) - 0.5 * d))
    t.inputs = {"X": x, "Y": y}
    t.attrs = {"delta": d}
    t.outputs = {"Residual": r, "Out": loss.astype("float32")}
    t.check_output()


def test_log_loss():
    t = OpTest()
    t.op_type = "log_loss"
    p = np.clip(_x(shape=(5, 1), lo=0.05, hi=0.95), 0.05, 0.95)
    y = (np.random.RandomState(2).rand(5, 1) > 0.5).astype("float32")
    eps = 1e-4
    t.inputs = {"Predicted": p, "Labels": y}
    t.attrs = {"epsilon": eps}
    t.outputs = {"Loss": (-y * np.log(p + eps)
                          - (1 - y) * np.log(1 - p + eps))}
    t.check_output()


def test_rank_loss_and_margin_rank_loss():
    left = _x(shape=(5, 1), seed=3)
    right = _x(shape=(5, 1), seed=4)
    label = (np.random.RandomState(5).rand(5, 1) > 0.5).astype("float32")

    t = OpTest()
    t.op_type = "rank_loss"
    t.inputs = {"Label": label, "Left": left, "Right": right}
    d = left - right
    t.outputs = {"Out": np.log1p(np.exp(d)) - label * d}
    t.check_output()

    t2 = OpTest()
    t2.op_type = "margin_rank_loss"
    lab = np.where(label > 0, 1.0, -1.0).astype("float32")
    t2.inputs = {"Label": lab, "X1": left, "X2": right}
    t2.attrs = {"margin": 0.1}
    out = np.maximum(0.0, -lab * (left - right) + 0.1)
    t2.outputs = {"Out": out, "Activated": (out > 0).astype("float32")}
    t2.check_output()


def test_squared_l2_distance_with_grad():
    t = OpTest()
    t.op_type = "squared_l2_distance"
    x = _x(shape=(4, 3), seed=6)
    y = _x(shape=(4, 3), seed=7)
    sub = x - y
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"sub_result": sub,
                 "Out": (sub * sub).sum(1, keepdims=True)}
    t.check_output()
    t.check_grad(["squared_l2_distance__X"], "squared_l2_distance__Out",
                 no_grad_set={"squared_l2_distance__Y"},
                 max_relative_error=5e-3)


def test_norm_scalars():
    x = _x(shape=(3, 4), seed=8)
    t = OpTest()
    t.op_type = "squared_l2_norm"
    t.inputs = {"X": x}
    t.outputs = {"Out": np.array([np.sum(x * x)], "float32")}
    t.check_output()

    t2 = OpTest()
    t2.op_type = "l1_norm"
    t2.inputs = {"X": x}
    t2.outputs = {"Out": np.array([np.sum(np.abs(x))], "float32")}
    t2.check_output()


def test_clip_by_norm():
    x = _x(shape=(3, 3), seed=9)
    norm = np.sqrt((x * x).sum())
    t = OpTest()
    t.op_type = "clip_by_norm"
    t.inputs = {"X": x}
    t.attrs = {"max_norm": float(norm / 2)}
    t.outputs = {"Out": x * (norm / 2) / norm}
    t.check_output()
    # under the cap: identity
    t2 = OpTest()
    t2.op_type = "clip_by_norm"
    t2.inputs = {"X": x}
    t2.attrs = {"max_norm": float(norm * 2)}
    t2.outputs = {"Out": x}
    t2.check_output()


# ---- metric / manipulation stragglers -------------------------------------

def test_mean_iou():
    pred = np.array([0, 1, 1, 2, 2, 2, 0], "int64")
    label = np.array([0, 1, 2, 2, 2, 1, 1], "int64")
    n = 3
    inter = np.zeros(n)
    pc = np.zeros(n)
    lc = np.zeros(n)
    for p, l in zip(pred, label):
        pc[p] += 1
        lc[l] += 1
        if p == l:
            inter[p] += 1
    union = pc + lc - inter
    iou = inter / np.maximum(union, 1)
    want = iou[union > 0].mean()
    t = OpTest()
    t.op_type = "mean_iou"
    t.inputs = {"Predictions": pred, "Labels": label}
    t.attrs = {"num_classes": n}
    t.outputs = {"OutMeanIou": np.array([want], "float32"),
                 "OutWrong": (lc - inter).astype("int32"),
                 "OutCorrect": inter.astype("int32")}
    t.check_output()


def test_multiplex():
    rng = np.random.RandomState(10)
    xs = [rng.rand(4, 3).astype("float32") for _ in range(3)]
    ids = np.array([[2], [0], [1], [2]], "int64")
    out = np.stack([xs[int(ids[b, 0])][b] for b in range(4)])
    t = OpTest()
    t.op_type = "multiplex"
    t.inputs = {"Ids": ids, "X": [("m%d" % i, x) for i, x in enumerate(xs)]}
    t.outputs = {"Out": out}
    t.check_output()


def test_maxout():
    rng = np.random.RandomState(11)
    x = rng.rand(2, 6, 3, 3).astype("float32")
    g = 3
    out = x.reshape(2, 2, g, 3, 3).max(axis=2)
    t = OpTest()
    t.op_type = "maxout"
    t.inputs = {"X": x}
    t.attrs = {"groups": g}
    t.outputs = {"Out": out}
    t.check_output()


def test_cumsum_variants():
    x = _x(shape=(3, 5), seed=12)
    for attrs, oracle in [
        ({"axis": 1}, np.cumsum(x, axis=1)),
        ({"axis": 0}, np.cumsum(x, axis=0)),
        ({"axis": 1, "exclusive": True},
         np.concatenate([np.zeros((3, 1), "float32"),
                         np.cumsum(x, axis=1)[:, :-1]], axis=1)),
        ({"axis": 1, "reverse": True},
         np.flip(np.cumsum(np.flip(x, 1), axis=1), 1)),
    ]:
        t = OpTest()
        t.op_type = "cumsum"
        t.inputs = {"X": x}
        t.attrs = dict(attrs)
        t.outputs = {"Out": oracle.astype("float32")}
        t.check_output()


def test_arg_max_min_flatten_fill_zeros():
    x = _x(shape=(3, 5), seed=13)
    for op, oracle in [("arg_max", x.argmax(1)), ("arg_min", x.argmin(1))]:
        t = OpTest()
        t.op_type = op
        t.inputs = {"X": x}
        t.attrs = {"axis": 1}
        t.outputs = {"Out": oracle.astype("int64")}
        t.check_output()

    x4 = _x(shape=(2, 3, 4), seed=14)
    t = OpTest()
    t.op_type = "flatten"
    t.inputs = {"X": x4}
    t.attrs = {"axis": 2}
    t.outputs = {"Out": x4.reshape(6, 4)}
    t.check_output()

    t2 = OpTest()
    t2.op_type = "fill_zeros_like"
    t2.inputs = {"X": x4}
    t2.outputs = {"Out": np.zeros_like(x4)}
    t2.check_output()


def test_elementwise_and_compare_families():
    rng = np.random.RandomState(15)
    x = (rng.rand(4, 5) * 6 + 1).astype("float32")
    y = (rng.rand(4, 5) * 3 + 1).astype("float32")
    cases = [
        ("elementwise_max", np.maximum(x, y), "float32"),
        ("elementwise_min", np.minimum(x, y), "float32"),
        ("elementwise_mod", np.mod(x, y), "float32"),
        ("elementwise_floordiv", np.floor_divide(x, y), "float32"),
        ("elementwise_pow", np.power(x, y), "float32"),
        ("greater_than", x > y, "bool"),
        ("greater_equal", x >= y, "bool"),
        ("less_equal", x <= y, "bool"),
        ("not_equal", x != y, "bool"),
    ]
    for op, want, dt in cases:
        t = OpTest()
        t.op_type = op
        t.inputs = {"X": x, "Y": y}
        t.outputs = {"Out": want.astype(dt)}
        t.check_output(rtol=1e-3)

    a = np.array([True, True, False, False])
    b = np.array([True, False, True, False])
    for op, want in [("logical_and", a & b), ("logical_or", a | b),
                     ("logical_xor", a ^ b)]:
        t = OpTest()
        t.op_type = op
        t.inputs = {"X": a, "Y": b}
        t.outputs = {"Out": want}
        t.check_output()
    t = OpTest()
    t.op_type = "logical_not"
    t.inputs = {"X": a}
    t.outputs = {"Out": ~a}
    t.check_output()


# ---- random creation ops: distribution contracts --------------------------

def _run_random(op_type, attrs, n=1):
    program, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(program, startup):
        block = program.global_block()
        outs = []
        for i in range(n):
            v = block.create_var(name="r%d" % i, dtype="float32")
            block.append_op(type=op_type, inputs={}, outputs={"Out": [v]},
                            attrs=dict(attrs))
            outs.append(v)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            return exe.run(program, feed={}, fetch_list=outs)


def test_uniform_random_contract():
    out, out2 = _run_random("uniform_random",
                            {"shape": [512, 16], "min": -2.0, "max": 3.0},
                            n=2)
    assert out.shape == (512, 16)
    assert out.min() >= -2.0 and out.max() < 3.0
    assert abs(out.mean() - 0.5) < 0.15  # mean of U(-2, 3)
    assert not np.allclose(out, out2)    # ops draw independent streams


def test_gaussian_random_contract():
    out, = _run_random("gaussian_random",
                       {"shape": [4096], "mean": 1.0, "std": 2.0})
    assert abs(out.mean() - 1.0) < 0.15
    assert abs(out.std() - 2.0) < 0.15


def test_truncated_gaussian_contract():
    out, = _run_random("truncated_gaussian_random",
                       {"shape": [4096], "mean": 0.0, "std": 1.0})
    assert np.abs(out).max() <= 2.0 + 1e-5  # +-2 std truncation
    assert abs(out.mean()) < 0.1


def test_sampling_id_distribution():
    probs = np.tile(np.array([[0.05, 0.9, 0.05]], "float32"), (2048, 1))
    program, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(program, startup):
        block = program.global_block()
        x = block.create_var(name="p", shape=probs.shape, dtype="float32",
                             is_data=True)
        v = block.create_var(name="ids", dtype="int64")
        block.append_op(type="sampling_id", inputs={"X": [x]},
                        outputs={"Out": [v]}, attrs={})
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            ids, = exe.run(program, feed={"p": probs}, fetch_list=[v])
    assert ids.shape == (2048,)
    frac1 = (ids == 1).mean()
    assert 0.85 < frac1 < 0.95  # matches the 0.9 mass on id 1


# ---- second wave: ops the dynamic audit found never-executed ---------------

def test_more_simple_activations():
    from scipy.special import erf  # available via jax's scipy dep? guard:
    x = _x()
    cases = [
        ("ceil", {}, np.ceil(x)),
        ("round", {}, np.round(x)),
        ("elu", {"alpha": 0.8},
         np.where(x >= 0, x, 0.8 * (np.exp(np.minimum(x, 0)) - 1))),
        ("gelu", {}, x * 0.5 * (1 + erf(x / np.sqrt(2)))),
        ("log_softmax", {},
         x - np.log(np.exp(x - x.max(1, keepdims=True)).sum(1, keepdims=True))
         - x.max(1, keepdims=True)),
    ]
    for op, attrs, want in cases:
        t = OpTest()
        t.op_type = op
        t.inputs = {"X": x}
        t.attrs = dict(attrs)
        t.outputs = {"Out": want.astype("float32")}
        t.check_output(atol=2e-5)


def test_manipulation_stragglers():
    x = _x(shape=(4, 6), seed=20)
    t = OpTest()
    t.op_type = "argsort"
    t.inputs = {"X": x}
    t.attrs = {"axis": 1}
    t.outputs = {"Out": np.sort(x, 1), "Indices": np.argsort(x, 1)}
    t.check_output()

    idx = np.array([3, 0, 2], "int64")
    t = OpTest()
    t.op_type = "gather"
    t.inputs = {"X": x, "Index": idx}
    t.outputs = {"Out": x[idx]}
    t.check_output()

    upd = _x(shape=(2, 6), seed=21)
    ids = np.array([1, 3], "int64")
    for overwrite in (True, False):
        want = x.copy()
        if overwrite:
            want[ids] = upd
        else:
            want[ids] += upd
        t = OpTest()
        t.op_type = "scatter"
        t.inputs = {"X": x, "Ids": ids, "Updates": upd}
        t.attrs = {"overwrite": overwrite}
        t.outputs = {"Out": want}
        t.check_output()

    t = OpTest()
    t.op_type = "reverse"
    t.inputs = {"X": x}
    t.attrs = {"axis": [1]}
    t.outputs = {"Out": x[:, ::-1]}
    t.check_output()

    t = OpTest()
    t.op_type = "minus"
    y = _x(shape=(4, 6), seed=22)
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": x - y}
    t.check_output()

    t = OpTest()
    t.op_type = "shape"
    t.inputs = {"Input": x}
    t.outputs = {"Out": np.array(x.shape, "int64")}
    t.check_output()

    t = OpTest()
    t.op_type = "reduce_prod"
    xp = _x(shape=(3, 4), lo=0.5, hi=1.5, seed=23)
    t.inputs = {"X": xp}
    t.attrs = {"dim": [1]}
    t.outputs = {"Out": xp.prod(1)}
    t.check_output(rtol=1e-3)

    t = OpTest()
    t.op_type = "pad"
    t.inputs = {"X": x}
    t.attrs = {"paddings": [1, 0, 0, 2], "pad_value": 9.0}
    t.outputs = {"Out": np.pad(x, [(1, 0), (0, 2)],
                               constant_values=9.0)}
    t.check_output()

    t = OpTest()
    t.op_type = "stack"
    xs = [_x(shape=(2, 3), seed=s) for s in (24, 25)]
    t.inputs = {"X": [("s%d" % i, a) for i, a in enumerate(xs)]}
    t.attrs = {"axis": 1}
    t.outputs = {"Y": np.stack(xs, axis=1)}
    t.check_output()

    t = OpTest()
    t.op_type = "split"
    t.inputs = {"X": x}
    t.attrs = {"axis": 1, "sections": [2, 4]}
    t.outputs = {"Out": [("sp0", x[:, :2]), ("sp1", x[:, 2:])]}
    t.check_output()

    t = OpTest()
    t.op_type = "isfinite"
    t.inputs = {"X": x}
    t.outputs = {"Out": np.array([True])}
    t.check_output()
    bad = x.copy()
    bad[0, 0] = np.inf
    t2 = OpTest()
    t2.op_type = "isfinite"
    t2.inputs = {"X": bad}
    t2.outputs = {"Out": np.array([False])}
    t2.check_output()

    t = OpTest()
    t.op_type = "lod_array_length"
    t.inputs = {"X": _x(shape=(5, 2), seed=26)}
    t.outputs = {"Out": np.array([5], "int64")}
    t.check_output()

    t = OpTest()
    t.op_type = "fake_dequantize_max_abs"
    q = np.array([[-127, 0, 64]], "float32")
    t.inputs = {"X": q, "Scale": np.array([0.5], "float32")}
    t.attrs = {"max_range": 127.0}
    t.outputs = {"Out": q * 0.5 / 127.0}
    t.check_output()


def test_prelu_modes():
    x = _x(shape=(2, 3, 2, 2), seed=27)
    alpha = np.array([0.1, 0.2, 0.3], "float32")
    t = OpTest()
    t.op_type = "prelu"
    t.inputs = {"X": x, "Alpha": alpha}
    t.attrs = {"mode": "channel"}
    t.outputs = {"Out": np.where(x >= 0, x,
                                 alpha.reshape(1, 3, 1, 1) * x)}
    t.check_output()
    t2 = OpTest()
    t2.op_type = "prelu"
    t2.inputs = {"X": x, "Alpha": np.array([0.25], "float32")}
    t2.attrs = {"mode": "all"}
    t2.outputs = {"Out": np.where(x >= 0, x, 0.25 * x)}
    t2.check_output()


def test_nearest_interp():
    x = _x(shape=(1, 1, 2, 2), seed=28)
    oh = ow = 4
    rh = (2 - 1) / (oh - 1)
    ys = np.round(np.arange(oh) * rh).astype(int)
    t = OpTest()
    t.op_type = "nearest_interp"
    t.inputs = {"X": x}
    t.attrs = {"out_h": oh, "out_w": ow}
    t.outputs = {"Out": x[:, :, ys][:, :, :, ys]}
    t.check_output()


def test_conv3d_pool3d():
    rng = np.random.RandomState(29)
    x = rng.rand(1, 1, 3, 4, 4).astype("float32")
    w = rng.rand(2, 1, 2, 2, 2).astype("float32") - 0.5
    out = np.zeros((1, 2, 2, 3, 3), "float32")
    for co in range(2):
        for d in range(2):
            for i in range(3):
                for j in range(3):
                    out[0, co, d, i, j] = np.sum(
                        x[0, 0, d:d + 2, i:i + 2, j:j + 2] * w[co, 0])
    t = OpTest()
    t.op_type = "conv3d"
    t.inputs = {"Input": x, "Filter": w}
    t.attrs = {"strides": [1, 1, 1], "paddings": [0, 0, 0]}
    t.outputs = {"Output": out}
    t.check_output(atol=1e-5)

    pout = np.zeros((1, 1, 2, 3, 3), "float32")
    for d in range(2):
        for i in range(3):
            for j in range(3):
                pout[0, 0, d, i, j] = x[0, 0, d:d + 2, i:i + 2,
                                        j:j + 2].max()
    t2 = OpTest()
    t2.op_type = "pool3d"
    t2.inputs = {"X": x}
    t2.attrs = {"ksize": [2, 2, 2], "strides": [1, 1, 1],
                "paddings": [0, 0, 0], "pooling_type": "max"}
    t2.outputs = {"Out": pout}
    t2.check_output()


def test_rnn_unit_ops():
    """gru_unit / lstm_unit / lstmp numpy oracles (reference
    gru_unit_op.cc, lstm_unit_op.cc, lstmp_op.cc)."""
    rng = np.random.RandomState(30)
    B, H = 3, 4

    # gru_unit: x [B,3H] pre-projected, w [H,3H], h = (1-u)*hp + u*c
    x = rng.randn(B, 3 * H).astype("float32")
    hp = rng.randn(B, H).astype("float32")
    w = (rng.randn(H, 3 * H) * 0.5).astype("float32")
    b = (rng.randn(3 * H) * 0.1).astype("float32")
    xb = x + b
    g = _sig(xb[:, :2 * H] + hp @ w[:, :2 * H])
    u, r = g[:, :H], g[:, H:]
    rhp = r * hp
    c = np.tanh(xb[:, 2 * H:] + rhp @ w[:, 2 * H:])
    hh = (1 - u) * hp + u * c
    t = OpTest()
    t.op_type = "gru_unit"
    t.inputs = {"Input": x, "HiddenPrev": hp, "Weight": w, "Bias": b}
    t.outputs = {"Hidden": hh,
                 "Gate": np.concatenate([g, c], -1),
                 "ResetHiddenPrev": rhp}
    t.check_output(atol=1e-5)

    # lstm_unit: x [B,4H] pre-projected gates (i, c, f, o order)
    x4 = rng.randn(B, 4 * H).astype("float32")
    cp = rng.randn(B, H).astype("float32")
    fb = 0.5
    gi, gc, gf, go = np.split(x4, 4, axis=-1)
    i = _sig(gi)
    f = _sig(gf + fb)
    cc = f * cp + i * np.tanh(gc)
    o = _sig(go)
    t2 = OpTest()
    t2.op_type = "lstm_unit"
    t2.inputs = {"X": x4, "C_prev": cp}
    t2.attrs = {"forget_bias": fb}
    t2.outputs = {"H": o * np.tanh(cc), "C": cc}
    t2.check_output(atol=1e-5)


def test_lstmp_projection():
    """lstmp: LSTM with recurrent projection (reference lstmp_op.cc):
    gate order (c, i, f, o), peephole connections, projected state."""
    rng = np.random.RandomState(31)
    B, T, H, P = 2, 3, 2, 2
    x = rng.randn(B, T, 4 * H).astype("float32") * 0.5
    w = rng.randn(P, 4 * H).astype("float32") * 0.5
    wp = rng.randn(H, P).astype("float32") * 0.5
    bias = rng.randn(1, 7 * H).astype("float32") * 0.1
    lens = np.array([3, 2], "int32")

    gb = bias[0, :4 * H]
    w_ic, w_fc, w_oc = (bias[0, 4 * H:5 * H], bias[0, 5 * H:6 * H],
                        bias[0, 6 * H:7 * H])
    proj = np.zeros((B, T, P), "float32")
    cell = np.zeros((B, T, H), "float32")
    for bi in range(B):
        rp = np.zeros(P)
        cp = np.zeros(H)
        for ti in range(lens[bi]):
            gates = x[bi, ti] + rp @ w + gb
            gc, gi, gf, go = np.split(gates, 4)
            i = _sig(gi + cp * w_ic)
            f = _sig(gf + cp * w_fc)
            c = f * cp + i * np.tanh(gc)
            o = _sig(go + c * w_oc)
            h = o * np.tanh(c)
            r = np.tanh(h @ wp)
            proj[bi, ti] = r
            cell[bi, ti] = c
            rp, cp = r, c
    t = OpTest()
    t.op_type = "lstmp"
    t.inputs = {"Input": x, "Weight": w, "ProjWeight": wp, "Bias": bias,
                "Length": lens}
    t.attrs = {"use_peepholes": True}
    t.outputs = {"Projection": proj, "Cell": cell}
    t.check_output(atol=1e-4)


def test_sequence_enumerate_and_slice():
    ids = np.array([[1, 2, 3, 4], [5, 6, 0, 0]], "int64")
    lens = np.array([4, 2], "int32")
    win, pad = 2, 0
    out = np.zeros((2, 4, win), "int64")
    for b in range(2):
        for tt in range(4):
            for j in range(win):
                out[b, tt, j] = ids[b, tt + j] \
                    if tt + j < lens[b] else pad
    t = OpTest()
    t.op_type = "sequence_enumerate"
    t.inputs = {"X": ids, "Length": lens}
    t.attrs = {"win_size": win, "pad_value": pad}
    t.outputs = {"Out": out}
    t.check_output()

    x = _x(shape=(2, 5, 3), seed=32)
    off = np.array([[1], [0]], "int64")
    sz = np.array([[3], [2]], "int64")
    want = np.zeros((2, 5, 3), "float32")
    want[0, :3] = x[0, 1:4]
    want[1, :2] = x[1, 0:2]
    t2 = OpTest()
    t2.op_type = "sequence_slice"
    t2.inputs = {"X": x, "Offset": off, "Size": sz,
                 "Length": np.array([5, 4], "int32")}
    t2.outputs = {"Out": want, "OutLength": sz.reshape(-1)}
    t2.check_output()


def test_proximal_optimizer_ops():
    rng = np.random.RandomState(33)
    p = rng.randn(4).astype("float32")
    g = rng.randn(4).astype("float32")
    lr = np.array([0.1], "float32")
    l1, l2 = 0.05, 0.02

    prox = p - 0.1 * g
    want = np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * l1, 0) / \
        (1 + 0.1 * l2)
    t = OpTest()
    t.op_type = "proximal_gd"
    t.inputs = {"Param": p, "Grad": g, "LearningRate": lr}
    t.attrs = {"l1": l1, "l2": l2}
    t.outputs = {"ParamOut": want}
    t.check_output()

    mom = np.abs(rng.randn(4)).astype("float32")
    mom_out = mom + g * g
    lr_t = 0.1 / np.sqrt(mom_out)
    prox = p - lr_t * g
    want = np.sign(prox) * np.maximum(np.abs(prox) - lr_t * l1, 0) / \
        (1 + lr_t * l2)
    t2 = OpTest()
    t2.op_type = "proximal_adagrad"
    t2.inputs = {"Param": p, "Moment": mom, "Grad": g, "LearningRate": lr}
    t2.attrs = {"l1": l1, "l2": l2}
    t2.outputs = {"ParamOut": want, "MomentOut": mom_out}
    t2.check_output()


def test_auc_streaming():
    """auc op: bucketed streaming ROC integration (reference auc_op.cc).
    Perfect separation -> 1.0; inverted -> 0.0; states accumulate."""
    n_bins = 101
    zeros = np.zeros(n_bins, "int64")

    def run(preds, labels, sp, sn):
        program, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(program, startup):
            block = program.global_block()
            names = {}
            for nm, arr in [("pr", preds), ("lb", labels), ("sp", sp),
                            ("sn", sn)]:
                block.create_var(name=nm, shape=arr.shape, dtype=arr.dtype,
                                 is_data=True)
                names[nm] = arr
            outs = []
            for nm, dt in [("auc", "float64"), ("spo", "int64"),
                           ("sno", "int64")]:
                outs.append(block.create_var(name=nm, dtype=dt))
            block.append_op(
                type="auc",
                inputs={"Predict": ["pr"], "Label": ["lb"],
                        "StatPos": ["sp"], "StatNeg": ["sn"]},
                outputs={"AUC": ["auc"], "StatPosOut": ["spo"],
                         "StatNegOut": ["sno"]})
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup)
                return exe.run(program, feed=names,
                               fetch_list=["auc", "spo", "sno"])

    rng = np.random.RandomState(34)
    pos_p = 0.8 + 0.15 * rng.rand(50)
    neg_p = 0.05 + 0.15 * rng.rand(50)
    p = np.concatenate([pos_p, neg_p]).astype("float32")
    preds = np.stack([1 - p, p], 1)
    labels = np.concatenate([np.ones(50), np.zeros(50)]).astype("int64")
    auc, spo, sno = run(preds, labels, zeros, zeros)
    assert abs(float(auc[0] if auc.ndim else auc) - 1.0) < 1e-6
    assert spo.sum() == 50 and sno.sum() == 50

    # inverted labels -> AUC 0; warm states accumulate counts
    auc2, spo2, sno2 = run(preds, 1 - labels, spo, sno)
    assert spo2.sum() == 100 and sno2.sum() == 100
    assert float(auc2[0] if auc2.ndim else auc2) < 0.6
