"""Named-mesh model parallelism (ISSUE 7 tentpole): the SpecLayout
table + logical-axis rules, program-structure parameter classification,
graceful per-dim degradation, BuildStrategy.sharding_rules wiring, the
fsdp acceptance criteria (loss parity vs single device AND per-device
HBM ~1/N for the sharded state, from the program-profile registry), and
cross-topology TrainState round trips (fsdp mesh save -> single-device
restore and back).  Runs on the 8-device virtual CPU mesh (conftest)."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu import compile_cache, monitor
from paddle_tpu.monitor import program_profile
from paddle_tpu.parallel import SpecLayout, make_mesh
from paddle_tpu.parallel import spec_layout as sl
from paddle_tpu.parallel.checkpoint import (_persistable_state,
                                            apply_train_state,
                                            capture_train_state,
                                            load_train_state,
                                            save_train_state)


@pytest.fixture(autouse=True)
def clean_profile_state():
    program_profile.reset()
    yield
    monitor.disable()
    monitor.registry().reset()
    monitor.step_stats().reset()
    program_profile.reset()


def _build_transformer(seed=11, t=8, vocab=32, dropout=0.1, n_layer=1):
    """The real enc-dec transformer at the smallest shape that still
    exercises every parameter class (tier-1 budget: compiles dominate
    these tests; n_layer=1/t=8 halves them vs the sp/pp suite's
    config — the classification tests that need 2 layers ask for
    them explicitly)."""
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    from paddle_tpu.models import transformer as tfm
    src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                            lod_level=1)
    tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                            lod_level=1)
    lbl = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                            lod_level=1)
    cost, _ = tfm.transformer(src, tgt, lbl, t, t, vocab, vocab,
                              n_layer=n_layer,
                              n_head=2, d_model=16, d_inner=32,
                              dropout_rate=dropout)
    fluid.optimizer.Adam(learning_rate=2e-3).minimize(cost)
    return cost


def _batches(steps=3, batch=8, t=8, vocab=32):
    rng = np.random.RandomState(3)
    out = []
    for _ in range(steps):
        ids = rng.randint(2, vocab, (batch, t, 1)).astype("int64")
        lens = rng.randint(t // 2, t + 1, (batch,)).astype("int32")
        out.append({"src_word": ids, "src_word@LEN": lens,
                    "tgt_word": ids, "tgt_word@LEN": lens,
                    "lbl_word": ids, "lbl_word@LEN": lens})
    return out


# ---------------------------------------------------------------------------
# the table + rules (unit)
# ---------------------------------------------------------------------------

def test_spec_layout_canonical_table():
    lay = SpecLayout()
    assert lay.embeddings() == P(("fsdp", "tp"), None)
    assert lay.qkv_projection() == P("fsdp", "tp")
    assert lay.attn_output() == P("tp", "fsdp")
    assert lay.ffn_up() == P("fsdp", "tp")
    assert lay.ffn_down() == P("tp", "fsdp")
    assert lay.norm_scale() == P("fsdp")
    assert lay.batch() == P(("dp", "fsdp"))


def test_spec_layout_axis_renaming():
    lay = SpecLayout(fsdp_axis="dp")       # pure-dp ZeRO layout
    assert lay.embeddings() == P(("dp", "tp"), None)
    assert dict(lay.rules)["embed"] == "dp"


def test_classify_transformer_params():
    _build_transformer()
    classes = sl.classify_params(fluid.default_main_program())
    assert classes["src_word_emb"] == ("vocab", "embed")
    assert classes["tgt_word_emb"] == ("vocab", "embed")
    # qkv in-projections are column-parallel ...
    assert classes["enc0_attn_q.w_0"] == ("embed", "mlp")
    # ... and the out-projection is row-parallel (Megatron pairing:
    # lineage propagates through reshape/transpose/fused_attention)
    assert classes["enc0_attn_o.w_0"] == ("mlp", "embed")
    # ffn pair likewise
    assert classes["enc0_ffn_fc1.w_0"] == ("embed", "mlp")
    assert classes["enc0_ffn_fc2.w_0"] == ("mlp", "embed")
    # layer_norm scales/shifts
    norm = [n for n, c in classes.items() if c == ("norm",)]
    assert len(norm) >= 8            # 2 per post_process x many sites


def test_optimizer_slots_inherit_param_class():
    loss = _build_transformer()
    del loss
    slots = sl.optimizer_slot_params(fluid.default_main_program())
    moments = {s: p for s, p in slots.items() if "_moment" in s}
    assert moments, "no adam moment slots found"
    for s, p in moments.items():
        assert s.startswith(p)       # moment var carries the param prefix
    assert any(p == "src_word_emb" for p in moments.values())


def test_resolve_degrades_gracefully():
    _build_transformer()
    program = fluid.default_main_program()
    lay = SpecLayout()
    # no tp axis and fsdp=2: tp entries drop, fsdp survives
    mesh = make_mesh((2, 2), ("dp", "fsdp"))
    specs = lay.resolve(program, mesh, [("src_word_emb", (64, 16)),
                                        ("enc0_attn_q.w_0", (16, 16))])
    assert specs["src_word_emb"] == P("fsdp")
    assert specs["enc0_attn_q.w_0"] == P("fsdp")
    # full (dp, fsdp, tp) mesh
    mesh3 = make_mesh((1, 2, 2), ("dp", "fsdp", "tp"))
    specs3 = lay.resolve(program, mesh3, [("src_word_emb", (64, 16)),
                                          ("enc0_attn_q.w_0", (16, 16)),
                                          ("enc0_attn_o.w_0", (16, 16))])
    assert specs3["src_word_emb"] == P(("fsdp", "tp"))
    assert specs3["enc0_attn_q.w_0"] == P("fsdp", "tp")
    assert specs3["enc0_attn_o.w_0"] == P("tp", "fsdp")
    # a dim the axis product does not divide sheds axes until it fits
    specs_bad = lay.resolve(program, mesh3, [("src_word_emb", (6, 16))])
    assert specs_bad["src_word_emb"] == P("fsdp")   # 6 % 2 == 0, % 4 != 0
    # vocab indivisible outright: dim 0 replicates, which frees fsdp
    # for the embed dim — the table still finds a 1/N layout
    specs_rep = lay.resolve(program, mesh3, [("src_word_emb", (7, 16))])
    assert specs_rep["src_word_emb"] == P(None, "fsdp")
    # scalar slots replicate; unclassified tensors ZeRO-shard dim 0
    specs_misc = lay.resolve(program, mesh3, [("learning_rate_0", (1,)),
                                              ("some_counter", (8, 3))])
    assert specs_misc["learning_rate_0"] == P()
    assert specs_misc["some_counter"] == P("fsdp")


def test_spec_layout_value_equality():
    """Two default tables are one policy: equality/hash are by value so
    separate executors with sharding_rules=True share one process-global
    trace-cache entry instead of recompiling per object."""
    assert SpecLayout() == SpecLayout()
    assert hash(SpecLayout()) == hash(SpecLayout())
    assert SpecLayout() != SpecLayout(fsdp_axis="dp")


def test_rules_do_not_shadow_kreduce_on_pure_dp_mesh():
    """sharding_rules on a mesh with no populated fsdp/tp axis resolves
    everything to replicate — that must fall THROUGH to the kReduce
    tier (ZeRO dim-0 over dp), not silently un-shard the state."""
    _build_mlp()
    loss_var = None
    for op in fluid.default_main_program().global_block().ops:
        if op.type == "mean":
            loss_var = op.outputs["Out"][0]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    bs = fluid.BuildStrategy()
    bs.sharding_rules = True
    bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
    pe = fluid.ParallelExecutor(loss_name=loss_var, mesh=make_mesh((8,)),
                                build_strategy=bs)
    x = np.random.RandomState(0).rand(8, 16).astype("float32")
    y = np.zeros((8, 1), "int64")
    pe.run(feed={"x": x, "label": y}, fetch_list=[loss_var])
    w = fluid.global_scope().var("fc_0.w_0")     # [16, 32]: 16 % 8 == 0
    assert isinstance(w, jax.Array) and w.sharding.spec == P("dp")


def test_axis_size_one_drops_out():
    _build_transformer()
    program = fluid.default_main_program()
    mesh = make_mesh((2, 1, 1), ("dp", "fsdp", "tp"))
    specs = SpecLayout().resolve(program, mesh,
                                 [("src_word_emb", (64, 16))])
    assert specs["src_word_emb"] == P()   # both axes size 1 -> replicated


# ---------------------------------------------------------------------------
# acceptance: fsdp transformer — loss parity + per-device HBM ~ 1/N
# ---------------------------------------------------------------------------

def test_fsdp_transformer_loss_parity_and_state_sharding():
    """The ISSUE 7 acceptance: the real transformer trains through
    ParallelExecutor with fsdp-sharded params AND optimizer state under
    sharding_rules, with the loss trajectory matching the single-device
    run (GSPMD only changes layout), and the sharded state visible in
    the scope's array shardings."""
    batches = _batches()
    loss = _build_transformer()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe2 = fluid.Executor(fluid.CPUPlace())
    single = [float(np.asarray(exe2.run(feed=b, fetch_list=[loss])[0])
                    .ravel()[0]) for b in batches]

    mesh = make_mesh((1, 2, 2), ("dp", "fsdp", "tp"))
    bs = fluid.BuildStrategy()
    bs.sharding_rules = True
    with fluid.scope_guard(fluid.Scope()):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                    build_strategy=bs)
        par = [float(np.asarray(pe.run(feed=b, fetch_list=[loss])[0])
                     .ravel()[0]) for b in batches]
        scope = fluid.global_scope()
        emb = scope.var("src_word_emb")
        assert isinstance(emb, jax.Array)
        assert emb.sharding.spec == P(("fsdp", "tp"))
        qkv = scope.var("enc0_attn_q.w_0")
        assert qkv.sharding.spec == P("fsdp", "tp")
        # optimizer slot state inherits the param's spec (ZeRO)
        moments = [n for n in
                   sl.optimizer_slot_params(
                       fluid.default_main_program())
                   if "src_word_emb_moment1" in n]
        assert moments
        mom = scope.var(moments[0])
        assert mom.sharding.spec == P(("fsdp", "tp"))
    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-4)
    assert par[-1] < par[0]


def test_fsdp_per_device_hbm_drops_one_over_n():
    """The program-profile registry's compiled-module memory analysis is
    per-device under SPMD: with the full state fsdp-sharded 4 ways the
    per-device argument bytes must drop to ~1/N of the replicated run
    for the state's share (scalar counters stay replicated, hence the
    tolerance band), and estimated peak HBM must drop too."""
    monitor.enable()
    b = _batches(steps=1)[0]
    loss = _build_transformer()
    fp = compile_cache.program_fingerprint(fluid.default_main_program())

    breakdown = {}
    for label, shape, axes, rules in [
            ("replicated", (4,), ("dp",), None),
            ("fsdp", (1, 4), ("dp", "fsdp"), True)]:
        mesh = make_mesh(shape, axes)
        bstrat = fluid.BuildStrategy()
        if rules:
            bstrat.sharding_rules = True
        with fluid.scope_guard(fluid.Scope()):
            fluid.Executor(fluid.CPUPlace()).run(
                fluid.default_startup_program())
            pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                        build_strategy=bstrat)
            pe.run(feed=b, fetch_list=[loss])
            prof = program_profile.get(fp)
            assert prof is not None, "capture did not run (%s)" % label
            breakdown[label] = prof.breakdown()

    with fluid.scope_guard(fluid.Scope()):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        state = _persistable_state(fluid.global_scope(),
                                   fluid.default_main_program())
        state_bytes = sum(np.asarray(v).nbytes for v in state.values())

    rep, fs = breakdown["replicated"], breakdown["fsdp"]
    # replicated run holds the full state per device
    assert rep["argument_bytes"] >= state_bytes
    # the fsdp run's per-device state share is ~1/4 (+ replicated
    # scalars): measured 26.2% at these shapes, assert < 35%
    fsdp_state = fs["argument_bytes"] - (rep["argument_bytes"]
                                         - state_bytes)
    assert fsdp_state / state_bytes < 0.35, (
        "fsdp per-device state share %.1f%% — not ~1/4"
        % (100 * fsdp_state / state_bytes))
    assert fsdp_state / state_bytes > 0.20          # sanity: not zero
    assert fs["peak_hbm_bytes"] < rep["peak_hbm_bytes"]


@pytest.mark.slow   # ~24s of transformer compiles; the precedence chain
# is also covered in tier-1 by test_rules_do_not_shadow_kreduce_on_pure_
# dp_mesh (rules->reduce tier) and test_parallel_tensor_parallel_policy
# (hook alone)
def test_param_sharding_fn_overrides_rules():
    """Precedence: the imperative hook wins per-param over the table."""
    b = _batches(steps=1)[0]
    loss = _build_transformer()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    mesh = make_mesh((1, 4), ("dp", "fsdp"))
    bs = fluid.BuildStrategy()
    bs.sharding_rules = True
    bs.param_sharding_fn = (
        lambda name, shape: P() if name == "src_word_emb" else None)
    with fluid.scope_guard(fluid.Scope()):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                    build_strategy=bs)
        pe.run(feed=b, fetch_list=[loss])
        scope = fluid.global_scope()
        assert scope.var("src_word_emb").sharding.spec == P()     # hook
        assert scope.var("enc0_attn_q.w_0").sharding.spec == P("fsdp")


# ---------------------------------------------------------------------------
# per-device HBM reporting (satellite): gauges -> JSONL -> report columns
# ---------------------------------------------------------------------------

class _FakeDev:
    def __init__(self, i, in_use, limit=1 << 30):
        self.platform = "tpu"
        self.id = i
        self._ms = {"bytes_in_use": in_use, "bytes_limit": limit}

    def memory_stats(self):
        return dict(self._ms)


def test_device_gauges_emit_stats_and_report_columns(tmp_path):
    """sample_device_gauges publishes per-device bytes_in_use(+peak)
    gauges and a decimated ``device_stats`` JSONL event; the
    program_report CLI folds those into the per-device peak-HBM table
    with the min/max summary the 1/N claim is read from."""
    monitor.enable(log_dir=str(tmp_path))
    devs = [_FakeDev(0, 100), _FakeDev(1, 400)]
    monitor.sample_device_gauges(devs)
    devs[1]._ms["bytes_in_use"] = 900          # peak moves up
    for _ in range(10):                        # cross the sample cadence
        monitor.sample_device_gauges(devs)
    reg = monitor.registry()
    assert reg.gauge("device/tpu1/bytes_in_use_peak").value == 900
    assert reg.gauge("device/tpu0/bytes_in_use_peak").value == 100
    monitor.disable()

    import sys
    sys.path.insert(0, __import__("os").path.join(
        __import__("os").path.dirname(__import__("os").path.dirname(
            __import__("os").path.abspath(__file__))), "tools"))
    import program_report
    records = program_report.load_records(str(tmp_path))
    devices = program_report.devices_from_records(records)
    assert devices["tpu0"]["bytes_in_use_peak"] == 100
    assert devices["tpu1"]["bytes_in_use_peak"] == 900
    table = program_report.render_device_table(devices)
    assert "min 100 B / max 900 B" in table


# ---------------------------------------------------------------------------
# cross-topology TrainState round trip (satellite)
# ---------------------------------------------------------------------------

def _train_mlp_steps(runner, steps=2):
    losses = []
    for i in range(steps):
        x = np.random.RandomState(i).rand(8, 16).astype("float32")
        y = (x[:, :4].argmax(1)).astype("int64").reshape(-1, 1)
        losses.append(float(np.asarray(
            runner({"x": x, "label": y})).ravel()[0]))
    return losses


def _build_mlp(seed=7):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    x = fluid.layers.data("x", shape=[16])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=32, act="relu")
    pred = fluid.layers.fc(h, size=4, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return loss


def test_train_state_fsdp_save_restores_single_device(tmp_path):
    """Save from a (dp=2, fsdp=2) mesh (sharded arrays gather to full
    host arrays in the artifact), restore single-device: params must be
    BIT-identical to the mesh state."""
    loss = _build_mlp()
    mesh = make_mesh((2, 2), ("dp", "fsdp"))
    bs = fluid.BuildStrategy()
    bs.sharding_rules = True
    mesh_scope = fluid.Scope()
    with fluid.scope_guard(mesh_scope):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                    build_strategy=bs)
        _train_mlp_steps(
            lambda f: pe.run(feed=f, fetch_list=[loss])[0])
        # state is mesh-sharded at this point
        w = mesh_scope.var("fc_0.w_0")
        assert isinstance(w, jax.Array) and w.sharding.spec == P("fsdp")
        ts = capture_train_state(2, scope=mesh_scope, executors=pe)
        save_train_state(str(tmp_path / "ck"), ts)
        full = {n: np.asarray(v) for n, v in ts.arrays.items()}

    # restore into a fresh single-device world
    solo = fluid.Scope()
    with fluid.scope_guard(solo):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        loaded = load_train_state(str(tmp_path / "ck"))
        exe = fluid.Executor(fluid.CPUPlace())
        apply_train_state(loaded, scope=solo, executors=exe)
        for n, v in full.items():
            np.testing.assert_array_equal(np.asarray(solo.var(n)), v,
                                          err_msg=n)


def test_train_state_single_device_save_restores_onto_mesh(tmp_path):
    """The other direction: train single-device, save, restore onto a
    (dp=2, fsdp=2) mesh with PE.state_shardings() — arrays land sharded
    per the rules, values bit-identical, and training continues."""
    loss = _build_mlp()
    solo = fluid.Scope()
    with fluid.scope_guard(solo):
        exe0 = fluid.Executor(fluid.CPUPlace())
        exe0.run(fluid.default_startup_program())
        exe = fluid.Executor(fluid.CPUPlace())
        _train_mlp_steps(
            lambda f: exe.run(feed=f, fetch_list=[loss])[0])
        ts = capture_train_state(2, scope=solo, executors=exe)
        save_train_state(str(tmp_path / "ck"), ts)
        full = {n: np.asarray(v) for n, v in ts.arrays.items()}

    mesh = make_mesh((2, 2), ("dp", "fsdp"))
    bs = fluid.BuildStrategy()
    bs.sharding_rules = True
    mesh_scope = fluid.Scope()
    with fluid.scope_guard(mesh_scope):
        fluid.Executor(fluid.CPUPlace()).run(
            fluid.default_startup_program())
        pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                    build_strategy=bs)
        loaded = load_train_state(str(tmp_path / "ck"))
        apply_train_state(loaded, scope=mesh_scope, executors=pe,
                          shardings=pe.state_shardings())
        w = mesh_scope.var("fc_0.w_0")
        assert isinstance(w, jax.Array) and w.sharding.spec == P("fsdp")
        for n, v in full.items():
            np.testing.assert_array_equal(np.asarray(mesh_scope.var(n)),
                                          v, err_msg=n)
        out = pe.run(feed={
            "x": np.random.RandomState(9).rand(8, 16).astype("float32"),
            "label": np.zeros((8, 1), "int64")}, fetch_list=[loss])
        assert np.isfinite(np.asarray(out[0])).all()
