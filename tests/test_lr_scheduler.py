"""LR schedule tests (reference test_learning_rate_scheduler.py pattern:
run N steps, compare the in-graph LR against the python formula)."""

import math

import numpy as np

import paddle_tpu as fluid


def _run_schedule(lr_var, steps=8):
    # LR vars live in the main program; a dummy op keeps the program
    # non-empty even though the schedule itself already adds ops
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    vals = []
    for _ in range(steps):
        (v,) = exe.run(feed={}, fetch_list=[lr_var])
        vals.append(float(np.asarray(v).ravel()[0]))
    return vals


def test_exponential_decay():
    lr = fluid.layers.exponential_decay(0.1, decay_steps=4, decay_rate=0.5)
    got = _run_schedule(lr)
    want = [0.1 * 0.5 ** (s / 4.0) for s in range(8)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_exponential_decay_staircase():
    lr = fluid.layers.exponential_decay(0.1, 4, 0.5, staircase=True)
    got = _run_schedule(lr)
    want = [0.1 * 0.5 ** (s // 4) for s in range(8)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_natural_exp_decay():
    lr = fluid.layers.natural_exp_decay(0.1, 4, 0.5)
    got = _run_schedule(lr)
    want = [0.1 * math.exp(-0.5 * s / 4.0) for s in range(8)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_inverse_time_decay():
    lr = fluid.layers.inverse_time_decay(0.1, 4, 0.5)
    got = _run_schedule(lr)
    want = [0.1 / (1 + 0.5 * s / 4.0) for s in range(8)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_polynomial_decay():
    lr = fluid.layers.polynomial_decay(0.1, decay_steps=5,
                                       end_learning_rate=0.01, power=2.0)
    got = _run_schedule(lr)
    want = [
        (0.1 - 0.01) * (1 - min(s, 5) / 5.0) ** 2 + 0.01 for s in range(8)
    ]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_piecewise_decay():
    lr = fluid.layers.piecewise_decay([3, 6], [0.1, 0.01, 0.001])
    got = _run_schedule(lr, steps=9)
    want = [0.1] * 3 + [0.01] * 3 + [0.001] * 3
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_noam_decay():
    lr = fluid.layers.noam_decay(d_model=64, warmup_steps=4)
    got = _run_schedule(lr)
    want = [
        64 ** -0.5 * min((s + 1) ** -0.5, (s + 1) * 4 ** -1.5)
        for s in range(8)
    ]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_optimizer_with_decayed_lr_trains():
    img = fluid.layers.data("img", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    pred = fluid.layers.fc(img, size=4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    lr = fluid.layers.exponential_decay(0.1, decay_steps=10, decay_rate=0.5)
    fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    x = rng.rand(16, 8).astype("float32")
    y = rng.randint(0, 4, (16, 1)).astype("int64")
    losses = []
    for _ in range(10):
        (lv,) = exe.run(feed={"img": x, "label": y}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0]
