"""OpTest coverage for the round-3 op-gap closure: crop, pad2d,
pad_constant_like, random_crop, unstack, lod_reset, is_empty,
modified_huber_loss, conv3d_transpose, depthwise_conv2d_transpose,
max_pool3d_with_index, positive_negative_pair, average_accumulates,
uniform/gaussian_random_batch_size_like, print, fill.

Reference oracles follow the corresponding ``paddle/fluid/operators/*.cc``
kernels (cited per test).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest


# -- crop (crop_op.cc) ------------------------------------------------------

class TestCropAttr(OpTest):
    op_type = "crop"

    def setup(self):
        x = np.random.RandomState(0).rand(4, 5, 6).astype("float32")
        offs, shp = [1, 0, 2], [2, 4, 3]
        self.inputs = {"X": x}
        self.attrs = {"offsets": offs, "shape": shp}
        self.outputs = {"Out": x[1:3, 0:4, 2:5]}

    def test_forward(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["crop__X"], "crop__Out")


def test_crop_runtime_offsets():
    """crop with the runtime Offsets input (crop_op.cc case 1)."""
    x = np.arange(24, dtype="float32").reshape(4, 6)
    offs = np.array([1, 2], dtype="int32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data("x", shape=[4, 6], append_batch_size=False)
        ov = fluid.layers.data("offs", shape=[2], dtype="int32",
                               append_batch_size=False)
        out = fluid.layers.crop(xv, shape=[2, 3], offsets=ov)
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(prog, feed={"x": x, "offs": offs},
                     fetch_list=[out.name])
    np.testing.assert_allclose(np.asarray(got), x[1:3, 2:5])


def test_crop_batch_dim_minus_one():
    """crop with a -1 (batch) dim takes the rest of the dim from the
    offset — the common layers.data(-1 batch) pattern."""
    x = np.random.rand(5, 6, 6).astype("float32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data("x", shape=[6, 6])
        out = fluid.layers.crop(xv, shape=[-1, 4, 4], offsets=[0, 1, 1])
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(prog, feed={"x": x}, fetch_list=[out.name])
    np.testing.assert_allclose(np.asarray(got), x[:, 1:5, 1:5])


def test_crop_shape_from_y():
    x = np.random.rand(5, 5).astype("float32")
    y = np.zeros((3, 2), "float32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data("x", shape=[5, 5], append_batch_size=False)
        yv = fluid.layers.data("y", shape=[3, 2], append_batch_size=False)
        out = fluid.layers.crop(xv, shape=yv)
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(prog, feed={"x": x, "y": y}, fetch_list=[out.name])
    np.testing.assert_allclose(np.asarray(got), x[:3, :2])


# -- pad2d (pad2d_op.cc) ----------------------------------------------------

class TestPad2dConstant(OpTest):
    op_type = "pad2d"

    def setup(self, mode="constant", fmt="NCHW"):
        x = np.random.RandomState(1).rand(2, 3, 4, 5).astype("float32")
        p = [1, 2, 0, 3]  # top, bottom, left, right
        np_mode = {"constant": "constant", "reflect": "reflect",
                   "edge": "edge"}[mode]
        pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])] if fmt == "NCHW" \
            else [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
        kw = {"constant_values": 0.25} if mode == "constant" else {}
        self.inputs = {"X": x}
        self.attrs = {"paddings": p, "mode": mode, "pad_value": 0.25,
                      "data_format": fmt}
        self.outputs = {"Out": np.pad(x, pads, mode=np_mode, **kw)}

    @pytest.mark.parametrize("mode", ["constant", "reflect", "edge"])
    def test_forward(self, mode):
        self.setup(mode)
        self.check_output()

    def test_nhwc(self):
        self.setup("constant", fmt="NHWC")
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["pad2d__X"], "pad2d__Out")


# -- pad_constant_like (pad_constant_like_op.cc) ----------------------------

class TestPadConstantLike(OpTest):
    op_type = "pad_constant_like"

    def setup(self):
        x = np.zeros((4, 3, 5), "float32")
        y = np.random.RandomState(2).rand(2, 3, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"pad_value": 1.5}
        self.outputs = {
            "Out": np.pad(y, [(0, 2), (0, 0), (0, 1)],
                          constant_values=1.5)}

    def test_forward(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["pad_constant_like__Y"], "pad_constant_like__Out",
                        no_grad_set={"pad_constant_like__X"})


# -- unstack (unstack_op.h) -------------------------------------------------

class TestUnstack(OpTest):
    op_type = "unstack"

    def setup(self, axis=1):
        x = np.random.RandomState(3).rand(2, 3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": axis, "num": x.shape[axis]}
        self.outputs = {"Y": [
            ("y%d" % i, np.squeeze(a, axis))
            for i, a in enumerate(np.split(x, x.shape[axis], axis))]}

    @pytest.mark.parametrize("axis", [0, 1, -1])
    def test_forward(self, axis):
        self.setup(axis)
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["unstack__X"], "y1")


# -- is_empty (is_empty_op.cc) ----------------------------------------------

def test_is_empty():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=[3, 2], append_batch_size=False)
        e = fluid.layers.data("e", shape=[0, 2], append_batch_size=False)
        c1 = fluid.layers.is_empty(x)
        c2 = fluid.layers.is_empty(e)
    exe = fluid.Executor(fluid.CPUPlace())
    r1, r2 = exe.run(prog, feed={"x": np.ones((3, 2), "float32"),
                                 "e": np.ones((0, 2), "float32")},
                     fetch_list=[c1.name, c2.name])
    assert not bool(np.asarray(r1)[0])
    assert bool(np.asarray(r2)[0])


# -- fill (fill_op.cc) ------------------------------------------------------

def test_fill_op():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        block = prog.global_block()
        block.append_op(type="fill", outputs={"Out": ["filled"]},
                        attrs={"shape": [2, 3], "dtype": "float32",
                               "value": [1, 2, 3, 4, 5, 6]})
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(prog, feed={}, fetch_list=["filled"])
    np.testing.assert_allclose(
        np.asarray(got), np.arange(1, 7, dtype="float32").reshape(2, 3))


# -- modified_huber_loss (modified_huber_loss_op.h) -------------------------

def _mhl_oracle(x, y):
    inter = x * (2 * y - 1)
    return np.where(inter < -1, -4 * inter,
                    np.where(inter < 1, (1 - inter) ** 2, 0.0))


class TestModifiedHuberLoss(OpTest):
    op_type = "modified_huber_loss"

    def setup(self):
        rs = np.random.RandomState(4)
        # keep x*y' away from the +-1 kinks so numeric grads are clean
        x = rs.uniform(-2.0, 2.0, (8, 1)).astype("float32")
        x[np.abs(np.abs(x) - 1.0) < 0.15] = 0.5
        y = (rs.rand(8, 1) > 0.5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {
            "IntermediateVal": x * (2 * y - 1),
            "Out": _mhl_oracle(x, y).astype("float32")}

    def test_forward(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["modified_huber_loss__X"],
                        "modified_huber_loss__Out")


# -- conv transpose 3d / depthwise (conv_transpose_op.cc:303,335) -----------

def _convt_oracle(x, w, strides, pads, dils):
    """Scatter-style transposed-conv oracle, any spatial rank."""
    n, cin = x.shape[:2]
    cout = w.shape[1]
    nd = x.ndim - 2
    out_sp = [(x.shape[2 + i] - 1) * strides[i] - 2 * pads[i]
              + dils[i] * (w.shape[2 + i] - 1) + 1 for i in range(nd)]
    full = [out_sp[i] + 2 * pads[i] for i in range(nd)]
    out = np.zeros([n, cout] + full, dtype=np.float64)
    for b in range(n):
        for ci in range(cin):
            for co in range(cout):
                for in_idx in np.ndindex(*x.shape[2:]):
                    for k_idx in np.ndindex(*w.shape[2:]):
                        pos = tuple(in_idx[i] * strides[i]
                                    + dils[i] * k_idx[i]
                                    for i in range(nd))
                        out[(b, co) + pos] += \
                            x[(b, ci) + in_idx] * w[(ci, co) + k_idx]
    slc = tuple(slice(pads[i], pads[i] + out_sp[i]) for i in range(nd))
    return out[(slice(None), slice(None)) + slc].astype("float32")


class TestConv3dTranspose(OpTest):
    op_type = "conv3d_transpose"

    def setup(self):
        rs = np.random.RandomState(5)
        x = rs.rand(1, 2, 3, 3, 2).astype("float32")
        w = rs.rand(2, 2, 2, 2, 2).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [2, 1, 1], "paddings": [1, 0, 1],
                      "dilations": [1, 1, 1]}
        self.outputs = {"Output": _convt_oracle(
            x, w, [2, 1, 1], [1, 0, 1], [1, 1, 1])}

    def test_forward(self):
        self.setup()
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.setup()
        self.check_grad(["conv3d_transpose__Input",
                         "conv3d_transpose__Filter"],
                        "conv3d_transpose__Output",
                        max_relative_error=0.02, delta=1e-2)


class TestDepthwiseConv2dTranspose(OpTest):
    op_type = "depthwise_conv2d_transpose"

    def setup(self):
        rs = np.random.RandomState(6)
        c = 3
        x = rs.rand(2, c, 4, 4).astype("float32")
        w = rs.rand(c, 1, 3, 3).astype("float32")
        # groups == channels: each channel transposed independently
        per = [_convt_oracle(x[:, i:i + 1], w[i:i + 1], [2, 2], [1, 1],
                             [1, 1]) for i in range(c)]
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [2, 2], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": c}
        self.outputs = {"Output": np.concatenate(per, axis=1)}

    def test_forward(self):
        self.setup()
        self.check_output(atol=1e-4)


# -- max_pool3d_with_index (pool_with_index_op.cc) --------------------------

class TestMaxPool3dWithIndex(OpTest):
    op_type = "max_pool3d_with_index"

    def setup(self):
        rs = np.random.RandomState(7)
        # well-separated values so delta-perturbation never flips an argmax
        x = (rs.permutation(2 * 2 * 4 * 4 * 4).astype("float32") * 0.1) \
            .reshape(2, 2, 4, 4, 4)
        ks, st = [2, 2, 2], [2, 2, 2]
        n, c, d, h, w = x.shape
        od, oh, ow = d // 2, h // 2, w // 2
        out = np.zeros((n, c, od, oh, ow), "float32")
        mask = np.zeros((n, c, od, oh, ow), "int32")
        for idx in np.ndindex(n, c, od, oh, ow):
            b, ch, i, j, k = idx
            win = x[b, ch, 2 * i:2 * i + 2, 2 * j:2 * j + 2,
                    2 * k:2 * k + 2]
            out[idx] = win.max()
            loc = np.unravel_index(win.argmax(), win.shape)
            mask[idx] = ((2 * i + loc[0]) * h + 2 * j + loc[1]) * w \
                + 2 * k + loc[2]
        self.inputs = {"X": x}
        self.attrs = {"ksize": ks, "strides": st, "paddings": [0, 0, 0]}
        self.outputs = {"Out": out, "Mask": mask}

    def test_forward(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["max_pool3d_with_index__X"],
                        "max_pool3d_with_index__Out",
                        max_relative_error=0.02, delta=1e-3)


# -- positive_negative_pair (positive_negative_pair_op.h) -------------------

def _pnp_oracle(score, label, query, weight=None, col=0):
    s = score[:, col]
    lbl, q = label.reshape(-1), query.reshape(-1)
    w = weight.reshape(-1) if weight is not None else np.ones_like(s)
    pos = neg = neu = 0.0
    for i in range(len(s)):
        for j in range(i + 1, len(s)):
            if q[i] != q[j] or lbl[i] == lbl[j]:
                continue
            pw = 0.5 * (w[i] + w[j])
            if s[i] == s[j]:
                neu += pw
            if (s[i] - s[j]) * (lbl[i] - lbl[j]) > 0:
                pos += pw
            else:
                neg += pw
    return pos, neg, neu


class TestPositiveNegativePair(OpTest):
    op_type = "positive_negative_pair"

    def setup(self, with_weight=False):
        rs = np.random.RandomState(8)
        n = 12
        score = rs.rand(n, 3).astype("float32")
        label = rs.randint(0, 3, (n, 1)).astype("float32")
        query = rs.randint(0, 3, (n, 1)).astype("int32")
        weight = rs.rand(n, 1).astype("float32") if with_weight else None
        pos, neg, neu = _pnp_oracle(score, label, query, weight, col=1)
        self.inputs = {"Score": score, "Label": label, "QueryID": query}
        if with_weight:
            self.inputs["Weight"] = weight
        self.attrs = {"column": 1}
        self.outputs = {"PositivePair": np.array([pos], "float32"),
                        "NegativePair": np.array([neg], "float32"),
                        "NeutralPair": np.array([neu], "float32")}

    @pytest.mark.parametrize("with_weight", [False, True])
    def test_forward(self, with_weight):
        self.setup(with_weight)
        self.check_output()

    def test_tied_scores(self):
        """A tied pair is neutral AND negative — the reference kernel's
        if-without-elif falls through the ternary into neg
        (positive_negative_pair_op.h)."""
        self.op_type = "positive_negative_pair"
        self.inputs = {
            "Score": np.array([[0.5], [0.5]], "float32"),
            "Label": np.array([[1.0], [0.0]], "float32"),
            "QueryID": np.array([[7], [7]], "int32")}
        self.attrs = {"column": 0}
        self.outputs = {"PositivePair": np.array([0.0], "float32"),
                        "NegativePair": np.array([1.0], "float32"),
                        "NeutralPair": np.array([1.0], "float32")}
        self.check_output()

    def test_accumulate(self):
        self.setup()
        self.inputs["AccumulatePositivePair"] = np.array([10.0], "float32")
        self.inputs["AccumulateNegativePair"] = np.array([20.0], "float32")
        self.inputs["AccumulateNeutralPair"] = np.array([30.0], "float32")
        self.outputs = {
            "PositivePair": self.outputs["PositivePair"] + 10.0,
            "NegativePair": self.outputs["NegativePair"] + 20.0,
            "NeutralPair": self.outputs["NeutralPair"] + 30.0}
        self.check_output()


# -- average_accumulates (average_accumulates_op.h) + ModelAverage ----------

def test_average_accumulates_window_restart():
    """Window restarts once num_accumulates reaches
    min(max_average_window, num_updates*average_window) and >= min_w."""
    param = np.full((3,), 2.0, "float32")
    s1 = np.ones((3,), "float32")
    s2 = np.zeros((3,), "float32")
    s3 = np.zeros((3,), "float32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        block = prog.global_block()
        names = {}
        for nm, arr in [("param", param), ("s1", s1), ("s2", s2),
                        ("s3", s3)]:
            block.create_var(name=nm, shape=arr.shape, dtype=arr.dtype,
                             is_data=True)
            names[nm] = arr
        for nm in ("na", "ona", "nu"):
            block.create_var(name=nm, shape=(1,), dtype="int64",
                             is_data=True)
        block.append_op(
            type="average_accumulates",
            inputs={"param": ["param"], "in_sum_1": ["s1"],
                    "in_sum_2": ["s2"], "in_sum_3": ["s3"],
                    "in_num_accumulates": ["na"],
                    "in_old_num_accumulates": ["ona"],
                    "in_num_updates": ["nu"]},
            outputs={"out_sum_1": ["o1"], "out_sum_2": ["o2"],
                     "out_sum_3": ["o3"], "out_num_accumulates": ["ona2"],
                     "out_old_num_accumulates": ["oona"],
                     "out_num_updates": ["onu"]},
            attrs={"average_window": 1.0, "min_average_window": 2,
                   "max_average_window": 2})
    exe = fluid.Executor(fluid.CPUPlace())
    feed = dict(names, na=np.array([1], "int64"),
                ona=np.array([0], "int64"), nu=np.array([5], "int64"))
    o1, o2, o3, na2, oona, onu = exe.run(
        prog, feed=feed,
        fetch_list=["o1", "o2", "o3", "ona2", "oona", "onu"])
    # num_acc 1->2 hits the window (min_w=2): restart with s3 = s1+s2
    np.testing.assert_allclose(np.asarray(o3), s1 + s2)
    np.testing.assert_allclose(np.asarray(o1), 0.0)
    np.testing.assert_allclose(np.asarray(o2), 0.0)
    assert int(np.asarray(na2)[0]) == 0
    assert int(np.asarray(oona)[0]) == 2
    assert int(np.asarray(onu)[0]) == 6


def test_model_average_apply():
    """ModelAverage accumulates via average_accumulates and apply() swaps
    the trailing mean in (reference optimizer.py:1209)."""
    import paddle_tpu.optimizer as opt

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=1, bias_attr=False)
        loss = fluid.layers.mean(y)
        sgd = opt.SGD(learning_rate=0.1)
        sgd.minimize(loss)
        ma = opt.ModelAverage(average_window_rate=1.0,
                              min_average_window=10000,
                              max_average_window=10000)
        ma._ensure_accumulators(prog)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rs = np.random.RandomState(9)
    params = []
    pname = prog.global_block().all_parameters()[0].name
    from paddle_tpu.scope import global_scope
    for _ in range(4):
        exe.run(prog, feed={"x": rs.rand(2, 4).astype("float32")},
                fetch_list=[loss.name])
        params.append(np.asarray(global_scope().var(pname)).copy())
    expect = np.mean(params, axis=0)
    with ma.apply(exe):
        np.testing.assert_allclose(
            np.asarray(global_scope().var(pname)), expect,
            rtol=1e-5, atol=1e-6)
    # restored after the context
    np.testing.assert_allclose(
        np.asarray(global_scope().var(pname)), params[-1])


# -- batch_size_like randoms ------------------------------------------------

@pytest.mark.parametrize("op", ["uniform_random_batch_size_like",
                                "gaussian_random_batch_size_like"])
def test_random_batch_size_like(op):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        ref = fluid.layers.data("ref", shape=[7, 3],
                                append_batch_size=False)
        layer = getattr(fluid.layers, op)
        out = layer(ref, shape=[-1, 5])
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(prog, feed={"ref": np.zeros((7, 3), "float32")},
                     fetch_list=[out.name])
    got = np.asarray(got)
    assert got.shape == (7, 5)
    if op.startswith("uniform"):
        assert got.min() >= -1.0 and got.max() <= 1.0
    assert got.std() > 0.05  # actually random


# -- random_crop ------------------------------------------------------------

def test_random_crop():
    rs = np.random.RandomState(10)
    x = rs.rand(6, 8, 8).astype("float32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data("x", shape=[6, 8, 8],
                               append_batch_size=False)
        out = fluid.layers.random_crop(xv, shape=[5, 5])
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(prog, feed={"x": x}, fetch_list=[out.name])
    got = np.asarray(got)
    assert got.shape == (6, 5, 5)
    # every cropped instance must be a contiguous window of its source
    for b in range(6):
        found = any(
            np.allclose(got[b], x[b, i:i + 5, j:j + 5])
            for i in range(4) for j in range(4))
        assert found, "instance %d is not a crop of its source" % b


# -- lod_reset --------------------------------------------------------------

def test_lod_reset_target_lod():
    x = np.random.rand(3, 6, 2).astype("float32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data("x", shape=[3, 6, 2],
                               append_batch_size=False)
        out = fluid.layers.lod_reset(xv, target_lod=[0, 2, 5, 6])
        from paddle_tpu.layers.sequence import sequence_length
        ln = sequence_length(out)
    exe = fluid.Executor(fluid.CPUPlace())
    got, lens = exe.run(prog, feed={"x": x},
                        fetch_list=[out.name, ln.name])
    np.testing.assert_allclose(np.asarray(got), x)
    np.testing.assert_array_equal(np.asarray(lens), [2, 3, 1])


def test_lod_reset_from_y():
    x = np.random.rand(2, 4).astype("float32")
    offsets = np.array([0, 3, 4], "int32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data("x", shape=[2, 4], append_batch_size=False)
        yv = fluid.layers.data("y", shape=[3], dtype="int32",
                               append_batch_size=False)
        out = fluid.layers.lod_reset(xv, y=yv)
        from paddle_tpu.layers.sequence import sequence_length
        ln = sequence_length(out)
    exe = fluid.Executor(fluid.CPUPlace())
    _, lens = exe.run(prog, feed={"x": x, "y": offsets},
                      fetch_list=[out.name, ln.name])
    np.testing.assert_array_equal(np.asarray(lens), [3, 1])


# -- print ------------------------------------------------------------------

def test_print_passthrough(capfd):
    x = np.arange(4, dtype="float32").reshape(2, 2)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data("x", shape=[2, 2], append_batch_size=False)
        xv.stop_gradient = False
        out = fluid.layers.Print(xv, message="dbg:")
        loss = fluid.layers.mean(out)
        fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    from paddle_tpu.framework import grad_var_name
    got, g = exe.run(prog, feed={"x": x},
                     fetch_list=[out.name, grad_var_name(xv.name)])
    np.testing.assert_allclose(np.asarray(got), x)
    np.testing.assert_allclose(np.asarray(g), np.full((2, 2), 0.25))
    captured = capfd.readouterr()
    assert "dbg:" in captured.out or "dbg:" in captured.err
