"""Ring attention (context parallelism) tests on the 8-device virtual
mesh: numerical parity with full attention, causal masking, gradients,
and composition with a dp axis."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.parallel import ring_attention
from paddle_tpu.parallel.mesh import make_mesh


def _full_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        t = q.shape[2]
        mask = np.tril(np.ones((t, t), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    rng = np.random.RandomState(0)
    b, h, t, d = 2, 3, 32, 8
    q = rng.randn(b, h, t, d).astype("float32")
    k = rng.randn(b, h, t, d).astype("float32")
    v = rng.randn(b, h, t, d).astype("float32")
    mesh = make_mesh((8,), ("sp",))
    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, axis="sp", causal=causal)
    want = _full_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), want, atol=2e-5)


def test_ring_attention_gradients_match_full():
    rng = np.random.RandomState(1)
    b, h, t, d = 1, 2, 16, 4
    q = jnp.asarray(rng.randn(b, h, t, d).astype("float32"))
    k = jnp.asarray(rng.randn(b, h, t, d).astype("float32"))
    v = jnp.asarray(rng.randn(b, h, t, d).astype("float32"))
    mesh = make_mesh((8,), ("sp",))

    def ring_loss(q_, k_, v_):
        return jnp.sum(ring_attention(q_, k_, v_, mesh, axis="sp",
                                      causal=True) ** 2)

    def full_loss(q_, k_, v_):
        s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) / np.sqrt(d)
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, v_) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=3e-4)


def test_ring_attention_with_dp_axis():
    """sp composes with dp: batch sharded over dp, time over sp."""
    rng = np.random.RandomState(2)
    b, h, t, d = 4, 2, 8, 4
    q = rng.randn(b, h, t, d).astype("float32")
    k = rng.randn(b, h, t, d).astype("float32")
    v = rng.randn(b, h, t, d).astype("float32")
    mesh = make_mesh((2, 4), ("dp", "sp"))
    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, axis="sp", batch_axis="dp")
    assert len(out.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(out),
                               _full_attention(q, k, v), atol=2e-5)


def test_ring_attention_bf16_accumulates_in_fp32():
    rng = np.random.RandomState(5)
    b, h, t, d = 1, 2, 32, 8
    q = rng.randn(b, h, t, d).astype("float32")
    k = rng.randn(b, h, t, d).astype("float32")
    v = rng.randn(b, h, t, d).astype("float32")
    mesh = make_mesh((8,), ("sp",))
    out = ring_attention(jnp.asarray(q, jnp.bfloat16),
                         jnp.asarray(k, jnp.bfloat16),
                         jnp.asarray(v, jnp.bfloat16), mesh)
    assert out.dtype == jnp.bfloat16
    want = _full_attention(q, k, v)
    # bf16 inputs, fp32 accumulation: error bounded by input precision
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), want, atol=0.05)


def test_ring_attention_rejects_unknown_axis():
    mesh = make_mesh((8,), ("dp",))
    with pytest.raises(ValueError, match="no axis"):
        ring_attention(jnp.zeros((1, 1, 8, 4)), jnp.zeros((1, 1, 8, 4)),
                       jnp.zeros((1, 1, 8, 4)), mesh, axis="sp")
    sp = make_mesh((8,), ("sp",))
    with pytest.raises(ValueError, match="must differ"):
        ring_attention(jnp.zeros((1, 1, 8, 4)), jnp.zeros((1, 1, 8, 4)),
                       jnp.zeros((1, 1, 8, 4)), sp, axis="sp",
                       batch_axis="sp")


def test_ring_attention_with_tp_sharded_heads():
    """sp composes with tp: heads sharded over tp inside the ring
    (ops/attention.py passes head_axis_name), batch over dp."""
    from paddle_tpu.ops.attention import _ring_attention

    rng = np.random.RandomState(7)
    b, h, t, d = 2, 4, 4, 4
    q = rng.randn(b, h, t, d).astype("float32")
    k = rng.randn(b, h, t, d).astype("float32")
    v = rng.randn(b, h, t, d).astype("float32")
    mesh = make_mesh((2, 2, 2), ("dp", "tp", "sp"))
    out = _ring_attention(mesh, jnp.asarray(q), jnp.asarray(k),
                          jnp.asarray(v), None, None, False, 0.0, None)
    assert len(out.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(out),
                               _full_attention(q, k, v), atol=2e-5)


def test_ring_attention_tp_heads_dropout_mask_parity():
    """The dropout hash must use GLOBAL head indices: a tp-sharded ring
    run reproduces the single-chip mask bit-for-bit."""
    from paddle_tpu.ops.attention import _ring_attention
    from paddle_tpu.ops.pallas.flash_attention import reference_attention

    rng = np.random.RandomState(8)
    b, h, t, d = 2, 4, 4, 4
    q = rng.randn(b, h, t, d).astype("float32")
    k = rng.randn(b, h, t, d).astype("float32")
    v = rng.randn(b, h, t, d).astype("float32")
    seed = jnp.asarray(12345, jnp.uint32)
    mesh = make_mesh((2, 2, 2), ("dp", "tp", "sp"))
    out = _ring_attention(mesh, jnp.asarray(q), jnp.asarray(k),
                          jnp.asarray(v), None, seed, False, 0.3, None)
    want = reference_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), None, seed, False, 0.3,
                               None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=2e-5)
