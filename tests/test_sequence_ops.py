"""Sequence/RNN op tests: numpy oracles over padded batches + lengths
(reference test_sequence_pool.py, test_lstm_op.py, test_gru_op.py,
test_sequence_conv.py, test_row_conv_op.py patterns translated to the
padded representation)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest


LENS = np.array([3, 5, 1, 4], dtype="int32")


def _seq(d=6, t=5, b=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(b, t, d).astype("float32")
    for i, ln in enumerate(LENS):
        x[i, ln:] = 0
    return x


class TestSequencePool(OpTest):
    op_type = "sequence_pool"

    def _run(self, ptype, oracle):
        x = _seq()
        self.inputs = {"X": x, "Length": [("len", LENS)]}
        self.attrs = {"pooltype": ptype}
        out = np.stack([oracle(x[i, :LENS[i]]) for i in range(len(LENS))])
        self.outputs = {"Out": out.astype("float32")}
        self.check_output()

    def test_average(self):
        self._run("AVERAGE", lambda s: s.mean(0))

    def test_sum(self):
        self._run("SUM", lambda s: s.sum(0))

    def test_sqrt(self):
        self._run("SQRT", lambda s: s.sum(0) / np.sqrt(len(s)))

    def test_max(self):
        self._run("MAX", lambda s: s.max(0))

    def test_last(self):
        self._run("LAST", lambda s: s[-1])

    def test_first(self):
        self._run("FIRST", lambda s: s[0])

    def test_grad_average(self):
        x = _seq(d=3, t=4)
        self.inputs = {"X": x, "Length": [("len", LENS)]}
        self.attrs = {"pooltype": "AVERAGE"}
        out = np.stack(
            [x[i, :LENS[i]].mean(0) for i in range(len(LENS))])
        self.outputs = {"Out": out.astype("float32")}
        self.check_grad(["sequence_pool__X"], "sequence_pool__Out",
                        no_grad_set={"len"}, max_relative_error=0.02)


class TestSequenceSoftmax(OpTest):
    op_type = "sequence_softmax"

    def test_output(self):
        x = _seq(d=1).squeeze(-1)  # [B, T]
        self.inputs = {"X": x, "Length": [("len", LENS)]}
        out = np.zeros_like(x)
        for i, ln in enumerate(LENS):
            e = np.exp(x[i, :ln] - x[i, :ln].max())
            out[i, :ln] = e / e.sum()
        self.outputs = {"Out": out}
        self.check_output()


class TestSequenceExpand(OpTest):
    op_type = "sequence_expand"

    def test_output(self):
        rng = np.random.RandomState(1)
        x = rng.rand(4, 6).astype("float32")
        y = _seq()
        self.inputs = {"X": x, "Y": y, "Length": [("len", LENS)]}
        out = np.zeros((4, 5, 6), "float32")
        for i, ln in enumerate(LENS):
            out[i, :ln] = x[i]
        self.outputs = {"Out": out}
        self.check_output()


class TestSequenceReverse(OpTest):
    op_type = "sequence_reverse"

    def test_output(self):
        x = _seq()
        self.inputs = {"X": x, "Length": [("len", LENS)]}
        out = x.copy()
        for i, ln in enumerate(LENS):
            out[i, :ln] = x[i, :ln][::-1]
        self.outputs = {"Out": out}
        self.check_output()


class TestSequenceMask(OpTest):
    op_type = "sequence_mask"

    def test_output(self):
        self.inputs = {"X": LENS}
        self.attrs = {"maxlen": 6, "out_dtype": "float32"}
        out = (np.arange(6)[None, :] < LENS[:, None]).astype("float32")
        self.outputs = {"Y": out}
        self.check_output()


class TestSequenceConcat(OpTest):
    op_type = "sequence_concat"

    def test_output(self):
        rng = np.random.RandomState(2)
        lens_a = np.array([3, 4, 1, 4], "int32")
        lens_b = np.array([2, 1, 4, 3], "int32")
        a = rng.rand(4, 4, 2).astype("float32")
        b = rng.rand(4, 5, 2).astype("float32")
        for i in range(4):
            a[i, lens_a[i]:] = 0
            b[i, lens_b[i]:] = 0
        self.inputs = {"X": [("a", a), ("b", b)],
                       "Length": [("la", lens_a), ("lb", lens_b)]}
        total = lens_a + lens_b
        t = 9
        out = np.zeros((4, t, 2), "float32")
        for i in range(4):
            out[i, :lens_a[i]] = a[i, :lens_a[i]]
            out[i, lens_a[i]:total[i]] = b[i, :lens_b[i]]
        self.outputs = {"Out": out, "OutLength": total.astype("int32")}
        self.check_output()


class TestSequenceErase(OpTest):
    op_type = "sequence_erase"

    def test_output(self):
        x = np.array([[1, 2, 3, 2, 1],
                      [2, 2, 2, 2, 2],
                      [5, 0, 0, 0, 0],
                      [1, 5, 2, 5, 0]], dtype="int64")
        lens = np.array([5, 5, 1, 4], "int32")
        self.inputs = {"X": x, "Length": [("len", lens)]}
        self.attrs = {"tokens": [2]}
        out = np.zeros_like(x)
        out_len = []
        for i, ln in enumerate(lens):
            kept = [v for v in x[i, :ln] if v != 2]
            out[i, :len(kept)] = kept
            out_len.append(len(kept))
        self.outputs = {"Out": out,
                        "OutLength": np.array(out_len, "int32")}
        self.check_output()


class TestSequenceConv(OpTest):
    op_type = "sequence_conv"

    def test_output(self):
        d, ctx, nf = 3, 3, 4
        x = _seq(d=d, t=5, seed=4)
        rng = np.random.RandomState(5)
        w = rng.rand(ctx * d, nf).astype("float32") - 0.5
        self.inputs = {"X": x, "Filter": w, "Length": [("len", LENS)]}
        self.attrs = {"contextLength": ctx, "contextStart": -1}
        out = np.zeros((4, 5, nf), "float32")
        for i, ln in enumerate(LENS):
            for t in range(ln):
                row = []
                for j in range(ctx):
                    p = t - 1 + j
                    row.append(x[i, p] if 0 <= p < ln else np.zeros(d))
                out[i, t] = np.concatenate(row) @ w
        self.outputs = {"Out": out}
        self.check_output(atol=1e-5)


class TestRowConv(OpTest):
    op_type = "row_conv"

    def test_output(self):
        d, k = 3, 2
        x = _seq(d=d, t=5, seed=6)
        rng = np.random.RandomState(7)
        w = rng.rand(k, d).astype("float32") - 0.5
        self.inputs = {"X": x, "Filter": w, "Length": [("len", LENS)]}
        out = np.zeros_like(x)
        for i, ln in enumerate(LENS):
            for t in range(ln):
                for j in range(k):
                    if t + j < ln:
                        out[i, t] += x[i, t + j] * w[j]
        self.outputs = {"Out": out}
        self.check_output(atol=1e-5)


def _np_lstm(x, w, b, lens, peep=True):
    """Oracle for the lstm op: gate order (c, i, f, o) per lstm_op.cc."""
    bt, t, h4 = x.shape
    h = h4 // 4
    gb = b[0, :4 * h]
    if peep:
        w_ic, w_fc, w_oc = (b[0, 4 * h:5 * h], b[0, 5 * h:6 * h],
                            b[0, 6 * h:7 * h])
    hs = np.zeros((bt, t, h), "float64")
    cs = np.zeros((bt, t, h), "float64")
    for bi in range(bt):
        hp = np.zeros(h)
        cp = np.zeros(h)
        for ti in range(lens[bi]):
            g = x[bi, ti] + hp @ w + gb
            gc, gi, gf, go = np.split(g, 4)
            sig = lambda v: 1 / (1 + np.exp(-v))
            if peep:
                i = sig(gi + cp * w_ic)
                f = sig(gf + cp * w_fc)
            else:
                i, f = sig(gi), sig(gf)
            c = f * cp + i * np.tanh(gc)
            o = sig(go + c * w_oc) if peep else sig(go)
            hh = o * np.tanh(c)
            hs[bi, ti] = hh
            cs[bi, ti] = c
            hp, cp = hh, c
    return hs.astype("float32"), cs.astype("float32")


class TestLSTM(OpTest):
    op_type = "lstm"

    def _setup(self, peep):
        h = 4
        rng = np.random.RandomState(8)
        x = _seq(d=4 * h, t=5, seed=8)
        w = (rng.rand(h, 4 * h).astype("float32") - 0.5) * 0.5
        b = (rng.rand(1, 7 * h if peep else 4 * h).astype("float32")
             - 0.5) * 0.5
        hs, cs = _np_lstm(x.astype("float64"), w.astype("float64"),
                          b.astype("float64"), LENS, peep)
        self.inputs = {"Input": x, "Weight": w, "Bias": b,
                       "Length": [("len", LENS)]}
        self.attrs = {"use_peepholes": peep}
        self.outputs = {"Hidden": hs, "Cell": cs}

    def test_peephole(self):
        self._setup(True)
        self.check_output(atol=1e-4)

    def test_no_peephole(self):
        self._setup(False)
        self.check_output(atol=1e-4)

    def test_grad(self):
        self._setup(False)
        self.check_grad(["lstm__Input", "lstm__Weight", "lstm__Bias"],
                        "lstm__Hidden", no_grad_set={"len"},
                        max_relative_error=0.03, delta=1e-2)


def _np_gru(x, w, lens):
    bt, t, h3 = x.shape
    h = h3 // 3
    hs = np.zeros((bt, t, h), "float64")
    sig = lambda v: 1 / (1 + np.exp(-v))
    for bi in range(bt):
        hp = np.zeros(h)
        for ti in range(lens[bi]):
            xt = x[bi, ti]
            g = sig(xt[:2 * h] + hp @ w[:, :2 * h])
            u, r = g[:h], g[h:]
            c = np.tanh(xt[2 * h:] + (r * hp) @ w[:, 2 * h:])
            hp = (1 - u) * hp + u * c
            hs[bi, ti] = hp
    return hs.astype("float32")


class TestGRU(OpTest):
    op_type = "gru"

    def test_output(self):
        h = 4
        rng = np.random.RandomState(9)
        x = _seq(d=3 * h, t=5, seed=9)
        w = (rng.rand(h, 3 * h).astype("float32") - 0.5) * 0.5
        hs = _np_gru(x.astype("float64"), w.astype("float64"), LENS)
        self.inputs = {"Input": x, "Weight": w, "Length": [("len", LENS)]}
        self.outputs = {"Hidden": hs}
        self.check_output(atol=1e-4)

    def test_grad(self):
        h = 3
        rng = np.random.RandomState(10)
        x = _seq(d=3 * h, t=5, seed=10)
        w = (rng.rand(h, 3 * h).astype("float32") - 0.5) * 0.5
        hs = _np_gru(x.astype("float64"), w.astype("float64"), LENS)
        self.inputs = {"Input": x, "Weight": w, "Length": [("len", LENS)]}
        self.outputs = {"Hidden": hs}
        self.check_grad(["gru__Input", "gru__Weight"], "gru__Hidden",
                        no_grad_set={"len"}, max_relative_error=0.03,
                        delta=1e-2)


class TestSequenceLayersEndToEnd:
    """Layer-level: LSTM text classifier trains on padded sequences fed
    through DataFeeder (the stacked_dynamic_lstm benchmark slice)."""

    def test_lstm_classifier_trains(self):
        dict_size, emb_dim, hid = 50, 16, 16
        word = fluid.layers.data("word", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(word, size=[dict_size, emb_dim])
        proj = fluid.layers.fc(emb, size=hid * 4, num_flatten_dims=2)
        h, c = fluid.layers.dynamic_lstm(proj, size=hid * 4)
        pooled = fluid.layers.sequence_pool(h, "max")
        pred = fluid.layers.fc(pooled, size=2, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

        feeder = fluid.DataFeeder(feed_list=[word, label], pad_to=8)
        rng = np.random.RandomState(0)

        def batch():
            rows = []
            for _ in range(8):
                ln = rng.randint(1, 9)
                seq = rng.randint(0, dict_size, (ln,)).astype("int64")
                y = np.int64(seq.max() > dict_size // 2)
                rows.append((seq, [y]))
            return feeder.feed(rows)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = []
        for _ in range(30):
            (lv,) = exe.run(feed=batch(), fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
        assert all(np.isfinite(losses))
        assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses

    def test_gru_pool_expand_pipeline(self):
        word = fluid.layers.data("w", shape=[4], dtype="float32", lod_level=1)
        proj = fluid.layers.fc(word, size=6 * 3, num_flatten_dims=2)
        h = fluid.layers.dynamic_gru(proj, size=6)
        pooled = fluid.layers.sequence_pool(h, "average")
        back = fluid.layers.sequence_expand(pooled, h)
        assert back.shape[1] == h.shape[1]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        feeder = fluid.DataFeeder(feed_list=[word], pad_to=5)
        rows = [(np.random.rand(3, 4).astype("float32"),),
                (np.random.rand(5, 4).astype("float32"),)]
        (out,) = exe.run(feed=feeder.feed(rows), fetch_list=[back])
        assert out.shape == (2, 5, 6)
        assert np.all(out[0, 3:] == 0)  # masked tail


class TestLSTMReverse:
    def test_reverse_differs_and_matches_flipped(self):
        """is_reverse=True on full-length sequences == flip(forward(flip(x)))."""
        import paddle_tpu as fluid
        h, b, t = 3, 2, 4
        rng = np.random.RandomState(12)
        x = rng.rand(b, t, 4 * h).astype("float32")
        w = (rng.rand(h, 4 * h).astype("float32") - 0.5) * 0.5
        bias = (rng.rand(1, 4 * h).astype("float32") - 0.5) * 0.5
        lens = np.full((b,), t, "int32")

        def run(xv, reverse):
            prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(prog, startup):
                blk = prog.global_block()
                for name, arr in [("x", xv), ("w", w), ("b", bias),
                                  ("len", lens)]:
                    blk.create_var(name=name, shape=arr.shape,
                                   dtype=arr.dtype, is_data=True,
                                   stop_gradient=True)
                blk.append_op(
                    type="lstm",
                    inputs={"Input": ["x"], "Weight": ["w"], "Bias": ["b"],
                            "Length": ["len"]},
                    outputs={"Hidden": ["hid"], "Cell": ["cell"]},
                    attrs={"use_peepholes": False, "is_reverse": reverse})
            exe = fluid.Executor(fluid.CPUPlace())
            (out,) = exe.run(prog, feed={"x": xv, "w": w, "b": bias,
                                         "len": lens}, fetch_list=["hid"])
            return np.asarray(out)

        fwd = run(x, False)
        rev = run(x, True)
        assert not np.allclose(fwd, rev)
        flipped = run(x[:, ::-1].copy(), False)[:, ::-1]
        np.testing.assert_allclose(rev, flipped, rtol=1e-5, atol=1e-6)


def test_lod_rank_table_family():
    """lod_rank_table / max_sequence_len / reorder_lod_tensor_by_rank
    (reference lod_rank_table_op.cc, max_sequence_len_op.cc,
    reorder_lod_tensor_by_rank_op.cc on the padded+@LEN design)."""
    import numpy as np
    import paddle_tpu as fluid

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[2], lod_level=1)
        x.stop_gradient = False  # data vars default True (fluid parity)
        table = fluid.layers.lod_rank_table(x)
        maxlen = fluid.layers.max_sequence_len(table)
        reordered = fluid.layers.reorder_lod_tensor_by_rank(x, table)
        # the reordered companion drives downstream masking
        relen = fluid.layers.sequence_length(reordered)
        loss = fluid.layers.mean(
            fluid.layers.sequence_pool(reordered, "sum"))
        grads = fluid.backward.calc_gradient(loss, [x])
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            xv = np.arange(24, dtype="float32").reshape(3, 4, 2)
            lens = np.array([2, 4, 3], "int64")
            tb, ml, ro, rl, g = exe.run(
                feed={"x": xv, "x@LEN": lens},
                fetch_list=[table, maxlen, reordered, relen, grads[0]])
    # stable descending sort by length: indices [1, 2, 0]
    np.testing.assert_array_equal(tb, [[1, 4], [2, 3], [0, 2]])
    assert int(ml) == 4
    np.testing.assert_array_equal(ro, xv[[1, 2, 0]])
    np.testing.assert_array_equal(rl, [4, 3, 2])
    # grad flows back through the gather: d(loss)/dx masks padding and
    # lands on the original row positions
    expect = np.zeros_like(xv)
    for b, ln in enumerate(lens):
        expect[b, :ln, :] = 1.0 / loss_batchsize_denom(ro)
    np.testing.assert_allclose(g, expect, rtol=1e-6)


def loss_batchsize_denom(ro):
    # mean over [B, D] pooled values -> each contributing element's grad
    return ro.shape[0] * ro.shape[2]


def test_lod_tensor_array_roundtrip():
    """lod_tensor_to_array -> array_to_lod_tensor is the identity on
    values and lengths; intermediate is time-major in rank order
    (reference lod_tensor_to_array_op.cc / array_to_lod_tensor_op.cc)."""
    import numpy as np
    import paddle_tpu as fluid

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[2], lod_level=1)
        x.stop_gradient = False
        table = fluid.layers.lod_rank_table(x)
        arr = fluid.layers.lod_tensor_to_array(x, table)
        back = fluid.layers.array_to_lod_tensor(arr, table)
        backlen = fluid.layers.sequence_length(back)
        loss = fluid.layers.mean(back)
        g, = fluid.backward.calc_gradient(loss, [x])
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            xv = np.arange(24, dtype="float32").reshape(3, 4, 2)
            lens = np.array([2, 4, 3], "int64")
            av, bv, blv, gv = exe.run(
                feed={"x": xv, "x@LEN": lens},
                fetch_list=[arr, back, backlen, g])
    assert av.shape == (4, 3, 2)  # time-major
    np.testing.assert_array_equal(av[:, 0], xv[1])  # longest seq first
    np.testing.assert_array_equal(bv, xv)           # roundtrip identity
    np.testing.assert_array_equal(blv, lens)
    np.testing.assert_allclose(gv, np.full_like(xv, 1.0 / xv.size))
