"""CNN op tests: numpy-oracle forward + numeric-vs-analytic gradients
(reference test_conv2d_op.py, test_pool2d_op.py, test_batch_norm_op.py,
test_layer_norm_op.py, test_lrn_op.py, test_bilinear_interp_op.py pattern).
"""

import numpy as np
import pytest

from op_test import OpTest


def np_conv2d(x, w, stride, pad, dilation=(1, 1), groups=1):
    n, cin, h, wd = x.shape
    cout, cin_g, kh, kw = w.shape
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilation
    ekh, ekw = dh * (kh - 1) + 1, dw * (kw - 1) + 1
    oh = (h + 2 * ph - ekh) // sh + 1
    ow = (wd + 2 * pw - ekw) // sw + 1
    xp = np.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    out = np.zeros((n, cout, oh, ow), dtype=x.dtype)
    cout_g = cout // groups
    for g in range(groups):
        for oc in range(g * cout_g, (g + 1) * cout_g):
            for i in range(oh):
                for j in range(ow):
                    patch = xp[:, g * cin_g:(g + 1) * cin_g,
                               i * sh:i * sh + ekh:dh,
                               j * sw:j * sw + ekw:dw]
                    out[:, oc, i, j] = np.sum(
                        patch * w[oc][None], axis=(1, 2, 3))
    return out


class TestConv2d(OpTest):
    op_type = "conv2d"

    def setup(self, stride=(1, 1), pad=(0, 0), dilation=(1, 1), groups=1,
              cin=4, cout=6, k=3):
        rng = np.random.RandomState(0)
        x = rng.rand(2, cin, 7, 7).astype("float32")
        w = rng.rand(cout, cin // groups, k, k).astype("float32") - 0.5
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": list(stride), "paddings": list(pad),
                      "dilations": list(dilation), "groups": groups}
        self.outputs = {
            "Output": np_conv2d(x, w, stride, pad, dilation, groups)
        }

    def test_basic(self):
        self.setup()
        self.check_output()

    def test_stride_pad(self):
        self.setup(stride=(2, 2), pad=(1, 1))
        self.check_output()

    def test_dilation(self):
        self.setup(dilation=(2, 2))
        self.check_output()

    def test_groups(self):
        self.setup(groups=2, cin=4, cout=6)
        self.check_output()

    def test_depthwise(self):
        rng = np.random.RandomState(1)
        x = rng.rand(2, 4, 6, 6).astype("float32")
        w = rng.rand(4, 1, 3, 3).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 4}
        self.outputs = {"Output": np_conv2d(x, w, (1, 1), (1, 1), (1, 1), 4)}
        self.op_type = "depthwise_conv2d"
        self.check_output()
        self.op_type = "conv2d"

    def test_grad(self):
        rng = np.random.RandomState(2)
        x = rng.rand(2, 2, 5, 5).astype("float32")
        w = rng.rand(3, 2, 3, 3).astype("float32") - 0.5
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1}
        self.outputs = {"Output": np_conv2d(x, w, (1, 1), (1, 1))}
        self.check_grad(["conv2d__Input", "conv2d__Filter"], "conv2d__Output",
                        max_relative_error=0.02)


class TestConv2dTranspose(OpTest):
    op_type = "conv2d_transpose"

    def test_output(self):
        """deconv oracle: scatter each input pixel times the kernel."""
        rng = np.random.RandomState(3)
        n, cin, h, w_ = 2, 3, 4, 4
        cout, k, stride, pad = 5, 3, 2, 1
        x = rng.rand(n, cin, h, w_).astype("float32")
        w = rng.rand(cin, cout, k, k).astype("float32") - 0.5
        oh = (h - 1) * stride - 2 * pad + k
        ow = (w_ - 1) * stride - 2 * pad + k
        full = np.zeros((n, cout, oh + 2 * pad, ow + 2 * pad), "float32")
        for i in range(h):
            for j in range(w_):
                contrib = np.einsum("nc,cokl->nokl", x[:, :, i, j], w)
                full[:, :, i * stride:i * stride + k,
                     j * stride:j * stride + k] += contrib
        want = full[:, :, pad:pad + oh, pad:pad + ow]
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [stride, stride], "paddings": [pad, pad],
                      "dilations": [1, 1], "groups": 1}
        self.outputs = {"Output": want}
        self.check_output()


def np_pool2d(x, ksize, stride, pad, ptype="max", ceil=False, exclusive=True):
    n, c, h, w = x.shape
    kh, kw = ksize
    sh, sw = stride
    ph, pw = pad
    rnd = (lambda v: int(np.ceil(v))) if ceil else (lambda v: int(np.floor(v)))
    oh = rnd((h + 2 * ph - kh) / sh) + 1
    ow = rnd((w + 2 * pw - kw) / sw) + 1
    out = np.zeros((n, c, oh, ow), x.dtype)
    for i in range(oh):
        for j in range(ow):
            hs, ws = i * sh - ph, j * sw - pw
            he, we = min(hs + kh, h), min(ws + kw, w)
            hs, ws = max(hs, 0), max(ws, 0)
            patch = x[:, :, hs:he, ws:we]
            if ptype == "max":
                out[:, :, i, j] = patch.max(axis=(2, 3))
            elif exclusive:
                out[:, :, i, j] = patch.mean(axis=(2, 3))
            else:
                out[:, :, i, j] = patch.sum(axis=(2, 3)) / (kh * kw)
    return out


class TestPool2d(OpTest):
    op_type = "pool2d"

    def _run(self, ptype, ksize=(2, 2), stride=(2, 2), pad=(0, 0),
             ceil=False, exclusive=True, shape=(2, 3, 6, 6)):
        rng = np.random.RandomState(4)
        x = rng.rand(*shape).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": ptype, "ksize": list(ksize),
                      "strides": list(stride), "paddings": list(pad),
                      "ceil_mode": ceil, "exclusive": exclusive}
        self.outputs = {"Out": np_pool2d(x, ksize, stride, pad, ptype, ceil,
                                         exclusive)}
        self.check_output()

    def test_max(self):
        self._run("max")

    def test_avg(self):
        self._run("avg")

    def test_max_pad(self):
        self._run("max", ksize=(3, 3), stride=(2, 2), pad=(1, 1))

    def test_avg_pad_exclusive(self):
        self._run("avg", ksize=(3, 3), stride=(2, 2), pad=(1, 1),
                  exclusive=True)

    def test_avg_pad_inclusive(self):
        self._run("avg", ksize=(3, 3), stride=(2, 2), pad=(1, 1),
                  exclusive=False)

    def test_ceil_mode(self):
        self._run("max", ksize=(3, 3), stride=(2, 2), pad=(0, 0), ceil=True,
                  shape=(2, 3, 7, 7))

    def test_global(self):
        rng = np.random.RandomState(5)
        x = rng.rand(2, 3, 5, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [1, 1],
                      "global_pooling": True}
        self.outputs = {"Out": x.mean(axis=(2, 3), keepdims=True)}
        self.check_output()

    def test_adaptive(self):
        rng = np.random.RandomState(6)
        x = rng.rand(1, 2, 6, 6).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [3, 3],
                      "adaptive": True}
        want = x.reshape(1, 2, 3, 2, 3, 2).mean(axis=(3, 5))
        self.outputs = {"Out": want}
        self.check_output()

    def test_grad_max(self):
        rng = np.random.RandomState(7)
        x = rng.rand(1, 2, 4, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        self.outputs = {"Out": np_pool2d(x, (2, 2), (2, 2), (0, 0), "max")}
        self.check_grad(["pool2d__X"], "pool2d__Out", max_relative_error=0.02)

    def test_grad_avg(self):
        rng = np.random.RandomState(8)
        x = rng.rand(1, 2, 4, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        self.outputs = {"Out": np_pool2d(x, (2, 2), (2, 2), (0, 0), "avg")}
        self.check_grad(["pool2d__X"], "pool2d__Out", max_relative_error=0.02)


class TestMaxPoolWithIndex(OpTest):
    op_type = "max_pool2d_with_index"

    def test_output(self):
        rng = np.random.RandomState(9)
        x = rng.rand(2, 3, 4, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]}
        out = np_pool2d(x, (2, 2), (2, 2), (0, 0), "max")
        mask = np.zeros_like(out, dtype="int32")
        for i in range(2):
            for j in range(2):
                patch = x[:, :, i * 2:i * 2 + 2, j * 2:j * 2 + 2]
                flat = patch.reshape(*patch.shape[:2], -1)
                am = flat.argmax(-1)
                r, c = am // 2, am % 2
                mask[:, :, i, j] = (i * 2 + r) * 4 + (j * 2 + c)
        self.outputs = {"Out": out, "Mask": mask}
        self.check_output()


def np_batch_norm(x, scale, bias, eps):
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    xn = (x - mean[None, :, None, None]) / np.sqrt(
        var[None, :, None, None] + eps)
    return xn * scale[None, :, None, None] + bias[None, :, None, None], \
        mean, var


class TestBatchNorm(OpTest):
    op_type = "batch_norm"

    def _setup(self, is_test=False):
        rng = np.random.RandomState(10)
        x = rng.rand(3, 4, 5, 5).astype("float32")
        scale = rng.rand(4).astype("float32") + 0.5
        bias = rng.rand(4).astype("float32")
        mean = rng.rand(4).astype("float32")
        var = rng.rand(4).astype("float32") + 0.5
        eps, momentum = 1e-5, 0.9
        if is_test:
            y = (x - mean[None, :, None, None]) / np.sqrt(
                var[None, :, None, None] + eps)
            y = y * scale[None, :, None, None] + bias[None, :, None, None]
            mean_out, var_out = mean, var
            saved_mean, saved_var = mean, var
        else:
            y, bm, bv = np_batch_norm(x, scale, bias, eps)
            mean_out = momentum * mean + (1 - momentum) * bm
            var_out = momentum * var + (1 - momentum) * bv
            saved_mean, saved_var = bm, bv
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"epsilon": eps, "momentum": momentum,
                      "is_test": is_test}
        self.outputs = {"Y": y, "MeanOut": mean_out, "VarianceOut": var_out,
                        "SavedMean": saved_mean, "SavedVariance": saved_var}

    def test_train(self):
        self._setup(is_test=False)
        self.check_output(atol=1e-4)

    def test_infer(self):
        self._setup(is_test=True)
        self.check_output(atol=1e-4)

    def _uncentered_setup(self, running_mean):
        """Pathological un-centered input (mean 1000, std 0.01): the naive
        one-pass E[x^2]-E[x]^2 variance cancels catastrophically in f32."""
        rng = np.random.RandomState(20)
        x = (1000.0 + 0.01 * rng.randn(16, 4, 4, 4)).astype("float32")
        scale = np.ones(4, "float32")
        bias = np.zeros(4, "float32")
        mean = np.full(4, running_mean, "float32")
        var = np.ones(4, "float32")
        eps = 1e-5
        x64 = x.astype(np.float64)
        y, bm, bv = np_batch_norm(x64, scale.astype(np.float64),
                                  bias.astype(np.float64), eps)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"epsilon": eps, "momentum": 0.9, "is_test": False}
        self.outputs = {"Y": y.astype("float32"),
                        "MeanOut": (0.9 * mean + 0.1 * bm).astype("float32"),
                        "VarianceOut": (0.9 * var + 0.1 * bv).astype(
                            "float32"),
                        "SavedMean": bm.astype("float32"),
                        "SavedVariance": bv.astype("float32")}

    def test_uncentered_input_stable(self):
        """Default (shifted one-pass): centering on the running mean kills
        the cancellation once running stats track batch stats — the state
        of every training step past the first few."""
        self._uncentered_setup(running_mean=1000.0)
        self.check_output(atol=5e-2, rtol=5e-2)

    def test_uncentered_input_two_pass_flag(self):
        """FLAGS_bn_two_pass restores the exact two-pass variance even
        with a cold (zero) running mean on pathological inputs."""
        import paddle_tpu as fluid
        fluid.set_flags({"FLAGS_bn_two_pass": True})
        try:
            self._uncentered_setup(running_mean=0.0)
            self.check_output(atol=5e-2, rtol=5e-2)
        finally:
            fluid.set_flags({"FLAGS_bn_two_pass": False})

    def test_grad(self):
        self._setup(is_test=False)
        self.check_grad(["batch_norm__X", "batch_norm__Scale", "batch_norm__Bias"], "batch_norm__Y",
                        max_relative_error=0.02,
                        no_grad_set={"batch_norm__Mean",
                                     "batch_norm__Variance"})


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def test_output_and_grad(self):
        rng = np.random.RandomState(11)
        x = rng.rand(3, 4, 5).astype("float32")
        scale = rng.rand(20).astype("float32") + 0.5
        bias = rng.rand(20).astype("float32")
        eps, axis = 1e-5, 1
        flat = x.reshape(3, -1)
        mean = flat.mean(-1)
        var = flat.var(-1)
        yn = (flat - mean[:, None]) / np.sqrt(var[:, None] + eps)
        y = (yn * scale[None] + bias[None]).reshape(x.shape)
        self.inputs = {"X": x,
                       "Scale": scale.reshape(4, 5),
                       "Bias": bias.reshape(4, 5)}
        self.attrs = {"epsilon": eps, "begin_norm_axis": axis}
        self.outputs = {"Y": y, "Mean": mean, "Variance": var}
        self.check_output(atol=1e-4)
        self.check_grad(["layer_norm__X", "layer_norm__Scale", "layer_norm__Bias"], "layer_norm__Y",
                        max_relative_error=0.02)


class TestGroupNorm(OpTest):
    op_type = "group_norm"

    def test_output(self):
        rng = np.random.RandomState(12)
        x = rng.rand(2, 4, 3, 3).astype("float32")
        scale = rng.rand(4).astype("float32") + 0.5
        bias = rng.rand(4).astype("float32")
        g, eps = 2, 1e-5
        xg = x.reshape(2, g, 2, 3, 3)
        mean = xg.mean(axis=(2, 3, 4), keepdims=True)
        var = xg.var(axis=(2, 3, 4), keepdims=True)
        y = ((xg - mean) / np.sqrt(var + eps)).reshape(x.shape)
        y = y * scale[None, :, None, None] + bias[None, :, None, None]
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"groups": g, "epsilon": eps}
        self.outputs = {"Y": y, "Mean": mean.reshape(2, g),
                        "Variance": var.reshape(2, g)}
        self.check_output(atol=1e-4)

    def test_nhwc(self):
        rng = np.random.RandomState(21)
        x = rng.rand(2, 3, 3, 4).astype("float32")  # NHWC
        scale = rng.rand(4).astype("float32") + 0.5
        bias = rng.rand(4).astype("float32")
        g, eps = 2, 1e-5
        xc = np.moveaxis(x, -1, 1)
        xg = xc.reshape(2, g, 2, 3, 3)
        mean = xg.mean(axis=(2, 3, 4), keepdims=True)
        var = xg.var(axis=(2, 3, 4), keepdims=True)
        y = ((xg - mean) / np.sqrt(var + eps)).reshape(xc.shape)
        y = y * scale[None, :, None, None] + bias[None, :, None, None]
        y = np.moveaxis(y, 1, -1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"groups": g, "epsilon": eps, "data_layout": "NHWC"}
        self.outputs = {"Y": y, "Mean": mean.reshape(2, g),
                        "Variance": var.reshape(2, g)}
        self.check_output(atol=1e-4)


class TestLRN(OpTest):
    op_type = "lrn"

    def test_output(self):
        rng = np.random.RandomState(13)
        x = rng.rand(2, 6, 4, 4).astype("float32")
        n, k, alpha, beta = 5, 2.0, 1e-4, 0.75
        half = n // 2
        sq = np.square(x)
        mid = np.full_like(x, k)
        for c in range(6):
            lo, hi = max(0, c - half), min(6, c + n - half)
            mid[:, c] += alpha * sq[:, lo:hi].sum(axis=1)
        self.inputs = {"X": x}
        self.attrs = {"n": n, "k": k, "alpha": alpha, "beta": beta}
        self.outputs = {"Out": x * np.power(mid, -beta), "MidOut": mid}
        self.check_output(atol=1e-5)


class TestNormOp(OpTest):
    op_type = "norm"

    def test_output(self):
        rng = np.random.RandomState(14)
        x = rng.rand(2, 5, 3).astype("float32")
        eps = 1e-10
        norm = np.sqrt((x * x).sum(axis=1, keepdims=True) + eps)
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "epsilon": eps}
        self.outputs = {"Out": x / norm, "Norm": norm}
        self.check_output()


class TestBilinearInterp(OpTest):
    op_type = "bilinear_interp"

    def test_output(self):
        rng = np.random.RandomState(15)
        x = rng.rand(2, 3, 4, 4).astype("float32")
        oh, ow = 7, 7
        h, w = 4, 4
        rh, rw = (h - 1) / (oh - 1), (w - 1) / (ow - 1)
        out = np.zeros((2, 3, oh, ow), "float32")
        for i in range(oh):
            for j in range(ow):
                fy, fx = i * rh, j * rw
                y0, x0 = int(np.floor(fy)), int(np.floor(fx))
                y1, x1 = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
                wy, wx = fy - y0, fx - x0
                out[:, :, i, j] = (
                    x[:, :, y0, x0] * (1 - wy) * (1 - wx)
                    + x[:, :, y0, x1] * (1 - wy) * wx
                    + x[:, :, y1, x0] * wy * (1 - wx)
                    + x[:, :, y1, x1] * wy * wx
                )
        self.inputs = {"X": x}
        self.attrs = {"out_h": oh, "out_w": ow}
        self.outputs = {"Out": out}
        self.check_output(atol=1e-5)


class TestConvShift(OpTest):
    op_type = "conv_shift"

    def test_output(self):
        rng = np.random.RandomState(16)
        x = rng.rand(3, 8).astype("float32")
        y = rng.rand(3, 3).astype("float32")
        m, n = 8, 3
        half = n // 2
        out = np.zeros_like(x)
        for b in range(3):
            for i in range(m):
                for j in range(n):
                    out[b, i] += x[b, (i + j - half) % m] * y[b, j]
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": out}
        self.check_output()
