"""Optimizer tests: each optimizer decreases a quadratic loss and matches
hand-computed first-step updates where cheap (reference
test_optimizer.py pattern)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _quadratic_problem(optimizer):
    x = fluid.layers.data("x", shape=[4])
    y = fluid.layers.fc(
        x, size=1, bias_attr=False,
        param_attr=fluid.ParamAttr(
            name="w0",
            initializer=fluid.initializer.ConstantInitializer(1.0)),
    )
    loss = fluid.layers.mean(fluid.layers.square(y))
    optimizer.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, loss


OPTIMIZERS = [
    lambda: fluid.optimizer.SGD(learning_rate=0.05),
    lambda: fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9),
    lambda: fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                     use_nesterov=True),
    lambda: fluid.optimizer.Adagrad(learning_rate=0.3),
    lambda: fluid.optimizer.Adam(learning_rate=0.1),
    lambda: fluid.optimizer.Adamax(learning_rate=0.1),
    lambda: fluid.optimizer.DecayedAdagrad(learning_rate=0.3),
    lambda: fluid.optimizer.Adadelta(learning_rate=1.0, rho=0.95),
    lambda: fluid.optimizer.RMSProp(learning_rate=0.05),
    lambda: fluid.optimizer.Ftrl(learning_rate=0.5),
]


@pytest.mark.parametrize("make_opt", OPTIMIZERS,
                         ids=[f().__class__.__name__ + str(i)
                              for i, f in enumerate(OPTIMIZERS)])
def test_optimizer_decreases_loss(make_opt):
    exe, loss = _quadratic_problem(make_opt())
    rng = np.random.RandomState(0)
    xv = rng.uniform(0.5, 1.5, (16, 4)).astype("float32")
    losses = []
    for _ in range(25):
        (lv,) = exe.run(feed={"x": xv}, fetch_list=[loss])
        losses.append(float(lv[0]))
    assert losses[-1] < losses[0] * 0.9, losses[:3] + losses[-3:]


def test_sgd_first_step_matches_formula():
    opt = fluid.optimizer.SGD(learning_rate=0.1)
    x = fluid.layers.data("x", shape=[2])
    y = fluid.layers.fc(
        x, size=1, bias_attr=False,
        param_attr=fluid.ParamAttr(
            name="w1",
            initializer=fluid.initializer.ConstantInitializer(2.0)),
    )
    loss = fluid.layers.mean(y)
    opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.ones((4, 2), dtype="float32")
    exe.run(feed={"x": xv}, fetch_list=[loss])
    w = np.asarray(fluid.global_scope().var("w1"))
    # grad of mean(x@w) wrt w = x.mean(0) = 1 -> w = 2 - 0.1
    np.testing.assert_allclose(w, np.full((2, 1), 1.9), rtol=1e-5)


def test_adam_first_step_matches_formula():
    opt = fluid.optimizer.Adam(learning_rate=0.1, beta1=0.9, beta2=0.999,
                               epsilon=1e-8)
    x = fluid.layers.data("x", shape=[2])
    y = fluid.layers.fc(
        x, size=1, bias_attr=False,
        param_attr=fluid.ParamAttr(
            name="w2",
            initializer=fluid.initializer.ConstantInitializer(2.0)),
    )
    loss = fluid.layers.mean(y)
    opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.ones((4, 2), dtype="float32")
    exe.run(feed={"x": xv}, fetch_list=[loss])
    w = np.asarray(fluid.global_scope().var("w2"))
    # bias-corrected adam first step with g=1: update = lr * 1 ≈ 0.1
    np.testing.assert_allclose(w, np.full((2, 1), 1.9), rtol=1e-4)


def test_learning_rate_variable():
    lr = fluid.layers.tensor.create_global_var(
        shape=[1], value=0.5, dtype="float32", persistable=True, name="lr0")
    opt = fluid.optimizer.SGD(learning_rate=lr)
    x = fluid.layers.data("x", shape=[2])
    y = fluid.layers.fc(
        x, size=1, bias_attr=False,
        param_attr=fluid.ParamAttr(
            name="w3",
            initializer=fluid.initializer.ConstantInitializer(1.0)),
    )
    loss = fluid.layers.mean(y)
    opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={"x": np.ones((2, 2), "float32")}, fetch_list=[loss])
    w = np.asarray(fluid.global_scope().var("w3"))
    np.testing.assert_allclose(w, np.full((2, 1), 0.5), rtol=1e-5)
