"""Localhost multi-process distributed training test (reference
``test_dist_base.py:31``: fork real OS processes, run N steps, assert
trainer losses match a local single-process reference run)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _local_reference(workload):
    """In-process single-device run of a dist_model workload; the loss
    sequence every distributed trainer must reproduce."""
    import dist_model

    build_fn, batches_fn = dist_model.MODELS[workload]
    loss = build_fn(fluid)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    ref = []
    for feed in batches_fn():
        (lv,) = exe.run(feed=feed, fetch_list=[loss])
        ref.append(float(np.asarray(lv).ravel()[0]))
    return ref


def _run_dist_parity(workload):
    """Single-process reference run, then 2 real trainer processes on the
    same workload; every trainer's per-step losses must match the local
    run (the reference's test_dist_base protocol)."""
    ref = _local_reference(workload)

    port = _free_port()
    coordinator = "127.0.0.1:%d" % port
    runner = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "dist_runner.py")
    env = dict(os.environ, DIST_MODEL=workload)
    env.pop("XLA_FLAGS", None)          # runner sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, runner, str(i), "2", coordinator],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for i in range(2)
    ]
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            assert p.returncode == 0, (out[-2000:], err[-4000:])
            line = [l for l in out.splitlines()
                    if l.startswith("DIST_LOSSES")]
            assert line, out[-2000:]
            losses = json.loads(line[0][len("DIST_LOSSES "):])
            np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-5)
    finally:
        # on any failure, don't leave the peer blocked in a collective
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_two_process_dist_matches_local():
    _run_dist_parity("mlp")


def test_transpiler_sharding_plan():
    """Plan inspection (the reference's test_dist_transpiler pattern)."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.param_attr import ParamAttr

    ids = fluid.layers.data("ids", shape=[4, 1], dtype="int64")
    emb = fluid.layers.embedding(ids, size=[64, 16], is_distributed=True,
                                 param_attr=ParamAttr(name="table_w"))
    big = fluid.layers.fc(fluid.layers.reduce_mean(emb, dim=1), size=1024,
                          param_attr=ParamAttr(name="big_w"),
                          bias_attr=ParamAttr(name="small_b"))
    loss = fluid.layers.mean(big)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, trainers=2)
    plan = t.sharding_plan()
    assert plan["table_w"] == ("table", P("ep"))
    assert plan["big_w"][0] == "sliced"        # 16*1024 = 16384 >= 8192
    assert plan["small_b"][0] == "replicated"

    mesh = fluid.make_mesh((8,), ("dp",))
    bs = t.build_strategy(mesh)
    # table axis 'ep' not on this mesh -> falls back to dp; 64 % 8 == 0
    assert bs.param_sharding_fn("table_w", (64, 16)) == P("dp")
    assert bs.param_sharding_fn("small_b", (1024,)) == P()
    # indivisible dim degrades to replication
    assert bs.param_sharding_fn("big_w", (15, 1024)) == P()
    # indivisible AFTER ep->dp substitution also degrades (63 % 8 != 0)
    assert bs.param_sharding_fn("table_w", (63, 16)) == P()

    with pytest.raises(RuntimeError, match="no parameter-server role"):
        t.get_pserver_program("127.0.0.1:7164")
    with pytest.raises(NotImplementedError, match="async"):
        fluid.DistributeTranspiler().transpile(
            trainer_id=0, trainers=2, sync_mode=False)


def test_slice_variable_accounting():
    """slice_variable: ZeRO dp-rank shard accounting (reference
    transpiler/distribute_transpiler.py:79)."""
    from paddle_tpu.transpiler.distribute_transpiler import slice_variable

    class V:
        def __init__(self, name, shape):
            self.name, self.shape = name, shape

    blocks = slice_variable(
        [V("big", (1000, 64)), V("small", (4, 4)), V("row", (1, 100000))],
        slice_count=4)
    big = [b for b in blocks if b[0] == "big"]
    assert len(big) == 4
    assert sum(n for _, _, n in big) == 1000 * 64
    assert max(n for _, _, n in big) - min(n for _, _, n in big) == 0
    # under-threshold and unsplittable vars stay whole
    assert ("small", 0, 16) in blocks
    assert ("row", 0, 100000) in blocks
    # split never exceeds the first-dim extent
    tiny = slice_variable([V("t", (3, 10000))], slice_count=8)
    assert len(tiny) == 3


def test_memory_optimize_reports():
    x = fluid.layers.data("x", shape=[16])
    y = fluid.layers.fc(x, size=32)
    fluid.layers.mean(y)
    saved = fluid.memory_optimize(print_log=False)
    assert saved >= 0
    assert fluid.release_memory() == 0


@pytest.mark.slow   # ~60s 2-process drill; the deterministic single-host
                    # kill-and-resume drill (test_elastic_drill) is tier-1
def test_dist_trainer_kill_and_resume(tmp_path):
    """Fault injection (SURVEY §5 checkpoint-on-signal, restart-resume):
    SIGTERM both trainer processes mid-run — they agree on a flush step
    via the preemption vote, write a collective sharded checkpoint, and
    exit 0; a restarted run resumes from it and the combined losses
    reproduce the uninterrupted single-process reference."""
    ref = _local_reference("mlp")
    ckpt = str(tmp_path / "preempt_ckpt")
    runner = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "dist_runner.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["DIST_MODEL"] = "mlp"   # must match the reference run above

    def launch(port):
        coordinator = "127.0.0.1:%d" % port
        return [
            subprocess.Popen(
                [sys.executable, runner, str(i), "2", coordinator, ckpt],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                bufsize=1, env=env)
            for i in range(2)
        ]

    # run 1: kill after the first completed steps
    procs = launch(_free_port())
    import signal as _signal
    seen_step = False
    for line in procs[0].stdout:
        if line.startswith("STEP"):
            seen_step = True
            for p in procs:
                p.send_signal(_signal.SIGTERM)
            break
    assert seen_step, procs[0].stderr.read()[-4000:]
    outs1 = []
    for p in procs:
        rest = p.stdout.read()
        err = p.stderr.read()
        p.wait(timeout=420)
        assert p.returncode == 0, err[-4000:]
        outs1.append(rest)
    # both processes flushed the SAME agreed step
    saved = [l for l in outs1[0].splitlines() if l.startswith("CKPT_SAVED")]
    assert saved, outs1[0][-2000:]
    flush_step = int(saved[0].split()[1])
    assert flush_step >= 1

    losses1 = json.loads(
        [l for l in outs1[0].splitlines()
         if l.startswith("DIST_LOSSES")][0][len("DIST_LOSSES "):])
    assert len(losses1) == flush_step

    # run 2: fresh processes resume from the flushed checkpoint
    procs = launch(_free_port())
    outs2 = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, (out[-2000:], err[-4000:])
        outs2.append(out)
    assert any(l.startswith("RESUMED %d" % flush_step)
               for l in outs2[0].splitlines()), outs2[0][-2000:]
    losses2 = json.loads(
        [l for l in outs2[0].splitlines()
         if l.startswith("DIST_LOSSES")][0][len("DIST_LOSSES "):])
    np.testing.assert_allclose(losses1 + losses2, ref,
                               rtol=1e-4, atol=1e-5)


def test_transpiler_plan_matches_compiled_shardings():
    """VERDICT r2 #10 (reference test_dist_transpiler.py pattern): the
    transpiler's plan must match the ACTUAL shardings the compiled
    ParallelExecutor puts on the mesh — embedding rows over ep, sliced
    params and their optimizer state over dp, small params replicated."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.param_attr import ParamAttr

    ids = fluid.layers.data("ids", shape=[4, 1], dtype="int64")
    emb = fluid.layers.embedding(ids, size=[64, 16], is_distributed=True,
                                 param_attr=ParamAttr(name="table_w"))
    big = fluid.layers.fc(fluid.layers.reduce_mean(emb, dim=1), size=1024,
                          param_attr=ParamAttr(name="big_w"),
                          bias_attr=ParamAttr(name="small_b"))
    loss = fluid.layers.mean(big)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, trainers=1)
    mesh = fluid.make_mesh((4, 2), ("dp", "ep"))
    bs = t.build_strategy(mesh)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                    build_strategy=bs, scope=scope)
        feed = {"ids": np.random.RandomState(0).randint(
            0, 64, (8, 4, 1)).astype("int64")}
        pe.run(feed=feed, fetch_list=[loss])

        def actual(name):
            v = scope.var(name)
            assert isinstance(v, jax.Array), name
            return v.sharding, v.ndim

        def assert_spec(name, spec):
            sh, ndim = actual(name)
            want = NamedSharding(mesh, spec)
            assert sh.is_equivalent_to(want, ndim), (
                "%s: actual %s != planned %s" % (name, sh, want))

        # plan says: table rows over ep, big fc weight over dp (16384
        # elements >= min_block_size), bias replicated
        assert_spec("table_w", P("ep"))
        assert_spec("big_w", P("dp"))
        assert_spec("small_b", P())
        # optimizer state follows the kReduce rule: Adam moments of the
        # sliced param shard dim 0 over dp; bias moments replicate
        moments = [n for n in scope.local_var_names()
                   if n.startswith("big_w_moment")]
        assert moments, "no Adam moment accumulators found for big_w"
        for n in moments:
            assert_spec(n, P("dp"))
        # the bias PARAM stays replicated per the plan, but its moments
        # still shard dim 0 over dp (the kReduce/ZeRO state rule applies
        # to optimizer state independently; 1024 divides dp=4)
        b_moments = [n for n in scope.local_var_names()
                     if n.startswith("small_b_moment")]
        assert b_moments
        for n in b_moments:
            assert_spec(n, P("dp"))


def test_two_process_dist_sparse_grads_match_local():
    """SelectedRows sparse embedding gradients across 2 real processes
    aggregate identically to the single-process run (the 'sparse grads
    under pjit' hard part of SURVEY §7; reference test_dist_base over
    dist_ctr-style models)."""
    _run_dist_parity("sparse")


@pytest.mark.parametrize("workload", ["text_cls", "word2vec"])
def test_two_process_dist_workload_matches_local(workload):
    """The remaining reference dist workloads (dist_text_classification's
    sequence-conv net; dist_word2vec's shared sparse n-gram table) train
    loss-identically across 2 real processes vs the single-process run —
    completing the test_dist_base model matrix (mnist/mlp, ctr, simnet_bow,
    se_resnext/transformer via PE tests, text_classification, word2vec)."""
    _run_dist_parity(workload)
