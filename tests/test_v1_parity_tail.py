"""Execution coverage for the v1 dialect parity tail (reference
trainer_config_helpers layers/networks/evaluators names added late):
every new layer builds ops on the shared graph and RUNS on the CPU
backend with value checks where the math is closed-form."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.trainer_config_helpers as tch
from paddle_tpu import v2 as paddle
from paddle_tpu.v2 import config as cfg


@pytest.fixture(autouse=True)
def _fresh():
    tch.reset_parser()
    yield
    tch.reset_parser()


def _run(fetch_layers, feed):
    g = cfg.graph()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(g.startup)
    outs = exe.run(g.main, feed=feed,
                   fetch_list=[l.var for l in fetch_layers])
    return [np.asarray(o) for o in outs]


def test_elementwise_geometric_layers_run():
    x = tch.data_layer("x", size=6)
    y = tch.data_layer("y", size=6)
    w = tch.data_layer("w", size=1)

    clip = tch.clip_layer(x, min=-0.5, max=0.5)
    rot = tch.rotate_layer(x, height=2, width=3)
    sw = tch.switch_order_layer(x, reshape_order=[1, 0])
    rs = tch.resize_layer(x, size=3)
    rep = tch.repeat_layer(x, 2)
    interp = tch.interpolation_layer([x, y], w)
    lc = tch.linear_comb_layer(weights=tch.resize_layer(x, 3),
                               vectors=tch.resize_layer(y, 3), size=1)
    op = tch.out_prod_layer(w, w)
    s2o = tch.sum_to_one_norm_layer(x)
    rl2 = tch.row_l2_norm_layer(x)
    l2d = tch.l2_distance_layer(x, x)
    sshift = tch.scale_shift_layer(x)
    tl = tch.tensor_layer(x, y, size=4)

    xv = np.arange(12, dtype="float32").reshape(2, 6) + 1.0
    yv = np.ones((2, 6), "float32")
    wv = np.full((2, 1), 0.25, "float32")
    (cv, rv, swv, rsv, repv, iv, lcv, opv, s2ov, rl2v, l2dv, ssv,
     tlv) = _run(
        [clip, rot, sw, rs, rep, interp, lc, op, s2o, rl2, l2d, sshift,
         tl],
        {"x": xv, "y": yv, "w": wv})
    assert cv.max() <= 0.5 and cv.min() >= -0.5
    assert rv.shape == (2, 6) and swv.shape == (6, 2)
    assert rsv.shape == (4, 3)
    np.testing.assert_allclose(repv[0, :6], xv[0])      # [a b a b]
    np.testing.assert_allclose(repv[0, 6:], xv[0])
    np.testing.assert_allclose(iv, 0.25 * xv + 0.75 * yv, rtol=1e-6)
    assert lcv.shape == (4, 1) and opv.shape == (2, 1)
    np.testing.assert_allclose(s2ov.sum(axis=1), np.ones(2), rtol=1e-5)
    np.testing.assert_allclose(
        np.linalg.norm(rl2v, axis=1), np.ones(2), rtol=1e-5)
    np.testing.assert_allclose(l2dv, np.zeros((2, 1)), atol=1e-6)
    assert ssv.shape == (2, 6) and tlv.shape == (2, 4)


def test_select_print_sample_layers_run():
    ids = tch.data_layer("ids", size=1)
    a = tch.data_layer("a", size=4)
    b = tch.data_layer("b", size=4)
    probs = tch.data_layer("p", size=4)

    mux = tch.multiplex_layer([ids, a, b])
    eos = tch.eos_layer(ids, eos_id=2)
    sid = tch.sampling_id_layer(probs)
    pr = tch.print_layer(a, format="v1-print")

    av = np.zeros((3, 4), "float32")
    bv = np.ones((3, 4), "float32")
    idv = np.array([[0.0], [1.0], [0.0]], "float32")
    pv = np.full((3, 4), 0.25, "float32")
    muxv, eosv, sidv, prv = _run([mux, eos, sid, pr],
                                 {"ids": idv, "a": av, "b": bv, "p": pv})
    np.testing.assert_allclose(muxv[:, 0], [0.0, 1.0, 0.0])
    np.testing.assert_allclose(eosv.ravel(), [0.0, 0.0, 0.0])
    assert sidv.shape[0] == 3 and (0 <= sidv).all() and (sidv < 4).all()
    np.testing.assert_allclose(prv, av)


def test_image_family_layers_run():
    img = tch.data_layer("img", size=3 * 8 * 8, height=8, width=8)

    mx = tch.maxout_layer(img, groups=3, num_channels=3)
    cmr = tch.img_cmrnorm_layer(img, size=3, num_channels=3)
    ccn = tch.cross_channel_norm_layer(img)
    pad = tch.pad_layer(img, pad_c=[1, 1], pad_h=[0, 0], pad_w=[0, 0],
                        num_channels=3)
    spp = tch.spp_layer(img, num_channels=3, pyramid_height=2)
    up = tch.upsample_layer(img, scale=2, num_channels=3)
    bi = tch.bilinear_interp_layer(img, out_size_x=4, out_size_y=4,
                                   num_channels=3)
    be = tch.block_expand_layer(img, block_x=4, block_y=4, stride_x=4,
                                stride_y=4, num_channels=3)
    pre = tch.prelu_layer(img)

    iv = np.random.RandomState(0).rand(2, 3 * 8 * 8).astype("float32")
    outs = _run([mx, cmr, ccn, pad, spp, up, bi, be, pre], {"img": iv})
    mxv, cmrv, ccnv, padv, sppv, upv, biv, bev, prev = outs
    assert mxv.shape == (2, 1, 8, 8)
    assert cmrv.shape == (2, 3, 8, 8)
    assert ccnv.shape == (2, 3, 8, 8)
    assert padv.shape == (2, 5, 8, 8)
    assert sppv.shape[0] == 2 and sppv.shape[1] == 3 * (1 + 4)
    assert upv.shape == (2, 3, 16, 16)
    assert biv.shape == (2, 3, 4, 4)
    assert bev.shape[0] == 2          # sequence of blocks
    assert prev.shape == (2, 3 * 8 * 8)


def test_3d_layers_build_and_run():
    vol = tch.data_layer("vol", size=2 * 4 * 4 * 4)
    with cfg.build():
        v5 = fluid.layers.reshape(vol.var, shape=[-1, 2, 4, 4, 4])
    vol5 = cfg.Layer(v5, parents=[vol])
    c3 = tch.img_conv3d_layer(vol5, filter_size=3, num_filters=4,
                              stride=1, padding=1, act="relu")
    p3 = tch.img_pool3d_layer(c3, pool_size=2, stride=2)
    vv = np.random.RandomState(1).rand(2, 2 * 4 * 4 * 4).astype("float32")
    c3v, p3v = _run([c3, p3], {"vol": vv})
    assert c3v.shape == (2, 4, 4, 4, 4)
    assert p3v.shape == (2, 4, 2, 2, 2)


def test_sequence_family_and_recurrences_run():
    seq = tch.data_layer("seq", size=6,
                         type=paddle.data_type.dense_vector_sequence(6))
    seq2 = tch.data_layer("seq2", size=6,
                          type=paddle.data_type.dense_vector_sequence(6))

    cat = tch.seq_concat_layer(seq, seq2)
    rsh = tch.seq_reshape_layer(seq, reshape_size=3)
    kmax = tch.kmax_seq_score_layer(
        tch.data_layer("scores", size=1,
                       type=paddle.data_type.dense_vector_sequence(1)),
        beam_size=2)
    rec = tch.recurrent_layer(seq, act=tch.TanhActivation())
    rc = tch.row_conv_layer(seq, context_len=2)
    gu = tch.gated_unit_layer(seq, size=5, act=tch.TanhActivation())
    fm = tch.factorization_machine(seq, factor_size=3)

    rng = np.random.RandomState(2)
    sv = rng.rand(2, 4, 6).astype("float32")
    s2v = rng.rand(2, 4, 6).astype("float32")
    scv = rng.rand(2, 4, 1).astype("float32")
    lens = np.array([4, 3], "int32")
    feed = {"seq": sv, "seq@LEN": lens, "seq2": s2v, "seq2@LEN": lens,
            "scores": scv, "scores@LEN": lens}
    starts = tch.data_layer("st", size=1)
    ends = tch.data_layer("en", size=1)
    ssl = tch.seq_slice_layer(seq, starts, ends)
    sub = tch.sub_seq_layer(seq, starts,
                            tch.resize_layer(ends, size=1))
    feed.update({"st": np.zeros((2, 1), "float32"),
                 "en": np.full((2, 1), 2.0, "float32")})
    catv, rshv, kmv, recv, rcv, guv, fmv, sslv, subv = _run(
        [cat, rsh, kmax, rec, rc, gu, fm, ssl, sub], feed)
    assert catv.shape[1] == 8          # 4 + 4 timesteps
    assert rshv.shape[-1] == 3
    # slice [0, 2): first two steps survive, the rest zeroed
    np.testing.assert_allclose(sslv[:, :2], sv[:, :2], rtol=1e-6)
    np.testing.assert_allclose(sslv[:, 2:], 0 * sv[:, 2:], atol=1e-7)
    assert subv.shape == sslv.shape
    assert kmv.shape == (2, 2)
    assert recv.shape == (2, 4, 6)
    assert rcv.shape == (2, 4, 6)
    assert guv.shape[-1] == 5
    assert fmv.shape == (2, 4, 1)      # per-timestep FM on a sequence


def test_step_units_run():
    x4 = tch.data_layer("x4", size=16)    # [B, 4H] for H=4
    c0 = tch.data_layer("c0", size=4)
    h = tch.lstm_step_layer(x4, c0, size=4)
    assert hasattr(h, "state")

    x3 = tch.data_layer("x3", size=12)    # [B, 3H] for H=4
    h0 = tch.data_layer("h0", size=4)
    g = tch.gru_step_layer(x3, h0, size=4)

    rng = np.random.RandomState(3)
    hv, cv, gv = _run(
        [h, h.state, g],
        {"x4": rng.rand(2, 16).astype("float32"),
         "c0": np.zeros((2, 4), "float32"),
         "x3": rng.rand(2, 12).astype("float32"),
         "h0": np.zeros((2, 4), "float32")})
    assert hv.shape == (2, 4) and cv.shape == (2, 4) and gv.shape == (2, 4)


def test_cost_layers_run_and_train():
    x = tch.data_layer("x", size=4)
    lbl = tch.data_layer("lbl", size=1)
    left = tch.fc_layer(x, size=1)
    right = tch.fc_layer(x, size=1)
    rank = tch.rank_cost(left, right, lbl)
    hub_r = tch.huber_regression_cost(left, lbl)
    hub_c = tch.huber_classification_cost(left, lbl)
    probs = tch.fc_layer(x, size=3, act=tch.SoftmaxActivation())
    ilbl = tch.data_layer("il", size=0,
                          type=paddle.data_type.integer_value(3))
    selfn = tch.cross_entropy_with_selfnorm(probs, ilbl)

    rng = np.random.RandomState(4)
    feed = {"x": rng.rand(6, 4).astype("float32"),
            "lbl": rng.randint(0, 2, (6, 1)).astype("float32"),
            "il": rng.randint(0, 3, (6, 1)).astype("int64")}
    rv, hrv, hcv, sv = _run([rank, hub_r, hub_c, selfn], feed)
    for v in (rv, hrv, hcv, sv):
        assert np.isfinite(v).all() and v.size == 1


def test_lambda_cost_ranks():
    sc = tch.data_layer("sc", size=1,
                        type=paddle.data_type.dense_vector_sequence(1))
    rel = tch.data_layer("rel", size=1,
                         type=paddle.data_type.dense_vector_sequence(1))
    lam = tch.lambda_cost(sc, rel, NDCG_num=3)
    perfect = np.array([[[3.], [2.], [1.]]], "float32")
    reversed_ = np.array([[[1.], [2.], [3.]]], "float32")
    lens = np.array([3], "int32")
    good, = _run([lam], {"sc": perfect, "sc@LEN": lens,
                         "rel": perfect, "rel@LEN": lens})
    tch.reset_parser()
    sc = tch.data_layer("sc", size=1,
                        type=paddle.data_type.dense_vector_sequence(1))
    rel = tch.data_layer("rel", size=1,
                         type=paddle.data_type.dense_vector_sequence(1))
    lam = tch.lambda_cost(sc, rel, NDCG_num=3)
    bad, = _run([lam], {"sc": reversed_, "sc@LEN": lens,
                        "rel": perfect, "rel@LEN": lens})
    assert float(np.asarray(bad).ravel()[0]) > \
        float(np.asarray(good).ravel()[0])


def test_projections_and_operators_in_mixed():
    x = tch.data_layer("x", size=6)
    y = tch.data_layer("y", size=6)
    m1 = tch.mixed_layer(input=[tch.trans_full_matrix_projection(x,
                                                                 size=4)])
    m2 = tch.mixed_layer(input=[tch.scaling_projection(x)])
    m3 = tch.mixed_layer(
        input=[tch.slice_projection(x, slices=[(0, 2), (4, 6)])])
    m4 = tch.mixed_layer(input=[tch.dotmul_operator(x, y, scale=2.0)])
    xv = np.ones((2, 6), "float32")
    yv = np.full((2, 6), 3.0, "float32")
    v1_, v2_, v3_, v4_ = _run([m1, m2, m3, m4], {"x": xv, "y": yv})
    assert v1_.shape == (2, 4)
    assert v2_.shape == (2, 6)
    assert v3_.shape == (2, 4)
    np.testing.assert_allclose(v4_, 6.0 * np.ones((2, 6)), rtol=1e-6)


def test_context_projection_window():
    seq = tch.data_layer("seq", size=2,
                         type=paddle.data_type.dense_vector_sequence(2))
    m = tch.mixed_layer(
        input=[tch.context_projection(seq, context_len=3)])
    sv = np.arange(2 * 3 * 2, dtype="float32").reshape(2, 3, 2)
    out, = _run([m], {"seq": sv, "seq@LEN": np.array([3, 3], "int32")})
    assert out.shape == (2, 3, 6)
    # middle timestep's window = [t-1, t, t+1] concatenated
    np.testing.assert_allclose(out[0, 1], sv[0].reshape(-1), rtol=1e-6)


def test_detection_layers_build_and_run():
    img = tch.data_layer("img", size=3 * 16 * 16, height=16, width=16)
    feat = tch.img_conv_layer(img, filter_size=3, num_filters=4,
                              num_channels=3, stride=4, padding=1)
    pb = tch.priorbox_layer(feat, img, aspect_ratio=[2.0],
                            variance=[0.1, 0.1, 0.2, 0.2],
                            min_size=[4.0], max_size=[8.0])
    n_priors_total = None
    with cfg.build():
        half = int(pb.var.shape[0]) // 2
        n_priors_total = half
    loc = tch.fc_layer(feat, size=n_priors_total * 4)
    conf = tch.fc_layer(feat, size=n_priors_total * 3)
    with cfg.build():
        loc3 = fluid.layers.reshape(loc.var, shape=[0, -1, 4])
        conf3 = fluid.layers.reshape(conf.var, shape=[0, -1, 3])
    loc_l = cfg.Layer(loc3, parents=[loc])
    conf_l = cfg.Layer(conf3, parents=[conf])
    det = tch.detection_output_layer(loc_l, conf_l, pb, num_classes=3)

    gt = tch.data_layer("gt", size=5,
                        type=paddle.data_type.dense_vector_sequence(5))
    loss = tch.multibox_loss_layer(loc_l, conf_l, pb, gt, num_classes=3,
                                   max_gt_boxes=2)

    rois = tch.data_layer("rois", size=4)
    roi = tch.roi_pool_layer(feat, rois, pooled_width=2, pooled_height=2,
                             spatial_scale=0.25)

    rng = np.random.RandomState(5)
    gtv = np.zeros((2, 2, 5), "float32")
    gtv[:, :, 0] = 1                        # class 1
    gtv[:, :, 1:] = rng.rand(2, 2, 4) * 0.5
    gtv[:, :, 3:] = gtv[:, :, 1:3] + 0.3    # xmax/ymax > xmin/ymin
    outs = _run([det, loss, roi],
                {"img": rng.rand(2, 3 * 16 * 16).astype("float32"),
                 "gt": gtv, "gt@LEN": np.array([2, 2], "int32"),
                 "rois": np.array([[0, 0, 8, 8],
                                   [2, 2, 12, 12]], "float32")})
    assert np.isfinite(outs[1]).all()
    assert outs[2].shape[-2:] == (2, 2)


def test_networks_compose_and_run():
    seq = tch.data_layer("seq", size=6,
                         type=paddle.data_type.dense_vector_sequence(6))
    g1 = tch.simple_gru2(seq, size=4)
    g2 = tch.gru_group(tch.fc_layer(seq, size=12), size=4)
    g3 = tch.gru_unit(tch.fc_layer(seq, size=12), size=4)
    l1 = tch.lstmemory_group(tch.fc_layer(seq, size=16), size=4)
    l2 = tch.lstmemory_unit(tch.fc_layer(seq, size=16), size=4)
    bi = tch.bidirectional_gru(seq, size=4)
    bis = tch.bidirectional_gru(seq, size=4, return_seq=True)
    att = tch.multi_head_attention(seq, seq, seq, key_proj_size=3,
                                   value_proj_size=3, head_num=2)
    tcp = tch.text_conv_pool(seq, context_len=3, hidden_size=5)

    rng = np.random.RandomState(6)
    sv = rng.rand(2, 4, 6).astype("float32")
    lens = np.array([4, 4], "int32")
    outs = _run([g1, g2, g3, l1, l2, bi, bis, att, tcp],
                {"seq": sv, "seq@LEN": lens})
    assert outs[0].shape == (2, 4, 4)
    assert outs[5].shape == (2, 8)          # last-step concat
    assert outs[6].shape == (2, 4, 8)       # full-seq concat
    assert outs[7].shape[-1] == 6           # heads*value_proj
    assert outs[8].shape == (2, 5)


def test_image_networks_build():
    img = tch.data_layer("img", size=3 * 32 * 32, height=32, width=32)
    a = tch.img_conv_bn_pool(img, filter_size=3, num_filters=4,
                             pool_size=2, num_channel=3, conv_padding=1,
                             pool_stride=2,
                             conv_act=tch.ReluActivation())
    b = tch.img_separable_conv(img, num_channels=3, num_out_channels=8,
                               filter_size=3, padding=1,
                               act=tch.ReluActivation())
    sv = tch.small_vgg(img, num_channels=3, num_classes=10)
    vg = tch.vgg_16_network(img, num_channels=3, num_classes=10)
    # run the two cheap ones; the VGGs are shape-checked at build
    iv = np.random.RandomState(7).rand(1, 3 * 32 * 32).astype("float32")
    av, bv = _run([a, b], {"img": iv})
    assert av.shape == (1, 4, 16, 16)
    assert bv.shape == (1, 8, 32, 32)
    assert int(sv.var.shape[-1]) == 10 and int(vg.var.shape[-1]) == 10


def test_evaluators_register_and_run():
    x = tch.data_layer("x", size=3)
    probs = tch.fc_layer(x, size=3, act=tch.SoftmaxActivation())
    tch.evaluator_base(probs, name="base_eval")
    tch.maxid_printer_evaluator(probs, name="maxid_print")
    g = cfg.graph()
    names = [n for n, _, _ in g.evaluators]
    assert "base_eval" in names and "maxid_print" in names

    det = tch.data_layer("det", size=2 * 6)
    with cfg.build():
        det3 = fluid.layers.reshape(det.var, shape=[0, 2, 6])
    gt = tch.data_layer("gtl", size=2 * 5)
    with cfg.build():
        gt3 = fluid.layers.reshape(gt.var, shape=[0, 2, 5])
    m = tch.detection_map_evaluator(cfg.Layer(det3, parents=[det]),
                                    cfg.Layer(gt3, parents=[gt]),
                                    class_num=3)
    assert m is not None

    score = tch.data_layer("s", size=1)
    lbl = tch.data_layer("l", size=1)
    qid = tch.data_layer("q", size=1)
    pn = tch.pnpair_evaluator(score, lbl, qid)
    rng = np.random.RandomState(8)
    dv = np.zeros((2, 12), "float32")
    dv[:, 1] = 0.9                        # (label, score, x1..y2) rows
    gv = np.zeros((2, 10), "float32")
    pnv, = _run([cfg.Layer(pn, parents=[])] if not hasattr(pn, "var")
                else [pn],
                {"x": rng.rand(4, 3).astype("float32"),
                 "s": rng.rand(4, 1).astype("float32"),
                 "l": np.array([[1.], [0.], [1.], [0.]], "float32"),
                 "q": np.zeros((4, 1), "float32"),
                 "det": np.tile(dv[:1], (4, 1)),
                 "gtl": np.tile(gv[:1], (4, 1))})
    assert np.isfinite(np.asarray(pnv)).all()


def test_markers_and_refusals():
    assert tch.AggregateLevel.TO_NO_SEQUENCE
    assert tch.ExpandLevel.FROM_NO_SEQUENCE
    assert tch.LayerType.is_layer_type("fc")

    x = tch.data_layer("x", size=4)
    si = tch.StaticInput(x, is_seq=False)
    gi = tch.GeneratedInput(size=8, embedding_name="emb",
                            embedding_size=4)
    bi = tch.BeamInput(x, x, x)
    sub = tch.SubsequenceInput(x)
    assert si.input is x and gi.size == 8 and bi.gold is x
    assert sub.input is x

    @tch.layer_support()
    def passthrough():
        return 42
    assert passthrough() == 42

    with pytest.raises(NotImplementedError):
        tch.sub_nested_seq_layer(x, x)
    with pytest.raises(NotImplementedError):
        tch.cross_entropy_over_beam([])
