"""Per-request distributed tracing (ISSUE 17): span trees across
serving admission, prefill/decode, and cluster RPC.

Tier-1 coverage: pure assembly/breakdown units, the flag gate, the
disabled-is-free raising-monkeypatch A/B (plus zero extra warm-path
lowerings in both arms), a traced InferenceEngine end to end (complete
trees, breakdown sums, p99 exemplars, slot-recycling hygiene), RPC
span propagation over a real TCP MasterServer (including reconnect
``rpc_retry`` markers and the per-method latency histogram), cluster
membership-session spans, the watchdog's in-flight request dump, and
chrome-trace request lanes.  The GenerationEngine end-to-end trees
(prefill/decode/page spans, expiry terminals) are slow-marked like
every decoder-LM test."""

import json
import os
import socket
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import paddle_tpu as fluid                                  # noqa: E402
from paddle_tpu import monitor, profiler                    # noqa: E402
from paddle_tpu.cloud.server import MasterClient, MasterServer  # noqa: E402
from paddle_tpu.cluster.membership import ClusterMaster     # noqa: E402
from paddle_tpu.cluster.runtime import ClusterMember        # noqa: E402
from paddle_tpu.monitor import tracing                      # noqa: E402
from paddle_tpu.serving import InferenceEngine              # noqa: E402


@pytest.fixture(autouse=True)
def tracing_off_after():
    """Every test leaves tracing disabled and both the span buffer and
    the monitor state empty — telemetry never leaks across modules."""
    tracing.reset()
    yield
    tracing.disable()
    tracing.reset()
    monitor.disable()
    monitor.registry().reset()
    monitor.step_stats().reset()


@pytest.fixture
def saved_mlp(tmp_path):
    fluid.default_startup_program().random_seed = 7
    x = fluid.layers.data("x", shape=[6])
    h = fluid.layers.fc(x, size=8, act="relu")
    pred = fluid.layers.fc(h, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        fluid.io.save_inference_model(str(tmp_path / "m"), ["x"],
                                      [pred], exe)
    return str(tmp_path / "m")


def _drive(eng, n, rows=1):
    rng = np.random.RandomState(0)
    reqs = [eng.submit({"x": rng.rand(rows, 6).astype("float32")},
                       rows=rows)
            for _ in range(n)]
    for r in reqs:
        r.result(timeout=120)
    return reqs


# ---------------------------------------------------------------------------
# flag gate + pure units
# ---------------------------------------------------------------------------

def test_flag_flips_module_bool():
    assert not tracing.enabled()
    fluid.set_flags({"FLAGS_trace": True})
    assert tracing.enabled()
    fluid.set_flags({"FLAGS_trace": False})
    assert not tracing.enabled()
    tracing.enable()
    assert tracing.enabled()
    tracing.disable()
    assert not tracing.enabled()


def test_assemble_dedup_and_completeness():
    tracing.enable()
    s = tracing.Span("cluster_session", attrs={"host_id": "h"})
    s.emit_open()                      # open anchor
    with tracing.span("cluster/heartbeat", parent=s):
        pass
    # before the terminal re-emit: rooted but not complete
    trees = tracing.assemble(tracing.spans())
    t = trees[s.trace_id]
    assert t["root"]["status"] == "open" and not t["complete"]
    s.finish("ok")
    trees = tracing.assemble(tracing.spans())
    t = trees[s.trace_id]
    # terminal record replaced the open anchor (dedup by span_id)
    assert len(t["spans"]) == 2
    assert t["root"]["status"] == "ok" and t["complete"]
    # a dangling parent link breaks completeness
    orphan = dict(t["spans"][0], span_id="zz", parent_id="missing")
    trees = tracing.assemble(tracing.spans() + [orphan])
    assert not trees[s.trace_id]["complete"]


def test_breakdown_attribution_model():
    """padding = pad share of the dispatch; spec_reject = rejected
    draft share of the verify window; stages sum to root latency."""
    tracing.enable()
    rt = tracing.RequestTrace("req-x", kind="generate", length=12)
    t0 = tracing.now_us()
    rt.admitted(16, 3, False)
    rt.note_prefill(t0, 8000.0, 0, 2, 16, 4)      # 8ms, 4/16 padding
    rt.note_decode(t0, 4000.0, 0, 1, 2,
                   spec_accepted=2, spec_proposed=3)   # 1 of 3 rejected
    rt.finish("ok")
    tree = tracing.assemble(tracing.spans())[rt.trace_id]
    assert tree["complete"]
    bd = tracing.breakdown(tree)
    st = bd["stages"]
    assert st["padding"] == pytest.approx(8.0 * 4 / 16)
    assert st["prefill"] == pytest.approx(8.0 - st["padding"])
    assert st["spec_reject"] == pytest.approx(4.0 * 1 / 4)
    assert st["decode"] == pytest.approx(4.0 - st["spec_reject"])
    # the synthetic children overrun the (instant) root, so the
    # unattributed remainder clamps to zero rather than going negative
    assert st["other"] == 0.0
    assert bd["attributed_ms"] == pytest.approx(
        sum(v for k, v in st.items() if k != "other"))
    summ = tracing.breakdown_summary({rt.trace_id: tree})
    assert summ["complete"] == 1 and summ["complete_fraction"] == 1.0
    assert "spec_reject" in tracing.render_table(summ)


def test_pre_admission_failure_closes_queue_wait():
    tracing.enable()
    rt = tracing.RequestTrace("req-y", kind="infer", length=1)
    rt.finish("expired", error="timed out")
    tree = tracing.assemble(tracing.spans())[rt.trace_id]
    assert tree["complete"] and tree["root"]["status"] == "expired"
    names = {s["name"]: s for s in tree["spans"]}
    assert names["queue_wait"]["status"] == "expired"
    rt.finish("ok")                    # terminal is idempotent
    tree = tracing.assemble(tracing.spans())[rt.trace_id]
    assert tree["root"]["status"] == "expired"


# ---------------------------------------------------------------------------
# disabled is free (the goodput precedent: raising monkeypatch A/B)
# ---------------------------------------------------------------------------

def test_disabled_path_performs_zero_tracing_calls(saved_mlp,
                                                   monkeypatch):
    """With FLAGS_trace off, the serving path must never reach a
    tracing call: every producer site is gated on ``enabled()`` or the
    ``req.trace is None`` it decided.  The monkeypatch raises from the
    emit path AND both span constructors, so any ungated call fails
    the request loudly."""
    def boom(*a, **k):
        raise AssertionError("tracing call on the disabled path")

    monkeypatch.setattr(tracing, "_emit", boom)
    monkeypatch.setattr(tracing.Span, "__init__", boom)
    monkeypatch.setattr(tracing.RequestTrace, "__init__", boom)
    assert not tracing.enabled()
    eng = InferenceEngine(model_dir=saved_mlp, slots=2, timeout_s=60.0)
    try:
        reqs = _drive(eng, 4)
        assert all(r.trace is None for r in reqs)
        assert eng.metrics.summary()["counts"]["completed"] == 4
        assert eng.metrics.p99_exemplars() == []
    finally:
        eng.close()
    assert tracing.spans() == []


def test_no_extra_lowerings_in_either_arm(saved_mlp):
    """Tracing must not perturb the compiled signature set: the warm
    engine serves traced and untraced windows through the same cached
    executables (zero extra warm-path lowerings in both arms)."""
    eng = InferenceEngine(model_dir=saved_mlp, slots=2, timeout_s=60.0)
    try:
        _drive(eng, 3)                       # warm (untraced arm)
        sigs = len(eng._exe._cache)
        assert sigs >= 1
        tracing.enable()
        _drive(eng, 3)                       # traced arm
        assert len(eng._exe._cache) == sigs
        tracing.disable()
        _drive(eng, 3)                       # back off
        assert len(eng._exe._cache) == sigs
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# InferenceEngine end to end (tier-1)
# ---------------------------------------------------------------------------

def test_inference_engine_traces_assemble_complete(saved_mlp):
    tracing.enable()
    eng = InferenceEngine(model_dir=saved_mlp, slots=2, timeout_s=60.0)
    try:
        reqs = _drive(eng, 8)
        summ = eng.metrics.summary()
    finally:
        eng.close()
    trees = tracing.assemble(tracing.spans())
    ts = tracing.breakdown_summary(trees)
    assert ts["requests"] == 8 and ts["complete"] == 8
    assert ts["complete_fraction"] == 1.0
    # slot-recycling hygiene: 8 requests over 2 slots — every request
    # kept its OWN trace identity (trace is keyed by request, never by
    # the slot it recycled)
    tids = [r.trace.trace_id for r in reqs]
    assert len(set(tids)) == 8
    for r in reqs:
        tree = trees[r.trace.trace_id]
        assert tree["complete"]
        root = tree["root"]
        assert root["name"] == "request" and root["status"] == "ok"
        assert root["attrs"]["request_id"] == r.id
        # every parent link resolves to the request's own root
        names = {s["name"] for s in tree["spans"]}
        assert {"request", "queue_wait", "batch"} <= names
        bd = tracing.breakdown(tree)
        # stage attribution sums to the root latency within 5%
        total = sum(bd["stages"].values())
        assert total == pytest.approx(bd["latency_ms"],
                                      rel=0.05, abs=0.5)
    # p99 exemplars resolve to assembled trees
    ex = summ["p99_exemplars"]
    assert ex and all(t in trees for t in ex)
    assert ex == eng.metrics.p99_exemplars()


def test_chrome_export_renders_request_lanes(saved_mlp, tmp_path):
    tracing.enable()
    eng = InferenceEngine(model_dir=saved_mlp, slots=2, timeout_s=60.0)
    try:
        reqs = _drive(eng, 3)
    finally:
        eng.close()
    path = profiler.export_chrome_tracing(str(tmp_path / "t.json"))
    data = json.load(open(path))
    evs = data["traceEvents"]
    lanes = [e for e in evs if e.get("ph") == "M"
             and e.get("name") == "thread_name"
             and str(e.get("args", {}).get("name", "")).startswith(
                 "req ")]
    assert len(lanes) == 3
    req_events = [e for e in evs if e.get("ph") == "X"
                  and e.get("args", {}).get("trace_id")]
    assert {e["args"]["trace_id"] for e in req_events} \
        == {r.trace.trace_id for r in reqs}
    # request lanes live in their own synthetic process group
    assert all(e["pid"] != os.getpid() for e in req_events)


def test_watchdog_probe_names_inflight_requests(saved_mlp):
    """The stall dump lists in-flight serving requests (trace_id, age,
    state) next to the last-program fingerprint."""
    tracing.enable()
    rng = np.random.RandomState(0)
    eng = InferenceEngine(model_dir=saved_mlp, slots=2, timeout_s=60.0,
                          start=False)        # loop off: stays queued
    try:
        req = eng.submit({"x": rng.rand(1, 6).astype("float32")})
        probe = monitor._stall_probe()
        inflight = probe["serving_requests"]
        assert [r["id"] for r in inflight] == [req.id]
        assert inflight[0]["trace_id"] == req.trace.trace_id
        assert inflight[0]["state"] == "queued"
        assert inflight[0]["age_s"] >= 0.0
        # the human-facing dump renders the request line
        text = monitor._format_diag(probe)
        assert req.id in text and req.trace.trace_id in text
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# RPC propagation (cross-process envelope, in one process over TCP)
# ---------------------------------------------------------------------------

class _EchoService:
    @staticmethod
    def rpc_methods():
        return ("echo",)

    def echo(self, v):
        return v


def test_rpc_spans_propagate_across_tcp():
    tracing.enable()
    srv = MasterServer(_EchoService()).start()
    client = MasterClient(srv.address, timeout=10.0)
    try:
        root = tracing.Span("test_session")
        with tracing.use_span(root):
            assert client.call("echo", 41) == 41
        root.finish("ok")
    finally:
        client.close()
        srv.shutdown()
    by_name = {}
    for s in tracing.spans():
        by_name.setdefault(s["name"], []).append(s)
    (cli,) = by_name["rpc/echo"]
    # the server leg open-anchors on entry (a handler killed mid-call
    # leaves a resolvable parent behind) and re-emits terminally;
    # assembly dedups to the terminal record
    statuses = [s["status"] for s in by_name["rpc_server/echo"]]
    assert statuses == ["open", "ok"]
    serv = by_name["rpc_server/echo"][-1]
    # one tree: client leg under the session, server leg under the
    # client leg (the envelope carried the context across the socket)
    assert cli["trace_id"] == root.trace_id
    assert cli["parent_id"] == root.span_id
    assert serv["trace_id"] == cli["trace_id"]
    assert serv["parent_id"] == cli["span_id"]
    assert cli["status"] == "ok" and cli["attrs"]["attempts"] == 1
    tree = tracing.assemble(tracing.spans())[root.trace_id]
    assert tree["complete"] and len(tree["spans"]) == 3


def test_rpc_reconnect_emits_retry_spans_and_fails_typed():
    tracing.enable()
    # a port with nothing listening: connect fails fast (ECONNREFUSED)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    client = MasterClient("127.0.0.1:%d" % port, timeout=0.5,
                          retry_interval=0.01, max_retries=3,
                          max_retry_interval=0.02, jitter=0.0)
    with pytest.raises(ConnectionError):
        client.ping()
    client.close()
    spans = tracing.spans()
    retries = [s for s in spans if s["name"] == "rpc_retry"]
    # attempts 1 and 2 emit markers; the final attempt reports through
    # the rpc span's terminal instead of a trailing sleep
    assert [r["attrs"]["attempt"] for r in retries] == [1, 2]
    assert all(r["attrs"]["method"] == "ping"
               and r["attrs"]["backoff_s"] > 0 for r in retries)
    (rpc,) = [s for s in spans if s["name"] == "rpc/ping"]
    assert rpc["status"] == "error"
    assert rpc["attrs"]["attempts"] == 3
    assert rpc["attrs"]["error"] == "unreachable"
    # the retry markers are children of the rpc span, one tree
    assert all(r["trace_id"] == rpc["trace_id"]
               and r["parent_id"] == rpc["span_id"] for r in retries)


def test_rpc_latency_histogram_per_method(tmp_path):
    monitor.enable(log_dir=str(tmp_path))
    srv = MasterServer(_EchoService()).start()
    client = MasterClient(srv.address, timeout=10.0)
    try:
        for _ in range(3):
            client.call("echo", 1)
        client.ping()
    finally:
        client.close()
        srv.shutdown()
    text = monitor.expose_text()
    assert "rpc/echo_seconds" in text.replace('"', "") \
        or "rpc_echo_seconds" in text
    assert "echo" in text and "ping" in text


# ---------------------------------------------------------------------------
# cluster membership-session spans
# ---------------------------------------------------------------------------

def test_cluster_session_spans_join_one_tree():
    tracing.enable()
    cm = ClusterMaster(lease_timeout=30.0)
    m = ClusterMember(cm, "host-a", auto_heartbeat=False,
                      register_local=False)
    m.heartbeat()
    res = m.enter_step(0, timeout=5)
    assert res["action"] == "go"
    m.close()
    trees = tracing.assemble(tracing.spans())
    # exactly one cluster tree: session root + join/heartbeat/barrier
    (tree,) = [t for t in trees.values()
               if t["root"] is not None
               and t["root"]["name"] == "cluster_session"]
    assert tree["complete"]
    assert tree["root"]["status"] == "ok"
    assert tree["root"]["attrs"]["host_id"] == "host-a"
    names = [s["name"] for s in tree["spans"]]
    assert "cluster/heartbeat" in names and "cluster/barrier" in names
    (bar,) = [s for s in tree["spans"]
              if s["name"] == "cluster/barrier"]
    assert bar["attrs"]["action"] == "go" and bar["attrs"]["polls"] == 1
    # breakdown ignores non-request roots
    assert tracing.breakdown(tree) is None


# ---------------------------------------------------------------------------
# GenerationEngine end to end (slow, like every decoder-LM test)
# ---------------------------------------------------------------------------

_DIMS = dict(n_layer=1, n_head=2, d_model=16, d_inner=32)


@pytest.mark.slow
def test_generation_engine_traces_with_pages_and_recycling():
    from paddle_tpu.serving.decoder import build_decoder_lm
    from paddle_tpu.serving.engine import GenerationEngine

    tracing.enable()
    V, L, S, PS = 31, 32, 2, 8
    spec = build_decoder_lm(V, L, S, paged=True, page_size=PS,
                            prefix="trg", **_DIMS)
    eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                           max_new_tokens=4, timeout_s=300.0)
    try:
        # 6 requests over 2 slots: recycling plus paged back-pressure
        reqs = [eng.submit(list(range(2, 2 + PS)) + [9 + i])
                for i in range(6)]
        outs = [r.result(600) for r in reqs]
    finally:
        eng.close()
    assert all(o["tokens"] for o in outs)
    trees = tracing.assemble(tracing.spans())
    assert len({r.trace.trace_id for r in reqs}) == 6   # hygiene
    for r in reqs:
        tree = trees[r.trace.trace_id]
        assert tree["complete"], tree
        names = {s["name"] for s in tree["spans"]}
        assert {"request", "queue_wait", "prefill", "page_alloc",
                "decode"} <= names
        root = tree["root"]
        assert root["attrs"]["request_id"] == r.id
        assert root["attrs"]["ticks"] >= 1
        decodes = [s for s in tree["spans"] if s["name"] == "decode"]
        # slot id rides every tick; the recycled slot belongs to THIS
        # request's spans only
        assert len({s["attrs"]["slot"] for s in decodes}) == 1
        bd = tracing.breakdown(tree)
        assert sum(bd["stages"].values()) == pytest.approx(
            bd["latency_ms"], rel=0.05, abs=0.5)
    summ = tracing.breakdown_summary(trees)
    assert summ["complete_fraction"] == 1.0
    assert summ["stages"]["decode"]["p99_ms"] > 0


@pytest.mark.slow
def test_generation_engine_expired_request_has_terminal_tree():
    from paddle_tpu.serving.decoder import build_decoder_lm
    from paddle_tpu.serving.engine import GenerationEngine
    from paddle_tpu.serving.scheduler import RequestTimeoutError

    tracing.enable()
    V, L, S, PS = 31, 32, 2, 8
    spec = build_decoder_lm(V, L, S, paged=True, page_size=PS,
                            prefix="tre", **_DIMS)
    eng = GenerationEngine(spec, place=fluid.CPUPlace(),
                           max_new_tokens=4, timeout_s=300.0,
                           start=False)
    try:
        req = eng.submit([2, 3, 4], timeout_s=0.01)
        import time as _t
        _t.sleep(0.05)
        eng.start()                     # first admit expires it
        with pytest.raises(RequestTimeoutError):
            req.result(60)
    finally:
        eng.close()
    tree = tracing.assemble(tracing.spans())[req.trace.trace_id]
    assert tree["complete"]
    assert tree["root"]["status"] == "expired"
    names = {s["name"]: s for s in tree["spans"]}
    # never admitted: queue_wait closed by the terminal, no dispatch
    assert names["queue_wait"]["status"] == "expired"
    assert "prefill" not in names and "decode" not in names
