"""Book e2e: label_semantic_roles — the db_lstm SRL model (reference
``python/paddle/fluid/tests/book/test_label_semantic_roles.py``): 8
embedded input features (word, 5 context words, predicate, mark), a
stack of alternating-direction LSTMs with direct mix edges, and a
linear-chain CRF cost, decoded with crf_decoding.  Miniature scale,
same topology shape; trains until the CRF NLL drops, then decodes.
"""

import numpy as np

import paddle_tpu as fluid

WORD_VOCAB = 30
PRED_VOCAB = 10
MARK_VOCAB = 2
WORD_DIM = 8
MARK_DIM = 4
HIDDEN = 32          # lstm hidden = HIDDEN // 4
DEPTH = 4
NUM_LABELS = 6
FEATURES = ["word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2",
            "predicate", "mark"]


def _db_lstm(inputs):
    """The 8-feature mixed bi-LSTM trunk (reference db_lstm)."""
    word_feats = [inputs[n] for n in FEATURES[:6]]
    embs = [fluid.layers.embedding(
        x, size=[WORD_VOCAB, WORD_DIM],
        param_attr=fluid.ParamAttr(name="emb")) for x in word_feats]
    embs.append(fluid.layers.embedding(
        inputs["predicate"], size=[PRED_VOCAB, WORD_DIM],
        param_attr=fluid.ParamAttr(name="vemb")))
    embs.append(fluid.layers.embedding(
        inputs["mark"], size=[MARK_VOCAB, MARK_DIM]))

    hidden_0 = fluid.layers.sums(
        [fluid.layers.fc(e, size=HIDDEN, num_flatten_dims=2) for e in embs])
    hidden_0._seq_len_name = inputs["word"]._seq_len_name
    lstm_0, _ = fluid.layers.dynamic_lstm(
        hidden_0, size=HIDDEN, candidate_activation="relu",
        gate_activation="sigmoid", cell_activation="sigmoid")

    input_tmp = [hidden_0, lstm_0]
    for i in range(1, DEPTH):
        mix = fluid.layers.sums([
            fluid.layers.fc(input_tmp[0], size=HIDDEN, num_flatten_dims=2),
            fluid.layers.fc(input_tmp[1], size=HIDDEN, num_flatten_dims=2),
        ])
        mix._seq_len_name = inputs["word"]._seq_len_name
        lstm, _ = fluid.layers.dynamic_lstm(
            mix, size=HIDDEN, candidate_activation="relu",
            gate_activation="sigmoid", cell_activation="sigmoid",
            is_reverse=(i % 2 == 1))
        input_tmp = [mix, lstm]

    feature_out = fluid.layers.sums([
        fluid.layers.fc(input_tmp[0], size=NUM_LABELS, num_flatten_dims=2,
                        act="tanh"),
        fluid.layers.fc(input_tmp[1], size=NUM_LABELS, num_flatten_dims=2,
                        act="tanh"),
    ])
    feature_out._seq_len_name = inputs["word"]._seq_len_name
    return feature_out


def _synthetic_batch(rng, b, t):
    feeds = {}
    lens = rng.randint(3, t + 1, (b,)).astype("int32")
    for name, vocab in zip(FEATURES, [WORD_VOCAB] * 6 + [PRED_VOCAB,
                                                         MARK_VOCAB]):
        feeds[name] = rng.randint(0, vocab, (b, t, 1)).astype("int64")
        feeds[name + "@LEN"] = lens
    # learnable tagging: the label is a deterministic function of the
    # word id (plus the mark bit), so the trunk can fit it
    feeds["target"] = ((feeds["word"] + feeds["mark"]) %
                       NUM_LABELS).astype("int64")
    feeds["target@LEN"] = lens
    return feeds


def test_label_semantic_roles_trains_and_decodes():
    rng = np.random.RandomState(7)
    b, t = 8, 7
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_startup_program().random_seed = 11
        inputs = {n: fluid.layers.data(n, shape=[1], dtype="int64",
                                       lod_level=1) for n in FEATURES}
        target = fluid.layers.data("target", shape=[1], dtype="int64",
                                   lod_level=1)
        feature_out = _db_lstm(inputs)
        crf_cost = fluid.layers.linear_chain_crf(
            feature_out, target,
            param_attr=fluid.ParamAttr(name="crfw"))
        avg_cost = fluid.layers.mean(crf_cost)
        # viterbi decode shares the trained transitions; built before
        # minimize so the inference clone carries no optimizer ops
        # (reference book flow: crf_decoding in the main program, the
        # saved inference model pruned to it)
        decode = fluid.layers.crf_decoding(
            feature_out, param_attr=fluid.ParamAttr(name="crfw"))
        infer = fluid.default_main_program().clone(
            for_test=True).prune_feed_fetch(
                [n for n in FEATURES] + [n + "@LEN" for n in FEATURES],
                [decode.name])
        # the book config uses SGD w/ decaying lr on the real dataset;
        # plain SGD suffices at miniature scale
        fluid.optimizer.SGD(learning_rate=0.02).minimize(avg_cost)

        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            batch = _synthetic_batch(rng, b, t)
            losses = []
            for _ in range(30):
                (lv,) = exe.run(feed=batch, fetch_list=[avg_cost])
                losses.append(float(np.asarray(lv).ravel()[0]))
            assert all(np.isfinite(losses)), losses
            assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])

            (path,) = exe.run(infer, feed={
                k: v for k, v in batch.items() if not k.startswith("target")
            }, fetch_list=[decode.name])
            path = np.asarray(path)
            assert path.shape[:2] == (b, t)
            assert path.min() >= 0 and path.max() < NUM_LABELS
