"""paddle_tpu benchmark CLI — prints ONE JSON line for the driver.

Methodology mirrors the reference's ``benchmark/fluid/fluid_benchmark.py``
(args.py: ``--iterations``, ``--skip_batch_num`` warmup; per-batch
wall-clock; throughput includes forward + backward + parameter update,
benchmark/IntelOptimizedPaddle.md:25).

The default (``--model auto``) measures the full flagship ladder and
emits every metric in the single JSON line: ResNet-50 and
Transformer-base, each in bf16 mixed precision (the A100 comparison
numbers are fp16, so bf16 is the apples-to-apples dtype) and fp32, plus
a reader-included ResNet-50 variant (the ``--use_reader_op`` analog:
fresh host batches crossing the host->device link every step).  The
top-level metric is ResNet-50 bf16; the rest ride in ``extra_metrics``.

``vs_baseline`` targets (BASELINE.json north star, 0.9x A100):
ResNet-50 ~2900 img/s fp16 => 2610; Transformer-base ~95k tok/s => 85.5k.
"""

import argparse
import json
import time

import numpy as np

RESNET_TARGET = 2900.0 * 0.9
TRANSFORMER_TARGET = 95000.0 * 0.9


def _bench_program(main, startup, feed_fn, fetch, place, iterations,
                   skip_batch_num, per_step_feed=False):
    """Measure mean step seconds.  ``per_step_feed`` re-feeds a fresh
    host batch every iteration (reader-included methodology,
    fluid_benchmark.py --use_reader_op); otherwise the feed is staged on
    device once and the loop measures pure compute."""
    import paddle_tpu as fluid

    import jax
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(place)
        exe.run(startup)
        dev = place.jax_device()
        if per_step_feed:
            # fresh host batches cross the host->device link every step
            feeds = [feed_fn() for _ in range(max(4, skip_batch_num))]
        else:
            # stage one feed on device — the input pipeline's job; keeps
            # the measured loop free of host-link transfers
            feeds = [{k: jax.device_put(v, dev)
                      for k, v in feed_fn().items()}]
        for i in range(skip_batch_num):
            exe.run(main, feed=feeds[i % len(feeds)], fetch_list=[fetch],
                    return_numpy=False)
        # two measurement windows, keep the faster: the tunnel-shared
        # chip suffers long-lived contention windows, and min-time is
        # the standard way to measure the machine rather than the noise
        best = None
        last = None
        for _ in range(2):
            t0 = time.perf_counter()
            for i in range(iterations):
                # async dispatch: loss stays on device; sync at the end
                last = exe.run(main, feed=feeds[i % len(feeds)],
                               fetch_list=[fetch], return_numpy=False)
            jax.block_until_ready(last)
            elapsed = time.perf_counter() - t0
            best = elapsed if best is None else min(best, elapsed)
    assert np.isfinite(
        np.asarray(last[0], dtype=np.float32)).all()
    return best / iterations


def _maybe_amp(optimizer, use_amp):
    if use_amp:
        from paddle_tpu.contrib import mixed_precision
        return mixed_precision.decorate(optimizer)
    return optimizer


def bench_mlp(args, use_amp=False, per_step_feed=False):
    import paddle_tpu as fluid

    batch = args.batch_size or 256
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("img", shape=[784])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, size=256, act="relu")
        h = fluid.layers.fc(h, size=256, act="relu")
        pred = fluid.layers.fc(h, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        _maybe_amp(fluid.optimizer.Adam(learning_rate=1e-3),
                   use_amp).minimize(loss)

        rng = np.random.RandomState(0)

        def feed_fn():
            return {"img": rng.rand(batch, 784).astype("float32"),
                    "label": rng.randint(0, 10, (batch, 1)).astype("int64")}

        step_time = _bench_program(
            fluid.default_main_program(), fluid.default_startup_program(),
            feed_fn, loss, _place(args), args.iterations,
            args.skip_batch_num, per_step_feed)
    ips = batch / step_time
    return {"metric": "mnist_mlp_images_per_sec" + _suffix(use_amp,
                                                           per_step_feed),
            "value": round(ips, 2), "unit": "images/sec",
            "vs_baseline": 1.0}


def bench_resnet50(args, use_amp=False, per_step_feed=False):
    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import resnet_imagenet

    batch = args.batch_size or 128
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("img", shape=[3, 224, 224])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = resnet_imagenet(img, class_dim=1000, depth=50)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        # small lr: benchmark data is random noise; higher rates diverge
        _maybe_amp(fluid.optimizer.Momentum(learning_rate=1e-3,
                                            momentum=0.9),
                   use_amp).minimize(loss)

        rng = np.random.RandomState(0)

        def feed_fn():
            return {
                "img": rng.rand(batch, 3, 224, 224).astype("float32"),
                "label": rng.randint(0, 1000, (batch, 1)).astype("int64"),
            }

        step_time = _bench_program(
            fluid.default_main_program(), fluid.default_startup_program(),
            feed_fn, loss, _place(args), args.iterations,
            args.skip_batch_num, per_step_feed)
    ips = batch / step_time
    return {"metric": "resnet50_images_per_sec" + _suffix(use_amp,
                                                          per_step_feed),
            "value": round(ips, 2), "unit": "images/sec",
            "vs_baseline": round(ips / RESNET_TARGET, 4)}


def bench_transformer(args, use_amp=False, per_step_feed=False):
    """Transformer-base fwd+bwd+Adam tokens/sec (BASELINE config 3)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    batch = args.batch_size or 64
    seq_len = 64
    vocab = 32000
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                                lod_level=1)
        label = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                                  lod_level=1)
        cost, _ = tfm.transformer(src, tgt, label, seq_len, seq_len, vocab,
                                  vocab, n_layer=6, n_head=8, d_model=512,
                                  d_inner=2048, dropout_rate=0.1)
        lr = fluid.layers.noam_decay(512, 4000)
        _maybe_amp(fluid.optimizer.Adam(learning_rate=lr, beta1=0.9,
                                        beta2=0.997, epsilon=1e-9),
                   use_amp).minimize(cost)

        rng = np.random.RandomState(0)

        def feed_fn():
            ids = rng.randint(2, vocab, (batch, seq_len, 1)).astype("int64")
            lens = np.full((batch,), seq_len, "int32")
            return {"src_word": ids, "src_word@LEN": lens,
                    "tgt_word": ids, "tgt_word@LEN": lens,
                    "lbl_word": ids, "lbl_word@LEN": lens}

        step_time = _bench_program(
            fluid.default_main_program(), fluid.default_startup_program(),
            feed_fn, cost, _place(args), args.iterations,
            args.skip_batch_num, per_step_feed)
    tps = batch * seq_len / step_time
    return {"metric": "transformer_base_tokens_per_sec" + _suffix(
                use_amp, per_step_feed),
            "value": round(tps, 2), "unit": "tokens/sec",
            "vs_baseline": round(tps / TRANSFORMER_TARGET, 4)}


def _suffix(use_amp, per_step_feed):
    s = "_bf16" if use_amp else ""
    if per_step_feed:
        s += "_with_reader"
    return s


def _place(args):
    import jax
    import paddle_tpu as fluid
    if args.device == "cpu":
        return fluid.CPUPlace()
    if not any(d.platform != "cpu" for d in jax.devices()):
        raise SystemExit("--device tpu requested but no TPU device present")
    return fluid.TPUPlace(0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="auto",
                   choices=["auto", "mlp", "resnet50", "transformer"])
    p.add_argument("--device", default="auto", choices=["auto", "cpu", "tpu"])
    p.add_argument("--batch_size", type=int, default=0)
    p.add_argument("--iterations", type=int, default=20)
    p.add_argument("--skip_batch_num", type=int, default=5)
    p.add_argument("--fp32_only", action="store_true")
    p.add_argument("--with_reader", action="store_true",
                   help="re-feed fresh host batches every step")
    args = p.parse_args()

    import jax
    if args.device == "auto":
        args.device = (
            "tpu" if any(d.platform != "cpu" for d in jax.devices()) else "cpu"
        )

    if args.model == "auto":
        # Full flagship ladder, primary = ResNet-50 bf16 (the dtype that
        # matches the A100 fp16 comparison numbers).  Each entry runs in
        # its OWN subprocess: sharing one XLA client across models
        # degrades later entries >20x (stale executables/buffers from
        # earlier ladder rungs), and isolation is the honest methodology
        # anyway (fluid_benchmark runs one model per invocation).
        import subprocess
        import sys

        runs = [
            ("resnet50", []),
            ("resnet50", ["--fp32_only"]),
            ("transformer", []),
            ("transformer", ["--fp32_only"]),
            ("resnet50", ["--with_reader"]),
        ]
        results = []
        for i, (model, extra) in enumerate(runs):
            if i:
                time.sleep(10)   # let the previous client release the chip
            cmd = [sys.executable, __file__, "--model", model,
                   "--device", args.device,
                   "--iterations", str(args.iterations),
                   "--skip_batch_num", str(args.skip_batch_num)] + extra
            if args.batch_size:
                cmd += ["--batch_size", str(args.batch_size)]
            detail = None
            for attempt in range(2):   # one retry: tunnel errors are
                try:                   # transient (remote_compile drops)
                    out = subprocess.run(
                        cmd, stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE, text=True, timeout=1800,
                        check=True).stdout
                    results.append(
                        json.loads(out.strip().splitlines()[-1]))
                    detail = None
                    break
                except Exception as e:  # noqa: BLE001 — keep the ladder
                    detail = str(e)
                    stderr = getattr(e, "stderr", None)
                    if stderr:
                        detail += " | stderr: " + stderr[-400:]
                    if attempt == 0:
                        time.sleep(20)   # settle before the one retry
            if detail is not None:
                results.append({"metric": "%s%s_error" % (model,
                                "".join(extra).replace("--", "_")),
                                "value": 0.0, "unit": "error",
                                "vs_baseline": 0.0, "error": detail[:600]})
        primary = dict(results[0])
        primary["extra_metrics"] = results[1:]
        print(json.dumps(primary))
        return

    fn = {"resnet50": bench_resnet50, "transformer": bench_transformer,
          "mlp": bench_mlp}[args.model]
    result = fn(args, use_amp=not args.fp32_only,
                per_step_feed=args.with_reader)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
