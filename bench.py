"""paddle_tpu benchmark CLI — emits driver-parseable JSON on stdout.

Single-model invocations print ONE JSON line.  The auto ladder prints
one enriched primary line after EVERY completed rung — the LAST line is
authoritative (``ladder_complete: true`` when the ladder finished) — so
a driver-side timeout kills rungs, never the artifact.

Methodology mirrors the reference's ``benchmark/fluid/fluid_benchmark.py``
(args.py: ``--iterations``, ``--skip_batch_num`` warmup; per-batch
wall-clock; throughput includes forward + backward + parameter update,
benchmark/IntelOptimizedPaddle.md:25).

The default (``--model auto``) measures the full flagship ladder and
emits every metric in the single JSON line: ResNet-50 and
Transformer-base, each in bf16 mixed precision (the A100 comparison
numbers are fp16, so bf16 is the apples-to-apples dtype) and fp32, plus
a reader-included ResNet-50 variant (the ``--use_reader_op`` analog:
fresh host batches crossing the host->device link every step).  The
top-level metric is ResNet-50 bf16; the rest ride in ``extra_metrics``.

``vs_baseline`` targets (BASELINE.json north star, 0.9x A100):
ResNet-50 ~2900 img/s fp16 => 2610; Transformer-base ~95k tok/s => 85.5k.

Timing is synced by FETCHING the final loss scalar to the host, not by
``jax.block_until_ready``: through this setup's tunnel the latter returns
before device execution completes, so block-synced windows measure
dispatch rate — numbers recorded before r3's fix (BENCH_r01/r02) are
inflated 2-4.5x by exactly that artifact and are not comparable.
"""

import argparse
import contextlib
import json
import time

import numpy as np

RESNET_TARGET = 2900.0 * 0.9
TRANSFORMER_TARGET = 95000.0 * 0.9

# artifact schema version, stamped top-level on every emitted JSON line
# together with the run correlation id: tools/bench_history.py keys its
# cross-run index on them.  Version 1 is the implicit pre-stamp format
# (BENCH_r01-r04: no schema_version/run_id/goodput fields); version 2
# adds the stamps and the per-rung goodput attribution summary.
SCHEMA_VERSION = 2

# chip peak for the est_mfu observability field (VERDICT r2 #7): bf16
# matmul peak in TFLOP/s; default is v5e (197).  Override via
# BENCH_PEAK_TFLOPS — one definition shared with the program-profile
# report so bench MFU and program-report MFU use the same denominator.
import os  # noqa: F401  (env reads elsewhere in this file)
from paddle_tpu.monitor.program_profile import (
    DEFAULT_PEAK_TFLOPS as PEAK_TFLOPS)

# --exact_mfu: report XLA cost-analysis exact flops/bytes alongside the
# conservative est_mfu heuristic (set in main)
EXACT_MFU = False

# --sync_feed: disable the reader-included path's prefetch overlap
# (blocking per-step feed conversion + transfer) — the synchronous half
# of the async-pipeline A/B (set in main)
SYNC_FEED = False

# --autotune: run the profile-guided batch-size tuner before the rung
# (paddle_tpu.autotune) and embed the TunedConfig evidence in the
# artifact; an explicit --batch_size pins and skips tuning (set in main)
AUTOTUNE = False

# model step-FLOPs estimates (fwd+bwd+update ~= 3x fwd), used only for
# the est_mfu observability field
FLOPS_PER_ITEM = {
    # ResNet-50 @224: ~4.1 GFLOP fwd/image
    "resnet50": 3 * 4.1e9,
    # Transformer-base enc-dec: active matmul params ~60.5M (enc 18.9M +
    # dec 25.2M + logits 16.4M) -> 2*60.5M fwd FLOPs/token
    "transformer": 3 * 2 * 60.5e6,
    "mlp": 3 * 2 * (784 * 256 + 256 * 256 + 256 * 10),
}

# min-of-windows is the estimator; the shared tunneled chip's noise is
# +/-2% between invocations (and load is bursty), so more windows
# tighten the min's variance — 7 spans ~70s of chip time per rung.
# The auto ladder overrides this per rung (--n_windows): the headline
# keeps 7, secondary rungs run 3 so the ladder fits the driver budget.
N_WINDOWS = 7


class _PassthroughFeeder:
    """PyReader feeder adapter: the bench reader already yields feed
    dicts (DataFeeder's job is sample->batch conversion, done here at
    pool-build time)."""

    def feed(self, rows):
        return rows


def _bench_program(main, startup, feed_fn, fetch, place, iterations,
                   skip_batch_num, per_step_feed=False, model="",
                   batch=0, reader_creator=None, post_startup=None):
    """Measure step seconds over N_WINDOWS windows; returns a stats dict.

    ``per_step_feed`` = reader-included methodology (fluid_benchmark.py
    --use_reader_op): fresh host batches cross the host->device link
    every step, staged ahead by the framework's own PyReader
    double-buffer thread so the transfer overlaps compute (the
    create_double_buffer_reader_op.cc capability).  Otherwise one feed
    is staged on device and the loop measures pure compute."""
    import paddle_tpu as fluid
    from paddle_tpu import monitor

    import jax
    # rungs run with always-on telemetry: the same StepStats records a
    # production run logs land in the BENCH artifact (step_stats below),
    # and the rung doubles as the monitor-on overhead check
    if not monitor.enabled():
        fluid.set_flags({"FLAGS_monitor": True})
    monitor.step_stats().reset()
    # per-rung program accounting: without this, A/B rungs that share a
    # program fingerprint (e.g. pallas on/off) would merge their steps/
    # wall clock and the rung's program_report MFU would be a blend
    from paddle_tpu.monitor import program_profile
    program_profile.reset_accounting()
    # per-rung goodput attribution: each rung's artifact carries its own
    # exclusive wall-clock breakdown (compute vs compile vs input wait
    # vs checkpoint/recovery/probe), reset alongside step_stats
    monitor.goodput_reset()
    scope = fluid.Scope()
    times = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(place)
        exe.run(startup)
        if post_startup is not None:
            # e.g. the bf16 inference transpiler, which rewrites the
            # program AND casts the initialized params in the scope
            post_startup(scope)
        dev = place.jax_device()
        last = None
        if per_step_feed:
            total = skip_batch_num + N_WINDOWS * iterations
            if reader_creator is not None:
                # the REAL pipeline: recordio scan + multi-process jpeg
                # decode (open_files capability) feeding fresh batches
                stream_src = reader_creator()

                def reader():
                    for _ in range(total):
                        yield next(stream_src)
            else:
                # fresh batch built on the host EVERY step (the stated
                # --use_reader_op methodology): batch synthesis +
                # conversion is real per-step host work, which the
                # prefetch thread overlaps with compute and the
                # --sync_feed half pays on the critical path
                def reader():
                    for i in range(total):
                        yield feed_fn()

            if SYNC_FEED:
                # synchronous half of the overlap A/B: no prefetch
                # thread, no dispatch window — feed staging, dispatch,
                # and the numpy fetch all serialize on the host every
                # step (the pre-pipeline Executor.run behavior)
                stream = reader()
                run_kw = {"return_numpy": True}
            else:
                # overlapped: DevicePrefetcher stages step N+1's feed
                # under step N's compute; the async dispatch window
                # keeps fetches on device between window edges
                pyreader = fluid.reader.PyReader(capacity=4)
                pyreader.decorate_batch_reader(reader, _PassthroughFeeder(),
                                               place)
                stream = iter(pyreader)
                run_kw = {"return_numpy": False}
            for _ in range(skip_batch_num):
                last = exe.run(main, feed=next(stream), fetch_list=[fetch],
                               **run_kw)
            if last is not None:
                np.asarray(last[0])
            for _ in range(N_WINDOWS):
                t0 = time.perf_counter()
                for _ in range(iterations):
                    last = exe.run(main, feed=next(stream),
                                   fetch_list=[fetch], **run_kw)
                np.asarray(last[0])   # true completion (see below)
                times.append(time.perf_counter() - t0)
        else:
            feeds = [{k: jax.device_put(v, dev)
                      for k, v in feed_fn().items()}]
            for i in range(skip_batch_num):
                last = exe.run(main, feed=feeds[0], fetch_list=[fetch],
                               return_numpy=False)
            if last is not None:
                np.asarray(last[0])
            # several measurement windows; min is the machine, the spread
            # is the (shared, tunneled) chip's noise — both are reported.
            # Window sync is a HOST FETCH of the final loss, not
            # block_until_ready: through the axon tunnel the latter
            # returns before execution finishes, and a window would
            # measure dispatch rate, not throughput (discovered r3:
            # block-based timing overstated 2-4.5x).
            for _ in range(N_WINDOWS):
                t0 = time.perf_counter()
                for i in range(iterations):
                    # async dispatch: loss stays on device; the final
                    # scalar fetch forces true completion of the chain
                    last = exe.run(main, feed=feeds[0],
                                   fetch_list=[fetch], return_numpy=False)
                np.asarray(last[0])
                times.append(time.perf_counter() - t0)
        # XLA's own compiled-module accounting: exact flops + bytes per
        # step (the est_mfu heuristic's ground truth).  The monitored
        # cold dispatch already captured the analysis into the program-
        # profile registry, so for warm programs this is FREE — it is
        # attempted on every rung.  --exact_mfu additionally authorizes
        # the explicit-compile fallback for programs the registry missed.
        try:
            ca = exe.cost_analysis(main, {k: np.asarray(v) for k, v
                                          in feed_fn().items()},
                                   [fetch],
                                   compile_if_missing=EXACT_MFU
                                   and not per_step_feed)
            if ca is None:
                exact = {}
            else:
                exact = {"exact_gflops_per_step":
                         round(ca.get("flops", 0.0) / 1e9, 2),
                         "exact_gbytes_per_step":
                         round(ca.get("bytes accessed", 0.0) / 1e9, 3)}
        except Exception as e:  # noqa: BLE001 — observability only
            exact = {"exact_mfu_error": str(e)[:200]} if EXACT_MFU else {}
    assert np.isfinite(
        np.asarray(last[0], dtype=np.float32)).all()
    per_step = sorted(t / iterations for t in times)
    best = per_step[0]
    stats = {"min_step_s": round(best, 6),
             "median_step_s": round(per_step[len(per_step) // 2], 6),
             "n_windows": len(per_step)}
    if model in FLOPS_PER_ITEM and batch:
        items_per_sec = batch / best
        stats["est_mfu"] = round(
            FLOPS_PER_ITEM[model] * items_per_sec / (PEAK_TFLOPS * 1e12), 4)
    stats.update(exact)
    if "exact_gflops_per_step" in stats:
        stats["exact_mfu"] = round(
            stats["exact_gflops_per_step"] * 1e9 / best /
            (PEAK_TFLOPS * 1e12), 4)
    # the headline MFU prefers the compiler's own flop accounting over
    # the 3x-forward heuristic whenever the profile registry served it
    if "exact_mfu" in stats:
        stats["mfu"], stats["mfu_source"] = stats["exact_mfu"], "xla"
    elif "est_mfu" in stats:
        stats["mfu"], stats["mfu_source"] = stats["est_mfu"], "heuristic"
    # the monitor's own view of the rung (all steps incl. warmup):
    # step-time aggregates, fetch-sync wait, cache hit ratio, queue
    # depth/occupancy — same fields a production JSONL log carries
    stats["step_stats"] = monitor.step_stats().summary()
    # where the rung's wall clock went (exclusive buckets + goodput
    # ratio): cross-run regression tracking reads this per rung
    stats["goodput"] = monitor.goodput_summary()
    # per-program attribution (startup vs train step vs eval programs):
    # fingerprint, steps, wall share, flops/bytes/peak-HBM, MFU.  Rows
    # with no steps belong to other rungs' programs (profiles are
    # process-global, accounting is per-rung) — drop them.
    stats["program_report"] = [
        r for r in program_profile.report_rows(peak_tflops=PEAK_TFLOPS)
        if r["steps"]]
    return best, stats


def _maybe_amp(optimizer, use_amp):
    if use_amp:
        from paddle_tpu.contrib import mixed_precision
        return mixed_precision.decorate(optimizer)
    return optimizer


def _maybe_autotune_batch(args, make_feed, fetch, default_batch,
                          model=""):
    """``--autotune`` batch-size pre-pass for the current default
    programs: geometric ladder gated by the HBM-preflight estimate plus
    short measured windows (``autotune.tune_batch_size``).  The probe
    compiles seed the process trace cache and AOT dispatch slots, so
    the measured rung that follows re-lowers nothing for the chosen
    batch.  An explicit ``--batch_size`` is a pin — the tuner never
    runs against it.  Returns (batch, tuned-decision-or-None); the
    decision lands in the rung artifact under ``autotune`` and, when
    ``FLAGS_autotune_dir`` is set, as a TunedConfig JSON artifact."""
    if not AUTOTUNE:
        return (args.batch_size or default_batch), None
    import paddle_tpu as fluid
    from paddle_tpu import autotune as at
    from paddle_tpu import flags as _fl

    if args.batch_size:
        return args.batch_size, {"knob": "batch_size",
                                 "chosen": args.batch_size,
                                 "source": "pinned_cli"}
    cfg = at.TunedConfig(meta={"model": model})
    decision = at.tune_batch_size(
        fluid.default_main_program(), fluid.default_startup_program(),
        make_feed, fetch, _place(args),
        start=max(16, default_batch // 8),
        max_batch=max(default_batch * 4, 16),
        probe_steps=3, config=cfg)
    adir = _fl.flag("autotune_dir")
    if adir:
        cfg.save(os.path.join(adir, "tuned_%s.json" % (model or "rung")))
    return (decision["chosen"] or default_batch), decision


def bench_fault_drill(args):
    """Guardian recovery drill as a bench rung (ISSUE 8): a monitored
    MLP run with a NaN injected into a weight at a fixed step, recovered
    by guardian rollback over TrainState checkpoints.  Reports the
    recovery's wall-clock overhead vs an identical clean run plus the
    guardian's decision counters — the robustness analog of a perf
    rung: recovery must be automatic AND cheap (CheckFreq's argument).
    Informational: drill mechanics, not a hardware-bound number."""
    import shutil
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu import fault, monitor
    from paddle_tpu.contrib import CheckpointConfig, Trainer
    from paddle_tpu.reader import checkpointable

    # below ~16 steps the wall-clock delta is residual-compile noise,
    # not recovery cost (measured on CPU; the warmup bounds but does
    # not eliminate it)
    iterations = max(16, args.iterations)
    batch = args.batch_size or 64
    inject_step = iterations // 2
    default_interval = max(2, iterations // 4)

    def one_run(workdir, inject, interval):
        fault.clear()
        fault.clear_injections()
        if inject:
            fault.inject_nan("fc_0.w_0",
                             fault.FaultSchedule(steps=[inject_step]),
                             once=True)

        def train_func():
            fluid.default_main_program().random_seed = 7
            fluid.default_startup_program().random_seed = 7
            img = fluid.layers.data("img", shape=[784])
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            h = fluid.layers.fc(img, size=256, act="relu")
            pred = fluid.layers.fc(h, size=10, act="softmax")
            return fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))

        def samples():
            rng = np.random.RandomState(0)
            for _ in range(iterations * batch):
                yield (rng.rand(784).astype("float32"),
                       rng.randint(0, 10, (1,)).astype("int64"))

        losses = []

        def handler(ev):
            if hasattr(ev, "metrics"):
                losses.append(float(np.ravel(ev.metrics[0])[0]))

        if not monitor.enabled():
            fluid.set_flags({"FLAGS_monitor": True})
        trainer = Trainer(
            train_func=train_func, place=_place(args),
            optimizer_func=lambda: fluid.optimizer.Adam(1e-3),
            checkpoint_config=CheckpointConfig(
                checkpoint_dir=os.path.join(workdir, "ckpt"),
                step_interval=interval,
                async_save=False),
            guardian_config={"policy": "rollback,abort"})
        t0 = time.monotonic()
        trainer.train(num_epochs=1, event_handler=handler,
                      reader=checkpointable(
                          fluid.batch(samples, batch_size=batch)),
                      feed_order=["img", "label"])
        wall = time.monotonic() - t0
        fault.clear()
        return losses, wall

    from paddle_tpu import autotune as at

    reg = monitor.registry()

    def span_sums():
        out = []
        for n in ("span/checkpoint/snapshot", "span/checkpoint/save"):
            h = reg.get(n)
            out.append((h.sum, h.count) if h is not None else (0.0, 0))
        return out

    workdir = tempfile.mkdtemp(prefix="bench_fault_")
    try:
        # untimed warmup: both timed runs then dispatch off the warm
        # process-global trace cache, so the reported overhead is the
        # RECOVERY cost (restore + replay), not a compile asymmetry
        one_run(os.path.join(workdir, "warm"), inject=False,
                interval=default_interval)
        # measurement pass: a warm clean run whose checkpoint/snapshot +
        # checkpoint/save span deltas are the tuner's evidence
        s0 = span_sums()
        meas_losses, meas_s = one_run(
            os.path.join(workdir, "meas"), inject=False,
            interval=default_interval)
        s1 = span_sums()
        step_s = meas_s / iterations
        snap_s = ((s1[0][0] - s0[0][0]) / max(1, s1[0][1] - s0[0][1]))
        save_s = ((s1[1][0] - s0[1][0]) / max(1, s1[1][1] - s0[1][1]))
        # CheckFreq-style cadence from the measured costs; the drill
        # additionally needs one CLEAN checkpoint committed before the
        # injection step, so the drill interval clamps to that bound
        # (reported separately — the unclamped choice is the tuner's)
        tuned = at.decide_checkpoint_interval(
            step_s, snap_s, save_s, async_save=False)
        drill_interval = max(2, min(tuned["chosen"], inject_step - 2))
        # timed pair at the drill interval, with the measured overhead
        # of checkpointing itself taken from the clean half's spans
        s2 = span_sums()
        clean_losses, clean_s = one_run(
            os.path.join(workdir, "clean"), inject=False,
            interval=drill_interval)
        s3 = span_sums()
        drilled_losses, drilled_s = one_run(
            os.path.join(workdir, "drill"), inject=True,
            interval=drill_interval)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    ckpt_cost_s = (s3[0][0] - s2[0][0]) + (s3[1][0] - s2[1][0])
    rollbacks = reg.get("guardian/rollbacks")
    recovered = (np.isfinite(drilled_losses[-1]) and abs(
        drilled_losses[-1] - clean_losses[-1])
        <= 1e-4 * abs(clean_losses[-1]))
    return {"metric": "fault_drill_recovery_overhead_s",
            "value": round(drilled_s - clean_s, 3), "unit": "seconds",
            "vs_baseline": 0.0, "informational": True,
            "recovered_to_clean_loss": bool(recovered),
            "clean_s": round(clean_s, 3),
            "drilled_s": round(drilled_s, 3),
            "steps": iterations,
            "inject_step": inject_step,
            "replayed_steps": len(drilled_losses) - len(clean_losses),
            "rollbacks": rollbacks.value if rollbacks else 0,
            "final_loss": drilled_losses[-1],
            "clean_final_loss": clean_losses[-1],
            # the tuned checkpoint cadence + its measured evidence: the
            # chosen interval keeps measured checkpoint overhead under
            # the budget (the drill clamps only so a clean rollback
            # target exists before the injection step)
            "autotune_checkpoint": dict(
                tuned, drill_interval=drill_interval,
                measured_ckpt_overhead_frac=round(
                    ckpt_cost_s / clean_s, 6) if clean_s > 0 else None,
                overhead_budget_met=bool(
                    clean_s > 0 and ckpt_cost_s / clean_s
                    <= tuned["budget"]
                    or drill_interval < tuned["chosen"]))}


def bench_ckpt_sharded(args):
    """Per-host sharded checkpoint IO rung (ISSUE 13): capture a real
    TrainState (~50MB of fc params + Adam slots) and write it as a
    per-host sharded artifact with N = 1/2/4 virtual hosts, timing each
    host's own shard write.  Evidence for the orbax-OCDBT-style scaling
    claim: per-host bytes written are 1/N of the state, so the per-host
    write RATE (MB/s) stays flat (±IO noise) as the mesh grows — i.e.
    checkpoint cost at constant per-host state is independent of host
    count.  ``save_wall_s`` (the N=4 per-host wall, lower is better) is
    indexed by tools/bench_history.py; informational, never a gate
    (disk-bound, not chip-bound).  The N=4 artifact is re-loaded and
    verified bit-identical against the capture."""
    import shutil
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu.parallel.checkpoint import (
        capture_train_state, commit_sharded_train_state,
        load_train_state, partition_shards, write_train_state_shards)

    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    x = fluid.layers.data("x", shape=[1024])
    h = fluid.layers.fc(x, size=2048, act="relu")
    h = fluid.layers.fc(h, size=1024, act="relu")
    loss = fluid.layers.mean(fluid.layers.fc(h, size=16))
    fluid.optimizer.Adam(1e-3).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(_place(args))
        exe.run(fluid.default_startup_program())
        exe.run(feed={"x": np.random.RandomState(0).rand(
            8, 1024).astype("float32")}, fetch_list=[loss])
        ts = capture_train_state(1, scope=scope, executors=exe,
                                 sharded=True)
    total_bytes = sum(e["data"].nbytes for e in ts.shards)

    workdir = tempfile.mkdtemp(prefix="bench_ckpt_")
    per_host = {}
    try:
        for n in (1, 2, 4):
            ck = os.path.join(workdir, "w%d" % n, "step_0000000001")
            os.makedirs(os.path.dirname(ck))
            parts = partition_shards(ts, n)
            walls, bytes_by_writer = [], []
            for w, entries in enumerate(parts):
                t0 = time.monotonic()
                b = write_train_state_shards(ck, ts, w, entries=entries)
                walls.append(time.monotonic() - t0)
                bytes_by_writer.append(b)
            t0 = time.monotonic()
            commit_sharded_train_state(ck, ts, n)
            commit_s = time.monotonic() - t0
            wall = max(walls)     # the parallel-hosts wall-clock analog
            per_host[str(n)] = {
                "wall_s": round(wall, 4),
                "commit_s": round(commit_s, 4),
                "bytes_max": max(bytes_by_writer),
                "mb_per_s": round(max(bytes_by_writer) / wall / 2**20,
                                  1) if wall > 0 else None,
            }
        # single-host restore of the sharded artifact round-trips
        # bit-identically (the elastic-resume precondition)
        loaded = load_train_state(
            os.path.join(workdir, "w4", "step_0000000001"))
        roundtrip_ok = all(
            np.array_equal(loaded.arrays[e["name"]][tuple(
                slice(a, b) for a, b in e["index"])], e["data"])
            for e in ts.shards)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    rates = [p["mb_per_s"] for p in per_host.values() if p["mb_per_s"]]
    # value is HIGHER-is-better across the whole artifact schema, so
    # the rung's value is the per-host write RATE; the wall clock rides
    # in save_wall_s (judged lower-is-better by bench_history)
    return {"metric": "ckpt_sharded_per_host_save",
            "value": per_host["4"]["mb_per_s"], "unit": "mb_per_s",
            "vs_baseline": 0.0, "informational": True,
            "save_wall_s": per_host["4"]["wall_s"],
            "state_bytes": total_bytes,
            "per_host": per_host,
            # flatness evidence: per-host write rate spread across
            # 1/2/4 virtual hosts (1.0 = perfectly flat cost at
            # constant per-host state)
            "mb_per_s_spread": round(max(rates) / min(rates), 3)
            if rates else None,
            "bytes_one_over_n": {
                n: round(per_host[n]["bytes_max"] / total_bytes, 3)
                for n in per_host},
            "roundtrip_bit_identical": bool(roundtrip_ok)}


def bench_rec_sparse(args):
    """Recommendation sparse-embedding rung (ISSUE 15): the vocab-
    scaling A/B for the end-to-end SelectedRows path.  A wide&deep-style
    embedding-dominated model (ctr_dnn's shape: id lookups -> sum pool
    -> small tower, Adam) trains with ``is_sparse=True`` (SelectedRows
    grad -> lazy touched-rows Adam) and ``is_sparse=False`` (dense
    [vocab, D] grad -> full-table Adam) at vocab = 1e4 / 1e5 / 1e6 with
    the SAME batch of ids.  The sparse step's work is O(batch·seq)
    while the dense step's gradient + moment update is O(vocab), so
    ``sparse_step_s`` stays ~flat where ``dense_step_s`` grows linearly
    (acceptance: >=5x at vocab=1e6).  The checkpoint side is the
    Check-N-Run claim: with incremental mode on, the delta artifact's
    bytes (``incr_ckpt_bytes``) scale with rows touched since the last
    save, not with vocab, while the full base grows linearly.
    ``sparse_step_s`` / ``dense_step_s`` / ``incr_ckpt_bytes`` are
    indexed by tools/bench_history.py; informational, never a gate
    (the scaling RATIO is the claim, not an absolute chip number).
    Touched-rows/step rides the monitor registry
    (``sparse/touched_rows``) and the per-step JSONL records."""
    import shutil
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from paddle_tpu.framework import program_guard
    from paddle_tpu.param_attr import ParamAttr
    from paddle_tpu.parallel.checkpoint import TrainStateCheckpointManager

    B, S, D = 64, 16, 16
    STEPS, WARM = 6, 2
    rng = np.random.RandomState(7)
    place = _place(args)

    def build(vocab, is_sparse):
        fluid.default_main_program().random_seed = 11
        fluid.default_startup_program().random_seed = 11
        ids = fluid.layers.data("ids", shape=[S, 1], dtype="int64")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        emb = fluid.layers.embedding(
            ids, size=[vocab, D], is_sparse=is_sparse,
            param_attr=ParamAttr(name="table"))
        pooled = fluid.layers.reduce_sum(emb, dim=1)
        x = fluid.layers.fc(pooled, size=32, act="relu")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, y)))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        return loss

    def batches(vocab, n):
        r = np.random.RandomState(3)
        return [{"ids": r.randint(0, vocab, (B, S, 1)).astype("int64"),
                 "y": r.rand(B, 1).astype("float32")} for _ in range(n)]

    def dir_bytes(d):
        return sum(os.path.getsize(os.path.join(root, f))
                   for root, _, fs in os.walk(d) for f in fs)

    def run_variant(vocab, is_sparse, ckpt_dir=None):
        """(min warm step seconds, {full, delta} artifact bytes)."""
        scope = fluid.Scope()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.scope_guard(scope), program_guard(main, startup):
            loss = build(vocab, is_sparse)
            exe = fluid.Executor(place)
            exe.run(startup)
            feeds = batches(vocab, STEPS)
            steps = []
            for i, f in enumerate(feeds):
                t0 = time.monotonic()
                out = exe.run(main, feed=f, fetch_list=[loss])
                float(np.asarray(out[0]).ravel()[0])   # fetch-sync
                if i >= WARM:
                    steps.append(time.monotonic() - t0)
            ck = {}
            if ckpt_dir is not None:
                mgr = TrainStateCheckpointManager(
                    ckpt_dir, async_save=False, incremental="auto",
                    incremental_full_every=8, max_to_keep=None)
                mgr.save(1, scope=scope, program=main, executors=exe)
                ck["full"] = dir_bytes(mgr._step_dir(1))
                exe.run(main, feed=feeds[-1], fetch_list=[loss])
                mgr.save(2, scope=scope, program=main, executors=exe)
                ck["delta"] = dir_bytes(mgr._step_dir(2))
        return min(steps), ck

    mon_dir = tempfile.mkdtemp(prefix="bench_rec_mon_")
    workdir = tempfile.mkdtemp(prefix="bench_rec_sparse_")
    monitor.enable(log_dir=mon_dir)
    per_vocab = {}
    try:
        for vocab in (10_000, 100_000, 1_000_000):
            ckd = os.path.join(workdir, "ck_%d" % vocab)
            sparse_s, ck = run_variant(vocab, True, ckpt_dir=ckd)
            dense_s, _ = run_variant(vocab, False)
            per_vocab[str(vocab)] = {
                "sparse_step_s": round(sparse_s, 5),
                "dense_step_s": round(dense_s, 5),
                "dense_over_sparse": round(dense_s / sparse_s, 2),
                "full_ckpt_bytes": ck["full"],
                "incr_ckpt_bytes": ck["delta"],
            }
        touched = monitor.registry().snapshot().get(
            "sparse/touched_rows", {}).get("value")
    finally:
        monitor.disable()
        shutil.rmtree(workdir, ignore_errors=True)
        shutil.rmtree(mon_dir, ignore_errors=True)

    v1m = per_vocab["1000000"]
    v10k = per_vocab["10000"]
    return {"metric": "rec_sparse_vocab_scaling",
            # value is HIGHER-is-better: the sparse path's step-time
            # advantage over the dense A/B at vocab=1e6 (the acceptance
            # predicate is >= 5x)
            "value": v1m["dense_over_sparse"], "unit": "x_dense_step",
            "vs_baseline": 0.0, "informational": True,
            "sparse_step_s": v1m["sparse_step_s"],
            "dense_step_s": v1m["dense_step_s"],
            "incr_ckpt_bytes": v1m["incr_ckpt_bytes"],
            "per_vocab": per_vocab,
            # flatness evidence across 100x vocab growth
            "sparse_step_spread": round(
                max(p["sparse_step_s"] for p in per_vocab.values())
                / min(p["sparse_step_s"] for p in per_vocab.values()), 2),
            "incr_bytes_spread": round(
                max(p["incr_ckpt_bytes"] for p in per_vocab.values())
                / min(p["incr_ckpt_bytes"] for p in per_vocab.values()),
                2),
            "full_over_incr_bytes_1e6": round(
                v1m["full_ckpt_bytes"] / v1m["incr_ckpt_bytes"], 1),
            "dense_step_growth_1e4_to_1e6": round(
                v1m["dense_step_s"] / v10k["dense_step_s"], 2),
            "touched_rows_total": touched}


def bench_serving(args):
    """Serving rung (ISSUE 11): throughput-vs-latency curve for the
    continuous-batching engine against the bs=16 sequential-dispatch
    baseline PERF.md showed is latency-bound (the chip idles between
    dispatches).

    Methodology: requests are bs=16 client micro-batches (the
    predictor's Run unit — what ``enable_serving`` delegation ships).
    The baseline serves them ONE DISPATCH PER REQUEST, fetch-synced (the
    thin predictor path the ISSUE names); the engine co-batches
    concurrent requests into fixed ``slots``-row dispatches.  The model
    is a small ranking-style classifier, the regime where per-dispatch
    overhead dominates per-example compute — the exact regime the
    forward-only rung measured.  Load is open-loop with a bounded
    outstanding window (two full batches), so admission always finds a
    full batch while per-request latency stays queue-bounded.  Emits
    per-point ``{slots, throughput_rps, p50_ms, p99_ms}``; the primary
    value is the best throughput whose p99 stays under the recorded
    bound, and ``vs_baseline`` is measured/(5x sequential) — the
    ROADMAP item 1 acceptance expressed as a ratio (>1 = met)."""
    import collections

    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from paddle_tpu.monitor import program_profile, tracing
    from paddle_tpu.serving import InferenceEngine
    from paddle_tpu.serving.metrics import ServingMetrics

    if not monitor.enabled():
        fluid.set_flags({"FLAGS_monitor": True})
    monitor.step_stats().reset()
    program_profile.reset_accounting()
    monitor.goodput_reset()
    # per-request tracing rides the rung: each curve point's measured
    # window assembles its own trees, so the artifact carries the stage
    # breakdown (where the p99 actually went) next to the p99 itself
    tracing.enable()
    place = _place(args)
    req_rows = 16
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("img", shape=[64])
        h = fluid.layers.fc(img, size=64, act="relu")
        pred = fluid.layers.fc(h, size=8, act="softmax")
        main = fluid.default_main_program()
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        with fluid.scope_guard(scope):
            exe = fluid.Executor(place)
            exe.run(fluid.default_startup_program())
            # --- baseline: one fetch-synced dispatch per bs=16 request
            feed16 = {"img": rng.rand(req_rows, 64).astype("float32")}
            for _ in range(max(2, args.skip_batch_num)):
                exe.run(main, feed=feed16, fetch_list=[pred])
            n_base = max(20, 3 * args.iterations)
            t0 = time.perf_counter()
            for _ in range(n_base):
                exe.run(main, feed=feed16, fetch_list=[pred])
            base_lat = (time.perf_counter() - t0) / n_base
        baseline_rps = 1.0 / base_lat
        # bounded p99: generous (this is a smoke-able CPU rung) but
        # recorded — the acceptance is throughput AT bounded latency,
        # not throughput with unbounded queueing
        p99_bound_ms = max(250.0, 40.0 * base_lat * 1e3)
        fetch_vars = [main.global_block().var(pred.name)]
        ladder = [s for s in (64, 128, 256, 512)
                  if args.batch_size == 0 or s <= args.batch_size] \
            or [max(req_rows,
                    args.batch_size // req_rows * req_rows)]
        curve = []
        xs = [rng.rand(req_rows, 64).astype("float32")
              for _ in range(64)]
        for slots in ladder:
            reqs_per_batch = slots // req_rows
            n_requests = (max(512, reqs_per_batch * 64)
                          if not args.smoke else 128)
            window = 2 * reqs_per_batch
            eng = InferenceEngine(
                program=main, feed_names=["img"], fetch_vars=fetch_vars,
                scope=scope, place=place, slots=slots, timeout_s=300.0,
                name="serving")
            try:
                # warm the slot signature, then measure a fresh window
                warm = [eng.submit({"img": xs[i % len(xs)]},
                                   rows=req_rows)
                        for i in range(reqs_per_batch)]
                for r in warm:
                    r.result(300)
                # fresh SLO window AND a fresh goodput window per
                # curve point: compute_seconds_per_request must divide
                # THIS rung's attributed compute by THIS rung's
                # requests, not the whole invocation's
                eng.metrics = ServingMetrics(name="serving")
                monitor.goodput_reset()
                tracing.reset()
                outstanding = collections.deque()
                t0 = time.perf_counter()
                for i in range(n_requests):
                    outstanding.append(
                        eng.submit({"img": xs[i % len(xs)]},
                                   rows=req_rows))
                    if len(outstanding) >= window:
                        outstanding.popleft().result(300)
                while outstanding:
                    outstanding.popleft().result(300)
                wall = time.perf_counter() - t0
                summ = eng.metrics.summary()
                trace_summ = tracing.breakdown_summary(
                    tracing.assemble(tracing.spans()))
                curve.append({
                    "slots": slots,
                    "throughput_rps": round(n_requests / wall, 2),
                    "examples_per_sec": round(
                        n_requests * req_rows / wall, 1),
                    "p50_ms": summ["p50_ms"], "p99_ms": summ["p99_ms"],
                    "mean_ms": summ["mean_ms"],
                    "batches": summ["counts"]["batches"],
                    "n_requests": n_requests,
                    "request_trace": trace_summ,
                    "p99_exemplars": summ.get("p99_exemplars"),
                    "goodput_view": summ["goodput_view"]})
            finally:
                eng.close()
    tracing.disable()
    bounded = [c for c in curve if c["p99_ms"] is not None
               and c["p99_ms"] <= p99_bound_ms]
    best = max(bounded or curve, key=lambda c: c["throughput_rps"])
    rps = best["throughput_rps"]
    best_tr = best.get("request_trace") or {}
    best_stages = best_tr.get("stages") or {}
    result = {"metric": "serving_requests_per_sec",
              "value": rps, "unit": "requests/sec",
              # acceptance ratio: >1.0 = beats 5x the sequential
              # bs=16 baseline at bounded p99
              "vs_baseline": round(rps / (5.0 * baseline_rps), 3),
              "throughput_rps": rps,
              "examples_per_sec": best["examples_per_sec"],
              "request_rows": req_rows,
              "p99_ms": best["p99_ms"],
              "p99_bound_ms": round(p99_bound_ms, 1),
              "p99_within_bound": best in bounded,
              "best_slots": best["slots"],
              "speedup_vs_sequential": round(rps / baseline_rps, 2),
              "baseline_bs16_rps": round(baseline_rps, 2),
              "baseline_bs16_latency_ms": round(base_lat * 1e3, 3),
              "n_requests": best.get("n_requests"),
              # the best point's stage breakdown, indexed (non-gating)
              # by bench_history: a p99 regression names its stage
              "request_trace": best_tr,
              "p99_queue_wait_ms": (best_stages.get("queue_wait")
                                    or {}).get("p99_ms"),
              "p99_exemplars": best.get("p99_exemplars"),
              # service seconds per admitted batch at the best point —
              # the cross-run step-time estimator for bench_history
              "min_step_s": round(
                  best["slots"] / req_rows / rps, 6),
              "n_windows": 1,
              "curve": curve,
              "step_stats": monitor.step_stats().summary(),
              "goodput": monitor.goodput_summary()}
    return result


def bench_serving_fleet(args):
    """Pod-scale serving-fleet rung (ISSUE 18): the multi-replica
    routed-serving fabric measured as two multi-process drills from
    ``tests/fleet_runner.py``:

    * **scaling** — aggregate routed req/s at 1/2/4 replicas against
      mock backends with a fixed per-request service dwell (each
      replica an exact ``slots/dwell`` capacity), so the curve measures
      the routing fabric — least-loaded spread, control-plane overhead
      — not the CI host's core count (a real engine's decode is
      host-CPU-bound and N replica processes share the same cores);
    * **failover** — 2 REAL GenerationEngine replicas under open-loop
      load, one SIGKILLed mid-flight: zero lost requests, measured
      re-route latency (first route -> accepted completion on the
      survivor), affinity hit rate, bit-identical parity with direct
      dispatch, and complete cross-process trace trees.

    The primary value is aggregate req/s at 4 replicas; ``vs_baseline``
    is the scaling efficiency measured/(4x the 1-replica point) — the
    near-linear-scaling acceptance expressed as a ratio (1.0 = perfectly
    linear).  ``aggregate_rps`` and ``reroute_latency_ms`` (p99) are
    the fields bench_history indexes."""
    import shutil
    import sys as _sys
    import tempfile

    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from fleet_runner import scaling, supervise

    workdir = tempfile.mkdtemp(prefix="bench_fleet_")
    try:
        curve = scaling(os.path.join(workdir, "scale"),
                        points=(1, 2, 4))
        drill = supervise(os.path.join(workdir, "drill"), replicas=2,
                          requests=24)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    r1, r4 = curve[0], curve[-1]
    efficiency = round(
        r4["aggregate_rps"] / (r4["replicas"] * r1["aggregate_rps"]), 4)
    return {"metric": "serving_fleet",
            "value": r4["aggregate_rps"], "unit": "req_s_4rep",
            # near-linear acceptance as a ratio: measured 4-replica
            # aggregate over 4x the 1-replica point
            "vs_baseline": efficiency, "informational": True,
            "aggregate_rps": r4["aggregate_rps"],
            "reroute_latency_ms": drill["reroute_latency_ms"]["p99_ms"],
            "scaling_efficiency": efficiency,
            "scaling_curve": curve,
            "failover": {k: drill[k] for k in (
                "replicas", "requests", "completed", "lost",
                "rerouted_requests", "client_reroutes",
                "reroute_latency_ms", "affinity_hit_rate",
                "parity_ok", "stale_completions", "p50_latency_ms",
                "p99_latency_ms", "quarantined")},
            "trace": drill["trace"],
            "n_windows": 1}


def bench_fleet_telemetry(args):
    """Fleet telemetry rung (ISSUE 19): the digest plane's own cost
    numbers, both informational.

    * ``digest_build_us`` — member-side cost of one heartbeat digest
      (``DigestBuilder.build`` + ``committed``) against a member-sized
      private registry (40 counters / 16 gauges / 8 live histograms,
      256-sample step ring) with a steady-state mutation profile
      between cycles.  The per-heartbeat overhead acceptance is
      <= ~50us (PERF.md r19); ``vs_baseline`` is measured/budget so
      < 1.0 reads as inside budget.
    * ``straggler_detect_windows`` — fake-clock 3-host FleetAggregator
      drill: digest windows from the moment one host goes 6x slow
      until the detector flags it (persist=2 means the floor is 2) —
      detection latency in heartbeat-window units.
    """
    from paddle_tpu.monitor import aggregate, alerts
    from paddle_tpu.monitor.registry import MetricsRegistry

    # -- digest build cost over a member-sized registry ----------------
    reg = MetricsRegistry()
    counters = [reg.counter("bench/c%02d" % i) for i in range(40)]
    gauges = [reg.gauge("bench/g%02d" % i) for i in range(16)]
    hists = [reg.histogram("bench/h%d" % i) for i in range(8)]
    for h in hists:
        for i in range(256):
            h.observe(0.001 * (i % 37 + 1))
    clock = [1000.0]
    builder = aggregate.DigestBuilder("bench-host", registry=reg,
                                      clock=lambda: clock[0])
    cycles = 2000
    digest_bytes = 0
    try:
        first = builder.build()      # warm: everything ships once
        builder.committed(first["seq"])
        t0 = time.perf_counter()
        for i in range(cycles):
            clock[0] += 1.0
            # steady-state mutation between heartbeats: a few counters
            # tick, a gauge moves, one histogram and the step ring take
            # samples — the delta filter does real work every cycle
            counters[i % 40].inc()
            counters[(i * 7) % 40].inc(3)
            gauges[i % 16].set(float(i))
            hists[i % 8].observe(0.002)
            aggregate.note_step_time(0.05, now=clock[0])
            d = builder.build()
            builder.committed(d["seq"])
        digest_build_us = (time.perf_counter() - t0) / cycles * 1e6
        digest_bytes = len(json.dumps(d))
    finally:
        aggregate._STEP_RING.clear()

    # -- fake-clock straggler-detection drill --------------------------
    t = [0.0]
    agg = aggregate.FleetAggregator(
        clock=lambda: t[0], stale_after=60.0,
        rules=alerts.default_rules(straggler_for_s=0.0))
    slow_from = 5
    detect_windows = -1              # -1 = never flagged (a failure)
    for w in range(1, 41):
        t[0] += 2.0
        for i in range(3):
            host = "h-%d" % i
            slow = 6.0 if (i == 0 and w > slow_from) else 1.0
            steps = [(t[0] - 2.0 + 0.2 * k, 0.05 * slow)
                     for k in range(1, 11)]
            agg.ingest(host, {"v": 1, "seq": w, "host": host,
                              "ts": t[0], "run": "bench",
                              "counters": {}, "gauges": {}, "hists": {},
                              "steps": steps})
        if "h-0" in agg.straggler_hosts():
            detect_windows = w - slow_from
            break

    return {"metric": "fleet_telemetry",
            "value": round(digest_build_us, 2), "unit": "us_per_digest",
            # acceptance as a ratio: measured digest cost over the
            # ~50us heartbeat budget (< 1.0 = inside budget)
            "vs_baseline": round(digest_build_us / 50.0, 4),
            "informational": True,
            "digest_build_us": round(digest_build_us, 2),
            "digest_bytes": digest_bytes,
            "straggler_detect_windows": detect_windows,
            "build_cycles": cycles,
            "n_windows": 1}


def bench_health(args):
    """Model-health probe rung (ISSUE 20): what FLAGS_health costs.

    Three arms over the same seeded MLP step, stepped round-robin so
    machine drift lands on all arms equally: probe off (baseline),
    probe on at cadence 1 (host publication every step — worst case),
    probe on at cadence 10 (the default).  Overheads are median-of-steps
    percentages; the acceptance is cadence-10 overhead <= ~5% of step
    time, so ``vs_baseline`` is overhead_c10/5.0 (< 1.0 = inside
    budget).  c1 ~ c10 is the expected reading: the stats are fused
    into the step module (computed every step), so cadence only moves
    the tiny host-publication slice.  On this CPU MLP the probe's extra
    pass over params+grads is a visible fraction of a bandwidth-bound
    step — the TPU/realistic-model ratio is far smaller (compute per
    byte is higher and the reductions fuse into the update).
    ``provenance_replay_ms`` is the one-shot op-walk replay latency on
    a poisoned step — the off-hot-path cost of naming the first
    non-finite op.  All informational (CPU wall clock).
    """
    import paddle_tpu as fluid
    from paddle_tpu import monitor

    iters = max(10, args.iterations or 30)
    warm = 3

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.unique_name.guard(), \
                fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[784])
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            h = fluid.layers.fc(img, size=1024, act="relu")
            h = fluid.layers.fc(h, size=1024, act="relu")
            pred = fluid.layers.fc(h, size=10, act="softmax")
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    batch = args.batch_size or 256
    feed = {"img": rng.rand(batch, 784).astype("float32"),
            "label": rng.randint(0, 10, (batch, 1)).astype("int64")}

    class Arm:
        def __init__(self, health, every):
            self.flags = {"FLAGS_health": health,
                          "FLAGS_health_every": every}
            fluid.set_flags(self.flags)
            self.main, startup, self.loss = build()
            self.scope = fluid.Scope()
            with fluid.scope_guard(self.scope):
                fluid.Executor(fluid.CPUPlace()).run(startup)
            self.exe = fluid.Executor(fluid.CPUPlace())
            self.times = []

        def step(self, record):
            fluid.set_flags(self.flags)
            with fluid.scope_guard(self.scope):
                t0 = time.perf_counter()
                self.exe.run(self.main, feed=feed,
                             fetch_list=[self.loss])
                if record:
                    self.times.append(time.perf_counter() - t0)

    replay_ms = None
    try:
        # interleaved round-robin: each round steps every arm once, so
        # machine drift (a shared CPU slowing over the run) lands on
        # all three arms equally instead of biasing the last one
        arms = [Arm(False, 10), Arm(True, 1), Arm(True, 10)]
        for i in range(iters + warm):
            for arm in arms:
                arm.step(record=i >= warm)
        base_s, c1_s, c10_s = (float(np.median(a.times)) for a in arms)
        main, scope = arms[2].main, arms[2].scope

        # provenance replay latency: poison a param in the surviving
        # scope and time the op-walk on the last stashed step
        pname = next(n for n in scope.local_var_names()
                     if n.endswith(".w_0"))
        bad = np.asarray(scope.var(pname)).copy()
        bad.flat[0] = np.nan
        scope.set_var(pname, bad)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe._run_counter = iters + warm
            exe.run(main, feed=feed, fetch_list=[])
            prov = monitor.health.nan_provenance(iters + warm)
        if prov and prov.get("found"):
            replay_ms = prov["replay_ms"]
    finally:
        fluid.set_flags({"FLAGS_health": False, "FLAGS_health_every": 10})
        monitor.health._clear_for_tests()

    over_c1 = (c1_s - base_s) / base_s * 100.0
    over_c10 = (c10_s - base_s) / base_s * 100.0
    return {"metric": "health_probe",
            "value": round(over_c10, 2), "unit": "pct_overhead",
            # acceptance as a ratio: cadence-10 overhead over the ~5%
            # budget (< 1.0 = inside budget)
            "vs_baseline": round(over_c10 / 5.0, 4),
            "informational": True,
            "health_overhead_pct_c1": round(over_c1, 2),
            "health_overhead_pct_c10": round(over_c10, 2),
            "provenance_replay_ms": replay_ms,
            "base_step_ms": round(base_s * 1e3, 3),
            "iterations": iters, "batch_size": batch}


def bench_decode_paged(args):
    """Paged-KV decode rung (ISSUE 16): concurrent generation sessions
    at fixed HBM, speculative-decoding token rate, and prefix-cache
    hit rate — the decode raw-speed numbers as one artifact.

    Three arms over the same prompt workload (a shared system prefix
    spanning whole pages plus unique per-request tails — the workload
    prefix sharing exists for):

    * **fixed** — the ISSUE-10 fixed-region f32 KV engine: the
      baseline, one ``max_len`` KV region per slot regardless of how
      short the session actually runs.
    * **paged int8** (headline) — block-indexed KV pool + page table,
      int8 pages, prefix sharing on.  ``sessions_at_fixed_hbm`` is the
      measured HBM-per-session ratio: fixed-region bytes/session over
      the paged arm's bytes/session at the *observed* lengths net of
      the pages prefix sharing actually aliased (counted by the
      engine's own prefix_hits telemetry, not assumed).  Acceptance is
      >= 4x; ``vs_baseline`` = ratio/4 so >1 = met.
    * **speculative** — paged f32 target + same-architecture draft
      sharing the target's weights (``sync_draft_weights``; the
      perfect-draft rig, so the rung exercises the full
      propose/verify/rollback machinery deterministically).
      ``spec_tok_s`` is measured, p99 recorded, and the greedy outputs
      must MATCH the fixed arm token-for-token — speculation that
      changes outputs is a failed rung, not a fast one.

    All three arms decode through ONE compiled signature each
    (lowering counts recorded); prefix_hit_rate comes from the paged
    arm's metrics snapshot.  CPU-smokeable; chip numbers come from the
    same rung on device."""
    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from paddle_tpu.monitor import tracing
    from paddle_tpu.serving.decoder import (build_decoder_lm,
                                            sync_draft_weights)
    from paddle_tpu.serving.engine import GenerationEngine

    if not monitor.enabled():
        fluid.set_flags({"FLAGS_monitor": True})
    monitor.step_stats().reset()
    monitor.goodput_reset()
    # per-request tracing on the paged + speculative arms: the artifact
    # carries the decode-tick breakdown (and the spec_reject share)
    # next to the token rates derived from the same windows
    tracing.enable()
    place = _place(args)
    vocab, max_len, slots, page_size = 61, 64, 4, 8
    dims = dict(n_layer=2, n_head=2, d_model=32, d_inner=64)
    max_new = 8
    rng = np.random.RandomState(0)
    # two full pages of shared system prompt + a unique tail per
    # request: the tail keeps sessions distinct, the prefix is the
    # aliasing opportunity
    system = [int(x) for x in rng.randint(1, vocab, size=2 * page_size)]
    n_requests = 8 if args.smoke else 16
    prompts = [system + [int(x) for x in
                         rng.randint(1, vocab, size=3 + (i % 4))]
               for i in range(n_requests)]

    def drive(eng):
        tracing.reset()
        t0 = time.perf_counter()
        outs = [r.result(600) for r in
                [eng.submit(p) for p in prompts]]
        wall = time.perf_counter() - t0
        toks = sum(len(o["tokens"]) for o in outs)
        summ = eng.metrics.summary()
        summ["request_trace"] = tracing.breakdown_summary(
            tracing.assemble(tracing.spans()))
        return ([o["tokens"] for o in outs], round(toks / wall, 2),
                wall, summ)

    # --- arm 1: fixed-region f32 baseline ------------------------------
    spec_fixed = build_decoder_lm(vocab, max_len, slots, prefix="bpfx",
                                  **dims)
    eng = GenerationEngine(spec_fixed, place=place,
                           max_new_tokens=max_new, timeout_s=600.0)
    try:
        fixed_toks, fixed_tok_s, _, fixed_summ = drive(eng)
        fixed_sigs = len(eng._exe_decode._cache)
    finally:
        eng.close()
    fixed_bytes_per_session = spec_fixed.cache.bytes() // slots

    # --- arm 2: paged int8 + prefix sharing (the HBM headline) ---------
    spec_paged = build_decoder_lm(vocab, max_len, slots, paged=True,
                                  page_size=page_size, kv_dtype="int8",
                                  prefix="bpq8", **dims)
    eng = GenerationEngine(spec_paged, place=place,
                           max_new_tokens=max_new, timeout_s=600.0)
    try:
        paged_toks, paged_tok_s, _, paged_summ = drive(eng)
        paged_sigs = len(eng._exe_decode._cache)
        snap = eng.metrics.paged_snapshot()
        leaks = eng._alloc.check_leaks()
    finally:
        eng.close()
    # measured bytes/session: page-slot demand at the OBSERVED lengths
    # minus the pages prefix sharing aliased (the engine's own hit
    # counter), times the int8 page cost
    alloc = spec_paged.cache.make_allocator()
    demand = sum(alloc.pages_needed(len(p), max_new) for p in prompts)
    fresh_pages = demand - snap["prefix_hits"]
    paged_bytes_per_session = (fresh_pages / float(n_requests)
                               * spec_paged.cache.bytes_per_page())
    sessions_ratio = round(
        fixed_bytes_per_session / paged_bytes_per_session, 2)

    # --- arm 3: speculative decoding (perfect-draft rig) ---------------
    spec_k = 4
    spec_sp = build_decoder_lm(vocab, max_len, slots, paged=True,
                               page_size=page_size, spec_k=spec_k,
                               prefix="bpsp", **dims)
    draft = build_decoder_lm(vocab, max_len, slots, prefix="bpspd",
                             **dims)
    eng = GenerationEngine(spec_sp, place=place, max_new_tokens=max_new,
                           timeout_s=600.0, draft_spec=draft,
                           start=False)
    try:
        sync_draft_weights(eng._scope, spec_sp, draft)
        eng.start()
        spec_toks, spec_tok_s, _, spec_summ = drive(eng)
        spec_snap = eng.metrics.paged_snapshot()
    finally:
        eng.close()
    # the correctness gate: speculation must reproduce the plain greedy
    # stream exactly (paged f32 matches fixed f32 bit-for-bit on the
    # argmax path; acceptance/rollback must not change that)
    spec_outputs_match = spec_toks == fixed_toks

    tracing.disable()
    int8_match = sum(a == b for a, b in zip(paged_toks, fixed_toks))
    paged_tr = paged_summ.get("request_trace") or {}
    paged_stages = paged_tr.get("stages") or {}
    result = {"metric": "decode_sessions_at_fixed_hbm",
              "value": sessions_ratio, "unit": "x",
              # acceptance: >= 4x concurrent sessions at fixed HBM
              "vs_baseline": round(sessions_ratio / 4.0, 3),
              "sessions_at_fixed_hbm": sessions_ratio,
              "bytes_per_session_fixed": int(fixed_bytes_per_session),
              "bytes_per_session_paged": int(paged_bytes_per_session),
              "prefix_hit_rate": snap["prefix_hit_rate"],
              "prefix_hits": snap["prefix_hits"],
              "page_slot_demand": demand,
              "spec_tok_s": spec_tok_s,
              "spec_k": spec_k,
              "spec_acceptance_rate": spec_snap["spec_acceptance_rate"],
              "spec_outputs_match": spec_outputs_match,
              "spec_p99_ms": spec_summ["p99_ms"],
              "fixed_tok_s": fixed_tok_s,
              "paged_int8_tok_s": paged_tok_s,
              "int8_outputs_match_f32": "%d/%d" % (int8_match,
                                                   n_requests),
              "p99_ms": paged_summ["p99_ms"],
              "decode_lowerings": {"fixed": fixed_sigs,
                                   "paged": paged_sigs},
              "kv_page_leaks": len(leaks),
              "n_requests": n_requests,
              "max_new_tokens": max_new,
              # stage breakdown of the headline (paged int8) arm plus
              # the speculative arm's (where spec_reject shows up);
              # bench_history indexes the p99s as informational fields
              "request_trace": paged_tr,
              "request_trace_spec": spec_summ.get("request_trace"),
              "p99_queue_wait_ms": (paged_stages.get("queue_wait")
                                    or {}).get("p99_ms"),
              "p99_decode_ms": (paged_stages.get("decode")
                                or {}).get("p99_ms"),
              # seconds per decode step on the headline arm — the
              # cross-run estimator bench_history indexes
              "min_step_s": round(
                  1.0 / (paged_tok_s / slots), 6) if paged_tok_s else None,
              "n_windows": 1,
              "step_stats": monitor.step_stats().summary(),
              "goodput": monitor.goodput_summary()}
    return result


def bench_quantized(args):
    """Quantized-vs-bf16 forward rung (ISSUE 14): the serving-shaped
    small-batch token forward — 3 wide FC layers in the latency-bound
    regime PERF.md's serving work measured — run through (a) the bf16
    AMP path serving actually ships (f32 master weights cast to bf16
    in-graph every step) and (b) the ``quantize_inference`` int8
    rewrite, accuracy-gated by ``autotune.tune_quantization`` (whose
    TunedConfig evidence embeds in the artifact).

    A/B windows interleave (bf16, quant, bf16, quant ...) so bursty
    host load hits both arms alike; min-of-windows is the estimator as
    everywhere in this file.  The headline value is the quantized arm's
    tok/s; ``vs_baseline`` is quant/bf16 (>1 = the int8 path wins) and
    ``gate_pass`` records the acceptance predicate (faster AND accuracy
    delta under budget).  ``accuracy_delta`` is measured against the
    bf16 arm's own outputs — the precision serving ships today is the
    baseline the gate defends."""
    import paddle_tpu as fluid
    from paddle_tpu import autotune, monitor
    from paddle_tpu.contrib.mixed_precision import AMPPolicy
    from paddle_tpu.monitor import program_profile

    if not monitor.enabled():
        fluid.set_flags({"FLAGS_monitor": True})
    monitor.step_stats().reset()
    program_profile.reset_accounting()
    monitor.goodput_reset()
    place = _place(args)
    on_tpu = args.device == "tpu"
    d_model, d_out, n_layers = (2048, 512, 3)
    batch = args.batch_size or (4 if on_tpu else 1)
    t = 64 if on_tpu else 16
    windows = max(2, N_WINDOWS)
    steps = max(3, args.iterations)
    budget = float(fluid.get_flags("quantize_accuracy_budget")
                   ["quantize_accuracy_budget"])
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_main_program().random_seed = 11
        fluid.default_startup_program().random_seed = 11
        x = fluid.layers.data("tok_feat", shape=[t, d_model])
        h = x
        for _ in range(n_layers):
            h = fluid.layers.fc(h, size=d_model, num_flatten_dims=2,
                                act="relu")
        logits = fluid.layers.fc(h, size=d_out, num_flatten_dims=2)
        main = fluid.default_main_program()
        # the serving bf16 configuration: matmuls whitelisted to bf16
        # over f32 master weights (cast in-graph per step)
        main._amp_policy = AMPPolicy()
        rng = np.random.RandomState(0)
        feed = {"tok_feat": rng.rand(batch, t, d_model).astype(
            "float32")}
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(place, donate_state=False)
            exe.run(fluid.default_startup_program())
            # accuracy-gated mode choice + TunedConfig evidence (the
            # same decision procedure serving consumes)
            cfg = autotune.TunedConfig(meta={"model": "quantized"})
            decision = autotune.tune_quantization(
                main, scope, feed, [logits], place,
                probe_steps=max(2, args.skip_batch_num),
                budget=budget, min_speedup=1.0, config=cfg)
            mode = decision["chosen"] or "weight_only"
            from paddle_tpu.transpiler import quantize_inference
            qprog = quantize_inference(main, scope=scope, mode=mode)

            def window(prog):
                return autotune.measure_step_window(
                    exe, prog, feed, [logits],
                    steps=steps, warmup=0, scope=scope)

            # warm both arms, then interleave the measured windows
            window(main)
            window(qprog)
            t_bf16, t_quant = [], []
            for _ in range(windows):
                t_bf16.append(window(main))
                t_quant.append(window(qprog))
            (ref,) = exe.run(main, feed=feed, fetch_list=[logits],
                             scope=scope)
            (out,) = exe.run(qprog, feed=feed, fetch_list=[logits],
                             scope=scope)
            delta = autotune.eval_delta([ref], [out])
    toks = batch * t
    bf16_tok_s = toks / min(t_bf16)
    quant_tok_s = toks / min(t_quant)
    gate_pass = quant_tok_s > bf16_tok_s and delta <= budget
    info = getattr(qprog, "_quantize_info", {})
    bytes_fp = sum(w["bytes_fp"] for w in info.get("weights", {})
                   .values())
    bytes_int8 = sum(w["bytes_int8"] for w in info.get("weights", {})
                     .values())
    return {"metric": "quantized_tok_per_sec",
            "value": round(quant_tok_s, 2), "unit": "tokens/sec",
            "vs_baseline": round(quant_tok_s / bf16_tok_s, 3),
            "bf16_tok_s": round(bf16_tok_s, 2),
            "speedup_vs_bf16": round(quant_tok_s / bf16_tok_s, 3),
            "accuracy_delta": round(delta, 6),
            "accuracy_budget": budget,
            "gate_pass": bool(gate_pass),
            "mode": mode,
            "gate_chosen": decision["chosen"],
            "batch": batch, "seq": t, "d_model": d_model,
            "n_layers": n_layers,
            "weight_bytes_fp": bytes_fp,
            "weight_bytes_int8": bytes_int8,
            "min_step_s": round(min(t_quant), 6),
            "bf16_min_step_s": round(min(t_bf16), 6),
            "n_windows": windows,
            "autotune": cfg.as_dict(),
            "step_stats": monitor.step_stats().summary(),
            "goodput": monitor.goodput_summary(),
            "informational": True}


def bench_mlp(args, use_amp=False, per_step_feed=False):
    import paddle_tpu as fluid

    batch = args.batch_size or 256
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("img", shape=[784])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, size=256, act="relu")
        h = fluid.layers.fc(h, size=256, act="relu")
        pred = fluid.layers.fc(h, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        _maybe_amp(fluid.optimizer.Adam(learning_rate=1e-3),
                   use_amp).minimize(loss)

        rng = np.random.RandomState(0)

        def make_feed(b):
            return {"img": rng.rand(b, 784).astype("float32"),
                    "label": rng.randint(0, 10, (b, 1)).astype("int64")}

        if not per_step_feed:
            batch, tuned = _maybe_autotune_batch(args, make_feed, loss,
                                                 batch, model="mlp")
        else:
            tuned = None

        def feed_fn():
            return make_feed(batch)

        step_time, stats = _bench_program(
            fluid.default_main_program(), fluid.default_startup_program(),
            feed_fn, loss, _place(args), args.iterations,
            args.skip_batch_num, per_step_feed, model="mlp", batch=batch)
    if tuned is not None:
        stats["autotune"] = tuned
        stats["batch_size"] = batch
    ips = batch / step_time
    return dict({"metric": "mnist_mlp_images_per_sec" + _suffix(
                     use_amp, per_step_feed),
                 "value": round(ips, 2), "unit": "images/sec",
                 "vs_baseline": 1.0}, **stats)


def bench_resnet50(args, use_amp=False, per_step_feed=False, infer=False):
    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import resnet_imagenet

    if infer:
        # forward-only methodology (IntelOptimizedPaddle.md:81-87
        # publishes 217.69 img/s bs=16 CPU for this config)
        return _bench_image_model(
            args, lambda img, is_test=False: resnet_imagenet(
                img, class_dim=1000, depth=50, is_test=is_test),
            "resnet50_images_per_sec", use_amp, per_step_feed,
            default_batch=16, infer=True, era_infer_img_s=217.69)

    # batch 512: fetch-synced A/Bs vs 256 give +3.4%/+5.4% img/s in two
    # run orders (larger reductions/fusions amortize fixed per-step
    # costs; same per-image HBM traffic), as 256 did over 128 (+3-4%).
    # fluid_benchmark tunes --batch_size the same way and the baseline
    # target is a throughput number.  The reader-included variant keeps
    # 128: the host->device uint8 feed scales per step and the
    # link-bound path only gets slower.
    batch = args.batch_size or (128 if per_step_feed else 512)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        if per_step_feed:
            # reader-included path: feed uint8 (4x fewer host->device
            # bytes than fp32) and normalize on device, like a real input
            # pipeline — decode/augment produce uint8, the cast+scale
            # fuses into the compiled step
            raw = fluid.layers.data("img", shape=[3, 224, 224],
                                    dtype="uint8")
            img = fluid.layers.scale(
                fluid.layers.cast(raw, "float32"), scale=1.0 / 255.0)
        else:
            img = fluid.layers.data("img", shape=[3, 224, 224])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = resnet_imagenet(img, class_dim=1000, depth=50)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        if args.nhwc:
            import sys
            n = fluid.transpiler.convert_to_nhwc(
                fluid.default_main_program())
            print("# convert_to_nhwc: %d convs converted" % n,
                  file=sys.stderr)
        if args.fuse_conv_bn:
            import sys
            n = fluid.transpiler.fuse_conv_bn(fluid.default_main_program())
            print("# fuse_conv_bn: %d batch_norms decomposed" % n,
                  file=sys.stderr)
        # small lr: benchmark data is random noise; higher rates diverge
        _maybe_amp(fluid.optimizer.Momentum(learning_rate=1e-3,
                                            momentum=0.9),
                   use_amp).minimize(loss)

        rng = np.random.RandomState(0)

        def make_feed(b):
            if per_step_feed:
                im = rng.randint(0, 256, (b, 3, 224, 224), "uint8")
            else:
                im = rng.rand(b, 3, 224, 224).astype("float32")
            return {"img": im,
                    "label": rng.randint(0, 1000, (b, 1)).astype(
                        "int64")}

        tuned = None
        if not per_step_feed:
            # the reader-included rung keeps its small batch (PERF.md:
            # link-bound; bigger feeds only hurt) — only the synthetic
            # compute rung tunes
            batch, tuned = _maybe_autotune_batch(args, make_feed, loss,
                                                 batch, model="resnet50")

        def feed_fn():
            return make_feed(batch)

        reader_creator = None
        if per_step_feed:
            reader_creator = _jpeg_pipeline(batch, rng)
        step_time, stats = _bench_program(
            fluid.default_main_program(), fluid.default_startup_program(),
            feed_fn, loss, _place(args), args.iterations,
            args.skip_batch_num, per_step_feed, model="resnet50",
            batch=batch, reader_creator=reader_creator)
    if tuned is not None:
        stats["autotune"] = tuned
        stats["batch_size"] = batch
    ips = batch / step_time
    return dict({"metric": "resnet50_images_per_sec" + _suffix(
                     use_amp, per_step_feed),
                 "value": round(ips, 2), "unit": "images/sec",
                 "vs_baseline": round(ips / RESNET_TARGET, 4)}, **stats)


def _jpeg_pipeline(batch, rng, num_workers=8):
    """A REAL input pipeline for the reader-included path: JPEG-encoded
    images in a chunked recordio file, scanned and decoded by a pool of
    worker processes (reader.creator.open_recordio_files — the
    open_files capability), batched into uint8 feed dicts.  Returns a
    batch-reader creator yielding {img, label} dicts forever."""
    import atexit
    import pickle
    import shutil
    import tempfile

    import cv2

    from paddle_tpu import recordio as rio
    from paddle_tpu.reader.creator import open_recordio_files

    tmp = tempfile.mkdtemp(prefix="bench_rio_")
    atexit.register(shutil.rmtree, tmp, ignore_errors=True)
    path = tmp + "/train.rio"
    # large enough that the per-epoch worker-pool restart amortizes
    # (an epoch = n_images/batch steps)
    n_images = 2048
    with rio.Writer(path, max_chunk_bytes=1 << 20) as w:
        for i in range(n_images):
            im = rng.randint(0, 256, (224, 224, 3), "uint8")
            ok, enc = cv2.imencode(".jpg", im)
            assert ok
            w.write(pickle.dumps((enc.tobytes(),
                                  rng.randint(0, 1000))))

    def decode(sample):
        buf, label = sample
        im = cv2.imdecode(np.frombuffer(buf, np.uint8), cv2.IMREAD_COLOR)
        return im.transpose(2, 0, 1), label   # CHW uint8

    def batch_reader():
        # repeat=True: one persistent worker pool streams epochs forever
        # (no per-epoch re-fork inside the timed windows); the daemon
        # workers die with the bench process
        r = open_recordio_files([path], num_workers=num_workers,
                                chunks_per_task=1, mapper=decode,
                                repeat=True)
        imgs, labels = [], []
        for im, lbl in r():
            imgs.append(im)
            labels.append(lbl)
            if len(imgs) == batch:
                yield {"img": np.stack(imgs),
                       "label": np.asarray(labels,
                                           "int64").reshape(-1, 1)}
                imgs, labels = [], []
    return batch_reader


def bench_reader_capacity(args):
    """Host-side input-pipeline capacity: the full jpeg->tensor pipeline
    (recordio scan + multi-process decode + batch assembly) into a null
    sink, NO device involved (VERDICT r4 #6).  Answers "could the
    8-worker pipeline feed a local chip at its ~2,500 img/s demand
    rate?" — reported next to the demand rate, with per-worker decode
    throughput and the host's core count so the projection to a real
    multi-core host is machine-readable.  Reference analog:
    operators/reader/open_files_op.cc multithreaded ingestion."""
    batch = args.batch_size or 128
    rng = np.random.RandomState(0)
    # pool size matched to the host: oversubscribing a small host with
    # the default 8 workers measures IPC thrash, not pipeline capacity
    cores = len(os.sched_getaffinity(0))
    workers = min(8, cores)
    stream = _jpeg_pipeline(batch, rng, num_workers=workers)()
    # warmup: worker-pool spinup + first chunks in flight
    for _ in range(3):
        next(stream)
    windows = []
    n_batches = 8
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n_batches):
            next(stream)
        windows.append(n_batches * batch / (time.perf_counter() - t0))
    ips = max(windows)
    # single-worker decode rate, measured inline (no pool): the unit of
    # scaling — capacity ~= per_worker * min(workers, host_cores)
    import cv2
    im = rng.randint(0, 256, (224, 224, 3), "uint8")
    ok, enc = cv2.imencode(".jpg", im)
    assert ok
    buf = enc.tobytes()
    t0 = time.perf_counter()
    n_dec = 200
    for _ in range(n_dec):
        d = cv2.imdecode(np.frombuffer(buf, np.uint8), cv2.IMREAD_COLOR)
        d.transpose(2, 0, 1)
    per_worker = n_dec / (time.perf_counter() - t0)
    demand = 2500.0   # the chip's bf16 ResNet-50 demand rate (img/s)
    return {"metric": "reader_capacity_img_s", "value": round(ips, 2),
            "unit": "images/sec", "vs_baseline": round(ips / demand, 4),
            "demand_img_s": demand, "host_cores": cores,
            "pool_workers": workers,
            "per_worker_decode_img_s": round(per_worker, 2),
            "projected_8core_img_s": round(per_worker * 8, 2),
            "n_windows": len(windows)}


def bench_transformer(args, use_amp=False, per_step_feed=False):
    """Transformer-base fwd+bwd+Adam tokens/sec (BASELINE config 3)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    # batch 256 (late r4, was 128): order-flipped same-epoch A/Bs on a
    # loaded chip read b256 at a stable 132.8-133.3k tok/s (median ~=
    # min) while b128 swung 85.6-95.8k with median >> min — the bigger
    # step amortizes per-step dispatch/window overhead exactly as
    # ResNet's b512 does, and the baseline target is a throughput
    # number (fluid_benchmark tunes --batch_size the same way).  The
    # r1-era "remote-compile limit at 256" note is stale: b256
    # compiled+ran repeatedly on this setup in late r4.
    batch = args.batch_size or 256
    seq_len = 64
    vocab = 32000
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                                lod_level=1)
        label = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                                  lod_level=1)
        cost, _ = tfm.transformer(src, tgt, label, seq_len, seq_len, vocab,
                                  vocab, n_layer=6, n_head=8, d_model=512,
                                  d_inner=2048, dropout_rate=0.1)
        lr = fluid.layers.noam_decay(512, 4000)
        _maybe_amp(fluid.optimizer.Adam(learning_rate=lr, beta1=0.9,
                                        beta2=0.997, epsilon=1e-9),
                   use_amp).minimize(cost)

        rng = np.random.RandomState(0)

        def make_feed(b):
            ids = rng.randint(2, vocab, (b, seq_len, 1)).astype("int64")
            lens = np.full((b,), seq_len, "int32")
            return {"src_word": ids, "src_word@LEN": lens,
                    "tgt_word": ids, "tgt_word@LEN": lens,
                    "lbl_word": ids, "lbl_word@LEN": lens}

        if not per_step_feed:
            batch, tuned = _maybe_autotune_batch(
                args, make_feed, cost, batch, model="transformer")
        else:
            tuned = None

        def feed_fn():
            return make_feed(batch)

        step_time, stats = _bench_program(
            fluid.default_main_program(), fluid.default_startup_program(),
            feed_fn, cost, _place(args), args.iterations,
            args.skip_batch_num, per_step_feed, model="transformer",
            batch=batch * seq_len)
    if tuned is not None:
        stats["autotune"] = tuned
        stats["batch_size"] = batch
    tps = batch * seq_len / step_time
    return dict({"metric": "transformer_base_tokens_per_sec" + _suffix(
                     use_amp, per_step_feed),
                 "value": round(tps, 2), "unit": "tokens/sec",
                 "vs_baseline": round(tps / TRANSFORMER_TARGET, 4)},
                **stats)


def _bench_image_model(args, model_fn, metric_name, use_amp,
                       per_step_feed, default_batch=128, image_size=224,
                       class_dim=1000, era_ms_per_batch=None, infer=False,
                       era_infer_img_s=None):
    """Shared harness for the image models (vgg, se_resnext, and the
    era-benchmark trio alexnet/googlenet/smallnet): synthetic feeds,
    Momentum, bf16 AMP.

    ``era_ms_per_batch`` is the reference's own published K40m number at
    this batch size (benchmark/README.md) — when set, ``vs_baseline``
    becomes era_ms / our_ms (>1 = beating the reference's headline
    benchmark on its own methodology: fwd+bwd+update wall clock).
    ``infer=True`` measures the forward-only inference program instead
    (the IntelOptimizedPaddle.md infer rows' methodology); with AMP the
    contrib Bfloat16Transpiler rewrites the program post-startup, so the
    _bf16 suffix on infer metrics reflects real bf16 execution."""
    import paddle_tpu as fluid

    batch = args.batch_size or default_batch
    place = _place(args)
    post_startup = None
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        img = fluid.layers.data("img", shape=[3, image_size, image_size])
        pred = model_fn(img, is_test=infer)
        if infer:
            # fetch a scalar distilled from the logits so the timing
            # window stays fetch-synced without pulling [B, classes]
            fetchvar = fluid.layers.mean(pred)
            if use_amp:
                from paddle_tpu.contrib import Bfloat16Transpiler

                main_prog = fluid.default_main_program()

                def post_startup(scope):
                    Bfloat16Transpiler().transpile(
                        main_prog, place, scope=scope,
                        fetch_targets=[fetchvar])
        else:
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            fetchvar = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            _maybe_amp(fluid.optimizer.Momentum(learning_rate=1e-3,
                                                momentum=0.9),
                       use_amp).minimize(fetchvar)
        rng = np.random.RandomState(0)

        def feed_fn():
            feed = {"img": rng.rand(batch, 3, image_size,
                                    image_size).astype("float32")}
            if not infer:
                feed["label"] = rng.randint(
                    0, class_dim, (batch, 1)).astype("int64")
            return feed

        step_time, stats = _bench_program(
            fluid.default_main_program(), fluid.default_startup_program(),
            feed_fn, fetchvar, place, args.iterations,
            args.skip_batch_num, per_step_feed, post_startup=post_startup)
    ips = batch / step_time
    stats["ms_per_batch"] = round(step_time * 1e3, 3)
    vs = 1.0
    # the era ratio is only meaningful at the published batch size —
    # ms/batch does not scale linearly with batch
    if era_ms_per_batch and not infer and batch == default_batch:
        stats["era_ms_per_batch_k40m"] = era_ms_per_batch
        vs = round(era_ms_per_batch / stats["ms_per_batch"], 2)
    if era_infer_img_s and infer and batch == default_batch:
        # IntelOptimizedPaddle.md CPU infer rows (bs=16, img/s)
        stats["era_infer_img_s_xeon"] = era_infer_img_s
        vs = round(ips / era_infer_img_s, 2)
    name = metric_name + ("_infer" if infer else "")
    return dict({"metric": name + _suffix(use_amp, per_step_feed),
                 "value": round(ips, 2), "unit": "images/sec",
                 "vs_baseline": vs}, **stats)


def bench_vgg(args, use_amp=False, per_step_feed=False, infer=False):
    """VGG-16 (fluid_benchmark models/vgg.py config)."""
    from paddle_tpu.models.vgg import vgg16_bn_drop

    return _bench_image_model(
        args, lambda img, is_test=False: vgg16_bn_drop(
            img, class_dim=1000, is_test=is_test),
        "vgg16_images_per_sec", use_amp, per_step_feed,
        default_batch=16 if infer else 128, infer=infer,
        era_infer_img_s=96.75 if infer else None)


def bench_se_resnext(args, use_amp=False, per_step_feed=False, infer=False):
    """SE-ResNeXt-50 (fluid_benchmark models/se_resnext.py config)."""
    from paddle_tpu.models.se_resnext import se_resnext_50

    return _bench_image_model(
        args, lambda img, is_test=False: se_resnext_50(
            img, class_dim=1000, is_test=is_test),
        "se_resnext50_images_per_sec", use_amp, per_step_feed,
        default_batch=16 if infer else 128, infer=infer)


def bench_alexnet(args, use_amp=False, per_step_feed=False, infer=False):
    """AlexNet at the era headline config (bs=128, 227x227; K40m
    published 334 ms/batch, benchmark/README.md:33-38; CPU infer row
    850.51 img/s bs=16, IntelOptimizedPaddle.md:101-107)."""
    from paddle_tpu.models.alexnet import alexnet

    return _bench_image_model(
        args, lambda img, is_test=False: alexnet(img, class_dim=1000,
                                                 is_test=is_test),
        "alexnet_images_per_sec", use_amp, per_step_feed,
        default_batch=16 if infer else 128, image_size=227,
        era_ms_per_batch=334.0, infer=infer)


def bench_googlenet(args, use_amp=False, per_step_feed=False, infer=False):
    """GoogLeNet (Inception v1) at the era headline config (bs=128;
    K40m published 1149 ms/batch, benchmark/README.md:47-51; CPU infer
    row 600.94 img/s bs=16, IntelOptimizedPaddle.md:91-97)."""
    from paddle_tpu.models.googlenet import googlenet_v1

    return _bench_image_model(
        args, lambda img, is_test=False: googlenet_v1(img, class_dim=1000,
                                                      is_test=is_test),
        "googlenet_images_per_sec", use_amp, per_step_feed,
        default_batch=16 if infer else 128, era_ms_per_batch=1149.0,
        infer=infer)


def bench_smallnet(args, use_amp=False, per_step_feed=False, infer=False):
    """SmallNet cifar config (bs=256, 32x32; K40m published 33.1
    ms/batch, benchmark/README.md:55-59)."""
    from paddle_tpu.models.smallnet import smallnet

    return _bench_image_model(
        args, lambda img, is_test=False: smallnet(img, class_dim=10,
                                                  is_test=is_test),
        "smallnet_images_per_sec", use_amp, per_step_feed,
        default_batch=16 if infer else 256, image_size=32, class_dim=10,
        era_ms_per_batch=33.1, infer=infer)


def bench_stacked_lstm(args, use_amp=False, per_step_feed=False):
    """Stacked dynamic LSTM sentiment net (fluid_benchmark
    models/stacked_dynamic_lstm.py config; the scan-based recurrence)."""
    import paddle_tpu as fluid
    from paddle_tpu.models.stacked_dynamic_lstm import stacked_lstm_net

    batch = args.batch_size or 64
    seq = 80
    dict_dim = 5147
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        word = fluid.layers.data("word", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        pred = stacked_lstm_net(word, dict_dim)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        _maybe_amp(fluid.optimizer.Adam(learning_rate=1e-3),
                   use_amp).minimize(loss)
        rng = np.random.RandomState(0)

        def feed_fn():
            ids = rng.randint(0, dict_dim, (batch, seq, 1)).astype("int64")
            # full-length sequences: words/sec = batch*seq/step exactly
            # (variable lengths would overstate by the padding fraction)
            lens = np.full((batch,), seq, "int32")
            return {"word": ids, "word@LEN": lens,
                    "label": rng.randint(0, 2, (batch, 1)).astype("int64")}

        step_time, stats = _bench_program(
            fluid.default_main_program(), fluid.default_startup_program(),
            feed_fn, loss, _place(args), args.iterations,
            args.skip_batch_num, per_step_feed)
    wps = batch * seq / step_time
    return dict({"metric": "stacked_lstm_words_per_sec" + _suffix(
                     use_amp, per_step_feed),
                 "value": round(wps, 2), "unit": "words/sec",
                 "vs_baseline": 1.0}, **stats)


def bench_machine_translation(args, use_amp=False, per_step_feed=False):
    """RNN seq2seq with attention (fluid_benchmark
    models/machine_translation.py config: bi-LSTM encoder, Bahdanau
    attention decoder, 512-wide, 30k dicts).  Words/sec counts target
    tokens; full-length sequences so the count is exact."""
    import paddle_tpu as fluid
    from paddle_tpu.models.machine_translation import seq_to_seq_net

    batch = args.batch_size or 64
    seq = 30
    dict_dim = 30000
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        src = fluid.layers.data("src", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt", shape=[1], dtype="int64",
                                lod_level=1)
        lbl = fluid.layers.data("lbl", shape=[1], dtype="int64",
                                lod_level=1)
        loss, _ = seq_to_seq_net(src, tgt, lbl, dict_dim, dict_dim)
        _maybe_amp(fluid.optimizer.Adam(learning_rate=1e-4),
                   use_amp).minimize(loss)
        rng = np.random.RandomState(0)

        def feed_fn():
            feed = {}
            for name in ("src", "tgt", "lbl"):
                feed[name] = rng.randint(
                    1, dict_dim, (batch, seq, 1)).astype("int64")
                feed[name + "@LEN"] = np.full((batch,), seq, "int32")
            return feed

        step_time, stats = _bench_program(
            fluid.default_main_program(), fluid.default_startup_program(),
            feed_fn, loss, _place(args), args.iterations,
            args.skip_batch_num, per_step_feed)
    wps = batch * seq / step_time
    return dict({"metric": "machine_translation_words_per_sec" + _suffix(
                     use_amp, per_step_feed),
                 "value": round(wps, 2), "unit": "words/sec",
                 "vs_baseline": 1.0}, **stats)


def bench_transformer_realdist(args, use_amp=True):
    """Transformer tokens/sec on a REALISTIC (wmt16-like, skewed) length
    distribution: pad-to-max vs length-bucketed batching (VERDICT r3 #5).

    Throughput counts REAL (non-padding) tokens.  Bucketing
    (reader.bucket_by_length + per-bucket pad bounds) trades one jit
    signature for four, recovering most of the padding waste.
    """
    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from paddle_tpu.models import transformer as tfm
    from paddle_tpu.reader import decorator as dec

    if not monitor.enabled():
        fluid.set_flags({"FLAGS_monitor": True})
    monitor.step_stats().reset()
    monitor.goodput_reset()
    batch = args.batch_size or 128
    max_len = 64
    vocab = 32000
    # measured A/B (fetch-synced, v5e): these 4 MXU-friendly bounds give
    # 108.4k real tok/s (1.94x pad-to-max; 80% of the fixed-length
    # headline = the bucket-fill ceiling).  SIX finer bounds
    # [12,20,28,36,48,64] measured WORSE (78k): higher fill loses to the
    # ragged-T attention shapes' poor MXU tiling — bucket bounds should
    # be hardware-friendly sizes first, fill-optimal second.
    bounds = [16, 32, 48, 64]
    bounds_decision = None
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                                lod_level=1)
        label = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                                  lod_level=1)
        cost, _ = tfm.transformer(src, tgt, label, max_len, max_len, vocab,
                                  vocab, n_layer=6, n_head=8, d_model=512,
                                  d_inner=2048, dropout_rate=0.1)
        lr = fluid.layers.noam_decay(512, 4000)
        _maybe_amp(fluid.optimizer.Adam(learning_rate=lr, beta1=0.9,
                                        beta2=0.997, epsilon=1e-9),
                   use_amp).minimize(cost)

        rng = np.random.RandomState(0)

        def sample_stream():
            # wmt16-like skew: lognormal-ish sentence lengths, clipped
            while True:
                n = int(np.clip(rng.lognormal(3.2, 0.55), 4, max_len))
                yield (rng.randint(2, vocab, (n, 1)).astype("int64"),)

        if AUTOTUNE:
            # derive the bounds from an observed length sample instead
            # of the hand-measured table above: the chooser maximizes
            # real-token fill over hardware-friendly multiples (asked
            # for up to 6 bounds, it returns the MXU-friendly set — the
            # PERF.md 4-not-6 ruling as a constraint).  The decision +
            # fill evidence embed in the artifact.
            from paddle_tpu import autotune as at

            _ss = sample_stream()
            lengths = [len(next(_ss)[0]) for _ in range(2048)]
            bounds_decision = at.choose_bucket_bounds(
                lengths, k=6, multiple=16, max_len=max_len)
            bounds = list(bounds_decision["chosen"])

        # batches feed through the framework's own bucket integration
        # path: DataFeeder.feed(samples, pad_to=bound)
        feeder = fluid.DataFeeder(feed_list=[src, tgt, label],
                                  place=_place(args))

        def make_feed(samples, pad_to):
            triple = [(s, s, s) for (s,) in samples]
            feed = feeder.feed(triple, pad_to=pad_to)
            return feed, int(feed["src_word@LEN"].sum())

        # pre-build feed pools (fixed: pad to max; bucketed: per-bound)
        stream = sample_stream()
        fixed_pool, bucket_pool = [], []
        for _ in range(8):
            samples = [next(stream) for _ in range(batch)]
            fixed_pool.append(make_feed(samples, max_len))
        # per-bucket batch sizes keep tokens/step constant (short
        # sequences are otherwise dispatch-latency-bound): batch*bound
        # ~= the fixed-length rung's 128x64 tokens
        sizes = [max(batch, batch * max_len // b) for b in bounds]
        br = dec.bucket_by_length(
            lambda: sample_stream(), lambda s: len(s[0]), bounds, sizes,
            drop_last=True)()
        per_bound = {}
        for bound, samples in br:
            per_bound.setdefault(bound, [])
            if len(per_bound[bound]) < 3:
                per_bound[bound].append(make_feed(samples, bound))
            if all(len(v) >= 3 for v in per_bound.values()) \
                    and len(per_bound) == len(bounds):
                break
        for vs in per_bound.values():
            bucket_pool.extend(vs)
        rng.shuffle(bucket_pool)

        import jax
        place = _place(args)
        dev = place.jax_device()
        main = fluid.default_main_program()
        results = {}
        for name, pool in (("fixed_pad_max", fixed_pool),
                           ("bucketed", bucket_pool)):
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(place)
                exe.run(fluid.default_startup_program())
                staged = [({k: jax.device_put(v, dev)
                            for k, v in f.items()}, toks) for f, toks in pool]
                # warmup covers every distinct jit signature
                last = None
                for f, _ in staged:
                    last = exe.run(main, feed=f, fetch_list=[cost],
                                   return_numpy=False)
                np.asarray(last[0])
                times, toks_done = [], []
                for _ in range(N_WINDOWS):
                    t0 = time.perf_counter()
                    tk = 0
                    for i in range(args.iterations):
                        f, toks = staged[i % len(staged)]
                        last = exe.run(main, feed=f, fetch_list=[cost],
                                       return_numpy=False)
                        tk += toks
                    np.asarray(last[0])   # fetch-sync
                    times.append(time.perf_counter() - t0)
                    toks_done.append(tk)
                best = max(t / w for t, w in zip(toks_done, times))
                results[name] = round(best, 2)
    out = dict({"metric": "transformer_real_tokens_per_sec_bucketed",
                "value": results["bucketed"], "unit": "real_tokens/sec",
                "vs_baseline": round(
                    results["bucketed"] / TRANSFORMER_TARGET, 4)},
               fixed_pad_max_real_tokens_per_sec=results["fixed_pad_max"],
               bucketed_vs_fixed=round(
                   results["bucketed"] / results["fixed_pad_max"], 3),
               bucket_bounds=bounds,
               step_stats=monitor.step_stats().summary(),
               goodput=monitor.goodput_summary())
    if bounds_decision is not None:
        out["autotune"] = bounds_decision
    return out


def bench_longctx(args, use_amp=True):
    """Long-context decoder-only LM step (T=4k/8k, single chip): the
    regime the Pallas flash-attention kernel exists for — XLA's batched
    attention materializes [B, H, T, T] scores (T=8192, H=8: 1GB bf16
    per direction per layer), the blockwise kernel never does.  Measures
    tokens/sec with the XLA fallback vs FLAGS_pallas_kernels at each T
    and reports both (VERDICT r3 #4: prove the kernel's regime or
    demote it)."""
    import paddle_tpu as fluid

    d_model, n_head, n_layer = 512, 8, 2
    vocab = 32000
    results = {}
    # --longctx_t trims the rung (the auto ladder runs T=4096 only: the
    # decisive A/B, half the compile count; T=8192 stays available via
    # --model longctx --longctx_t 8192/both)
    configs = {"4096": ((4096, 2),), "8192": ((8192, 1),),
               "both": ((4096, 2), (8192, 1))}[args.longctx_t]
    for seq_len, batch in configs:
        fluid.set_flags({"FLAGS_pallas_attention_max_seq": seq_len})
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            ids = fluid.layers.data("ids", shape=[seq_len, 1],
                                    dtype="int64")
            emb = fluid.layers.embedding(ids, size=[vocab, d_model])
            x = fluid.layers.reshape(emb, shape=[-1, seq_len, d_model])
            dh = d_model // n_head
            for _ in range(n_layer):
                qkv = fluid.layers.fc(x, size=3 * d_model, act=None,
                                      num_flatten_dims=2)
                qkv = fluid.layers.reshape(
                    qkv, shape=[-1, seq_len, 3, n_head, dh])
                qkv = fluid.layers.transpose(qkv, perm=[2, 0, 3, 1, 4])
                q = fluid.layers.reshape(
                    fluid.layers.slice(qkv, axes=[0], starts=[0],
                                       ends=[1]),
                    shape=[-1, n_head, seq_len, dh])
                k = fluid.layers.reshape(
                    fluid.layers.slice(qkv, axes=[0], starts=[1],
                                       ends=[2]),
                    shape=[-1, n_head, seq_len, dh])
                v = fluid.layers.reshape(
                    fluid.layers.slice(qkv, axes=[0], starts=[2],
                                       ends=[3]),
                    shape=[-1, n_head, seq_len, dh])
                att = fluid.layers.fused_attention(q, k, v, causal=True)
                att = fluid.layers.reshape(
                    fluid.layers.transpose(att, perm=[0, 2, 1, 3]),
                    shape=[-1, seq_len, d_model])
                x = fluid.layers.elementwise_add(
                    x, fluid.layers.fc(att, size=d_model,
                                       num_flatten_dims=2))
                x = fluid.layers.elementwise_add(
                    x, fluid.layers.fc(
                        fluid.layers.fc(x, size=2 * d_model, act="relu",
                                        num_flatten_dims=2),
                        size=d_model, num_flatten_dims=2))
            pool = fluid.layers.reduce_mean(x, dim=1)
            logits = fluid.layers.fc(pool, size=vocab, act=None)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(logits))
            _maybe_amp(fluid.optimizer.Adam(learning_rate=1e-4),
                       use_amp).minimize(loss)

            rng = np.random.RandomState(0)

            def feed_fn():
                return {"ids": rng.randint(
                    2, vocab, (batch, seq_len, 1)).astype("int64")}

            for pallas in (False, True):
                fluid.set_flags({"FLAGS_pallas_kernels": pallas})
                try:
                    step_time, _ = _bench_program(
                        fluid.default_main_program(),
                        fluid.default_startup_program(),
                        feed_fn, loss, _place(args), args.iterations,
                        args.skip_batch_num)
                    tps = batch * seq_len / step_time
                    results["T%d_%s" % (seq_len,
                                        "pallas" if pallas else "xla")] = \
                        round(tps, 2)
                except Exception as e:  # noqa: BLE001 — record the rung
                    results["T%d_%s_error" % (
                        seq_len, "pallas" if pallas else "xla")] = \
                        str(e)[:200]
            fluid.set_flags({"FLAGS_pallas_kernels": False})
    for t in (4096, 8192):
        p = results.get("T%d_pallas" % t)
        x = results.get("T%d_xla" % t)
        if isinstance(p, float) and isinstance(x, float) and x > 0:
            results["T%d_pallas_vs_xla" % t] = round(p / x, 3)
    # the primary is PINNED to the T=4096 Pallas rung so the metric's
    # meaning is stable across rounds; vs_baseline for this entry is the
    # pallas/xla ratio at that T (there is no era-hardware target)
    val = results.get("T4096_pallas")
    return dict({"metric": "longctx_decoder_tokens_per_sec_pallas",
                 "value": val if isinstance(val, float) else 0.0,
                 "unit": "tokens/sec",
                 "vs_baseline": results.get("T4096_pallas_vs_xla", 0.0)},
                **results)


def build_longctx_ring_graph(t, d_model, n_head, vocab):
    """Build the T>=32k single-block causal decoder forward graph used
    by both the ``longctx_ring`` bench rung and the MULTICHIP dryrun's
    longctx rung (``__graft_entry__``): embedding -> fused QKV ->
    ``fused_attention`` (rings when the mesh has a populated ``sp``
    axis) -> residual projection -> scalar score.  Appends into the
    CURRENT default program; returns the score Variable."""
    import paddle_tpu as fluid

    dh = d_model // n_head
    ids = fluid.layers.data("ids", shape=[t, 1], dtype="int64")
    emb = fluid.layers.embedding(ids, size=[vocab, d_model])
    x = fluid.layers.reshape(emb, shape=[-1, t, d_model])
    qkv = fluid.layers.fc(x, size=3 * d_model, act=None,
                          num_flatten_dims=2)
    qkv = fluid.layers.reshape(qkv, shape=[-1, t, 3, n_head, dh])
    qkv = fluid.layers.transpose(qkv, perm=[2, 0, 3, 1, 4])

    def head(i):
        return fluid.layers.reshape(
            fluid.layers.slice(qkv, axes=[0], starts=[i], ends=[i + 1]),
            shape=[-1, n_head, t, dh])

    att = fluid.layers.fused_attention(head(0), head(1), head(2),
                                       causal=True)
    att = fluid.layers.reshape(
        fluid.layers.transpose(att, perm=[0, 2, 1, 3]),
        shape=[-1, t, d_model])
    x = fluid.layers.elementwise_add(
        x, fluid.layers.fc(att, size=d_model, num_flatten_dims=2))
    return fluid.layers.reduce_mean(x)


@contextlib.contextmanager
def ring_attention_spy():
    """Count ``_ring_attention`` lowerings (proof the sp ring engaged,
    not the single-chip fallback); yields a dict with ``n``."""
    import paddle_tpu.ops.attention as _att

    calls = {"n": 0}
    orig = _att._ring_attention

    def spy(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    _att._ring_attention = spy
    try:
        yield calls
    finally:
        _att._ring_attention = orig


def bench_longctx_ring(args):
    """Long-context decoder rung over a sequence-parallel RING
    (T >= 32k, default 32768): the regime ring attention exists for —
    a single chip cannot even hold the [T, T] score matrix, the ring
    holds [T/sp, T/sp] blocks and streams K/V over ICI
    (parallel/ring_attention.py).  Forward-only (serving-shaped)
    tokens/sec through the ParallelExecutor on a (dp=1, sp) mesh, with
    per-bucket goodput attribution embedded in the rung.

    On hosts with fewer than ``--longctx_sp`` devices (the single-chip
    bench box) the rung re-execs itself on a virtual CPU mesh — the
    number is then a schedule/lowering health signal, not a hardware
    claim, and is marked ``virtual_mesh`` (informational in
    bench_history either way)."""
    import jax

    t = int(args.longctx_ring_t)
    sp = int(args.longctx_sp)
    metric = "longctx_ring_tokens_per_sec"
    if len(jax.devices()) < sp:
        import re
        import subprocess
        import sys

        env = dict(os.environ)
        env.pop("BENCH_OUT", None)
        env["JAX_PLATFORMS"] = "cpu"
        xf = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                    env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (
            xf + " --xla_force_host_platform_device_count=%d" % sp
        ).strip()
        cmd = [sys.executable, os.path.abspath(__file__),
               "--model", "longctx_ring", "--device", "cpu",
               "--iterations", str(args.iterations),
               "--skip_batch_num", str(args.skip_batch_num),
               "--longctx_ring_t", str(t), "--longctx_sp", str(sp)]
        try:
            # below the auto ladder's 600s rung cap: the INNER timeout
            # must fire first, or a ladder kill of the direct child
            # orphans this grandchild under the later rungs
            out = subprocess.run(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, timeout=540, check=True, env=env).stdout
            r = json.loads(out.strip().splitlines()[-1])
            r["virtual_mesh"] = True
            return r
        except Exception as e:  # noqa: BLE001 — record the rung
            detail = str(e)
            stderr = getattr(e, "stderr", None)
            if stderr:
                detail += " | stderr: " + stderr[-400:]
            return {"metric": metric, "value": 0.0, "unit": "error",
                    "vs_baseline": 0.0, "error": detail[:600]}

    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from paddle_tpu.parallel import make_mesh

    on_tpu = args.device == "tpu"
    d_model = 512 if on_tpu else 16
    n_head = 8 if on_tpu else 2
    vocab = 32000 if on_tpu else 64
    batch = 1
    if t % sp:
        return {"metric": metric, "value": 0.0, "unit": "error",
                "vs_baseline": 0.0,
                "error": "T=%d not divisible by sp=%d" % (t, sp)}

    was_on = monitor.enabled()
    if not was_on:
        monitor.enable()
    monitor.goodput_reset()
    try:
        with ring_attention_spy() as ring_calls, \
                fluid.program_guard(fluid.Program(), fluid.Program()):
            fluid.default_main_program().random_seed = 17
            fluid.default_startup_program().random_seed = 17
            score = build_longctx_ring_graph(t, d_model, n_head, vocab)

            mesh = make_mesh((1, sp), ("dp", "sp"),
                             devices=jax.devices()[:sp])
            rng = np.random.RandomState(0)
            feed = {"ids": rng.randint(
                2, vocab, (batch, t, 1)).astype("int64")}
            scope = fluid.Scope()
            with fluid.scope_guard(scope), mesh:
                fluid.Executor(fluid.CPUPlace()).run(
                    fluid.default_startup_program())
                pe = fluid.ParallelExecutor(
                    loss_name=score.name, mesh=mesh, scope=scope)
                for _ in range(max(1, args.skip_batch_num)):
                    (sv,) = pe.run(feed=feed, fetch_list=[score])
                steps = []
                for _ in range(max(1, args.iterations)):
                    t0 = time.perf_counter()
                    (sv,) = pe.run(feed=feed, fetch_list=[score])
                    np.asarray(sv)
                    steps.append(time.perf_counter() - t0)
        assert np.isfinite(np.asarray(sv)).all(), sv
        gp = monitor.goodput_stamp()
    finally:
        if not was_on:
            monitor.disable()
    if not ring_calls["n"]:
        return {"metric": metric, "value": 0.0, "unit": "error",
                "vs_baseline": 0.0,
                "error": "ring attention did not engage (sp=%d)" % sp}
    mean_s = sum(steps) / len(steps)
    return {"metric": metric,
            "value": round(batch * t / mean_s, 2),
            "unit": "tokens/sec", "vs_baseline": 0.0,
            "seq_len": t, "sp": sp, "batch": batch,
            "d_model": d_model, "n_head": n_head,
            "min_step_s": round(min(steps), 6),
            "n_windows": len(steps),
            "ring_lowerings": ring_calls["n"],
            "virtual_mesh": False,
            "goodput": {"goodput_ratio": gp.get("goodput_ratio"),
                        "buckets": {k: v for k, v in
                                    gp["buckets"].items() if v > 0}},
            "informational": True}


def _ladder_run_id():
    """The process's monitor run correlation id — one id across the
    artifact, the JSONL log, /metrics, and chrome traces."""
    from paddle_tpu import monitor

    return monitor.run_id()


def _suffix(use_amp, per_step_feed):
    s = "_bf16" if use_amp else ""
    if per_step_feed:
        s += "_with_reader"
    return s


def _place(args):
    import jax
    import paddle_tpu as fluid
    if args.device == "cpu":
        return fluid.CPUPlace()
    if not any(d.platform != "cpu" for d in jax.devices()):
        raise SystemExit("--device tpu requested but no TPU device present")
    return fluid.TPUPlace(0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="auto",
                   choices=["auto", "mlp", "resnet50", "transformer",
                            "transformer_realdist", "longctx",
                            "longctx_ring", "vgg",
                            "se_resnext", "stacked_lstm",
                            "machine_translation", "alexnet", "googlenet",
                            "smallnet", "reader_capacity", "fault_drill",
                            "serving", "ckpt_sharded", "quantized",
                            "rec_sparse", "decode_paged",
                            "serving_fleet", "fleet_telemetry",
                            "health"])
    p.add_argument("--device", default="auto", choices=["auto", "cpu", "tpu"])
    p.add_argument("--batch_size", type=int, default=0)
    p.add_argument("--iterations", type=int, default=20)
    p.add_argument("--skip_batch_num", type=int, default=5)
    p.add_argument("--fp32_only", action="store_true")
    p.add_argument("--with_reader", action="store_true",
                   help="re-feed fresh host batches every step")
    p.add_argument("--pallas", action="store_true",
                   help="enable FLAGS_pallas_kernels (flash attention etc.)")
    p.add_argument("--longctx_t", default="both",
                   choices=["4096", "8192", "both"],
                   help="which long-context rungs to measure")
    p.add_argument("--longctx_ring_t", type=int, default=32768,
                   help="sequence length for the longctx_ring rung "
                        "(ring attention over sp; T >= 32k is the "
                        "regime the ring exists for)")
    p.add_argument("--longctx_sp", type=int, default=8,
                   help="sequence-parallel ring width for longctx_ring;"
                        " with fewer local devices the rung re-execs on"
                        " a virtual CPU mesh (marked virtual_mesh)")
    p.add_argument("--fuse_conv_bn", action="store_true",
                   help="apply transpiler.fuse_conv_bn to the ResNet "
                        "program (fused Pallas 1x1-conv+BN kernels)")
    p.add_argument("--nhwc", action="store_true",
                   help="apply transpiler.convert_to_nhwc to the ResNet "
                        "program (whole-trunk NHWC layout; composes "
                        "with --fuse_conv_bn)")
    p.add_argument("--fast_prng", action="store_true",
                   help="rbg counter PRNG for in-graph randomness")
    p.add_argument("--infer", action="store_true",
                   help="forward-only inference methodology (the "
                        "IntelOptimizedPaddle.md infer rows); image "
                        "models only, default bs=16")
    p.add_argument("--autotune", action="store_true",
                   help="profile-guided batch-size tuning before the"
                        " rung (paddle_tpu.autotune): HBM-preflight"
                        " gated geometric ladder + measured windows;"
                        " evidence embeds in the artifact under"
                        " 'autotune'.  An explicit --batch_size pins"
                        " and skips the tuner.")
    p.add_argument("--exact_mfu", action="store_true",
                   help="also report XLA cost-analysis exact flops/bytes"
                        " per step (one extra compile per rung)")
    p.add_argument("--n_windows", type=int, default=0,
                   help="override the measurement-window count for this"
                        " invocation (auto ladder trims secondary rungs"
                        " to 3)")
    p.add_argument("--budget_s", "--budget-seconds", type=float,
                   default=float(os.environ.get("BENCH_BUDGET_S", "1100")),
                   help="global wall-clock budget for the auto ladder;"
                        " rungs that don't fit are listed in 'omitted'"
                        " (the primary JSON line is reprinted after every"
                        " rung so a hard kill still leaves an artifact)")
    p.add_argument("--sync_feed", action="store_true",
                   help="disable the reader-included path's prefetch +"
                        " async-dispatch overlap (blocking per-step feed"
                        " staging and numpy fetch) — the synchronous half"
                        " of the step-overlap A/B")
    p.add_argument("--smoke", action="store_true",
                   help="tiny 2-rung × 1-window ladder (mlp compute +"
                        " mlp with_reader) through the full subprocess/"
                        "budget/artifact machinery; CI regression gate"
                        " for the real ladder")
    p.add_argument("--out", default=os.environ.get("BENCH_OUT", ""),
                   help="also write the (partial) primary JSON artifact"
                        " to this file after every rung, atomically — a"
                        " driver kill at any point leaves a valid file")
    p.add_argument("--compile_cache_dir",
                   default=os.environ.get("FLAGS_compile_cache_dir", ""),
                   help="persistent XLA compilation cache directory,"
                        " shared by every ladder rung subprocess: a warm"
                        " second invocation skips XLA recompilation")
    args = p.parse_args()
    global EXACT_MFU, N_WINDOWS, SYNC_FEED, AUTOTUNE
    EXACT_MFU = args.exact_mfu
    SYNC_FEED = args.sync_feed
    AUTOTUNE = args.autotune
    if args.n_windows > 0:
        N_WINDOWS = args.n_windows
    if args.smoke:
        args.model = "auto"
    if args.compile_cache_dir:
        # children of the auto ladder inherit it via the environment
        # (flags.py reads FLAGS_* at import); single-model runs apply it
        # below once paddle_tpu is imported
        os.environ["FLAGS_compile_cache_dir"] = args.compile_cache_dir

    def _write_out(line):
        if not args.out:
            return
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(line + "\n")
        os.replace(tmp, args.out)

    if args.model == "reader_capacity":
        # pure host-side pipeline measurement: no device, no jax client
        result = bench_reader_capacity(args)
        result["schema_version"] = SCHEMA_VERSION
        result["run_id"] = _ladder_run_id()
        line = json.dumps(result)
        print(line)
        _write_out(line)
        return

    if args.pallas or args.fast_prng:
        import paddle_tpu as fluid
        fluid.set_flags({"FLAGS_pallas_kernels": args.pallas,
                         "FLAGS_fast_prng": args.fast_prng})
    if args.compile_cache_dir:
        import paddle_tpu as fluid
        fluid.set_flags({"FLAGS_compile_cache_dir": args.compile_cache_dir})

    import jax
    if args.device == "cpu":
        # the axon TPU plugin overrides JAX_PLATFORMS at import time; the
        # config update wins over it (same trick as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    if args.device == "auto":
        args.device = (
            "tpu" if any(d.platform != "cpu" for d in jax.devices()) else "cpu"
        )

    if args.model == "auto" and args.infer:
        raise SystemExit("--infer needs an explicit image --model "
                         "(the auto ladder measures training)")

    if args.model == "auto":
        # Full flagship ladder, primary = ResNet-50 bf16 (the dtype that
        # matches the A100 fp16 comparison numbers).  Each entry runs in
        # its OWN subprocess: sharing one XLA client across models
        # degrades later entries >20x (stale executables/buffers from
        # earlier ladder rungs), and isolation is the honest methodology
        # anyway (fluid_benchmark runs one model per invocation).
        #
        # r5 redesign (VERDICT r4 #1: BENCH_r04 was an rc=124 timeout
        # with NO parsed line): the ladder now (a) REPRINTS the full
        # primary JSON line after EVERY rung, so a timeout kills rungs,
        # never the artifact; (b) runs under a global --budget_s —
        # rungs that don't fit are listed in "omitted", not attempted;
        # (c) orders scored rungs first and marks everything that is
        # not a first-class scored comparison "informational": true
        # (fp32 = dtype-ruling rungs, era/infer = load-noise-hostage
        # rungs per PERF.md, with_reader = tunnel-bound, longctx = a
        # pallas-vs-xla A/B with no era target).
        import subprocess
        import sys

        # configs are the fetch-synced-measured best (r3): the XLA
        # attention beats the Pallas flash kernel at these short-sequence
        # shapes (101.6k vs 65.2k tok/s true), and the rbg PRNG saves the
        # threefry dropout-mask cost (135.9k with both).  --pallas stays
        # available for long-context/memory-bound regimes.
        # (model, extra, informational, per-rung cap seconds)
        runs = [
            # --- scored rungs (compute-bound; PERF.md measured them
            # moving <1% under host load) ---
            # headline carries the XLA-exact flops/bytes accounting
            # (one extra compile; errors degrade to a field, not a
            # failed rung) and the full 7 windows
            ("resnet50", ["--exact_mfu", "--n_windows", "7"], False, 900),
            ("transformer", ["--fast_prng", "--n_windows", "5"],
             False, 600),
            ("transformer_realdist", ["--fast_prng", "--n_windows", "3"],
             False, 600),
            # --- informational rungs ---
            # host-side pipeline capacity first: no device, ~60s, and
            # VERDICT r4 #6 wants it in the artifact every round
            ("reader_capacity", [], True, 300),
            # guardian recovery drill (ISSUE 8): NaN at a fixed step ->
            # rollback over TrainState -> recovery overhead in seconds;
            # cheap (~15s) and keeps the robustness loop in the artifact
            ("fault_drill", [], True, 300),
            # serving engine (ISSUE 11): continuous-batching throughput-
            # vs-latency curve against the bs=16 sequential-dispatch
            # baseline; informational while the rung accumulates history
            ("serving", [], True, 300),
            # per-host sharded checkpoint IO (ISSUE 13): 1/2/4 virtual
            # hosts each write 1/N of a real TrainState; per-host save
            # wall + MB/s flatness; disk-bound -> informational
            ("ckpt_sharded", [], True, 300),
            # int8 quantized execution (ISSUE 14): accuracy-gated
            # quantized-vs-bf16 forward A/B in the serving small-batch
            # regime; informational while the rung accumulates history
            ("quantized", ["--n_windows", "3"], True, 300),
            # sparse embedding scale-up (ISSUE 15): dense-vs-sparse
            # vocab-scaling A/B + incremental-checkpoint bytes; the
            # ratio is the claim, not an absolute chip number
            ("rec_sparse", [], True, 300),
            # paged-KV decode (ISSUE 16): sessions-at-fixed-HBM ratio
            # (paged int8 vs fixed-region), speculative tok/s, prefix
            # hit rate; informational while the rung accumulates
            # history — the >=4x acceptance reads off vs_baseline
            ("decode_paged", [], True, 300),
            # serving fleet (ISSUE 18): 1/2/4-replica routed aggregate
            # req/s (fabric scaling vs mock-backend capacity) + the
            # real-engine SIGKILL failover drill (zero loss, measured
            # re-route latency); multi-process, engine compiles in
            # subprocesses -> the longer budget
            ("serving_fleet", [], True, 600),
            # fleet telemetry (ISSUE 19): digest build us/heartbeat
            # (the <=~50us acceptance, measured against a member-sized
            # registry) + fake-clock straggler-detection latency in
            # windows; pure in-process, cheap
            ("fleet_telemetry", [], True, 300),
            # model-health probe (ISSUE 20): FLAGS_health step overhead
            # at cadence 1 and 10 (the <=~5% acceptance reads off
            # vs_baseline) + the one-shot NaN-provenance replay latency
            ("health", [], True, 300),
            # fp32: the A100 comparison config is bf16 (BASELINE.md
            # ruling; fp32 is 2.12x HBM bytes on a chip with less
            # bandwidth — PERF.md roofline proof)
            ("resnet50", ["--fp32_only", "--n_windows", "3"], True, 480),
            ("transformer",
             ["--fp32_only", "--fast_prng", "--n_windows", "3"],
             True, 480),
            # tunnel-bound on this setup (PERF.md: reader matches
            # synthetic off-tunnel)
            ("resnet50", ["--with_reader", "--n_windows", "3"],
             True, 480),
            # pallas-vs-xla A/B at T=4096; compile-heavy
            ("longctx", ["--iterations", "8", "--skip_batch_num", "2",
                         "--longctx_t", "4096", "--n_windows", "3"],
             True, 600),   # rung_name special-cases this to longctx_t4096
            # T>=32k ring-attention decoder over sp (ISSUE 12): the
            # sequence-parallel axis's own speed number, goodput-
            # attributed; bootstraps a virtual CPU mesh when the host
            # has a single chip (marked virtual_mesh — indexed by
            # bench_history, never a cross-host baseline)
            ("longctx_ring", ["--iterations", "3",
                              "--skip_batch_num", "1"], True, 600),
            # the reference's own era headline benchmarks
            # (benchmark/README.md K40m ms/batch): vs_baseline here =
            # published_ms / measured_ms at the published batch size.
            # Small nets are dispatch-bound and host-load-sensitive
            # (PERF.md: smallnet swings 0.89x-3.9x) => informational.
            ("alexnet", ["--n_windows", "3"], True, 300),
            ("googlenet", ["--n_windows", "3"], True, 300),
            ("smallnet", ["--n_windows", "3"], True, 300),
            # IntelOptimizedPaddle.md CPU infer rows (forward-only,
            # bs=16): vs_baseline = our img/s over the published Xeon
            # number
            ("resnet50", ["--infer", "--n_windows", "3"], True, 300),
            ("vgg", ["--infer", "--n_windows", "3"], True, 300),
        ]
        if args.smoke:
            # the machinery is the product under test here (subprocess
            # rungs, budget gate, partial-artifact emit), not the
            # numbers: 2 rungs x 1 window at toy shapes — one
            # pure-compute, one through the prefetch + async-dispatch
            # reader path
            tiny = ["--batch_size", "32", "--iterations", "2",
                    "--skip_batch_num", "1", "--n_windows", "1"]
            runs = [("mlp", list(tiny), False, 120),
                    ("mlp", ["--with_reader"] + tiny, False, 120)]

        t_start = time.monotonic()

        def remaining():
            return args.budget_s - (time.monotonic() - t_start)

        def emit(results, omitted, done=False):
            primary = dict(results[0]) if results else {
                "metric": "resnet50_images_per_sec_bf16", "value": 0.0,
                "unit": "images/sec", "vs_baseline": 0.0,
                "error": "no rung completed"}
            if len(results) > 1:
                primary["extra_metrics"] = results[1:]
            if omitted:
                primary["omitted"] = list(omitted)
            primary["elapsed_s"] = round(time.monotonic() - t_start, 1)
            primary["ladder_complete"] = done
            # stable cross-run keys at the TOP level (bench_history
            # ingests artifacts by them; rung subprocesses stamp their
            # own run_ids, the ladder's id names the whole artifact)
            primary["schema_version"] = SCHEMA_VERSION
            primary["run_id"] = _ladder_run_id()
            line = json.dumps(primary)
            print(line, flush=True)
            _write_out(line)

        def rung_name(model, extra):
            if model == "longctx":
                return "longctx_t4096"
            drop = {"--n_windows", "--iterations", "--skip_batch_num",
                    "--batch_size"}
            return model + "".join(
                a.replace("--", "_") for a in extra
                if a.startswith("--") and a not in drop)

        def host_load():
            # sampled per gated rung, not once up front: the ladder runs
            # for many minutes and the load picture changes under it
            try:
                return os.getloadavg()[0] / max(
                    1, len(os.sched_getaffinity(0)))
            except OSError:
                return 0.0

        results, omitted = [], []
        first = True
        for model, extra, informational, cap in runs:
            name = rung_name(model, extra)
            # informational rungs only run on remaining budget; a rung
            # that cannot finish inside the budget is omitted up front
            min_need = 90 if informational else 150
            if remaining() < min_need:
                omitted.append(name)
                continue
            # era/infer rungs are load-noise hostages (PERF.md): skip
            # them when the host is busy AT RUNG TIME rather than
            # record nonsense ratios
            if informational and (
                    model in ("alexnet", "googlenet", "smallnet")
                    or "--infer" in extra):
                load = host_load()
                if load > 1.5:
                    omitted.append(name + "#host_load=%.2f" % load)
                    continue
            if not first and not args.smoke:
                time.sleep(10)   # let the previous client release the chip
            first = False
            cmd = [sys.executable, __file__, "--model", model,
                   "--device", args.device,
                   "--iterations", str(args.iterations),
                   "--skip_batch_num", str(args.skip_batch_num)] + extra
            if args.batch_size and not args.smoke:
                # smoke rungs pin their own toy --batch_size in `extra`;
                # appending the user's here would last-wins override it
                cmd += ["--batch_size", str(args.batch_size)]
            if args.sync_feed:
                # the overlap A/B must reach the rung subprocesses
                cmd += ["--sync_feed"]
            if args.autotune:
                # tuning decisions (and their artifact evidence) happen
                # inside each rung subprocess
                cmd += ["--autotune"]
            detail = None
            # children must not inherit BENCH_OUT: a rung subprocess
            # would parse it as its own --out and atomically overwrite
            # the parent's partial ladder artifact with single-rung JSON
            child_env = {k: v for k, v in os.environ.items()
                         if k != "BENCH_OUT"}
            # one retry for scored rungs only (tunnel errors are
            # transient), and only while the budget allows it
            max_attempts = 2 if not informational else 1
            for attempt in range(max_attempts):
                timeout_s = min(cap, max(60, remaining() - 20))
                try:
                    out = subprocess.run(
                        cmd, stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE, text=True,
                        timeout=timeout_s, check=True,
                        env=child_env).stdout
                    r = json.loads(out.strip().splitlines()[-1])
                    if informational:
                        r["informational"] = True
                        if "--fp32_only" in extra:
                            r["ruling"] = (
                                "fp32 is informational: the A100 "
                                "comparison config is bf16 (BASELINE.md; "
                                "fp32 = 2.12x HBM bytes, PERF.md "
                                "roofline)")
                    results.append(r)
                    detail = None
                    break
                except Exception as e:  # noqa: BLE001 — keep the ladder
                    detail = str(e)
                    stderr = getattr(e, "stderr", None)
                    if stderr:
                        detail += " | stderr: " + stderr[-400:]
                    if isinstance(e, subprocess.TimeoutExpired):
                        # a rung that hit its cap won't fit in the
                        # (smaller) remaining budget either — retrying
                        # would only starve the later scored rungs
                        break
                    if attempt + 1 < max_attempts and remaining() > 120:
                        time.sleep(20)   # settle before the one retry
                    else:
                        break
            if detail is not None:
                results.append({"metric": name + "_error",
                                "value": 0.0, "unit": "error",
                                "vs_baseline": 0.0,
                                "informational": informational,
                                "error": detail[:600]})
            # reprint the enriched primary after every rung: the
            # artifact is whatever line was printed last when the
            # driver's clock runs out
            emit(results, omitted)
        emit(results, omitted, done=True)
        return

    _INFER_MODELS = {"resnet50", "vgg", "se_resnext", "alexnet",
                     "googlenet", "smallnet"}
    if args.infer and args.model not in _INFER_MODELS:
        raise SystemExit("--infer supports the image models only")

    if args.model == "fault_drill":
        result = bench_fault_drill(args)
    elif args.model == "serving":
        result = bench_serving(args)
    elif args.model == "serving_fleet":
        result = bench_serving_fleet(args)
    elif args.model == "fleet_telemetry":
        result = bench_fleet_telemetry(args)
    elif args.model == "health":
        result = bench_health(args)
    elif args.model == "decode_paged":
        result = bench_decode_paged(args)
    elif args.model == "ckpt_sharded":
        result = bench_ckpt_sharded(args)
    elif args.model == "quantized":
        result = bench_quantized(args)
    elif args.model == "rec_sparse":
        result = bench_rec_sparse(args)
    elif args.model == "transformer_realdist":
        result = bench_transformer_realdist(args,
                                            use_amp=not args.fp32_only)
    elif args.model == "longctx":
        result = bench_longctx(args, use_amp=not args.fp32_only)
    elif args.model == "longctx_ring":
        result = bench_longctx_ring(args)
    else:
        fn = {"resnet50": bench_resnet50, "transformer": bench_transformer,
              "mlp": bench_mlp, "vgg": bench_vgg,
              "se_resnext": bench_se_resnext,
              "stacked_lstm": bench_stacked_lstm,
              "machine_translation": bench_machine_translation,
              "alexnet": bench_alexnet, "googlenet": bench_googlenet,
              "smallnet": bench_smallnet}[args.model]
        kwargs = {"infer": True} if args.infer else {}
        result = fn(args, use_amp=not args.fp32_only,
                    per_step_feed=args.with_reader, **kwargs)
    # record the kernel/PRNG choices so A/Bs stay distinguishable in the
    # artifact (metric names stay stable across rounds)
    result["pallas"] = bool(args.pallas)
    result["fast_prng"] = bool(args.fast_prng)
    # recorded unconditionally; the passes only apply to the resnet model
    result["fuse_conv_bn"] = bool(args.fuse_conv_bn)
    result["nhwc"] = bool(args.nhwc)
    # distinguishes the two halves of the step-overlap A/B in artifacts
    result["sync_feed"] = bool(args.sync_feed)
    # stable cross-run keys (see the ladder's emit): single-model
    # invocations are artifacts too
    result["schema_version"] = SCHEMA_VERSION
    result["run_id"] = _ladder_run_id()
    line = json.dumps(result)
    print(line)
    _write_out(line)


if __name__ == "__main__":
    main()
