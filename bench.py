"""paddle_tpu benchmark CLI — prints ONE JSON line for the driver.

Methodology mirrors the reference's ``benchmark/fluid/fluid_benchmark.py``
(args.py: ``--iterations``, ``--skip_batch_num`` warmup; per-batch
wall-clock; throughput includes forward + backward + parameter update,
benchmark/IntelOptimizedPaddle.md:25).

Flagship config ladder (BASELINE.json): ResNet-50 images/sec when the CNN
op set is present, else the MNIST MLP slice.  ``vs_baseline`` is measured
against the north-star target (0.9x A100 step time): A100 ResNet-50 fp16
training throughput ~2900 img/s => target 2610 img/s/chip.
"""

import argparse
import json
import time

import numpy as np


def _bench_program(main, startup, feed_fn, fetch, place, iterations,
                   skip_batch_num):
    import paddle_tpu as fluid

    import jax
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(place)
        exe.run(startup)
        # stage the feed on device once — the input pipeline's job; keeps
        # the measured loop free of host-link transfers (py_reader parity)
        dev = place.jax_device()
        feed = {k: jax.device_put(v, dev) for k, v in feed_fn().items()}
        # compile + warmup
        for i in range(skip_batch_num):
            exe.run(feed=feed, fetch_list=[fetch], return_numpy=False)
        t0 = time.perf_counter()
        last = None
        for i in range(iterations):
            # async dispatch: loss stays on device; sync once at the end
            last = exe.run(feed=feed, fetch_list=[fetch],
                           return_numpy=False)
        jax.block_until_ready(last)
        elapsed = time.perf_counter() - t0
    assert np.isfinite(np.asarray(last[0])).all()
    return elapsed / iterations


def bench_mlp(args):
    import paddle_tpu as fluid

    batch = args.batch_size or 256
    img = fluid.layers.data("img", shape=[784])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=256, act="relu")
    h = fluid.layers.fc(h, size=256, act="relu")
    pred = fluid.layers.fc(h, size=10, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    rng = np.random.RandomState(0)
    x = rng.rand(batch, 784).astype("float32")
    y = rng.randint(0, 10, (batch, 1)).astype("int64")

    step_time = _bench_program(
        fluid.default_main_program(), fluid.default_startup_program(),
        lambda: {"img": x, "label": y}, loss,
        _place(args), args.iterations, args.skip_batch_num)
    ips = batch / step_time
    # no published reference number for this slice; report vs the ResNet-50
    # target scaled by FLOP ratio is meaningless — use 1.0 placeholder until
    # the ResNet-50 path (below) is the flagship.
    return {"metric": "mnist_mlp_images_per_sec", "value": round(ips, 2),
            "unit": "images/sec", "vs_baseline": 1.0}


def bench_resnet50(args):
    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import resnet_imagenet

    batch = args.batch_size or 128
    img = fluid.layers.data("img", shape=[3, 224, 224])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    pred = resnet_imagenet(img, class_dim=1000, depth=50)
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    # small lr: benchmark data is random noise; higher rates diverge to
    # inf losses within ~6 steps (log of a collapsed softmax)
    fluid.optimizer.Momentum(learning_rate=1e-3, momentum=0.9).minimize(loss)

    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, 224, 224).astype("float32")
    y = rng.randint(0, 1000, (batch, 1)).astype("int64")

    step_time = _bench_program(
        fluid.default_main_program(), fluid.default_startup_program(),
        lambda: {"img": x, "label": y}, loss,
        _place(args), args.iterations, args.skip_batch_num)
    ips = batch / step_time
    target = 2900.0 * 0.9  # 0.9x A100 ResNet-50 train throughput
    return {"metric": "resnet50_images_per_sec", "value": round(ips, 2),
            "unit": "images/sec", "vs_baseline": round(ips / target, 4)}


def bench_transformer(args):
    """Transformer-base fwd+bwd+Adam tokens/sec (BASELINE config 3).
    Target: 0.9x A100 Transformer-base NMT training ~ 95k tok/s
    (transformer-base, fp16, effective bs~12k tokens) => 85.5k tok/s."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    batch = args.batch_size or 64
    seq_len = 64
    vocab = 32000
    src = fluid.layers.data("src_word", shape=[1], dtype="int64", lod_level=1)
    tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64", lod_level=1)
    label = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                              lod_level=1)
    cost, _ = tfm.transformer(src, tgt, label, seq_len, seq_len, vocab,
                              vocab, n_layer=6, n_head=8, d_model=512,
                              d_inner=2048, dropout_rate=0.1)
    lr = fluid.layers.noam_decay(512, 4000)
    fluid.optimizer.Adam(learning_rate=lr, beta1=0.9, beta2=0.997,
                         epsilon=1e-9).minimize(cost)

    rng = np.random.RandomState(0)
    ids = rng.randint(2, vocab, (batch, seq_len, 1)).astype("int64")
    lens = np.full((batch,), seq_len, "int32")
    feed = {"src_word": ids, "src_word@LEN": lens,
            "tgt_word": ids, "tgt_word@LEN": lens,
            "lbl_word": ids, "lbl_word@LEN": lens}

    step_time = _bench_program(
        fluid.default_main_program(), fluid.default_startup_program(),
        lambda: feed, cost,
        _place(args), args.iterations, args.skip_batch_num)
    tps = batch * seq_len / step_time
    target = 95000.0 * 0.9
    return {"metric": "transformer_base_tokens_per_sec",
            "value": round(tps, 2), "unit": "tokens/sec",
            "vs_baseline": round(tps / target, 4)}


def _place(args):
    import jax
    import paddle_tpu as fluid
    if args.device == "cpu":
        return fluid.CPUPlace()
    if not any(d.platform != "cpu" for d in jax.devices()):
        raise SystemExit("--device tpu requested but no TPU device present")
    return fluid.TPUPlace(0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="auto",
                   choices=["auto", "mlp", "resnet50", "transformer"])
    p.add_argument("--device", default="auto", choices=["auto", "cpu", "tpu"])
    p.add_argument("--batch_size", type=int, default=0)
    p.add_argument("--iterations", type=int, default=20)
    p.add_argument("--skip_batch_num", type=int, default=5)
    args = p.parse_args()

    import jax
    if args.device == "auto":
        args.device = (
            "tpu" if any(d.platform != "cpu" for d in jax.devices()) else "cpu"
        )

    model = args.model
    if model == "auto":
        try:
            from paddle_tpu.models.resnet import resnet_imagenet  # noqa: F401
            model = "resnet50"
        except ImportError:
            model = "mlp"
    result = {"resnet50": bench_resnet50, "transformer": bench_transformer,
              "mlp": bench_mlp}[model](args)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
