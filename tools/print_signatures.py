"""Public-API signature dump — the API-drift gate.

Parity: reference ``tools/print_signatures.py`` + ``tools/diff_api.py``
(CI diffs the printed signatures against a checked-in golden list so
accidental API breaks fail the build, paddle_build.sh).

Usage:
    python tools/print_signatures.py            # print to stdout
    python tools/print_signatures.py --update   # rewrite the golden file

The golden file is ``tools/api_signatures.txt``;
``tests/test_api_signatures.py`` enforces the match.
"""

import argparse
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "api_signatures.txt")

MODULES = [
    "paddle_tpu",
    "paddle_tpu.autotune",
    "paddle_tpu.serving",
    "paddle_tpu.fault",
    "paddle_tpu.guardian",
    "paddle_tpu.layers",
    "paddle_tpu.optimizer",
    "paddle_tpu.initializer",
    "paddle_tpu.regularizer",
    "paddle_tpu.clip",
    "paddle_tpu.metrics",
    "paddle_tpu.nets",
    "paddle_tpu.io",
    "paddle_tpu.inference",
    "paddle_tpu.profiler",
    "paddle_tpu.monitor",
    "paddle_tpu.monitor.program_profile",
    "paddle_tpu.monitor.tracing",
    "paddle_tpu.monitor.aggregate",
    "paddle_tpu.monitor.alerts",
    "paddle_tpu.monitor.health",
    "paddle_tpu.debugger",
    "paddle_tpu.recordio",
    "paddle_tpu.reader",
    "paddle_tpu.reader.creator",
    "paddle_tpu.cloud",
    "paddle_tpu.cluster",
    "paddle_tpu.parallel",
    "paddle_tpu.parallel.checkpoint",
    "paddle_tpu.transpiler",
    "paddle_tpu.compat",
    "paddle_tpu.utils",
    "paddle_tpu.utils.image_util",
    "paddle_tpu.utils.preprocess_util",
    "paddle_tpu.utils.torch2paddle",
    "paddle_tpu.contrib",
    "paddle_tpu.contrib.mixed_precision",
    "paddle_tpu.contrib.decoder",
    "paddle_tpu.v2",
    "paddle_tpu.v2.layer",
    "paddle_tpu.v2.networks",
    "paddle_tpu.v2.optimizer",
    "paddle_tpu.v2.data_type",
    "paddle_tpu.v2.parameters",
    "paddle_tpu.v2.event",
    "paddle_tpu.v2.evaluator",
    "paddle_tpu.v2.trainer",
    "paddle_tpu.v2.inference",
    "paddle_tpu.v2.activation",
    "paddle_tpu.v2.pooling",
    "paddle_tpu.v2.attr",
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def collect():
    import importlib

    lines = []
    for mod_name in MODULES:
        mod = importlib.import_module(mod_name)
        names = getattr(mod, "__all__", None)
        if names is None:
            names = [n for n in dir(mod) if not n.startswith("_")]
        for name in sorted(set(names)):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            if inspect.isclass(obj):
                lines.append("%s.%s%s" % (mod_name, name,
                                          _sig(obj.__init__)))
                for m_name, m in sorted(inspect.getmembers(obj)):
                    if m_name.startswith("_"):
                        continue
                    if inspect.isfunction(m):
                        lines.append("%s.%s.%s%s" % (mod_name, name,
                                                     m_name, _sig(m)))
            elif callable(obj):
                lines.append("%s.%s%s" % (mod_name, name, _sig(obj)))
    return lines


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--update", action="store_true",
                   help="rewrite the golden file")
    args = p.parse_args()
    lines = collect()
    if args.update:
        with open(GOLDEN, "w") as f:
            f.write("\n".join(lines) + "\n")
        print("wrote %d signatures to %s" % (len(lines), GOLDEN))
    else:
        print("\n".join(lines))


if __name__ == "__main__":
    main()
