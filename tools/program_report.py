"""Per-program cost/memory/step report — which compiled program spends
the time and the HBM.

Renders the table the program-profile registry maintains in-process
(fingerprint, executor kind, steps, wall clock + share, flops/step,
bytes/step, estimated peak HBM, ground-truth MFU from the compiler's
own flop accounting) from a monitor JSONL log — the offline twin of
calling ``paddle_tpu.monitor.program_profile.report_rows()`` /
``render_table()`` on a live registry.

Usage:
    python tools/program_report.py /path/to/monitor_logs        # dir
    python tools/program_report.py monitor-1234.jsonl           # one file
    python tools/program_report.py logs/ --peak_tflops 197 --json

The log must come from a run with the monitor on
(``FLAGS_monitor_log_dir=...``): ``program_profile`` events carry each
compiled program's cost/memory analysis, ``step_stats`` events carry the
per-step fingerprint tags this report joins on.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_records(path):
    """All JSONL records under ``path`` (a file, or a directory whose
    ``*.jsonl`` files — including rotated ``.jsonl.N`` generations — are
    read).  Unparseable lines are skipped (a crashed writer can leave a
    torn tail)."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.jsonl"))
                       + glob.glob(os.path.join(path, "*.jsonl.*")))
    else:
        files = [path]
    records = []
    for f in files:
        with open(f) as fh:
            for ln in fh:
                try:
                    records.append(json.loads(ln))
                except ValueError:
                    continue
    return records


def rows_from_records(records, peak_tflops=None, run_id=None):
    """Replay JSONL records into program-report rows: profiles from
    ``program_profile`` events (latest per fingerprint wins), step
    accounting from fingerprint-tagged ``step_stats`` events.
    ``run_id`` filters to one run's records (a shared log dir holds
    many)."""
    from paddle_tpu.monitor.program_profile import (ProgramProfile,
                                                    report_rows)

    profiles, acct = {}, {}
    for r in records:
        if not isinstance(r, dict):
            continue
        if run_id and r.get("run_id") not in (None, run_id):
            continue
        ev = r.get("event")
        if ev == "program_profile" and r.get("fingerprint"):
            profiles[r["fingerprint"]] = ProgramProfile(
                r["fingerprint"], (), r.get("kind", "executor"),
                flops=r.get("flops", 0.0) or 0.0,
                bytes_accessed=r.get("bytes_accessed", 0.0) or 0.0,
                argument_bytes=r.get("argument_bytes", 0),
                output_bytes=r.get("output_bytes", 0),
                temp_bytes=r.get("temp_bytes", 0),
                generated_code_bytes=r.get("generated_code_bytes", 0),
                alias_bytes=r.get("alias_bytes", 0),
                peak_hbm_bytes=r.get("peak_hbm_bytes", 0),
                device=r.get("device"))
        elif ev == "step_stats" and r.get("fingerprint"):
            a = acct.setdefault(r["fingerprint"],
                                {"steps": 0, "wall_s": 0.0, "examples": 0,
                                 "kind": r.get("executor", "")})
            a["steps"] += 1
            a["wall_s"] += r.get("step_seconds", 0.0) or 0.0
            a["examples"] += r.get("examples", 0) or 0
    return report_rows(peak_tflops=peak_tflops, profiles_by_fp=profiles,
                       acct_by_fp=acct)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="per-program cost/memory/step report from a monitor "
                    "JSONL log")
    p.add_argument("log", help="monitor JSONL file, or a "
                               "FLAGS_monitor_log_dir directory")
    p.add_argument("--peak_tflops", type=float, default=None,
                   help="chip peak TFLOP/s for the MFU column "
                        "(default: BENCH_PEAK_TFLOPS env or 197)")
    p.add_argument("--run_id", default=None,
                   help="only records of this run correlation id")
    p.add_argument("--top", type=int, default=0,
                   help="show only the top N programs by wall clock")
    p.add_argument("--json", action="store_true",
                   help="emit the rows as JSON instead of a table")
    args = p.parse_args(argv)

    from paddle_tpu.monitor.program_profile import render_table

    records = load_records(args.log)
    rows = rows_from_records(records, peak_tflops=args.peak_tflops,
                             run_id=args.run_id)
    if args.top:
        rows = rows[:args.top]
    if not rows:
        print("no program_profile / fingerprint-tagged step_stats "
              "records in %s (monitor on? FLAGS_monitor_log_dir set?)"
              % args.log)
        return 1
    if args.json:
        print(json.dumps(rows, indent=2))
    else:
        print(render_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
