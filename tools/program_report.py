"""Per-program cost/memory/step report — which compiled program spends
the time and the HBM.

Renders the table the program-profile registry maintains in-process
(fingerprint, executor kind, steps, wall clock + share, flops/step,
bytes/step, estimated peak HBM, ground-truth MFU from the compiler's
own flop accounting) from a monitor JSONL log — the offline twin of
calling ``paddle_tpu.monitor.program_profile.report_rows()`` /
``render_table()`` on a live registry.

Usage:
    python tools/program_report.py /path/to/monitor_logs        # dir
    python tools/program_report.py monitor-1234.jsonl           # one file
    python tools/program_report.py logs/ --peak_tflops 197 --json

The log must come from a run with the monitor on
(``FLAGS_monitor_log_dir=...``): ``program_profile`` events carry each
compiled program's cost/memory analysis, ``step_stats`` events carry the
per-step fingerprint tags this report joins on, and ``device_stats``
events (mesh runs) feed the per-device peak-HBM block — min/max across
the mesh devices, the one-table readout of the fsdp 1/N claim.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_records(path):
    """All JSONL records under ``path`` (a file, or a directory whose
    ``*.jsonl`` files — including rotated ``.jsonl.N`` generations — are
    read).  Unparseable lines are skipped (a crashed writer can leave a
    torn tail)."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.jsonl"))
                       + glob.glob(os.path.join(path, "*.jsonl.*")))
    else:
        files = [path]
    records = []
    for f in files:
        with open(f) as fh:
            for ln in fh:
                try:
                    records.append(json.loads(ln))
                except ValueError:
                    continue
    return records


def rows_from_records(records, peak_tflops=None, run_id=None):
    """Replay JSONL records into program-report rows: profiles from
    ``program_profile`` events (latest per fingerprint wins), step
    accounting from fingerprint-tagged ``step_stats`` events.
    ``run_id`` filters to one run's records (a shared log dir holds
    many)."""
    from paddle_tpu.monitor.program_profile import (ProgramProfile,
                                                    report_rows)

    profiles, acct, probe_acct = {}, {}, {}
    partitions = {}     # fingerprint -> set of distinct partition ids
    for r in records:
        if not isinstance(r, dict):
            continue
        if run_id and r.get("run_id") not in (None, run_id):
            continue
        ev = r.get("event")
        if ev == "program_profile" and r.get("fingerprint"):
            partitions.setdefault(r["fingerprint"], set()).add(
                r.get("partition"))
            profiles[r["fingerprint"]] = ProgramProfile(
                r["fingerprint"], (), r.get("kind", "executor"),
                flops=r.get("flops", 0.0) or 0.0,
                bytes_accessed=r.get("bytes_accessed", 0.0) or 0.0,
                argument_bytes=r.get("argument_bytes", 0),
                output_bytes=r.get("output_bytes", 0),
                temp_bytes=r.get("temp_bytes", 0),
                generated_code_bytes=r.get("generated_code_bytes", 0),
                alias_bytes=r.get("alias_bytes", 0),
                peak_hbm_bytes=r.get("peak_hbm_bytes", 0),
                device=r.get("device"))
        elif ev == "step_stats" and r.get("fingerprint"):
            # tuner-probe steps (tagged by probe_accounting at record
            # time) accumulate separately, mirroring note_step: probe
            # wall clock never blends into a steady row, even for the
            # same fingerprint
            bucket = probe_acct if r.get("probe") else acct
            a = bucket.setdefault(r["fingerprint"],
                                  {"steps": 0, "wall_s": 0.0,
                                   "examples": 0,
                                   "kind": r.get("executor", "")})
            a["steps"] += 1
            a["wall_s"] += r.get("step_seconds", 0.0) or 0.0
            a["examples"] += r.get("examples", 0) or 0
    rows = report_rows(peak_tflops=peak_tflops, profiles_by_fp=profiles,
                       acct_by_fp=acct, probe_acct_by_fp=probe_acct)
    # one program compiled under SEVERAL mesh/sharding layouts (the
    # replicated-vs-fsdp A/B) shares a fingerprint: step accounting
    # covers all layouts while the profile columns are the latest
    # layout's — flag the multiplicity so the row isn't read as one
    # homogeneous program
    for row in rows:
        n = len(partitions.get(row["fingerprint"], ()))
        if n > 1:
            row["partitions"] = n
            row["fp12"] = row["fp12"][:11] + "*"   # visible in the table
    return rows


def devices_from_records(records, run_id=None):
    """Per-device memory summary from ``device_stats`` events (the JSONL
    twin of the ``device/<id>/bytes_in_use`` gauges ParallelExecutor
    publishes each sampled mesh step): ``{device: {bytes_in_use_peak,
    bytes_limit}}``.  The min/max across the mesh makes the fsdp 1/N
    per-device HBM claim readable from one table."""
    out = {}
    for r in records:
        if not isinstance(r, dict) or r.get("event") != "device_stats":
            continue
        if run_id and r.get("run_id") not in (None, run_id):
            continue
        for dev, ms in (r.get("devices") or {}).items():
            cur = out.setdefault(dev, {"bytes_in_use_peak": 0,
                                       "bytes_limit": None})
            peak = ms.get("bytes_in_use_peak") or ms.get("bytes_in_use")
            if peak and peak > cur["bytes_in_use_peak"]:
                cur["bytes_in_use_peak"] = int(peak)
            if ms.get("bytes_limit"):
                cur["bytes_limit"] = int(ms["bytes_limit"])
    return out


def render_device_table(devices):
    """Fixed-width per-device peak-HBM block + the min/max summary."""
    from paddle_tpu.monitor.program_profile import _fmt_mib

    lines = ["", "%-12s %12s %12s" % ("device", "peakHBM", "limit"),
             "-" * 38]
    for dev in sorted(devices):
        d = devices[dev]
        lines.append("%-12s %12s %12s" % (
            dev, _fmt_mib(d["bytes_in_use_peak"]),
            _fmt_mib(d["bytes_limit"]) if d["bytes_limit"] else "-"))
    peaks = [d["bytes_in_use_peak"] for d in devices.values()]
    lines.append("per-device peak HBM across %d devices: min %s / max %s"
                 % (len(peaks), _fmt_mib(min(peaks)), _fmt_mib(max(peaks))))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="per-program cost/memory/step report from a monitor "
                    "JSONL log")
    p.add_argument("log", help="monitor JSONL file, or a "
                               "FLAGS_monitor_log_dir directory")
    p.add_argument("--peak_tflops", type=float, default=None,
                   help="chip peak TFLOP/s for the MFU column "
                        "(default: BENCH_PEAK_TFLOPS env or 197)")
    p.add_argument("--run_id", default=None,
                   help="only records of this run correlation id")
    p.add_argument("--top", type=int, default=0,
                   help="show only the top N programs by wall clock")
    p.add_argument("--json", action="store_true",
                   help="emit the rows as JSON instead of a table")
    args = p.parse_args(argv)

    from paddle_tpu.monitor.program_profile import render_table

    records = load_records(args.log)
    rows = rows_from_records(records, peak_tflops=args.peak_tflops,
                             run_id=args.run_id)
    devices = devices_from_records(records, run_id=args.run_id)
    if args.top:
        rows = rows[:args.top]
    if not rows:
        print("no program_profile / fingerprint-tagged step_stats "
              "records in %s (monitor on? FLAGS_monitor_log_dir set?)"
              % args.log)
        return 1
    if args.json:
        # one stable schema: devices is {} on runs whose backend
        # reports no memory stats (single-device/CPU)
        print(json.dumps({"programs": rows, "devices": devices},
                         indent=2))
    else:
        print(render_table(rows))
        if devices:
            print(render_device_table(devices))
    return 0


if __name__ == "__main__":
    sys.exit(main())
