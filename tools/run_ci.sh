#!/usr/bin/env bash
# CI driver (the reference's paddle/scripts/paddle_build.sh role):
# full test suite, API-signature gate, multi-device dryrun, and a bench
# smoke — everything the round driver checks, runnable locally.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1/4 full test suite =="
python -m pytest tests/ -q

echo "== 2/4 API signature gate =="
python tools/print_signatures.py > /tmp/api_live.txt
python tools/diff_api.py tools/api_signatures.txt /tmp/api_live.txt

echo "== 3/4 8-device virtual-mesh dryrun =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== 4/4 bench smoke (CPU backend, tiny) =="
python bench.py --model mlp --device cpu --iterations 5 --skip_batch_num 1

echo "CI OK"
