#!/usr/bin/env bash
# CI driver (the reference's paddle/scripts/paddle_build.sh role):
# full test suite, API-signature gate, multi-device dryrun, and a bench
# smoke — everything the round driver checks, runnable locally.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1/5 full test suite =="
python -m pytest tests/ -q

echo "== 2/5 API signature gate =="
python tools/print_signatures.py > /tmp/api_live.txt
python tools/diff_api.py tools/api_signatures.txt /tmp/api_live.txt

echo "== 3/5 8-device virtual-mesh dryrun =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== 4/5 bench smoke (CPU backend, tiny) =="
python bench.py --model mlp --device cpu --iterations 5 --skip_batch_num 1

echo "== 5/5 observability tooling smoke (program_report + trace_summary) =="
OBS_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR"' EXIT
JAX_PLATFORMS=cpu python - "$OBS_DIR" <<'PY'
import sys
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor, profiler

out = sys.argv[1]
monitor.enable(log_dir=out)
x = fluid.layers.data("x", shape=[8])
loss = fluid.layers.mean(fluid.layers.fc(x, size=4, act="relu"))
fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
with profiler.profiler("CPU", profile_path=None):
    for _ in range(3):
        exe.run(feed={"x": np.random.rand(4, 8).astype("float32")},
                fetch_list=[loss])
profiler.export_chrome_tracing(out + "/trace.json")
monitor.disable()
PY
python tools/program_report.py "$OBS_DIR" --top 5
python tools/trace_summary.py "$OBS_DIR/trace.json" --top 10 --sorted_key calls

echo "CI OK"
