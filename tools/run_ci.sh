#!/usr/bin/env bash
# CI driver (the reference's paddle/scripts/paddle_build.sh role):
# full test suite, API-signature gate, multi-device dryrun, and a bench
# smoke — everything the round driver checks, runnable locally.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1/6 test suite (tier-1 gate: -m 'not slow'; run the slow set =="
echo "==     explicitly with: python -m pytest tests/ -m slow)        =="
python -m pytest tests/ -q -m 'not slow'

echo "== 2/6 API signature gate =="
python tools/print_signatures.py > /tmp/api_live.txt
python tools/diff_api.py tools/api_signatures.txt /tmp/api_live.txt

echo "== 3/6 8-device virtual-mesh dryrun =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== 4/6 bench smoke (CPU backend, tiny) =="
python bench.py --model mlp --device cpu --iterations 5 --skip_batch_num 1

echo "== 5/6 observability tooling smoke (program_report + trace_summary) =="
OBS_DIR=$(mktemp -d)
SMOKE_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR"' EXIT
JAX_PLATFORMS=cpu python - "$OBS_DIR" <<'PY'
import sys
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor, profiler

out = sys.argv[1]
monitor.enable(log_dir=out)
x = fluid.layers.data("x", shape=[8])
loss = fluid.layers.mean(fluid.layers.fc(x, size=4, act="relu"))
fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
with profiler.profiler("CPU", profile_path=None):
    for _ in range(3):
        exe.run(feed={"x": np.random.rand(4, 8).astype("float32")},
                fetch_list=[loss])
profiler.export_chrome_tracing(out + "/trace.json")
monitor.disable()
PY
python tools/program_report.py "$OBS_DIR" --top 5
python tools/trace_summary.py "$OBS_DIR/trace.json" --top 10 --sorted_key calls

echo "== 6/6 preemption smoke (SIGTERM a monitored run -> exact resume) =="
cat > "$SMOKE_DIR/smoke.py" <<'PY'
import os, signal, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.getcwd())          # run_ci runs from the repo root
mode, ckpt = sys.argv[1], sys.argv[2]
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.contrib import Trainer, CheckpointConfig
from paddle_tpu.reader import checkpointable

monitor.enable(log_dir=os.path.join(os.path.dirname(ckpt), "monitor"))

def train_func():
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    x = fluid.layers.data("x", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    pred = fluid.layers.fc(h, size=4, act="softmax")
    return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

def samples():
    rng = np.random.RandomState(0)
    for _ in range(24):
        x = rng.rand(8).astype("float32")
        yield x, np.array([int(np.argmax(x[:4]))], "int64")

cfg = CheckpointConfig(checkpoint_dir=ckpt, step_interval=1)
trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                  optimizer_func=lambda: fluid.optimizer.Adam(1e-2),
                  checkpoint_config=cfg)
if mode == "resume":
    print("RESUMED", cfg.load_serial, flush=True)
    assert cfg.load_serial == 3, cfg.load_serial
state = {"step": cfg.load_serial or 0}

def handler(event):
    if not hasattr(event, "metrics"):
        return
    state["step"] += 1
    print("STEP %d %r" % (state["step"],
                          float(np.ravel(event.metrics[0])[0])),
          flush=True)
    if mode == "run" and state["step"] == 3:
        os.kill(os.getpid(), signal.SIGTERM)   # preemption notice

trainer.train(num_epochs=1, event_handler=handler,
              reader=checkpointable(fluid.batch(samples, batch_size=4)),
              feed_order=["x", "label"])
PY
JAX_PLATFORMS=cpu python "$SMOKE_DIR/smoke.py" ref "$SMOKE_DIR/ref_ckpt" \
  > "$SMOKE_DIR/ref.out"
set +e
JAX_PLATFORMS=cpu python "$SMOKE_DIR/smoke.py" run "$SMOKE_DIR/ckpt" \
  > "$SMOKE_DIR/run.out"
rc=$?
set -e
test "$rc" -eq 143  # the flush ran, then SIGTERM's default proceeded
JAX_PLATFORMS=cpu python "$SMOKE_DIR/smoke.py" resume "$SMOKE_DIR/ckpt" \
  > "$SMOKE_DIR/resume.out"
grep -q "^RESUMED 3$" "$SMOKE_DIR/resume.out"
# resumed steps 4-6 must reproduce the uninterrupted run's losses exactly
diff <(grep "^STEP [456] " "$SMOKE_DIR/ref.out") \
     <(grep "^STEP [456] " "$SMOKE_DIR/resume.out")
grep -ql checkpoint_saved "$SMOKE_DIR"/monitor/*.jsonl

echo "CI OK"
