#!/usr/bin/env bash
# CI driver (the reference's paddle/scripts/paddle_build.sh role):
# full test suite, API-signature gate, multi-device dryrun, and a bench
# smoke — everything the round driver checks, runnable locally.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1/20 test suite (tier-1 gate: -m 'not slow'; run the slow set =="
echo "==     explicitly with: python -m pytest tests/ -m slow)        =="
python -m pytest tests/ -q -m 'not slow'

echo "== 2/20 API signature gate =="
python tools/print_signatures.py > /tmp/api_live.txt
python tools/diff_api.py tools/api_signatures.txt /tmp/api_live.txt

echo "== 3/20 8-device virtual-mesh dryrun =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== 4/20 bench smoke (CPU backend, tiny) =="
python bench.py --model mlp --device cpu --iterations 5 --skip_batch_num 1

echo "== 5/20 observability tooling smoke (program_report + trace_summary) =="
OBS_DIR=$(mktemp -d)
SMOKE_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR"' EXIT
JAX_PLATFORMS=cpu python - "$OBS_DIR" <<'PY'
import sys
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor, profiler

out = sys.argv[1]
monitor.enable(log_dir=out)
x = fluid.layers.data("x", shape=[8])
loss = fluid.layers.mean(fluid.layers.fc(x, size=4, act="relu"))
fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
with profiler.profiler("CPU", profile_path=None):
    for _ in range(3):
        exe.run(feed={"x": np.random.rand(4, 8).astype("float32")},
                fetch_list=[loss])
profiler.export_chrome_tracing(out + "/trace.json")
monitor.disable()
PY
python tools/program_report.py "$OBS_DIR" --top 5
python tools/trace_summary.py "$OBS_DIR/trace.json" --top 10 --sorted_key calls

echo "== 6/20 preemption smoke (SIGTERM a monitored run -> exact resume) =="
cat > "$SMOKE_DIR/smoke.py" <<'PY'
import os, signal, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.getcwd())          # run_ci runs from the repo root
mode, ckpt = sys.argv[1], sys.argv[2]
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.contrib import Trainer, CheckpointConfig
from paddle_tpu.reader import checkpointable

monitor.enable(log_dir=os.path.join(os.path.dirname(ckpt), "monitor"))

def train_func():
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    x = fluid.layers.data("x", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    pred = fluid.layers.fc(h, size=4, act="softmax")
    return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

def samples():
    rng = np.random.RandomState(0)
    for _ in range(24):
        x = rng.rand(8).astype("float32")
        yield x, np.array([int(np.argmax(x[:4]))], "int64")

cfg = CheckpointConfig(checkpoint_dir=ckpt, step_interval=1)
trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                  optimizer_func=lambda: fluid.optimizer.Adam(1e-2),
                  checkpoint_config=cfg)
if mode == "resume":
    print("RESUMED", cfg.load_serial, flush=True)
    assert cfg.load_serial == 3, cfg.load_serial
state = {"step": cfg.load_serial or 0}

def handler(event):
    if not hasattr(event, "metrics"):
        return
    state["step"] += 1
    print("STEP %d %r" % (state["step"],
                          float(np.ravel(event.metrics[0])[0])),
          flush=True)
    if mode == "run" and state["step"] == 3:
        os.kill(os.getpid(), signal.SIGTERM)   # preemption notice

trainer.train(num_epochs=1, event_handler=handler,
              reader=checkpointable(fluid.batch(samples, batch_size=4)),
              feed_order=["x", "label"])
PY
JAX_PLATFORMS=cpu python "$SMOKE_DIR/smoke.py" ref "$SMOKE_DIR/ref_ckpt" \
  > "$SMOKE_DIR/ref.out"
set +e
JAX_PLATFORMS=cpu python "$SMOKE_DIR/smoke.py" run "$SMOKE_DIR/ckpt" \
  > "$SMOKE_DIR/run.out"
rc=$?
set -e
test "$rc" -eq 143  # the flush ran, then SIGTERM's default proceeded
JAX_PLATFORMS=cpu python "$SMOKE_DIR/smoke.py" resume "$SMOKE_DIR/ckpt" \
  > "$SMOKE_DIR/resume.out"
grep -q "^RESUMED 3$" "$SMOKE_DIR/resume.out"
# resumed steps 4-6 must reproduce the uninterrupted run's losses exactly
diff <(grep "^STEP [456] " "$SMOKE_DIR/ref.out") \
     <(grep "^STEP [456] " "$SMOKE_DIR/resume.out")
grep -ql checkpoint_saved "$SMOKE_DIR"/monitor/*.jsonl

echo "== 7/20 fsdp mesh smoke (4 virtual devices, sharding_rules) =="
FSDP_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR"' EXIT
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
  python - "$FSDP_DIR" <<'PY'
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
import jax
import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.models import transformer as tfm
from paddle_tpu.parallel import make_mesh

out = sys.argv[1]
monitor.enable(log_dir=out)
fluid.default_main_program().random_seed = 7
fluid.default_startup_program().random_seed = 7
src = fluid.layers.data("src_word", shape=[1], dtype="int64", lod_level=1)
tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64", lod_level=1)
lbl = fluid.layers.data("lbl_word", shape=[1], dtype="int64", lod_level=1)
loss, _ = tfm.transformer(src, tgt, lbl, 8, 8, 32, 32, n_layer=2,
                          n_head=2, d_model=16, d_inner=32,
                          dropout_rate=0.1)
fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

mesh = make_mesh((1, 4), ("dp", "fsdp"))
bs = fluid.BuildStrategy()
bs.sharding_rules = True
fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                            build_strategy=bs)
rng = np.random.RandomState(0)
for step in range(4):
    ids = rng.randint(2, 32, (8, 8, 1)).astype("int64")
    lens = rng.randint(4, 9, (8,)).astype("int32")
    (lv,) = pe.run(feed={"src_word": ids, "src_word@LEN": lens,
                         "tgt_word": ids, "tgt_word@LEN": lens,
                         "lbl_word": ids, "lbl_word@LEN": lens},
                   fetch_list=[loss])
    lv = float(np.asarray(lv).ravel()[0])
    assert np.isfinite(lv), lv
    print("FSDP STEP %d loss %.6f" % (step, lv), flush=True)
from jax.sharding import PartitionSpec as P
emb = fluid.global_scope().var("src_word_emb")
assert isinstance(emb, jax.Array) and emb.sharding.spec == P("fsdp"), \
    emb.sharding
print("FSDP SHARDED src_word_emb", emb.sharding.spec, flush=True)
monitor.disable()
PY
# the profile registry captured the SHARDED per-device peak HBM
# (the kind column truncates to 10 chars: "parallel_e")
python tools/program_report.py "$FSDP_DIR" --top 3 | tee "$FSDP_DIR/report.txt"
grep -q "parallel_e" "$FSDP_DIR/report.txt"

echo "== 8/20 guardian smoke (NaN injected at step 5 -> rollback -> finite) =="
GUARD_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR"' EXIT
# the drill is installed purely from the environment (FLAGS_fault_spec)
# and the guardian purely from flags — no code changes to the script
JAX_PLATFORMS=cpu \
FLAGS_guardian=1 FLAGS_guardian_policy=rollback,abort \
FLAGS_fault_spec='nan_var:fc_0.w_0@5' \
  python - "$GUARD_DIR" <<'PY'
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.contrib import Trainer, CheckpointConfig
from paddle_tpu.reader import checkpointable

out = sys.argv[1]
monitor.enable(log_dir=os.path.join(out, "monitor"))

def train_func():
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    x = fluid.layers.data("x", shape=[8])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    pred = fluid.layers.fc(h, size=4, act="softmax")
    return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

def samples():
    rng = np.random.RandomState(0)
    for _ in range(48):
        x = rng.rand(8).astype("float32")
        yield x, np.array([int(np.argmax(x[:4]))], "int64")

losses = []
def handler(ev):
    if hasattr(ev, "metrics"):
        losses.append(float(np.ravel(ev.metrics[0])[0]))
        print("STEP %d %.6f" % (len(losses), losses[-1]), flush=True)

trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                  optimizer_func=lambda: fluid.optimizer.Adam(1e-2),
                  checkpoint_config=CheckpointConfig(
                      checkpoint_dir=os.path.join(out, "ckpt"),
                      step_interval=2, async_save=False))
trainer.train(num_epochs=1, event_handler=handler,
              reader=checkpointable(fluid.batch(samples, batch_size=4)),
              feed_order=["x", "label"])
assert np.isfinite(losses[-1]), losses[-1]
print("GUARDIAN FINAL %.6f after %d observed steps" %
      (losses[-1], len(losses)), flush=True)
PY
# the decision trail landed in the JSONL, run_id-correlated
grep -ql fault_injected "$GUARD_DIR"/monitor/*.jsonl
grep -ql guardian_rollback "$GUARD_DIR"/monitor/*.jsonl

echo "== 9/20 autotune smoke (tune toy MLP -> artifact -> report -> Trainer) =="
TUNE_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR"' EXIT
JAX_PLATFORMS=cpu python - "$TUNE_DIR" <<'PY'
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import autotune, monitor

out = sys.argv[1]
monitor.enable(log_dir=os.path.join(out, "monitor"))
# a fake device-memory ceiling: the probe's rejection mechanism is the
# compiled module's own peak-HBM ESTIMATE vs this limit, never an OOM —
# which is exactly what makes the ladder drivable on the CPU backend
fluid.set_flags({"FLAGS_autotune_hbm_bytes": 3_000_000})
img = fluid.layers.data("img", shape=[784])
label = fluid.layers.data("label", shape=[1], dtype="int64")
h = fluid.layers.fc(img, size=64, act="relu")
pred = fluid.layers.fc(h, size=10, act="softmax")
loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
fluid.optimizer.Adam(1e-3).minimize(loss)
rng = np.random.RandomState(0)
def make_feed(b):
    return {"img": rng.rand(b, 784).astype("float32"),
            "label": rng.randint(0, 10, (b, 1)).astype("int64")}
cfg = autotune.TunedConfig(meta={"model": "mlp_smoke"})
d = autotune.tune_batch_size(
    fluid.default_main_program(), fluid.default_startup_program(),
    make_feed, loss, fluid.CPUPlace(), start=16, max_batch=1024,
    probe_steps=2, config=cfg)
assert d["chosen"], d
# a checkpoint-interval decision from synthetic-but-plausible measured
# costs rides in the same artifact (the Trainer consumes it below)
cfg.add(autotune.decide_checkpoint_interval(
    step_s=0.02, snapshot_s=0.002, save_s=0.01, async_save=False))
path = cfg.save(os.path.join(out, "tuned.json"))
print("TUNED batch=%s -> %s" % (d["chosen"], path), flush=True)
PY
test -s "$TUNE_DIR/tuned.json"
python tools/autotune_report.py "$TUNE_DIR/tuned.json" --verbose \
  | tee "$TUNE_DIR/report.txt"
grep -q "batch_size" "$TUNE_DIR/report.txt"
grep -q "checkpoint_interval" "$TUNE_DIR/report.txt"
# the decision trail landed in the JSONL
grep -ql autotune_decision "$TUNE_DIR"/monitor/*.jsonl
# a Trainer run CONSUMING the artifact completes with finite loss (the
# tuned checkpoint interval re-gates its manager; nothing is pinned)
JAX_PLATFORMS=cpu python - "$TUNE_DIR" <<'PY'
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
import paddle_tpu as fluid
from paddle_tpu.contrib import Trainer, CheckpointConfig
from paddle_tpu.reader import checkpointable

out = sys.argv[1]

def train_func():
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    img = fluid.layers.data("img", shape=[784])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=64, act="relu")
    pred = fluid.layers.fc(h, size=10, act="softmax")
    return fluid.layers.mean(fluid.layers.cross_entropy(pred, label))

def samples():
    rng = np.random.RandomState(0)
    for _ in range(64):
        yield (rng.rand(784).astype("float32"),
               rng.randint(0, 10, (1,)).astype("int64"))

losses = []
def handler(ev):
    if hasattr(ev, "metrics"):
        losses.append(float(np.ravel(ev.metrics[0])[0]))

trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                  optimizer_func=lambda: fluid.optimizer.Adam(1e-3),
                  checkpoint_config=CheckpointConfig(
                      checkpoint_dir=os.path.join(out, "ckpt"),
                      async_save=False),
                  autotune=os.path.join(out, "tuned.json"))
# ceil((0.002+0.01) / (0.035 * 0.02)) = 18: the artifact's tuned
# cadence re-gated the manager (step_interval was NOT pinned)
assert trainer.checkpoint_cfg.step_interval == 18, \
    trainer.checkpoint_cfg.step_interval
trainer.train(num_epochs=1, event_handler=handler,
              reader=checkpointable(fluid.batch(samples, batch_size=16)),
              feed_order=["img", "label"])
assert losses and np.isfinite(losses[-1]), losses[-1:]
print("AUTOTUNE TRAINER FINAL %.6f over %d steps"
      % (losses[-1], len(losses)), flush=True)
PY

echo "== 10/20 goodput smoke + bench-history regression gate =="
GOOD_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR"' EXIT
# (a) a 3-step monitored MLP run -> the goodput ledger attributes its
# wall clock, the report renders it, and the ratio is in (0, 1]
JAX_PLATFORMS=cpu python - "$GOOD_DIR" <<'PY'
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor

out = sys.argv[1]
monitor.enable(log_dir=os.path.join(out, "monitor"))
x = fluid.layers.data("x", shape=[8])
loss = fluid.layers.mean(fluid.layers.fc(x, size=4, act="relu"))
fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
for _ in range(3):
    exe.run(feed={"x": np.random.rand(4, 8).astype("float32")},
            fetch_list=[loss])
s = monitor.goodput_stamp()
assert s["goodput_ratio"] is not None and 0 < s["goodput_ratio"] <= 1, s
print("GOODPUT ratio %.4f over %.3fs (%d steps)"
      % (s["goodput_ratio"], s["wall_seconds"], s["steps"]), flush=True)
PY
python tools/goodput_report.py "$GOOD_DIR/monitor" | tee "$GOOD_DIR/report.txt"
grep -q "goodput ratio" "$GOOD_DIR/report.txt"
grep -q "trace_compile" "$GOOD_DIR/report.txt"
# (b) cross-run regression gate: the committed BENCH_r01-r04 evolution
# PASSes, and a synthetically perturbed (+20% step time) copy of the
# newest comparable artifact comes back REGRESSED
python tools/bench_history.py BENCH_r0*.json --json \
  | python -c "import json,sys; r=json.load(sys.stdin); \
assert r['overall']=='PASS', r['overall']; print('bench_history: committed history PASS')"
python - "$GOOD_DIR" <<'PY'
import copy, json, sys
d = json.load(open("BENCH_r03.json"))
p = copy.deepcopy(d); p["n"] = 99
p["parsed"]["min_step_s"] = round(d["parsed"]["min_step_s"] * 1.2, 6)
p["parsed"]["value"] = round(d["parsed"]["value"] / 1.2, 2)
json.dump(p, open(sys.argv[1] + "/BENCH_r99_perturbed.json", "w"))
PY
set +e
python tools/bench_history.py BENCH_r0*.json "$GOOD_DIR/BENCH_r99_perturbed.json" \
  --json > "$GOOD_DIR/history.json"
rc=$?
set -e
test "$rc" -eq 1   # a regression exits 1 (the CI contract)
python - "$GOOD_DIR" <<'PY'
import json, sys
r = json.load(open(sys.argv[1] + "/history.json"))
assert r["overall"] == "REGRESSED", r["overall"]
bad = [x for x in r["runs"] if x["run"] == "r99"][0]
assert any(c["field"] == "min_step_s" and c["verdict"] == "REGRESSED"
           for c in bad["comparisons"]), bad
print("bench_history: +20% perturbation flagged REGRESSED")
PY

echo "== 11/20 serving smoke (engine over toy MLP, concurrent requests) =="
SERVE_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR"' EXIT
JAX_PLATFORMS=cpu python - "$SERVE_DIR" <<'PY'
import os, sys, threading
sys.path.insert(0, os.getcwd())
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.serving import InferenceEngine

out = sys.argv[1]
monitor.enable(log_dir=os.path.join(out, "monitor"))
fluid.default_startup_program().random_seed = 7
x = fluid.layers.data("x", shape=[32])
h = fluid.layers.fc(x, size=32, act="relu")
pred = fluid.layers.fc(h, size=4, act="softmax")
exe = fluid.Executor(fluid.CPUPlace())
scope = fluid.Scope()
with fluid.scope_guard(scope):
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(os.path.join(out, "model"), ["x"],
                                  [pred], exe)
eng = InferenceEngine(model_dir=os.path.join(out, "model"), slots=8,
                      timeout_s=60.0)
xs = [np.random.RandomState(i).rand(32).astype("float32")
      for i in range(24)]
results = {}
def client(i):
    results[i] = eng.run({"x": xs[i]}, timeout=120)
threads = [threading.Thread(target=client, args=(i,))
           for i in range(len(xs))]
for t in threads:
    t.start()
for t in threads:
    t.join()
assert len(results) == len(xs)
assert all(np.isfinite(v[0]).all() for v in results.values())
s = eng.metrics.summary()
assert s["counts"]["completed"] == len(xs), s
# generous p99 bound: the smoke asserts the SLO pipeline, not the chip
assert s["p99_ms"] is not None and s["p99_ms"] < 10000, s
assert s["goodput_view"]["goodput_ratio"] is not None, s
print("SERVING p50 %.2fms p99 %.2fms over %d requests (%d batches)"
      % (s["p50_ms"], s["p99_ms"], s["counts"]["completed"],
         s["counts"]["batches"]), flush=True)
text = monitor.expose_text()
assert "serving_request_latency_seconds" in text, "missing histogram"
assert "serving_queue_depth" in text, "missing gauge"
eng.close()
monitor.disable()
PY
# per-request serving/* events landed in the JSONL, run_id-correlated
grep -ql serving_request "$SERVE_DIR"/monitor/*.jsonl

echo "== 12/20 pipeline schedules smoke (2 virtual devices: 1F1B/interleaved =="
echo "==       loss parity vs GPipe + measured pipeline_bubble drop)        =="
PIPE_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR" "$PIPE_DIR"' EXIT
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
  python - "$PIPE_DIR" <<'PY'
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.models import transformer as tfm
from paddle_tpu.parallel import make_mesh

out = sys.argv[1]
monitor.enable(log_dir=out)
mesh = make_mesh((1, 2), ("dp", "pp"))
rng = np.random.RandomState(3)
batches = []
for _ in range(3):
    ids = rng.randint(2, 32, (8, 8, 1)).astype("int64")
    lens = rng.randint(4, 9, (8,)).astype("int32")
    batches.append({"src_word": ids, "src_word@LEN": lens,
                    "tgt_word": ids, "tgt_word@LEN": lens,
                    "lbl_word": ids, "lbl_word@LEN": lens})
losses, fractions = {}, {}
# EQUAL (S=2, M=2): the same 4-layer model — gpipe/1f1b run it as 2 fat
# stages, interleaved as 4 thin stages (v=2 chunks per device)
for sched, lps in (("gpipe", 2), ("1f1b", 2), ("interleaved", 1)):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_main_program().random_seed = 13
        fluid.default_startup_program().random_seed = 13
        src = fluid.layers.data("src_word", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data("tgt_word", shape=[1], dtype="int64",
                                lod_level=1)
        lbl = fluid.layers.data("lbl_word", shape=[1], dtype="int64",
                                lod_level=1)
        loss, _ = tfm.transformer(src, tgt, lbl, 8, 8, 32, 32,
                                  n_layer=4, n_head=2, d_model=16,
                                  d_inner=32, dropout_rate=0.0,
                                  pipeline_microbatches=2,
                                  pipeline_layers_per_stage=lps)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        bs = fluid.BuildStrategy()
        bs.pipeline_schedule = sched
        with fluid.scope_guard(fluid.Scope()):
            fluid.Executor(fluid.CPUPlace()).run(
                fluid.default_startup_program())
            pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                        build_strategy=bs)
            pe.run(feed=batches[0], fetch_list=[loss])      # warm
            monitor.goodput_reset()
            losses[sched] = [
                float(np.asarray(pe.run(feed=b, fetch_list=[loss])[0])
                      .ravel()[0]) for b in batches]
        stamp = monitor.goodput_stamp()
        assert stamp["buckets"]["pipeline_bubble"] > 0, stamp
        warm = stamp["buckets"]["pipeline_bubble"] + \
            stamp["buckets"]["compute"]
        fractions[sched] = stamp["buckets"]["pipeline_bubble"] / warm
np.testing.assert_allclose(losses["gpipe"], losses["1f1b"],
                           rtol=2e-4, atol=2e-4)
np.testing.assert_allclose(losses["gpipe"], losses["interleaved"],
                           rtol=2e-4, atol=2e-4)
assert fractions["interleaved"] < fractions["gpipe"], fractions
print("PIPELINE schedules loss parity OK; measured bubble fractions: "
      "gpipe=%.3f 1f1b=%.3f interleaved=%.3f"
      % (fractions["gpipe"], fractions["1f1b"],
         fractions["interleaved"]), flush=True)
monitor.disable()
PY
# the pipeline_bubble bucket landed in the goodput JSONL stamps
grep -ql pipeline_bubble "$PIPE_DIR"/*.jsonl

echo "== 13/20 cluster elastic-resume drill (2 members, SIGKILL one mid-run) =="
CLUSTER_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR" "$PIPE_DIR" "$CLUSTER_DIR"' EXIT
# the supervisor runs the whole acceptance drill: an uninterrupted
# small-mesh reference, a 2-member gloo world over one ClusterMaster
# with per-host sharded checkpoints, SIGKILL of member 1 at step 8, and
# the survivor's barrier-observed lease expiry -> reshape -> re-exec
# onto the smaller mesh -> resume from the last committed step.  It
# asserts the parity band, the manifest's ~1/N per-host bytes, and the
# resume provenance itself; the grep re-checks the headline landed.
python tests/cluster_runner.py supervise "$CLUSTER_DIR" \
  | tee "$CLUSTER_DIR/drill.out"
grep -q "CLUSTER_DRILL OK" "$CLUSTER_DIR/drill.out"
# the ckpt_sharded bench rung emits per-host save wall-clock evidence
# (1/N bytes per host, flat MB/s) that bench_history indexes
python bench.py --model ckpt_sharded --device cpu > "$CLUSTER_DIR/ckpt_bench.json"
python - "$CLUSTER_DIR" <<'PY'
import json, sys
r = json.loads(open(sys.argv[1] + "/ckpt_bench.json").read().strip().splitlines()[-1])
assert r["roundtrip_bit_identical"] is True, r
assert r["bytes_one_over_n"]["4"] < 0.3, r["bytes_one_over_n"]
assert r["save_wall_s"] is not None and r["informational"] is True
print("CKPT_SHARDED per-host wall %.3fs, bytes/N %s, MB/s spread %.2f"
      % (r["save_wall_s"], r["bytes_one_over_n"], r["mb_per_s_spread"]))
PY

echo "== 14/20 quantized inference smoke (pass -> gate -> save -> serving) =="
QUANT_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR" "$PIPE_DIR" "$CLUSTER_DIR" "$QUANT_DIR"' EXIT
# end-to-end int8: accuracy-gated tune_quantization over a toy inference
# program -> TunedConfig evidence -> quantize_inference rewrite ->
# save_inference_model (int8 persistables, fp masters gone) -> a COLD
# serving-engine load of the quantized artifact answers requests with
# finite outputs and an eval delta under the budget
JAX_PLATFORMS=cpu python - "$QUANT_DIR" <<'PY'
import os, sys
sys.path.insert(0, os.getcwd())
import json
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import autotune, monitor
from paddle_tpu.serving import InferenceEngine
from paddle_tpu.transpiler import quantize_inference

out = sys.argv[1]
monitor.enable(log_dir=os.path.join(out, "monitor"))
fluid.default_main_program().random_seed = 11
fluid.default_startup_program().random_seed = 11
x = fluid.layers.data("x", shape=[64])
h = fluid.layers.fc(x, size=256, act="relu")
pred = fluid.layers.fc(h, size=16, act="softmax")
main = fluid.default_main_program()
scope = fluid.Scope()
rng = np.random.RandomState(0)
feed = {"x": rng.rand(8, 64).astype("float32")}
exe = fluid.Executor(fluid.CPUPlace())
with fluid.scope_guard(scope):
    exe.run(fluid.default_startup_program())
    (ref,) = exe.run(main, feed=feed, fetch_list=[pred])
    cfg = autotune.TunedConfig(meta={"model": "quant_smoke"})
    d = autotune.tune_quantization(main, scope, feed, [pred],
                                   fluid.CPUPlace(), probe_steps=2,
                                   min_speedup=0.0, config=cfg)
    assert d["chosen"] is not None, d   # a mode survived the gate
    cfg.save(os.path.join(out, "tuned.json"))
    qprog = quantize_inference(main, scope=scope, mode=d["chosen"])
    fluid.io.save_inference_model(
        os.path.join(out, "model"), ["x"],
        [qprog.global_block().var(pred.name)], exe, main_program=qprog)
# artifact holds int8 weights, not the fp masters
mm = json.load(open(os.path.join(out, "model", "__model__")))
names = [v["name"] for b in mm["program"]["blocks"] for v in b["vars"]]
assert any(n.endswith("@INT8") for n in names), names
assert "fc_0.w_0" not in names, "fp master weight still in artifact"
# cold load into the serving engine; finite outputs, delta under budget
eng = InferenceEngine(model_dir=os.path.join(out, "model"), slots=4,
                      timeout_s=60.0)
outs = [eng.run({"x": feed["x"][i]}) for i in range(8)]
eng.close()
q = np.stack([np.asarray(o[0]) for o in outs])
assert np.isfinite(q).all()
delta = autotune.eval_delta([np.asarray(ref)], [q])
budget = fluid.get_flags("quantize_accuracy_budget")[
    "quantize_accuracy_budget"]
assert delta <= budget, (delta, budget)
print("QUANTIZED mode=%s accuracy_delta=%.6f (budget %.3f), "
      "cold serving load OK" % (d["chosen"], delta, budget), flush=True)
PY
# the gate's decision trail landed in the JSONL
grep -ql '"knob": "quantization"' "$QUANT_DIR"/monitor/*.jsonl || \
  grep -ql quantization "$QUANT_DIR"/monitor/*.jsonl

echo "== 15/20 sparse-embedding smoke (ctr_dnn is_sparse + incremental =="
echo "==       checkpoints: SIGTERM flush -> base+delta resume bit-identical) =="
SPARSE_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR" "$PIPE_DIR" "$CLUSTER_DIR" "$QUANT_DIR" "$SPARSE_DIR"' EXIT
cat > "$SPARSE_DIR/sparse_smoke.py" <<'PY'
import os, signal, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.getcwd())
mode, ckpt = sys.argv[1], sys.argv[2]
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.contrib import Trainer, CheckpointConfig
from paddle_tpu.models.ctr_dnn import ctr_dnn
from paddle_tpu.reader import checkpointable

monitor.enable(log_dir=os.path.join(os.path.dirname(ckpt), "monitor"))
DNN_V, LR_V, T = 400, 50, 5

def train_func():
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    dnn = fluid.layers.data("dnn_ids", shape=[1], dtype="int64",
                            lod_level=1)
    lr = fluid.layers.data("lr_ids", shape=[1], dtype="int64",
                           lod_level=1)
    label = fluid.layers.data("click", shape=[1], dtype="int64")
    cost, _p, _a = ctr_dnn(dnn, lr, label, DNN_V, LR_V)
    return cost

def samples():
    rng = np.random.RandomState(0)
    for _ in range(24):
        yield (rng.randint(0, DNN_V, (T, 1)).astype("int64"),
               rng.randint(0, LR_V, (2, 1)).astype("int64"),
               np.array([int(rng.rand() < 0.5)], "int64"))

# incremental='auto': every is_sparse table + its Adam moments are
# delta-encoded against the step-1 full base
cfg = CheckpointConfig(checkpoint_dir=ckpt, step_interval=1,
                       incremental="auto")
trainer = Trainer(train_func=train_func, place=fluid.CPUPlace(),
                  optimizer_func=lambda: fluid.optimizer.Adam(1e-2),
                  checkpoint_config=cfg)
if mode == "resume":
    print("RESUMED", cfg.load_serial, flush=True)
    assert cfg.load_serial == 3, cfg.load_serial
state = {"step": cfg.load_serial or 0}

def handler(event):
    if not hasattr(event, "metrics"):
        return
    state["step"] += 1
    print("STEP %d %r" % (state["step"],
                          float(np.ravel(event.metrics[0])[0])),
          flush=True)
    if mode == "run" and state["step"] == 3:
        os.kill(os.getpid(), signal.SIGTERM)   # preemption notice

trainer.train(num_epochs=1, event_handler=handler,
              reader=checkpointable(fluid.batch(samples, batch_size=4)),
              feed_order=["dnn_ids", "lr_ids", "click"])
PY
JAX_PLATFORMS=cpu python "$SPARSE_DIR/sparse_smoke.py" ref "$SPARSE_DIR/ref_ckpt" \
  > "$SPARSE_DIR/ref.out"
set +e
JAX_PLATFORMS=cpu python "$SPARSE_DIR/sparse_smoke.py" run "$SPARSE_DIR/ckpt" \
  > "$SPARSE_DIR/run.out"
rc=$?
set -e
test "$rc" -eq 143  # checkpoint flushed, then SIGTERM's default proceeded
# the flushed artifacts are an incremental chain: step 1 full, 2-3 deltas
python - "$SPARSE_DIR/ckpt" <<'PY'
import json, os, sys
ck = sys.argv[1]
steps = sorted(d for d in os.listdir(ck) if d.startswith("step_"))
assert len(steps) >= 3, steps
kinds = []
for d in steps[:3]:
    m = json.load(open(os.path.join(ck, d, "MANIFEST.json")))
    kinds.append("delta" if m.get("incremental") else "full")
assert kinds == ["full", "delta", "delta"], kinds
print("INCREMENTAL CHAIN", kinds, flush=True)
PY
JAX_PLATFORMS=cpu python "$SPARSE_DIR/sparse_smoke.py" resume "$SPARSE_DIR/ckpt" \
  > "$SPARSE_DIR/resume.out"
grep -q "^RESUMED 3$" "$SPARSE_DIR/resume.out"
# base+delta restore: resumed steps 4-6 reproduce the uninterrupted
# run's losses bit-exactly (%r prints full precision)
diff <(grep "^STEP [456] " "$SPARSE_DIR/ref.out") \
     <(grep "^STEP [456] " "$SPARSE_DIR/resume.out")
# touched-row telemetry rode the per-step JSONL records
grep -ql sparse_touched_rows "$SPARSE_DIR"/monitor/*.jsonl

echo "== 16/20 paged-KV + speculative decode smoke (prefix reuse, =="
echo "==       spec==greedy parity, page-leak-free teardown)      =="
PAGED_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR" "$PIPE_DIR" "$CLUSTER_DIR" "$QUANT_DIR" "$SPARSE_DIR" "$PAGED_DIR"' EXIT
JAX_PLATFORMS=cpu python - "$PAGED_DIR/monitor" <<'PY'
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.getcwd())
import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.serving.decoder import build_decoder_lm, sync_draft_weights
from paddle_tpu.serving.engine import GenerationEngine

monitor.enable(log_dir=sys.argv[1])
V, L, S, PS, K = 31, 32, 2, 8, 3
dims = dict(n_layer=1, n_head=2, d_model=16, d_inner=32)
# one full shared page of system prompt + a unique tail token: the
# within-batch aliasing opportunity prefix reuse exists for
system = list(range(2, 2 + PS))
prompts = [system + [9 + i] for i in range(4)]

# baseline: plain greedy through the fixed-region engine
fixed = build_decoder_lm(V, L, S, prefix="cif", **dims)
eng = GenerationEngine(fixed, place=fluid.CPUPlace(),
                       max_new_tokens=6, timeout_s=300.0)
try:
    base = [r.result(600)["tokens"] for r in
            [eng.submit(p) for p in prompts]]
finally:
    eng.close()

# paged target + perfect self-draft (target weights copied onto the
# draft): the full propose/verify/rollback path, deterministically
spec = build_decoder_lm(V, L, S, paged=True, page_size=PS, spec_k=K,
                        prefix="cip", **dims)
draft = build_decoder_lm(V, L, S, prefix="cid", **dims)
eng = GenerationEngine(spec, place=fluid.CPUPlace(), max_new_tokens=6,
                       timeout_s=300.0, draft_spec=draft, start=False)
try:
    synced = sync_draft_weights(eng._scope, spec, draft)
    eng.start()
    outs = [r.result(600)["tokens"] for r in
            [eng.submit(p) for p in prompts]]
    snap = eng.metrics.paged_snapshot()
    leaks = eng._alloc.check_leaks()
finally:
    eng.close()
assert outs == base, (outs, base)            # speculation = greedy
assert snap["prefix_hits"] > 0, snap         # prefix pages aliased
assert snap["spec_accepted"] > 0, snap       # draft tokens survived
assert leaks == [], leaks                    # every page returned
print("PAGED+SPEC OK prefix_hits=%d accepted=%d/%d synced=%d"
      % (snap["prefix_hits"], snap["spec_accepted"],
         snap["spec_proposed"], synced), flush=True)
PY
# the paged/speculation counters rode the run_id-stamped JSONL
grep -ql prefix_hits "$PAGED_DIR"/monitor/*.jsonl

echo "== 17/20 traced serving smoke (request trace trees from JSONL) =="
TRACE_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR" "$PIPE_DIR" "$CLUSTER_DIR" "$QUANT_DIR" "$SPARSE_DIR" "$PAGED_DIR" "$TRACE_DIR"' EXIT
JAX_PLATFORMS=cpu python - "$TRACE_DIR/monitor" <<'PY'
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.getcwd())
import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.monitor import tracing
from paddle_tpu.serving.decoder import build_decoder_lm
from paddle_tpu.serving.engine import GenerationEngine

monitor.enable(log_dir=sys.argv[1])
tracing.enable()
V, L, S, PS = 31, 32, 2, 8
dims = dict(n_layer=1, n_head=2, d_model=16, d_inner=32)
spec = build_decoder_lm(V, L, S, paged=True, page_size=PS,
                        prefix="tci", **dims)
eng = GenerationEngine(spec, place=fluid.CPUPlace(), max_new_tokens=5,
                       timeout_s=300.0)
try:
    # open-loop: more requests than slots, so the trace trees cover
    # queueing, paged admission back-pressure, and slot recycling
    reqs = [eng.submit(list(range(2, 2 + PS)) + [9 + i])
            for i in range(8)]
    outs = [r.result(600) for r in reqs]
finally:
    eng.close()
assert len(outs) == 8 and all(o["tokens"] for o in outs)
# slot-recycling hygiene: every request kept its own trace identity
tids = {r.trace.trace_id for r in reqs}
assert len(tids) == 8, tids
print("TRACED SERVING OK requests=%d" % len(outs), flush=True)
PY
# cross-process assembly gate: >=99% of terminal requests must form
# complete trees (admission -> terminal, every parent link resolving),
# breakdown table printed from the same JSONL the run wrote
python tools/request_trace.py "$TRACE_DIR"/monitor --assert-complete 0.99

echo "== 18/20 serving-fleet failover smoke (2 replicas, SIGKILL one =="
echo "==      under load -> zero lost requests, re-routed completes) =="
FLEET_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR" "$PIPE_DIR" "$CLUSTER_DIR" "$QUANT_DIR" "$SPARSE_DIR" "$PAGED_DIR" "$TRACE_DIR" "$FLEET_DIR"' EXIT
# the supervisor asserts the acceptance criteria itself: 24/24
# completed (zero loss), the victim quarantined, re-routed requests
# finishing on the survivor, pages drained there, parity with direct
# dispatch, and measured re-route latency in the FLEET_DRILL line
JAX_PLATFORMS=cpu python tests/fleet_runner.py supervise "$FLEET_DIR" 2 24
# fleet-assembled trace trees: client + master + both replicas wrote
# one shared JSONL dir; every terminal request must assemble complete
# ACROSS the SIGKILL (rpc-server spans open-anchor on entry)
python tools/request_trace.py "$FLEET_DIR"/monitor --assert-complete 0.99

echo "== 19/20 fleet telemetry drill (3 members, digests over heartbeat, =="
echo "==      delay_dispatch straggler -> alert fires + resolves) =="
TELEM_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR" "$PIPE_DIR" "$CLUSTER_DIR" "$QUANT_DIR" "$SPARSE_DIR" "$PAGED_DIR" "$TRACE_DIR" "$FLEET_DIR" "$TELEM_DIR"' EXIT
# the supervisor asserts the acceptance evidence itself: all 3 members
# push digests over the real heartbeat RPC, the slowed member (m-0)
# flags as straggler and the alert fires with its member_id, merged
# fleet series appear on the master's /metrics, the alert resolves
# after the fault window disarms, and the master JSONL holds the
# firing -> resolved pair
JAX_PLATFORMS=cpu python tests/fleet_telemetry_runner.py supervise "$TELEM_DIR" 3
# the operator pane renders from the same master JSONL (replay path)
python tools/fleet_report.py "$TELEM_DIR"/master

echo "== 20/20 model-health + NaN-provenance drill (fault nan at a named =="
echo "==      param -> guardian quarantines -> provenance names the op)  =="
HEALTH_DIR=$(mktemp -d)
trap 'rm -rf "$OBS_DIR" "$SMOKE_DIR" "$FSDP_DIR" "$GUARD_DIR" "$TUNE_DIR" "$GOOD_DIR" "$SERVE_DIR" "$PIPE_DIR" "$CLUSTER_DIR" "$QUANT_DIR" "$SPARSE_DIR" "$PAGED_DIR" "$TRACE_DIR" "$FLEET_DIR" "$TELEM_DIR" "$HEALTH_DIR"' EXIT
# drill installed purely from the environment: FLAGS_health turns the
# in-graph probe on, FLAGS_fault_spec poisons fc_0.w_0 after step 5, so
# step 6's first consumer of that param (mul -> fc_0.tmp_0) goes
# non-finite — the provenance record must name exactly that op
JAX_PLATFORMS=cpu \
FLAGS_health=1 FLAGS_health_every=2 \
FLAGS_guardian=1 FLAGS_guardian_policy=skip,abort \
FLAGS_fault_spec='nan_var:fc_0.w_0@5' \
  python - "$HEALTH_DIR" <<'PY'
import glob, json, os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import guardian, monitor

out = sys.argv[1]
monitor.enable(log_dir=os.path.join(out, "monitor"))
fluid.default_main_program().random_seed = 7
fluid.default_startup_program().random_seed = 7
x = fluid.layers.data("x", shape=[8])
label = fluid.layers.data("label", shape=[1], dtype="int64")
h = fluid.layers.fc(x, size=16, act="relu")
pred = fluid.layers.fc(h, size=4, act="softmax")
loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
g = guardian.install(guardian.Guardian(
    quarantine_dir=os.path.join(out, "quarantine")))
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
rng = np.random.RandomState(0)
aborted = None
try:
    for step in range(10):
        exe.run(feed={"x": rng.rand(4, 8).astype("float32"),
                      "label": rng.randint(0, 4, (4, 1)).astype("int64")},
                fetch_list=[loss])
    g.flush()
except guardian.GuardianAbortError as e:
    aborted = str(e)
stats = g.stats()
guardian.uninstall()
assert stats["quarantined"] >= 1, stats
# the sidecar carries the op-level attribution of the poisoned param
sidecars = sorted(glob.glob(os.path.join(out, "quarantine", "*.json")))
assert sidecars, "no quarantine sidecar written"
prov = json.load(open(sidecars[0])).get("provenance")
assert prov and prov["found"], prov
assert prov["out_var"] == "fc_0.tmp_0", prov
assert "fc_0.w_0" in prov["in_vars"], prov
# an abort (skip budget) must carry the per-layer health snapshot
if aborted is not None:
    assert "health" in aborted, aborted
print("HEALTH DRILL OK: %s -> %r (op #%d, layer %s)"
      % (prov["op_type"], prov["out_var"], prov["op_index"],
         prov.get("layer")), flush=True)
monitor.disable()
PY
# the provenance event and the per-layer health records landed in the
# JSONL, and the offline report renders both
grep -ql guardian_nan_provenance "$HEALTH_DIR"/monitor/*.jsonl
grep -ql model_health "$HEALTH_DIR"/monitor/*.jsonl
python tools/health_report.py "$HEALTH_DIR/monitor" \
  | tee "$HEALTH_DIR/report.txt"
grep -q "grad_norm" "$HEALTH_DIR/report.txt"
grep -q "nan provenance" "$HEALTH_DIR/report.txt"

echo "CI OK"
