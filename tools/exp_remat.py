"""Remat A/B experiment: can rematerialization remove HBM bytes from the
ResNet-50 train step?

PERF.md's roofline analysis puts the b256 bf16 step at ~91% of the v5e's
HBM bandwidth with est. MXU utilization ~28% — compute is cheap, bytes
are not.  jax.checkpoint trades FLOPs for bytes: instead of storing
every intra-block activation for backward, store a subset and recompute
the rest.  Variants:

  base        store everything (XLA CSEs the auto-vjp recompute away)
  names       per-block jax.checkpoint saving ONLY conv outputs
              (checkpoint_name + save_only_these_names): BN/ReLU
              recomputed in backward — elementwise recompute, removes
              the normalized-activation stores
  full        per-block jax.checkpoint saving nothing but block
              boundaries: one extra forward of FLOPs, maximum byte cut
  offload     save_and_offload_only_these_names is TPU-host offload —
              pointless through this tunnel, not measured

Usage: python tools/exp_remat.py [--batch 256] [--iters 20]
"""
import argparse
import functools
import time

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

CFG = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2), (3, 512, 2048, 2)]


def conv(x, w, stride):
    kh = w.shape[0]
    pad = (kh - 1) // 2
    y = jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=("NCHW", "HWIO", "NCHW"))
    return checkpoint_name(y, "conv_out")


def bn_relu(x, gamma, beta, relu=True):
    red = (0, 2, 3)
    bshape = [1, x.shape[1], 1, 1]
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=red)
    var = jnp.maximum(jnp.mean(jnp.square(xf), axis=red) - jnp.square(mean),
                      0.0)
    y = (xf - mean.reshape(bshape)) * jax.lax.rsqrt(var.reshape(bshape) + 1e-5)
    y = y * gamma.reshape(bshape) + beta.reshape(bshape)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


def init_params(rng):
    params = []

    def w(sh):
        nonlocal rng
        rng, sub = jax.random.split(rng)
        return jax.random.normal(sub, sh, jnp.float32) * 0.05

    params.append(dict(w=w((7, 7, 3, 64)), g=jnp.ones(64), b=jnp.zeros(64)))
    in_c = 64
    for n, mid, out, stride in CFG:
        for i in range(n):
            blk = dict(
                w1=w((1, 1, in_c, mid)), g1=jnp.ones(mid), b1=jnp.zeros(mid),
                w2=w((3, 3, mid, mid)), g2=jnp.ones(mid), b2=jnp.zeros(mid),
                w3=w((1, 1, mid, out)), g3=jnp.ones(out), b3=jnp.zeros(out),
            )
            if i == 0:
                blk["wp"] = w((1, 1, in_c, out))
                blk["gp"] = jnp.ones(out)
                blk["bp"] = jnp.zeros(out)
            params.append(blk)
            in_c = out
    params.append(dict(fc=w((2048, 1000))))
    return params


def block(p, x, stride, cdtype):
    def cast(a):
        return a.astype(cdtype)

    sc = x
    y = conv(x, cast(p["w1"]), 1)
    y = bn_relu(y, p["g1"], p["b1"])
    y = conv(y, cast(p["w2"]), stride)
    y = bn_relu(y, p["g2"], p["b2"])
    y = conv(y, cast(p["w3"]), 1)
    y = bn_relu(y, p["g3"], p["b3"], relu=False)
    if "wp" in p:
        sc = conv(sc, cast(p["wp"]), stride)
        sc = bn_relu(sc, p["gp"], p["bp"], relu=False)
    return jnp.maximum(y + sc, 0.0)


def forward(params, x, cdtype, mode):
    blk = block
    if mode == "names":
        blk = jax.checkpoint(
            block, static_argnums=(2, 3),
            policy=jax.checkpoint_policies.save_only_these_names("conv_out"))
    elif mode == "full":
        blk = jax.checkpoint(block, static_argnums=(2, 3))

    p = params[0]
    x = conv(x.astype(cdtype), p["w"].astype(cdtype), 2)
    x = bn_relu(x, p["g"], p["b"])
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 1, 3, 3),
                              (1, 1, 2, 2), [(0, 0), (0, 0), (1, 1), (1, 1)])
    i = 1
    for n, mid, out, stride in CFG:
        for j in range(n):
            x = blk(params[i], x, stride if j == 0 else 1, cdtype)
            i += 1
    x = jnp.mean(x.astype(jnp.float32), axis=(2, 3))
    return x @ params[-1]["fc"]


def loss_fn(params, x, labels, cdtype, mode):
    lp = jax.nn.log_softmax(forward(params, x, cdtype, mode))
    return -jnp.mean(jnp.take_along_axis(lp, labels[:, None], axis=1))


@functools.partial(jax.jit, static_argnames=("cdtype", "mode"),
                   donate_argnums=(0, 1))
def step(params, vel, x, labels, cdtype, mode):
    loss, grads = jax.value_and_grad(loss_fn)(params, x, labels, cdtype, mode)
    new_p, new_v = [], []
    for p, v in zip(params, vel):
        np_, nv_ = {}, {}
        for k in p:
            nv_[k] = 0.9 * v[k] + grads[len(new_p)][k]
            np_[k] = p[k] - 1e-3 * nv_[k]
        new_p.append(np_)
        new_v.append(nv_)
    return loss, new_p, new_v


def analyze(mode, batch, cdtype):
    params = init_params(jax.random.key(0))
    vel = [{k: jnp.zeros_like(v) for k, v in p.items()} for p in params]
    x = jax.random.normal(jax.random.key(1), (batch, 3, 224, 224),
                          jnp.float32)
    labels = jax.random.randint(jax.random.key(2), (batch,), 0, 1000)
    lowered = step.lower(params, vel, x, labels, cdtype, mode)
    c = lowered.compile()
    ca = c.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    print("  %s: %.2f GB accessed, %.2f TFLOP per step" %
          (mode, ca.get("bytes accessed", 0) / 1e9, ca.get("flops", 0) / 1e12))
    return params, vel, x, labels


def run(mode, batch, iters, cdtype_name):
    cdtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[cdtype_name]
    params, vel, x, labels = analyze(mode, batch, cdtype)
    for _ in range(3):
        loss, params, vel = step(params, vel, x, labels, cdtype, mode)
    float(loss)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, params, vel = step(params, vel, x, labels, cdtype, mode)
        float(loss)  # fetch-sync
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    ips = batch / best
    print("%s %s b%d: %.1f img/s (%.2f ms/step) vs2610=%.3f" %
          (mode, cdtype_name, batch, ips, best * 1e3, ips / 2610.0))
    return ips


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="bf16")
    ap.add_argument("--modes", default="base,names,full")
    args = ap.parse_args()
    for mode in args.modes.split(","):
        run(mode, args.batch, args.iters, args.dtype)
