"""Isolate why the fused kernel runs at ~320 GB/s on an 820 GB/s chip.

Ablations on the stage-1 shape (M=401408, K=256, N=64):
  copy     — read x, write x (pure DMA ceiling through Pallas)
  mm       — matmul only
  mm+bn    — + normalize prologue
  mm+stats — + stats epilogue
  full     — everything
Each chained depth× inside one jit; fetch-synced.
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def make_kernel(prologue, stats):
    def kernel(x_ref, w_ref, mean_ref, rstd_ref, z_ref, sum_ref, sumsq_ref):
        i = pl.program_id(1)
        x = x_ref[...]
        if prologue:
            xf = x.astype(jnp.float32)
            xf = jnp.maximum((xf - mean_ref[...]) * rstd_ref[...], 0.0)
            x = xf.astype(x_ref.dtype)
        z = jax.lax.dot_general(x, w_ref[...], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        z_ref[...] = z.astype(z_ref.dtype)

        @pl.when(i == 0)
        def _init():
            sum_ref[...] = jnp.zeros_like(sum_ref)
            sumsq_ref[...] = jnp.zeros_like(sumsq_ref)

        if stats:
            sum_ref[...] += jnp.sum(z, axis=0)
            sumsq_ref[...] += jnp.sum(z * z, axis=0)
    return kernel


def fused(x, w, mean, rstd, prologue, stats, bm=8192):
    m, k = x.shape
    n = w.shape[1]
    kern = make_kernel(prologue, stats)
    return pl.pallas_call(
        kern,
        grid=(1, m // bm),
        in_specs=[pl.BlockSpec((bm, k), lambda j, i: (i, 0)),
                  pl.BlockSpec((k, n), lambda j, i: (0, j)),
                  pl.BlockSpec((1, k), lambda j, i: (0, 0)),
                  pl.BlockSpec((1, k), lambda j, i: (0, 0))],
        out_specs=[pl.BlockSpec((bm, n), lambda j, i: (i, j)),
                   pl.BlockSpec((n,), lambda j, i: (j,)),
                   pl.BlockSpec((n,), lambda j, i: (j,))],
        out_shape=[jax.ShapeDtypeStruct((m, n), x.dtype),
                   jax.ShapeDtypeStruct((n,), jnp.float32),
                   jax.ShapeDtypeStruct((n,), jnp.float32)],
    )(x, w, mean, rstd)


def copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def copy(x, bm=8192):
    m, k = x.shape
    return pl.pallas_call(
        copy_kernel,
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, k), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, k), x.dtype),
    )(x)


def bench(name, fn, args, bytes_per, iters=20):
    f = jax.jit(fn)

    def sync(out):
        leaf = jax.tree_util.tree_leaves(out)[0]
        float(leaf.reshape(-1)[0])  # scalar fetch, not a full download

    out = f(*args)
    sync(out)
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(*args)
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    print("%-10s %.3f ms  %.0f GB/s" % (name, best * 1e3,
                                        bytes_per / best / 1e9))


def main():
    m, k, n = 401408, 256, 64
    depth = 8
    x = jax.random.normal(jax.random.key(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16)
    ws = [(jax.random.normal(jax.random.key(i + 1), (k, n), jnp.float32)
           * 0.05).astype(jnp.bfloat16) for i in range(depth)]
    w2s = [(jax.random.normal(jax.random.key(100 + i), (n, k), jnp.float32)
            * 0.05).astype(jnp.bfloat16) for i in range(depth)]
    mean = jnp.zeros((1, k), jnp.float32)
    rstd = jnp.ones((1, k), jnp.float32)

    def chain(prologue, stats):
        def f(x):
            s = None
            for w, w2 in zip(ws, w2s):
                z, s1, ss1 = fused(x, w, mean, rstd, prologue, stats)
                x, s, ss = fused(z, w2, mean[:, :n], rstd[:, :n], prologue,
                                 stats)
            return x, s
        return f

    def copy_chain(x):
        for _ in range(depth * 2):
            x = copy(x)
        return x

    bpp = m * k * 2 * 2  # read+write per copy
    bench("copy", copy_chain, (x,), bpp * depth * 2)
    # per fused pair: read x[m,k], write z[m,n], read z, write x'[m,k]
    bpp_pair = (2 * m * k + 2 * m * n) * 2
    for name, pro, st in [("mm", False, False), ("mm+bn", True, False),
                          ("mm+stats", False, True), ("full", True, True)]:
        bench(name, chain(pro, st), (x,), bpp_pair * depth)


if __name__ == "__main__":
    main()
