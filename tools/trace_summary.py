"""Summarize a chrome-trace JSON offline.

Reads a trace exported by ``profiler.export_chrome_tracing`` (or any
chrome://tracing JSON with X-phase ``dur``-microsecond events) and
prints the per-name total/calls/avg/max table — the exact format
``stop_profiler`` prints live — so traces shipped back from remote runs
can be summarized without replaying them.  Zero-duration marks
(``mark_event``: cache hits/misses and other point occurrences) are
tallied separately as ``mark/<name>`` counter totals, matching the
monitor counters they double-publish into.

A goodput attribution block follows the span table: every span is run
through the SAME span->bucket classifier the live goodput ledger uses
(``paddle_tpu.monitor.goodput.classify_span`` — one classification
table, two consumers), so an offline trace and the run's own
``goodput_report`` agree on which seconds were compile, input wait,
checkpoint stall, or recovery.  Spans the ledger excludes (containers,
nested spans, overlapped background work) are totalled separately, and
a trace whose metadata carries the exporter-stamped ``goodput`` summary
prints it verbatim.

Usage:
    python tools/trace_summary.py /path/to/trace.json
    python tools/trace_summary.py trace.json --sorted_key calls --top 10
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(
        description="offline per-name summary of a chrome-trace JSON")
    p.add_argument("trace", help="chrome-trace JSON file "
                                 "(export_chrome_tracing output)")
    p.add_argument("--sorted_key", default=None,
                   choices=["total", "calls", "ave", "max"],
                   help="sort column (default: total)")
    p.add_argument("--top", type=int, default=50,
                   help="max table rows (default 50)")
    args = p.parse_args(argv)

    from paddle_tpu import profiler

    with open(args.trace) as f:
        data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    run_id = (data.get("metadata") or {}).get("run_id") \
        if isinstance(data, dict) else None
    spans, marks = [], {}
    for e in events:
        if not isinstance(e, dict) or e.get("ph", "X") != "X" \
                or "name" not in e:
            continue   # M-phase metadata (a thread may carry only these)
        if not e.get("dur"):
            marks[e["name"]] = marks.get(e["name"], 0) + 1
        else:
            spans.append(e)
    if run_id:
        print("run_id %s" % run_id)
    if not spans and not marks:
        print("no X-phase span events in %s (metadata-only trace)"
              % args.trace)
        return 0
    if spans:
        print(profiler.summarize_events(spans, args.sorted_key,
                                        top=args.top))
    if marks:
        print("\n%-40s %12s" % ("Counter", "count"))
        for name in sorted(marks, key=marks.get, reverse=True)[:args.top]:
            print("%-40s %12d" % ("mark/" + name, marks[name]))
    if spans:
        print("\n" + bucket_block(spans, data))
    return 0


def bucket_block(spans, data):
    """Span->bucket attribution over the trace's X-phase spans, via the
    ledger's own classifier (bucket hints in span args win, then the
    shared name table; excluded spans are shown, not dropped)."""
    from paddle_tpu.monitor.goodput import classify_span

    buckets, excluded = {}, 0.0
    for e in spans:
        dur_s = (e.get("dur") or 0.0) / 1e6
        b = classify_span(e["name"], e.get("args"))
        if b is None:
            excluded += dur_s
        else:
            buckets[b] = buckets.get(b, 0.0) + dur_s
    lines = ["%-18s %12s" % ("bucket (spans)", "seconds"), "-" * 31]
    for b, s in sorted(buckets.items(), key=lambda kv: -kv[1]):
        lines.append("%-18s %12.3f" % (b, s))
    lines.append("%-18s %12.3f" % ("(excluded)", excluded))
    lines.append("(containers/nested/overlapped spans are excluded; "
                 "compute is the live ledger's step remainder, not a "
                 "span — see goodput_report for the exhaustive view)")
    meta_gp = (data.get("metadata") or {}).get("goodput") \
        if isinstance(data, dict) else None
    if meta_gp:
        lines.append("exporter-stamped goodput summary: %s"
                     % json.dumps(meta_gp))
    return "\n".join(lines)


if __name__ == "__main__":
    sys.exit(main())
