"""Summarize a chrome-trace JSON offline.

Reads a trace exported by ``profiler.export_chrome_tracing`` (or any
chrome://tracing JSON with X-phase ``dur``-microsecond events) and
prints the per-name total/calls/avg/max table — the exact format
``stop_profiler`` prints live — so traces shipped back from remote runs
can be summarized without replaying them.

Usage:
    python tools/trace_summary.py /path/to/trace.json
    python tools/trace_summary.py trace.json --sorted_key calls
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(
        description="offline per-name summary of a chrome-trace JSON")
    p.add_argument("trace", help="chrome-trace JSON file "
                                 "(export_chrome_tracing output)")
    p.add_argument("--sorted_key", default=None,
                   choices=["total", "calls", "ave", "max"],
                   help="sort column (default: total)")
    args = p.parse_args(argv)

    from paddle_tpu import profiler

    with open(args.trace) as f:
        data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    spans = [e for e in events if e.get("ph", "X") == "X"]
    if not spans:
        print("no X-phase span events in %s" % args.trace)
        return 1
    print(profiler.summarize_events(spans, args.sorted_key))
    return 0


if __name__ == "__main__":
    sys.exit(main())
