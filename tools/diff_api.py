#!/usr/bin/env python
"""Public-API drift gate CLI (reference ``tools/diff_api.py``): diff the
live signature dump against the checked-in golden file.  The pytest gate
(`tests/test_api_signatures.py`) runs the same comparison in CI; this
script is the developer-facing form:

    python tools/print_signatures.py > /tmp/api.txt
    python tools/diff_api.py tools/api_signatures.txt /tmp/api.txt
"""

import difflib
import sys


def main():
    if len(sys.argv) != 3:
        print(__doc__)
        return 1
    with open(sys.argv[1]) as f:
        origin = f.read().splitlines(keepends=True)
    with open(sys.argv[2]) as f:
        new = f.read().splitlines(keepends=True)
    diffs = list(difflib.unified_diff(
        origin, new, fromfile=sys.argv[1], tofile=sys.argv[2]))
    if not diffs:
        return 0
    sys.stdout.writelines(diffs)
    print(
        "\nAPI drift detected. If intentional, regenerate the golden "
        "file:\n  python tools/print_signatures.py > %s" % sys.argv[1])
    return 1


if __name__ == "__main__":
    sys.exit(main())
