"""Per-run goodput report — where every second of wall clock went.

Renders the exclusive wall-clock attribution the goodput ledger
maintains in-process (``paddle_tpu.monitor.goodput_summary()``) from a
monitor JSONL log: bucket seconds (compute, input_wait, trace_compile,
checkpoint_stall, recovery, probe, stall_idle, other), the goodput
ratio, and the overlapped (non-stall) background work — the offline
twin of the live summary, like ``tools/program_report.py`` is for the
program-profile registry.

Replay sources, in preference order:

* ``goodput`` summary records (the ledger's own cumulative arithmetic,
  stamped periodically, at ``monitor.goodput_stamp()`` calls, and by
  ``Trainer.train`` on exit) — the record with the largest attributed
  wall clock wins;
* failing that, the per-step ``goodput`` delta dicts riding in every
  ``step_stats`` record are summed (exact by construction: each delta
  is the ledger's attribution of all wall clock up to that step).

Usage:
    python tools/goodput_report.py /path/to/monitor_logs        # dir
    python tools/goodput_report.py monitor-1234.jsonl --json
    python tools/goodput_report.py logs/ --run_id 6a711a1e-7060
"""

import argparse
import json
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS_DIR))   # repo root: paddle_tpu
sys.path.insert(0, _TOOLS_DIR)                    # sibling tools

from program_report import load_records  # noqa: E402  (same tools dir)


def summary_from_records(records, run_id=None):
    """Rebuild the per-run attribution summary from JSONL records.
    Returns the summary dict (same shape as
    ``monitor.goodput_summary()``) or None when the log carries no
    goodput records at all."""
    from paddle_tpu.monitor.goodput import BUCKETS

    best_stamp = None
    deltas = {b: 0.0 for b in BUCKETS}
    steps = probe_steps = 0
    saw_delta = False
    for r in records:
        if not isinstance(r, dict):
            continue
        if run_id and r.get("run_id") not in (None, run_id):
            continue
        ev = r.get("event")
        if ev == "goodput" and isinstance(r.get("buckets"), dict):
            if best_stamp is None or (r.get("wall_seconds") or 0.0) \
                    > (best_stamp.get("wall_seconds") or 0.0):
                best_stamp = r
        elif ev == "step_stats":
            steps += 1
            if r.get("probe"):
                probe_steps += 1
            gp = r.get("goodput")
            if isinstance(gp, dict):
                saw_delta = True
                for b, s in gp.items():
                    if b in deltas:
                        deltas[b] += float(s or 0.0)
    delta_wall = sum(deltas.values())
    if best_stamp is not None and \
            (best_stamp.get("wall_seconds") or 0.0) >= delta_wall:
        return {k: best_stamp[k] for k in
                ("buckets", "wall_seconds", "goodput_ratio", "steps",
                 "probe_steps", "recovery_replayed_steps",
                 "overlap_seconds") if k in best_stamp}
    if not saw_delta:
        return None
    buckets = {b: round(s, 6) for b, s in deltas.items()}
    return {"buckets": buckets,
            "wall_seconds": round(delta_wall, 6),
            "goodput_ratio": round(buckets["compute"] / delta_wall, 4)
            if delta_wall > 0 else None,
            "steps": steps, "probe_steps": probe_steps}


def render(summary):
    """Fixed-width attribution table + the one-line verdict."""
    from paddle_tpu.monitor.goodput import BUCKETS

    wall = summary.get("wall_seconds") or 0.0
    lines = ["%-18s %12s %8s" % ("bucket", "seconds", "share"),
             "-" * 40]
    for b in BUCKETS:
        s = (summary.get("buckets") or {}).get(b, 0.0)
        lines.append("%-18s %12.3f %7.1f%%"
                     % (b, s, 100.0 * s / wall if wall > 0 else 0.0))
    lines.append("-" * 40)
    ratio = summary.get("goodput_ratio")
    lines.append("goodput ratio %.4f over %.3fs wall (%s steps)"
                 % (ratio if ratio is not None else 0.0, wall,
                    summary.get("steps", "?")))
    for k, v in sorted((summary.get("overlap_seconds") or {}).items()):
        lines.append("overlapped (not badput): %s %.3fs" % (k, v))
    if summary.get("recovery_replayed_steps"):
        lines.append("recovery replayed %d steps"
                     % summary["recovery_replayed_steps"])
    if summary.get("probe_steps"):
        lines.append("autotune probe steps: %d" % summary["probe_steps"])
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="per-run goodput/badput attribution from a monitor "
                    "JSONL log")
    p.add_argument("log", help="monitor JSONL file, or a "
                               "FLAGS_monitor_log_dir directory")
    p.add_argument("--run_id", default=None,
                   help="only records of this run correlation id")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as JSON instead of a table")
    args = p.parse_args(argv)

    records = load_records(args.log)
    summary = summary_from_records(records, run_id=args.run_id)
    if summary is None:
        print("no goodput records in %s (monitor on? this run predates "
              "the goodput ledger?)" % args.log)
        return 1
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
