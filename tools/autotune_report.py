"""Render a TunedConfig artifact as a human-readable decision table.

The auto-tuner (``paddle_tpu.autotune``) records every decision with
its evidence — probe measurements, rejected candidates, the preflight
estimates vs measured windows that drove each choice.  This CLI turns
that JSON artifact into the table an operator reads before trusting
(or pinning over) a tuned configuration.

Usage:
    python tools/autotune_report.py /path/to/tuned.json
    python tools/autotune_report.py tuned.json --json       # passthrough
    python tools/autotune_report.py tuned.json --verbose    # + candidates
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fmt_value(d):
    """The chosen value column: each knob renders its own shape."""
    knob = d.get("knob")
    if knob == "attention_kernel":
        return "%s @ %s" % ("pallas" if d.get("pallas") else "xla",
                            d.get("shape", "?"))
    v = d.get("chosen")
    if isinstance(v, list):
        return "{%s}" % ",".join(str(x) for x in v)
    return str(v)


def _fmt_evidence(d):
    """One-line evidence summary per knob."""
    knob = d.get("knob")
    if knob == "batch_size":
        cands = d.get("candidates", [])
        ok = sum(1 for c in cands if c.get("status") == "ok")
        rej = [c for c in cands if str(c.get("status", "")).startswith(
            "rejected")]
        parts = ["%d measured" % ok]
        if rej:
            parts.append("%d rejected by HBM estimate" % len(rej))
        reg = [c for c in cands if c.get("status") == "regressed"]
        if reg:
            parts.append("stopped at b%d (s/example regressed)"
                         % reg[0]["batch"])
        if d.get("hbm_limit_bytes"):
            parts.append("ceiling %.1f MiB"
                         % (d["hbm_limit_bytes"] / 1048576.0))
        return ", ".join(parts)
    if knob == "attention_kernel":
        if d.get("cached"):
            return "decision table (warm, no probes)"
        if d.get("xla_step_s") is not None:
            return "A/B xla %.4fs vs pallas %.4fs (speedup %s, min %s)" % (
                d.get("xla_step_s", 0.0), d.get("pallas_step_s", 0.0),
                d.get("speedup"), d.get("min_speedup"))
        return d.get("evidence", "")
    if knob == "bucket_bounds":
        return "fill %.1f%% vs pad-to-max %.1f%% (%d multiples-of-%d " \
            "considered)" % (100 * d.get("fill", 0.0),
                             100 * d.get("pad_to_max_fill", 0.0),
                             d.get("candidates_considered", 0),
                             d.get("multiple", 0))
    if knob == "checkpoint_interval":
        return ("step %.4fs, snapshot %.4fs, save %.4fs -> overhead "
                "%.2f%% of %.2f%% budget%s" % (
                    d.get("step_s", 0.0), d.get("snapshot_s", 0.0),
                    d.get("save_s", 0.0),
                    100 * d.get("overhead_frac", 0.0),
                    100 * d.get("budget", 0.0),
                    ", drain-bound" if d.get("drain_bound_steps", 0)
                    and d.get("chosen") == d.get("drain_bound_steps")
                    else ""))
    return d.get("evidence", "")


def _rejected(d):
    """Rejected/regressed candidate summaries for the verbose view."""
    out = []
    for c in d.get("candidates", []) or []:
        status = c.get("status", "")
        if status == "ok":
            continue
        line = "b%s: %s" % (c.get("batch"), status)
        if c.get("peak_hbm_bytes"):
            line += " (est peak %.1f MiB)" % (c["peak_hbm_bytes"]
                                              / 1048576.0)
        if c.get("projected_peak_hbm_bytes"):
            line += " (projected peak %.1f MiB, no compile spent)" % (
                c["projected_peak_hbm_bytes"] / 1048576.0)
        if c.get("s_per_example") is not None:
            line += " (%.3g s/example)" % c["s_per_example"]
        out.append(line)
    return out


def render(doc, verbose=False):
    meta = doc.get("meta", {})
    decisions = doc.get("decisions", [])
    lines = []
    head = "TunedConfig"
    if meta.get("model"):
        head += " [%s]" % meta["model"]
    if meta.get("run_id"):
        head += "  run_id=%s" % meta["run_id"]
    lines.append(head)
    hdr = "%-20s %-24s %-8s %s" % ("knob", "chosen", "source",
                                   "evidence")
    lines += [hdr, "-" * max(len(hdr), 72)]
    for d in decisions:
        lines.append("%-20s %-24s %-8s %s" % (
            d.get("knob", "?"), _fmt_value(d)[:24],
            (d.get("source", "") or "")[:8], _fmt_evidence(d)))
        if verbose:
            for r in _rejected(d):
                lines.append("    rejected %s" % r)
            if d.get("fingerprint"):
                lines.append("    program %s" % d["fingerprint"])
    if not decisions:
        lines.append("(no decisions recorded)")
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="decision table from a TunedConfig JSON artifact "
                    "(paddle_tpu.autotune)")
    p.add_argument("artifact", help="TunedConfig JSON file (written by "
                                    "TunedConfig.save / bench.py "
                                    "--autotune)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw artifact JSON (validated)")
    p.add_argument("--verbose", action="store_true",
                   help="also list every rejected candidate with the "
                        "evidence that rejected it")
    args = p.parse_args(argv)

    with open(args.artifact) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "decisions" not in doc:
        print("not a TunedConfig artifact: %s" % args.artifact,
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(render(doc, verbose=args.verbose))
    return 0


if __name__ == "__main__":
    sys.exit(main())
