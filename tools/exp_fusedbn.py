"""Fused bn+relu -> 1x1-conv(matmul) -> stats Pallas kernel experiment.

A/B per ResNet-50 1x1 layer shape (b128): XLA chain (normalize+relu,
matmul, one-pass stats of output) vs one Pallas kernel doing all three in
a single HBM pass over the activation.  Decides whether the fused kernel
ships in ops/pallas/conv_bn.py.
"""
import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _kernel(x_ref, w_ref, mean_ref, rstd_ref, gamma_ref, beta_ref,
            z_ref, sum_ref, sumsq_ref, *, apply_bn, relu, m, bm):
    i = pl.program_id(1)  # m block (inner)
    x = x_ref[...]
    # rows beyond m (partial last block) are undefined: zero them so the
    # stats epilogue stays clean (their z rows are write-masked anyway)
    tail = (i + 1) * bm > m
    rows_ok = (i * bm + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)) < m
    if apply_bn:
        xf = x.astype(jnp.float32)
        xf = (xf - mean_ref[...]) * rstd_ref[...] * gamma_ref[...] \
            + beta_ref[...]
        if relu:
            xf = jnp.maximum(xf, 0.0)
        xf = jnp.where(rows_ok, xf, 0.0)
        x = xf.astype(x_ref.dtype)
    else:
        if relu:
            x = jnp.maximum(x, 0.0)
        x = jnp.where(rows_ok, x, jnp.zeros_like(x))
    z = jax.lax.dot_general(
        x, w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    z_ref[...] = z.astype(z_ref.dtype)

    @pl.when(i == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sumsq_ref[...] = jnp.zeros_like(sumsq_ref)

    sum_ref[...] += jnp.sum(z, axis=0)
    sumsq_ref[...] += jnp.sum(z * z, axis=0)


def fused_bn_matmul_stats(x, w, mean, rstd, gamma, beta, apply_bn=True,
                          relu=True, bm=None, bn=None, interpret=False):
    m, k = x.shape
    n = w.shape[1]
    if bn is None:
        bn = n if n <= 2048 else 512
    if bm is None:
        # biggest m-block fitting VMEM: double-buffered x and out blocks,
        # resident w, and the fp32 dot accumulator on the stack
        bm = 8192
        while bm > 128 and (2 * bm * k * 2 + k * bn * 2 + 2 * bm * bn * 2
                            + bm * bn * 4) > 13 * 2**20:
            bm //= 2
    bm = min(bm, m)
    grid = (pl.cdiv(n, bn), pl.cdiv(m, bm))
    zeros1 = jnp.zeros((1, k), jnp.float32)
    args = (x, w) + ((mean.reshape(1, k), rstd.reshape(1, k),
                      gamma.reshape(1, k), beta.reshape(1, k))
                     if apply_bn else (zeros1, zeros1, zeros1, zeros1))
    z, s, ss = pl.pallas_call(
        functools.partial(_kernel, apply_bn=apply_bn, relu=relu,
                          m=m, bm=bm),
        grid=grid,
        in_specs=[pl.BlockSpec((bm, k), lambda j, i: (i, 0)),
                  pl.BlockSpec((k, bn), lambda j, i: (0, j)),
                  pl.BlockSpec((1, k), lambda j, i: (0, 0)),
                  pl.BlockSpec((1, k), lambda j, i: (0, 0)),
                  pl.BlockSpec((1, k), lambda j, i: (0, 0)),
                  pl.BlockSpec((1, k), lambda j, i: (0, 0))],
        out_specs=[pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
                   pl.BlockSpec((bn,), lambda j, i: (j,)),
                   pl.BlockSpec((bn,), lambda j, i: (j,))],
        out_shape=[jax.ShapeDtypeStruct((m, n), x.dtype),
                   jax.ShapeDtypeStruct((n,), jnp.float32),
                   jax.ShapeDtypeStruct((n,), jnp.float32)],
        interpret=interpret,
    )(*args)
    return z, s, ss


def xla_chain(x, w, mean, rstd, gamma, beta, apply_bn=True, relu=True):
    if apply_bn:
        xf = x.astype(jnp.float32)
        xf = (xf - mean) * rstd * gamma + beta
        if relu:
            xf = jnp.maximum(xf, 0.0)
        x = xf.astype(x.dtype)
    elif relu:
        x = jnp.maximum(x, 0.0)
    z = (x @ w).astype(x.dtype)
    zf = z.astype(jnp.float32)
    return z, jnp.sum(zf, axis=0), jnp.sum(zf * zf, axis=0)


SHAPES = [  # (M, K, N) for b128 ResNet-50 1x1 convs
    (128 * 56 * 56, 256, 64),
    (128 * 56 * 56, 64, 256),
    (128 * 28 * 28, 512, 128),
    (128 * 28 * 28, 128, 512),
    (128 * 14 * 14, 1024, 256),
    (128 * 14 * 14, 256, 1024),
    (128 * 7 * 7, 2048, 512),
    (128 * 7 * 7, 512, 2048),
]


def bench_one(fn, args, iters=30):
    f = jax.jit(fn)
    z, s, ss = f(*args)
    jax.block_until_ready(z)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            z, s, ss = f(*args)
        np.asarray(s)  # fetch-sync
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best, (z, s, ss)


def bench_chain(mode, m, k_out, k_mid, depth, dtype, iters=10):
    """Chain `depth` bottleneck pairs (k_out->k_mid->k_out) inside one jit
    so tunnel dispatch latency amortizes; returns seconds per pair."""
    ws = []
    for d in range(depth):
        ws.append((
            (jax.random.normal(jax.random.key(2 * d), (k_out, k_mid),
                               jnp.float32) * (1.0 / k_out ** 0.5)
             ).astype(dtype),
            (jax.random.normal(jax.random.key(2 * d + 1), (k_mid, k_out),
                               jnp.float32) * (1.0 / k_mid ** 0.5)
             ).astype(dtype),
        ))

    def norm_params(s, ss, c):
        mean = s / m
        var = jnp.maximum(ss / m - mean * mean, 0.0)
        return mean, jax.lax.rsqrt(var + 1e-5)

    ones = {k_mid: jnp.ones((k_mid,), jnp.float32),
            k_out: jnp.ones((k_out,), jnp.float32)}
    zeros = {k_mid: jnp.zeros((k_mid,), jnp.float32),
             k_out: jnp.zeros((k_out,), jnp.float32)}

    def one(mode, x, w, mean, rstd, c):
        if mode.startswith("pallas"):
            return fused_bn_matmul_stats(x, w, mean, rstd, ones[c],
                                         zeros[c])
        return xla_chain(x, w, mean.reshape(1, -1), rstd.reshape(1, -1),
                         ones[c].reshape(1, -1), zeros[c].reshape(1, -1))

    def op_nchw(x4, w, mean, rstd, c):
        # models the framework op boundary: NCHW logical in/out, kernel
        # works on [M, C] row-major — transposes between chained ops must
        # cancel in XLA for this integration to be viable
        b, cc, h, wd = x4.shape
        x2 = x4.transpose(0, 2, 3, 1).reshape(-1, cc)
        z, s, ss = fused_bn_matmul_stats(x2, w, mean, rstd, ones[c],
                                         zeros[c])
        z4 = z.reshape(b, h, wd, w.shape[1]).transpose(0, 3, 1, 2)
        return z4, s, ss

    def step(x):
        # x enters raw (pre-BN); stats computed on the fly like the net does
        zf = x.astype(jnp.float32)
        if mode == "pallas_nchw":
            s = jnp.sum(zf, (0, 2, 3))
            ss = jnp.sum(zf * zf, (0, 2, 3))
        else:
            s, ss = jnp.sum(zf, 0), jnp.sum(zf * zf, 0)
        for wa, wb in ws:
            mean, rstd = norm_params(s, ss, k_out)
            if mode == "pallas_nchw":
                z, s, ss = op_nchw(x, wa, mean, rstd, k_out)
                mean, rstd = norm_params(s, ss, k_mid)
                x, s, ss = op_nchw(z, wb, mean, rstd, k_mid)
            else:
                z, s, ss = one(mode, x, wa, mean, rstd, k_out)
                mean, rstd = norm_params(s, ss, k_mid)
                x, s, ss = one(mode, z, wb, mean, rstd, k_mid)
        return x, s

    f = jax.jit(step)
    if mode == "pallas_nchw":
        b = 128
        h = int((m // b) ** 0.5)
        x0 = jax.random.normal(jax.random.key(9), (b, k_out, h, h),
                               jnp.float32).astype(dtype)
    else:
        x0 = jax.random.normal(jax.random.key(9), (m, k_out), jnp.float32
                               ).astype(dtype)
    x, s = f(x0)
    jax.block_until_ready(x)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            x, s = f(x0)
        np.asarray(s)
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best / depth


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="bf16")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--chain", action="store_true")
    args = ap.parse_args()
    if args.chain:
        dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
        # (M, k_out, k_mid) per ResNet-50 stage at b128
        for m, k_out, k_mid, depth in [
                (128 * 56 * 56, 256, 64, 6),
                (128 * 28 * 28, 512, 128, 8),
                (128 * 14 * 14, 1024, 256, 12),
                (128 * 7 * 7, 2048, 512, 12)]:
            # interleave the modes: the shared chip's noise is larger
            # than the effect size in any single window
            tx = tp = tn = 1e9
            for _ in range(3):
                tx = min(tx, bench_chain("xla", m, k_out, k_mid, depth,
                                         dtype))
                tp = min(tp, bench_chain("pallas", m, k_out, k_mid, depth,
                                         dtype))
                tn = min(tn, bench_chain("pallas_nchw", m, k_out, k_mid,
                                         depth, dtype))
            gb = (2 * m * k_out + 2 * m * k_mid) * (
                2 if dtype == jnp.bfloat16 else 4) / 1e9
            print("M%7d %4d<->%4d: xla %.3f ms/pair (%.0f GB/s)  pallas "
                  "%.3f (%.0f GB/s, %.2fx)  nchw %.3f (%.2fx)" %
                  (m, k_out, k_mid, tx * 1e3, gb / tx, tp * 1e3, gb / tp,
                   tx / tp, tn * 1e3, tx / tn))
        return
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    tot_x, tot_p = 0.0, 0.0
    for (m, k, n) in SHAPES:
        key = jax.random.key(0)
        x = jax.random.normal(key, (m, k), jnp.float32).astype(dtype)
        w = (jax.random.normal(jax.random.key(1), (k, n), jnp.float32)
             * 0.05).astype(dtype)
        mean = jnp.zeros((k,), jnp.float32) + 0.1
        rstd = jnp.ones((k,), jnp.float32)
        gamma = jnp.ones((k,), jnp.float32)
        beta = jnp.zeros((k,), jnp.float32)
        if args.check:
            zp, sp, ssp = fused_bn_matmul_stats(x, w, mean, rstd, gamma,
                                                beta)
            zx, sx, ssx = xla_chain(x, w, mean.reshape(1, k),
                                    rstd.reshape(1, k), gamma.reshape(1, k),
                                    beta.reshape(1, k))
            err = np.abs(np.asarray(zp, np.float32)
                         - np.asarray(zx, np.float32)).max()
            serr = np.abs(np.asarray(sp) - np.asarray(sx)).max() / m
            print("  check M%d K%d N%d: z err %.4g  s err %.4g" %
                  (m, k, n, err, serr))
            continue
        tx, _ = bench_one(
            lambda x, w: xla_chain(x, w, mean.reshape(1, k),
                                   rstd.reshape(1, k), gamma.reshape(1, k),
                                   beta.reshape(1, k)), (x, w))
        tp, _ = bench_one(
            lambda x, w: fused_bn_matmul_stats(x, w, mean, rstd, gamma,
                                               beta), (x, w))
        tot_x += tx
        tot_p += tp
        gb = (m * k + m * n) * x.dtype.itemsize / 1e9
        print("M%7d K%5d N%5d: xla %.3f ms (%.0f GB/s)  pallas %.3f ms "
              "(%.0f GB/s)  speedup %.2fx" %
              (m, k, n, tx * 1e3, gb / tx, tp * 1e3, gb / tp, tx / tp))
    if tot_p:
        print("TOTAL: xla %.3f ms  pallas %.3f ms  speedup %.2fx" %
              (tot_x * 1e3, tot_p * 1e3, tot_x / tot_p))


if __name__ == "__main__":
    main()
