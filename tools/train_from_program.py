"""Train from a saved program — no python graph build.

The reference can train a model whose graph was built elsewhere: its
C++ demo trainer loads serialized ProgramDescs and drives the executor
(``paddle/fluid/train/demo/demo_trainer.cc:1``).  This CLI is the
TPU-native analog over the JSON ProgramDesc
(``io.save_train_program``/``load_train_program``): load the FULL
training program (forward + backward + optimizer ops), initialize or
restore parameters, feed data, and step the jit-compiled executor.

Usage:
    python tools/train_from_program.py --model_dir DIR [--steps N]
        [--batch_size B] [--device cpu|tpu] [--params_dir DIR]
        [--feed data.npz] [--save_params_dir DIR]

Without ``--feed``, synthetic batches are generated from the program's
data-var shapes/dtypes (integer fields draw from {0, 1} so any
embedding table size is valid).  ``--feed`` supplies real named arrays
(full-batch; sliced into ``--batch_size`` chunks per step).
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def synthesize_feed(program, feed_names, batch_size, rng):
    """One batch per data var from its declared shape/dtype."""
    feed = {}
    block = program.global_block()
    for name in feed_names:
        v = block.var(name)
        shape = [batch_size if (s is None or s < 0) else s
                 for s in (v.shape or (1,))]
        dtype = str(v.dtype or "float32")
        if "int" in dtype:
            feed[name] = rng.randint(0, 2, shape).astype(dtype)
        else:
            feed[name] = rng.standard_normal(shape).astype(dtype)
        if (v.lod_level or 0) >= 1:
            feed[name + "@LEN"] = np.full((shape[0],), shape[1], "int32")
    return feed


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model_dir", required=True)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--device", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--params_dir", default=None,
                   help="restore persistables instead of running startup")
    p.add_argument("--save_params_dir", default=None,
                   help="save persistables after training")
    p.add_argument("--feed", default=None,
                   help="npz of named arrays (real data; sliced per step)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    import paddle_tpu as fluid

    main_prog, startup, loss_name, feed_names = \
        fluid.io.load_train_program(args.model_dir)
    if not loss_name:
        raise SystemExit("no loss found: save with loss_name or include "
                         "a mean op in the program")
    place = fluid.CPUPlace() if args.device == "cpu" else fluid.TPUPlace(0)
    exe = fluid.Executor(place)
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        if args.params_dir:
            exe.run(startup)   # create optimizer state, then overwrite
            fluid.io.load_persistables(exe, args.params_dir, main_prog)
        else:
            exe.run(startup)
        rng = np.random.RandomState(args.seed)
        data = dict(np.load(args.feed)) if args.feed else None
        for step in range(args.steps):
            if data is not None:
                n = next(iter(data.values())).shape[0]
                lo = (step * args.batch_size) % max(n - args.batch_size + 1,
                                                   1)
                feed = {k: v[lo:lo + args.batch_size]
                        for k, v in data.items()}
            else:
                feed = synthesize_feed(main_prog, feed_names,
                                       args.batch_size, rng)
            (lv,) = exe.run(main_prog, feed=feed, fetch_list=[loss_name])
            val = float(np.asarray(lv).ravel()[0])
            losses.append(val)
            print("step: %d loss: %.6f" % (step, val), flush=True)
            if not np.isfinite(val):
                # fail BEFORE publishing parameters: a diverged run must
                # not leave NaN weights in --save_params_dir
                raise SystemExit("non-finite loss at step %d" % step)
        if args.save_params_dir:
            fluid.io.save_persistables(exe, args.save_params_dir,
                                       main_prog)
    return losses


if __name__ == "__main__":
    main()
