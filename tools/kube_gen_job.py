#!/usr/bin/env python
"""Generate a Kubernetes job spec for multi-host training (reference
``benchmark/fluid/kube_gen_job.py``: emits pserver/trainer
ReplicaSet+Job YAML wired by PADDLE_* env vars).

TPU-native form: one indexed Job of N host processes joined through
``parallel.distributed.init_distributed`` — the same PADDLE_COORDINATOR
/ PADDLE_TRAINERS / PADDLE_TRAINER_ID env contract the runtime reads
(parallel/distributed.py).  There is no pserver role to generate; rank
0's pod DNS name is the coordination service.

    python tools/kube_gen_job.py --name mnist --image my/img \
        --entry "python train.py" --hosts 4 > job.yaml
"""

import argparse
import json


def gen_job(name, image, entry, hosts, port=7164, cpu=4, memory="8Gi",
            tpu_resource=None, tpu_count=0):
    """Build the Job manifest dict (indexed completion mode: the pod's
    completion index IS the trainer id)."""
    coordinator = "%s-0.%s:%d" % (name, name, port)
    env = [
        {"name": "PADDLE_COORDINATOR", "value": coordinator},
        {"name": "PADDLE_TRAINERS", "value": str(hosts)},
        {"name": "PADDLE_TRAINER_ID",
         "valueFrom": {"fieldRef": {
             "fieldPath":
                 "metadata.annotations['batch.kubernetes.io/"
                 "job-completion-index']"}}},
    ]
    resources = {"requests": {"cpu": str(cpu), "memory": memory}}
    if tpu_resource and tpu_count:
        resources["limits"] = {tpu_resource: str(tpu_count)}
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name},
        "spec": {
            "completions": hosts,
            "parallelism": hosts,
            "completionMode": "Indexed",
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "subdomain": name,   # stable pod DNS for rank 0
                    "restartPolicy": "Never",
                    "containers": [{
                        "name": "trainer",
                        "image": image,
                        "command": ["sh", "-c", entry],
                        "ports": [{"containerPort": port}],
                        "env": env,
                        "resources": resources,
                    }],
                },
            },
        },
    }


def gen_service(name, port=7164):
    """Headless service providing the stable ``<name>-0.<name>`` DNS the
    coordinator address uses."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name},
        "spec": {"clusterIP": "None",
                 "selector": {"app": name},
                 "ports": [{"port": port}]},
    }


def _to_yaml(obj, indent=0):
    """Minimal YAML emitter (no external deps): dicts/lists/scalars."""
    pad = "  " * indent
    lines = []
    if isinstance(obj, dict):
        for k, v in obj.items():
            if isinstance(v, (dict, list)) and v:
                lines.append("%s%s:" % (pad, k))
                lines.append(_to_yaml(v, indent + 1))
            elif isinstance(v, dict):
                lines.append("%s%s: {}" % (pad, k))   # empty mapping
            elif isinstance(v, list):
                lines.append("%s%s: []" % (pad, k))   # empty sequence
            else:
                lines.append("%s%s: %s" % (pad, k, _scalar(v)))
    elif isinstance(obj, list):
        for item in obj:
            if isinstance(item, dict) and not item:
                lines.append("%s- {}" % pad)        # empty mapping item
            elif isinstance(item, list) and not item:
                lines.append("%s- []" % pad)        # empty sequence item
            elif isinstance(item, (dict, list)):
                body = _to_yaml(item, indent + 1).splitlines()
                first = body[0].strip() if body else ""
                lines.append("%s- %s" % (pad, first))
                lines.extend(body[1:])
            else:
                lines.append("%s- %s" % (pad, _scalar(item)))
    else:
        lines.append("%s%s" % (pad, _scalar(obj)))
    return "\n".join(lines)


def _scalar(v):
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, (int, float)):
        return str(v)
    s = str(v)
    if s == "" or any(c in s for c in ":{}[]#&*!|>'\"%@`") or \
            s.strip() != s:
        return json.dumps(s)
    # strings YAML would type as something else must stay strings
    # (k8s env values are strings; bare `4`, `true`, `0x1F` would
    # parse as int/bool/int)
    if s.lower() in ("true", "false", "yes", "no", "on", "off",
                     "null", "none", "~"):
        return json.dumps(s)
    try:
        float(s)
        return json.dumps(s)
    except ValueError:
        pass
    try:
        int(s, 0)              # hex/octal/binary literals
        return json.dumps(s)
    except ValueError:
        pass
    return s


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--name", required=True)
    p.add_argument("--image", required=True)
    p.add_argument("--entry", required=True,
                   help="training command run in each host pod")
    p.add_argument("--hosts", type=int, default=1)
    p.add_argument("--port", type=int, default=7164)
    p.add_argument("--cpu", type=int, default=4)
    p.add_argument("--memory", default="8Gi")
    p.add_argument("--tpu_resource", default="google.com/tpu",
                   help="device resource name (empty to omit)")
    p.add_argument("--tpu_count", type=int, default=0)
    args = p.parse_args()
    docs = [gen_service(args.name, args.port),
            gen_job(args.name, args.image, args.entry, args.hosts,
                    args.port, args.cpu, args.memory,
                    args.tpu_resource or None, args.tpu_count)]
    print("\n---\n".join(_to_yaml(d) for d in docs))


if __name__ == "__main__":
    main()
