"""Fleet telemetry report — the operator's one pane over a pod.

Renders the per-host table the master-side ``FleetAggregator``
maintains (step time, goodput ratio, queue depth, digest age, straggler
flag) plus merged fleet series (exact p50/p99 of every merged
histogram, fleet goodput ratio) and the alert state (active alerts from
a live master; full firing→resolved history from a JSONL replay).

Two sources:

* a live master — ``--master host:port`` calls the ``fleet_view`` RPC
  verb (any ClusterMaster/FleetMaster with a FleetAggregator attached);
* JSONL replay — point it at a monitor log dir (or one file) from the
  MASTER process: the latest ``fleet_view`` record is the table, the
  ``alert`` records are the history.

Usage:
    python tools/fleet_report.py --master 127.0.0.1:7164
    python tools/fleet_report.py /path/to/master_monitor_logs
    python tools/fleet_report.py logs/ --json       # bench embedding
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_records(path):
    """All JSONL records under ``path`` (file or directory, rotated
    generations included).  Torn tail lines are skipped."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.jsonl"))
                       + glob.glob(os.path.join(path, "*.jsonl.*")))
    else:
        files = [path]
    records = []
    for f in files:
        with open(f) as fh:
            for ln in fh:
                try:
                    records.append(json.loads(ln))
                except ValueError:
                    continue
    return records


def view_from_records(records):
    """(latest fleet_view record, full alert event history) from a
    master-process JSONL replay — None view when the log has no
    ``fleet_view`` records (telemetry was off, or this is a member's
    log, not the master's)."""
    view = None
    alerts = []
    for rec in records:
        ev = rec.get("event")
        if ev == "fleet_view":
            if view is None or rec.get("ts", 0) >= view.get("ts", 0):
                view = rec
        elif ev == "alert":
            alerts.append(rec)
    alerts.sort(key=lambda a: a.get("ts", 0))
    return view, alerts


def _fmt(v, spec="%s", none="-"):
    return none if v is None else spec % v


def render_table(view, alert_history=None):
    """The per-host table + fleet summary + alert block as text lines."""
    lines = []
    hosts = (view or {}).get("hosts") or {}
    lines.append("%-20s %10s %10s %8s %8s %6s %10s" % (
        "host", "step_s", "goodput", "queue", "dig_age", "strag",
        "ckpt_age"))
    for h in sorted(hosts):
        d = hosts[h]
        lines.append("%-20s %10s %10s %8s %8s %6s %10s" % (
            h,
            _fmt(d.get("step_time_s"), "%.4f"),
            _fmt(d.get("goodput_ratio"), "%.3f"),
            _fmt(d.get("queue_depth"), "%d"),
            _fmt(d.get("digest_age_s"), "%.1f"),
            ("YES z=%s" % d.get("z")) if d.get("straggler") else "no",
            _fmt(d.get("checkpoint_age_s"), "%.0fs")))
    if not hosts:
        lines.append("  (no hosts reporting)")
    gp = (view or {}).get("goodput_ratio")
    lines.append("fleet goodput ratio: %s" % _fmt(gp, "%.4f"))
    for name, p in sorted(((view or {}).get("percentiles") or {})
                          .items()):
        lines.append("  %-40s p50 %-10s p99 %-10s n=%d" % (
            name, _fmt(p.get("p50"), "%.4g"), _fmt(p.get("p99"), "%.4g"),
            p.get("count", 0)))
    for label, d in (("expired", (view or {}).get("expired")),
                     ("quarantined", (view or {}).get("quarantined"))):
        for h, age in sorted((d or {}).items()):
            lines.append("  %s %-20s %.0fs ago" % (label, h, age))
    active = (view or {}).get("alerts") or []
    lines.append("active alerts: %d" % len(active))
    for a in active:
        lines.append("  [%s] %-24s %s value=%s threshold=%s" % (
            a.get("severity"), a.get("rule"),
            ("host=%s" % a["member_id"]) if a.get("member_id") else
            "fleet", a.get("value"), a.get("threshold")))
    for a in alert_history or []:
        lines.append("  %s %-9s [%s] %-24s %s" % (
            _fmt(a.get("ts"), "%.1f"), a.get("state"),
            a.get("severity"), a.get("rule"),
            ("host=%s" % a["member_id"]) if a.get("member_id") else
            "fleet"))
    return lines


def fetch_live(address, timeout=10.0):
    """The ``fleet_view`` RPC from a live master."""
    from paddle_tpu.cloud.server import MasterClient

    client = MasterClient(address, timeout=timeout, max_retries=3)
    try:
        return client.call("fleet_view")
    finally:
        client.close()


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="fleet telemetry report (live master or JSONL replay)")
    ap.add_argument("log", nargs="?", default=None,
                    help="monitor JSONL file or log dir (master process)")
    ap.add_argument("--master", default=None,
                    help="live master address host:port (fleet_view RPC)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (bench embedding)")
    args = ap.parse_args(argv)
    if (args.master is None) == (args.log is None):
        ap.error("pass exactly one source: a JSONL path or --master")
    alert_history = []
    if args.master is not None:
        view = fetch_live(args.master)
    else:
        view, alert_history = view_from_records(load_records(args.log))
        if view is None:
            print("no fleet_view records in %r — was fleet telemetry on "
                  "and is this the MASTER's log dir?" % args.log,
                  file=sys.stderr)
            return 1
    if args.json:
        print(json.dumps({"view": view, "alert_history": alert_history},
                         indent=2, sort_keys=True))
    else:
        print("\n".join(render_table(view, alert_history)))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:   # |head closed the pipe: a clean exit
        os._exit(0)
