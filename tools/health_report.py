"""Model-health report — per-layer gradient/update statistics and NaN
provenance from a monitor JSONL log.

Renders the ``model_health`` records the FLAGS_health probe publishes
(per layer class: gradient L2 norm, parameter L2 norm, update/param
ratio, non-finite element count) as a per-layer table — latest value,
max gradient norm over the run, and the step it peaked at — plus every
``guardian_nan_provenance`` event (the op-level attribution of a
non-finite step: first offending op, its output var, layer class,
replay latency).  The offline twin of watching the ``health/<layer>/*``
gauges live.

Usage:
    python tools/health_report.py /path/to/monitor_logs        # dir
    python tools/health_report.py monitor-1234.jsonl --json
    python tools/health_report.py logs/ --run_id 6a711a1e-7060
"""

import argparse
import json
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS_DIR))   # repo root: paddle_tpu
sys.path.insert(0, _TOOLS_DIR)                    # sibling tools

from program_report import load_records  # noqa: E402  (same tools dir)

_STATS = ("grad_norm", "param_norm", "update_ratio", "nonfinite")


def health_from_records(records, run_id=None):
    """Replay JSONL records into the report model: per-layer rows (the
    LAST ``model_health`` record's values + per-run peaks) and the list
    of provenance events, in step order.  ``run_id`` filters to one
    run's records (a shared log dir holds many)."""
    layers = {}      # label -> row dict
    provenance = []
    steps_seen = 0
    last_step = None
    for r in records:
        if not isinstance(r, dict):
            continue
        if run_id and r.get("run_id") not in (None, run_id):
            continue
        ev = r.get("event")
        if ev == "model_health" and isinstance(r.get("layers"), dict):
            steps_seen += 1
            last_step = r.get("step", last_step)
            for label, d in r["layers"].items():
                row = layers.setdefault(label, {
                    "layer": label, "grad_norm_peak": 0.0,
                    "grad_norm_peak_step": None, "nonfinite_total": 0})
                for k in _STATS:
                    if d.get(k) is not None:
                        row[k] = d[k]
                gn = d.get("grad_norm")
                if gn is not None and gn >= row["grad_norm_peak"]:
                    row["grad_norm_peak"] = gn
                    row["grad_norm_peak_step"] = r.get("step")
                row["nonfinite_total"] += int(d.get("nonfinite") or 0)
        elif ev == "guardian_nan_provenance":
            provenance.append(r)
    provenance.sort(key=lambda r: (r.get("step") or 0))
    return {
        "records": steps_seen,
        "last_step": last_step,
        "layers": [layers[k] for k in sorted(layers)],
        "provenance": provenance,
    }


def render_table(report):
    """The human-facing tables (one string)."""
    lines = []
    rows = report["layers"]
    if not rows:
        lines.append("no model_health records found "
                     "(run with FLAGS_health=1 and the monitor on)")
    else:
        lines.append("model health — %d records, last step %s"
                     % (report["records"], report["last_step"]))
        hdr = ("layer", "grad_norm", "param_norm", "update_ratio",
               "nonfinite", "peak grad_norm", "@step")
        table = [hdr]
        for r in rows:
            table.append((
                r["layer"],
                "%.4g" % r.get("grad_norm", float("nan")),
                "%.4g" % r.get("param_norm", float("nan")),
                "%.4g" % r.get("update_ratio", float("nan")),
                "%d" % r.get("nonfinite_total", 0),
                "%.4g" % r["grad_norm_peak"],
                str(r["grad_norm_peak_step"]),
            ))
        widths = [max(len(row[i]) for row in table)
                  for i in range(len(hdr))]
        for i, row in enumerate(table):
            lines.append("  ".join(c.ljust(w)
                                   for c, w in zip(row, widths)).rstrip())
            if i == 0:
                lines.append("-" * (sum(widths) + 2 * (len(widths) - 1)))
    prov = report["provenance"]
    if prov:
        lines.append("")
        lines.append("nan provenance (%d event%s):"
                     % (len(prov), "" if len(prov) == 1 else "s"))
        for p in prov:
            if p.get("found"):
                lines.append(
                    "  step %s: %s -> %r (op #%s%s) replay %.3g ms"
                    % (p.get("step"), p.get("op_type"),
                       p.get("out_var"), p.get("op_index"),
                       ", layer %s" % p["layer"] if p.get("layer")
                       else "", p.get("replay_ms") or 0.0))
            else:
                lines.append(
                    "  step %s: replay stayed finite%s"
                    % (p.get("step"),
                       " (error: %s)" % p["error"] if p.get("error")
                       else " — host-side corruption?"))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Per-layer model-health + NaN-provenance report "
                    "from monitor JSONL logs")
    ap.add_argument("path", help="monitor .jsonl file or log directory")
    ap.add_argument("--run_id", default=None,
                    help="filter to one run's records")
    ap.add_argument("--json", action="store_true",
                    help="emit the report dict as JSON")
    args = ap.parse_args(argv)
    report = health_from_records(load_records(args.path),
                                 run_id=args.run_id)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_table(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
