"""Cross-run bench regression tracking over BENCH_r*.json artifacts.

The committed ``BENCH_r*.json`` artifacts (driver wrappers:
``{n, cmd, rc, tail, parsed}``) and freshly produced bench.py artifacts
(the bare primary JSON line, ``--out`` files) sit on disk with no tool
that compares them — this one ingests both into a history index,
compares every rung's step time / throughput / MFU / goodput ratio
against the **best prior comparable run** with a noise band, and emits
a PASS/REGRESSED table (``--json`` for CI).

Comparability gating (the honest part): bench.py's fetch-sync fix (r3)
invalidated every number recorded before it — BENCH_r01/r02 windows
were synced by ``block_until_ready``, which through this setup's tunnel
returns before execution completes, inflating throughput 2-4.5x
(bench.py docstring; PERF.md).  Runs whose rungs carry no
``min_step_s``/``n_windows`` fields predate that methodology and are
indexed as ``legacy_methodology``: listed, never used as baselines,
never judged.  Runs whose wrapper has ``parsed: null`` (a driver
timeout that killed the artifact, BENCH_r04) are ``incomplete``.

Per-rung fields compared, each with the same relative noise band
(default 5%; the shared chip's invocation-to-invocation noise is ~2%
and load is bursty, PERF.md):

* ``min_step_s``   — lower is better (the primary estimator)
* ``value``        — higher is better (throughput)
* ``mfu``          — higher is better (falls back to ``est_mfu``)
* ``goodput``      — higher is better (``goodput.goodput_ratio``,
  artifacts from schema_version 2 on)

Error rungs (``unit == "error"``) and rungs marked ``informational``
are listed but excluded from the overall verdict — the scored rungs
are the regression gate, exactly as bench.py's ladder defines them.

Usage:
    python tools/bench_history.py BENCH_r0*.json
    python tools/bench_history.py BENCH_r0*.json new_run.json --json
    python tools/bench_history.py ... --noise 0.08 --index history.json
"""

import argparse
import glob
import json
import os
import sys

# (field, better, pretty) — the comparison schema per rung.
# throughput_rps / p99_ms are the serving rung's SLO pair (schema v2+);
# that rung is informational, so they index and judge without gating.
# save_wall_s is the ckpt_sharded rung's per-host checkpoint save wall
# clock (also informational: disk-bound, not chip-bound).
# accuracy_delta is the quantized rung's eval delta vs full precision
# (informational like the rung: indexed and judged, never gating).
# sparse_step_s / dense_step_s / incr_ckpt_bytes are the rec_sparse
# rung's vocab-scaling evidence at vocab=1e6 (sparse warm step, the
# dense A/B step, and the incremental-checkpoint delta bytes — all
# lower is better; informational like the rung).
# sessions_at_fixed_hbm / spec_tok_s / prefix_hit_rate are the
# decode_paged rung's ISSUE-16 triple (HBM-per-session ratio,
# speculative token rate, prefix-cache hit rate — all higher is
# better; informational like the rung, indexed so regressions in the
# decode path surface across rounds without gating).
# p99_queue_wait_ms / p99_decode_ms are the ISSUE-17 request-trace
# stage p99s (serving admission wait; per-tick decode share on the
# paged arm) — informational, never gating: they attribute a p99_ms
# move to a stage, they don't independently gate a run.
# aggregate_rps / reroute_latency_ms are the ISSUE-18 serving-fleet
# pair (4-replica routed aggregate req/s against mock-backend
# capacity; p99 first-route-to-accepted-completion failover latency)
# — informational: both ride multi-process drills whose absolute
# numbers move with host load, so they index trends, never gate.
# fields that are informational PER-FIELD, even inside a gating rung:
# judged against history and printed, but never counted into a run's
# ``regressions`` — stage attribution explains a p99_ms move, it must
# not double-gate it
INFORMATIONAL_FIELDS = frozenset({"p99_queue_wait_ms",
                                  "p99_decode_ms",
                                  "aggregate_rps",
                                  "reroute_latency_ms",
                                  "digest_build_us",
                                  "straggler_detect_windows",
                                  "health_overhead_pct_c1",
                                  "health_overhead_pct_c10",
                                  "provenance_replay_ms"})

FIELDS = (("min_step_s", "lower", "step_s"),
          ("value", "higher", "value"),
          ("mfu", "higher", "mfu"),
          ("goodput", "higher", "goodput"),
          ("throughput_rps", "higher", "rps"),
          ("p99_ms", "lower", "p99"),
          ("save_wall_s", "lower", "save_s"),
          ("accuracy_delta", "lower", "acc_d"),
          ("sparse_step_s", "lower", "sp_step"),
          ("dense_step_s", "lower", "dn_step"),
          ("incr_ckpt_bytes", "lower", "incr_b"),
          ("sessions_at_fixed_hbm", "higher", "sess_x"),
          ("spec_tok_s", "higher", "spec_ts"),
          ("prefix_hit_rate", "higher", "pfx_hit"),
          ("p99_queue_wait_ms", "lower", "p99_qw"),
          ("p99_decode_ms", "lower", "p99_dec"),
          ("aggregate_rps", "higher", "agg_rps"),
          ("reroute_latency_ms", "lower", "rerte"),
          ("digest_build_us", "lower", "dig_us"),
          ("straggler_detect_windows", "lower", "strag_w"),
          # ISSUE-20 model-health probe: FLAGS_health step overhead at
          # publication cadence 1 / 10 and the one-shot NaN-provenance
          # replay latency — informational (CPU wall clock), indexed so
          # probe-cost regressions surface across rounds
          ("health_overhead_pct_c1", "lower", "hlth_c1"),
          ("health_overhead_pct_c10", "lower", "hlth_c10"),
          ("provenance_replay_ms", "lower", "prov_ms"))


def _rung_record(r):
    """Normalize one rung dict (primary or extra_metrics entry)."""
    if not isinstance(r, dict) or not r.get("metric"):
        return None
    out = {"metric": r["metric"], "unit": r.get("unit"),
           "value": r.get("value"),
           "vs_baseline": r.get("vs_baseline"),
           "informational": bool(r.get("informational"))
           or r.get("unit") == "error" or "error" in r,
           "error": r.get("error")}
    if r.get("min_step_s") is not None:
        out["min_step_s"] = r["min_step_s"]
        out["n_windows"] = r.get("n_windows")
    mfu = r.get("mfu", r.get("exact_mfu", r.get("est_mfu")))
    if mfu is not None:
        out["mfu"] = mfu
    for f in ("throughput_rps", "p99_ms", "save_wall_s",
              "accuracy_delta", "sparse_step_s", "dense_step_s",
              "incr_ckpt_bytes", "sessions_at_fixed_hbm",
              "spec_tok_s", "prefix_hit_rate",
              "p99_queue_wait_ms", "p99_decode_ms",
              "aggregate_rps", "reroute_latency_ms",
              "digest_build_us", "straggler_detect_windows",
              "health_overhead_pct_c1", "health_overhead_pct_c10",
              "provenance_replay_ms"):
        if r.get(f) is not None:
            out[f] = r[f]
    gp = r.get("goodput")
    if isinstance(gp, dict) and gp.get("goodput_ratio") is not None:
        out["goodput"] = gp["goodput_ratio"]
    return out


def normalize_run(payload, key, order):
    """One artifact -> a normalized history entry.  ``payload`` is the
    bench.py primary dict (already unwrapped); ``key`` a stable run
    name; ``order`` the comparison ordering index."""
    rungs = []
    for r in [payload] + list(payload.get("extra_metrics") or []):
        rec = _rung_record(r)
        if rec is not None:
            rungs.append(rec)
    comparable = any("min_step_s" in r for r in rungs)
    return {"run": key, "order": order,
            "run_id": payload.get("run_id"),
            "schema_version": payload.get("schema_version", 1),
            "ladder_complete": payload.get("ladder_complete"),
            "status": "ok" if comparable else "legacy_methodology",
            "rungs": rungs}


def load_artifact(path, order):
    """Load one artifact file: a driver wrapper ({n, rc, parsed}), a
    bare bench.py JSON line/dict, or a JSONL whose LAST parseable line
    is the artifact (the ladder reprints the primary after every
    rung)."""
    with open(path) as f:
        text = f.read().strip()
    try:
        data = json.loads(text)
    except ValueError:
        data = None
        for ln in reversed(text.splitlines()):
            try:
                data = json.loads(ln)
                break
            except ValueError:
                continue
        if data is None:
            return {"run": _run_key(path, None), "order": order,
                    "status": "unparseable", "rungs": []}
    if isinstance(data, dict) and "parsed" in data and "rc" in data:
        # driver wrapper (the committed BENCH_r*.json shape)
        key = _run_key(path, data.get("n"))
        if not isinstance(data.get("parsed"), dict):
            return {"run": key, "order": order, "status": "incomplete",
                    "rc": data.get("rc"), "rungs": []}
        out = normalize_run(data["parsed"], key, order)
        out["rc"] = data.get("rc")
        return out
    if isinstance(data, dict):
        return normalize_run(data, _run_key(path, None), order)
    return {"run": _run_key(path, None), "order": order,
            "status": "unparseable", "rungs": []}


def _run_key(path, n):
    if n is not None:
        return "r%02d" % int(n)
    return os.path.splitext(os.path.basename(path))[0]


def _judge(field, better, cur, best, noise):
    """PASS/REGRESSED verdict for one field against the prior best."""
    if cur is None or best is None:
        return None
    if better == "lower":
        regressed = cur > best * (1.0 + noise)
        delta = (cur - best) / best if best else 0.0
    else:
        regressed = cur < best * (1.0 - noise)
        delta = (cur - best) / best if best else 0.0
    return {"field": field, "current": cur, "best_prior": best,
            "delta": round(delta, 4),
            "verdict": "REGRESSED" if regressed else "PASS"}


def compare(runs, noise=0.05):
    """Judge every comparable run against the best prior comparable
    values per (metric, field).  Mutates each run dict with a
    ``comparisons`` list; returns the overall report."""
    runs = sorted(runs, key=lambda r: r["order"])
    # best-so-far per (metric, field), built run by run so each run is
    # judged only against STRICTLY PRIOR history
    best = {}
    latest_judged = None
    for run in runs:
        comparisons = []
        if run["status"] == "ok":
            for rung in run["rungs"]:
                if rung.get("error"):
                    continue   # failed rung: nothing meaningful to judge
                for field, better, _ in FIELDS:
                    cur = rung.get(field)
                    if cur is None:
                        continue
                    v = _judge(field, better,
                               cur, best.get((rung["metric"], field)),
                               noise)
                    if v is not None:
                        v.update(metric=rung["metric"],
                                 informational=rung["informational"]
                                 or field in INFORMATIONAL_FIELDS)
                        comparisons.append(v)
            run["comparisons"] = comparisons
            run["regressions"] = [
                c for c in comparisons
                if c["verdict"] == "REGRESSED" and not c["informational"]]
            run["verdict"] = "REGRESSED" if run["regressions"] else "PASS"
            latest_judged = run
            # fold this run into the baselines AFTER judging it
            # (informational rungs too: they are judged-not-gating, so
            # they need baselines; error rungs carry no numbers)
            for rung in run["rungs"]:
                if rung.get("error"):
                    continue
                for field, better, _ in FIELDS:
                    cur = rung.get(field)
                    if cur is None:
                        continue
                    k = (rung["metric"], field)
                    if k not in best:
                        best[k] = cur
                    elif better == "lower":
                        best[k] = min(best[k], cur)
                    else:
                        best[k] = max(best[k], cur)
    overall = latest_judged["verdict"] if latest_judged is not None \
        else "NO_COMPARABLE_RUNS"
    return {"noise_band": noise, "runs": runs,
            "latest": latest_judged["run"] if latest_judged else None,
            "overall": overall}


def render(report):
    lines = []
    for run in report["runs"]:
        if run["status"] != "ok":
            lines.append("%-12s %s%s" % (
                run["run"], run["status"],
                " (rc=%s)" % run.get("rc")
                if run.get("rc") not in (None, 0) else ""))
            continue
        lines.append("%-12s %s  (%d rungs, schema v%s)"
                     % (run["run"], run.get("verdict", "-"),
                        len(run["rungs"]), run.get("schema_version")))
        for c in run.get("comparisons", []):
            lines.append(
                "  %-44s %-10s %12.6g vs best %12.6g  %+6.1f%%  %s%s"
                % (c["metric"], c["field"], c["current"],
                   c["best_prior"], 100 * c["delta"], c["verdict"],
                   " (informational)" if c["informational"] else ""))
    lines.append("overall (latest comparable run%s): %s"
                 % (" %s" % report["latest"] if report["latest"] else "",
                    report["overall"]))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="cross-run bench regression tracking over bench "
                    "artifacts (driver wrappers or bare bench.py JSON)")
    p.add_argument("artifacts", nargs="+",
                   help="artifact files in run order (globs ok); driver "
                        "wrappers order by their 'n', the rest by "
                        "position")
    p.add_argument("--noise", type=float, default=0.05,
                   help="relative noise band before a delta counts as a "
                        "regression (default 0.05)")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as JSON (CI mode); exit "
                        "code stays 0/1/2 either way")
    p.add_argument("--index", default=None,
                   help="also write the normalized history index to "
                        "this JSON file")
    args = p.parse_args(argv)

    paths = []
    for a in args.artifacts:
        hits = sorted(glob.glob(a))
        paths.extend(hits if hits else [a])
    runs = []
    for i, path in enumerate(paths):
        try:
            runs.append(load_artifact(path, i))
        except OSError as e:
            print("cannot read %s: %s" % (path, e), file=sys.stderr)
            return 2
    # wrapper runs carry their own ordinal: honor it over file order
    for r in runs:
        if r["run"].startswith("r") and r["run"][1:].isdigit():
            r["order"] = (0, int(r["run"][1:]))
        else:
            r["order"] = (1, r["order"])
    report = compare(runs, noise=args.noise)
    if args.index:
        tmp = args.index + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2)
        os.replace(tmp, args.index)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report))
    return 1 if report["overall"] == "REGRESSED" else 0


if __name__ == "__main__":
    sys.exit(main())
