"""Assemble per-request trace trees from monitor JSONL logs.

Reads ``trace_span`` records out of one or more monitor JSONL files (or
directories of them — every host's ``monitor-<pid>.jsonl`` plus rotated
generations), joins them by ``trace_id`` across processes, and prints
the latency-breakdown table (route / queue_wait / padding / page_wait /
prefill / decode / spec_reject / other) the tracing module computes —
one attribution model, two consumers (this CLI and the bench rung
embeds).

Fleet-routed requests assemble the same way: point this tool at the
SHARED log dir of a serving fleet (client + fleet master + every
replica write there) and each request is one ``fleet_request``-rooted
tree spanning three processes — the client root, the master's ``route``
decision span, and the replica-side ``request`` subtree — with the
``route`` stage carrying the control-plane cost.  A replica SIGKILLed
mid-request still leaves a resolvable subtree (rpc-server spans and
request roots open-anchor on entry), so ``--assert-complete`` holds
across failovers.

Usage:
    python tools/request_trace.py /path/to/logdir
    python tools/request_trace.py host-a.jsonl host-b.jsonl --json
    python tools/request_trace.py logdir --trace 3900f6574ed14446
    python tools/request_trace.py logdir --assert-complete 0.99

``--trace <id>`` prints one request's span tree (indent = parent depth,
cross-process spans annotated with their run_id).  ``--assert-complete
F`` exits nonzero unless at least fraction F of terminal requests
assembled into complete trees — the CI serving-smoke gate.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_records(paths):
    """trace_span records from JSONL files/directories (rotated
    ``*.jsonl.N`` generations included); non-JSON and non-trace lines
    are skipped, not fatal — the logs carry every monitor event."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*.jsonl")))
                         + sorted(glob.glob(os.path.join(p,
                                                         "*.jsonl.*"))))
        else:
            files.append(p)
    records = []
    for fp in files:
        try:
            with open(fp) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) \
                            and rec.get("event") == "trace_span":
                        records.append(rec)
        except OSError as e:
            print("warning: cannot read %s: %s" % (fp, e),
                  file=sys.stderr)
    return records, files


def render_tree(tree):
    """One request's span tree, indented by parent depth."""
    from paddle_tpu.monitor import tracing

    by_parent = {}
    for s in tree["spans"]:
        by_parent.setdefault(s.get("parent_id"), []).append(s)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s.get("mono_us") or 0.0)
    run_ids = tree.get("run_ids") or []
    multi = len(run_ids) > 1
    lines = ["trace %s  (%s, %d spans, run_ids: %s)" % (
        tree["trace_id"],
        "complete" if tree["complete"] else "INCOMPLETE",
        len(tree["spans"]), ", ".join(run_ids) or "-")]

    def walk(parent_id, depth):
        for s in by_parent.get(parent_id, []):
            attrs = s.get("attrs") or {}
            extra = " ".join("%s=%s" % kv for kv in sorted(attrs.items()))
            tag = ("  [run %s]" % s.get("run_id")) if multi else ""
            lines.append("%s%-24s %10.3fms  %-8s %s%s" % (
                "  " * depth, s.get("name"),
                float(s.get("dur_ms") or 0.0), s.get("status"),
                extra, tag))
            walk(s.get("span_id"), depth + 1)

    walk(None, 1)
    # orphans (unresolved parent links) still print, flagged
    known = {s.get("span_id") for s in tree["spans"]}
    for s in tree["spans"]:
        pid = s.get("parent_id")
        if pid and pid not in known:
            lines.append("  (orphan) %-24s %10.3fms  %-8s parent=%s"
                         % (s.get("name"), float(s.get("dur_ms") or 0.0),
                            s.get("status"), pid))
    bd = tracing.breakdown(tree)
    if bd is not None:
        lines.append("breakdown: " + "  ".join(
            "%s=%.3fms" % (k, v) for k, v in bd["stages"].items()))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="assemble cross-process request trace trees from "
                    "monitor JSONL logs")
    p.add_argument("paths", nargs="+",
                   help="JSONL files or log directories")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable summary (the schema "
                        "bench rungs embed) instead of the table")
    p.add_argument("--trace", default=None, metavar="TRACE_ID",
                   help="print one request's span tree")
    p.add_argument("--assert-complete", type=float, default=None,
                   metavar="FRACTION",
                   help="exit 1 unless >= FRACTION of terminal requests "
                        "assembled into complete trees")
    args = p.parse_args(argv)

    from paddle_tpu.monitor import tracing

    records, files = load_records(args.paths)
    trees = tracing.assemble(records)

    if args.trace is not None:
        tree = trees.get(args.trace)
        if tree is None:
            print("no spans for trace %r in %d files"
                  % (args.trace, len(files)), file=sys.stderr)
            return 1
        print(render_tree(tree))
        return 0

    summary = tracing.breakdown_summary(trees)
    if args.json:
        out = dict(summary)
        out["files"] = len(files)
        out["spans"] = len(records)
        out["requests_detail"] = sorted(
            (b for b in (tracing.breakdown(t) for t in trees.values())
             if b is not None),
            key=lambda b: -b["latency_ms"])
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print("%d trace_span records in %d files; %d traces"
              % (len(records), len(files), len(trees)))
        print(tracing.render_table(summary))

    if args.assert_complete is not None:
        frac = summary["complete_fraction"]
        if summary["terminal"] == 0 or frac is None \
                or frac < args.assert_complete:
            print("FAIL: complete fraction %s < required %.3f "
                  "(%d terminal requests)"
                  % (frac, args.assert_complete, summary["terminal"]),
                  file=sys.stderr)
            return 1
        print("complete fraction %.4f >= %.3f  (%d terminal requests)"
              % (frac, args.assert_complete, summary["terminal"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
