"""Layout A/B experiment: ResNet-50 fwd+bwd+momentum in pure JAX.

Measures NCHW vs NHWC emitted convs on the real chip, bf16 and fp32,
fetch-synced (device_get of the loss forces completion of the donated
step chain).  Drives the layout decision for ops/conv.py: the framework
keeps the NCHW API; this tells us what to emit internally.

Usage: python tools/exp_layout.py [--batch 128] [--iters 20]
"""
import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


DOT1X1 = False


def conv(x, w, stride, layout):
    # w stored as [kh, kw, cin, cout] always; dimension numbers pick layout
    kh = w.shape[0]
    if DOT1X1 and layout == "NHWC" and kh == 1 and stride == 1:
        b, h, wd, c = x.shape
        z = x.reshape(-1, c) @ w.reshape(c, -1)
        return z.reshape(b, h, wd, -1)
    if layout == "NCHW":
        dn = ("NCHW", "HWIO", "NCHW")
    else:
        dn = ("NHWC", "HWIO", "NHWC")
    pad = (kh - 1) // 2
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=dn)


ONEPASS = False


def bn_relu(x, gamma, beta, layout, relu=True):
    c_axis = 1 if layout == "NCHW" else 3
    red = tuple(i for i in range(4) if i != c_axis)
    bshape = [1, 1, 1, 1]
    bshape[c_axis] = x.shape[c_axis]
    xf = x.astype(jnp.float32)
    if ONEPASS:
        mean = jnp.mean(xf, axis=red)
        var = jnp.maximum(jnp.mean(jnp.square(xf), axis=red)
                          - jnp.square(mean), 0.0)
    else:
        mean = jnp.mean(xf, axis=red)
        var = jnp.mean(jnp.square(xf - mean.reshape(bshape)), axis=red)
    y = (xf - mean.reshape(bshape)) * jax.lax.rsqrt(var.reshape(bshape) + 1e-5)
    y = y * gamma.reshape(bshape) + beta.reshape(bshape)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


CFG = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2), (3, 512, 2048, 2)]


def init_params(rng, dtype):
    params = []
    k = 64

    def w(sh):
        nonlocal rng
        rng, sub = jax.random.split(rng)
        return jax.random.normal(sub, sh, jnp.float32) * 0.05

    params.append(dict(w=w((7, 7, 3, 64)), g=jnp.ones(64), b=jnp.zeros(64)))
    in_c = 64
    for n, mid, out, stride in CFG:
        for i in range(n):
            s = stride if i == 0 else 1
            blk = dict(
                w1=w((1, 1, in_c, mid)), g1=jnp.ones(mid), b1=jnp.zeros(mid),
                w2=w((3, 3, mid, mid)), g2=jnp.ones(mid), b2=jnp.zeros(mid),
                w3=w((1, 1, mid, out)), g3=jnp.ones(out), b3=jnp.zeros(out),
            )
            if i == 0:
                blk["wp"] = w((1, 1, in_c, out))
                blk["gp"] = jnp.ones(out)
                blk["bp"] = jnp.zeros(out)
            params.append(blk)
            in_c = out
    params.append(dict(fc=w((2048, 1000))))
    return params


def forward(params, x, layout, cdtype):
    def cast(a):
        return a.astype(cdtype)

    p = params[0]
    x = conv(cast(x), cast(p["w"]), 2, layout)
    x = bn_relu(x, p["g"], p["b"], layout)
    # 3x3 maxpool stride 2
    if layout == "NCHW":
        x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 1, 3, 3),
                                  (1, 1, 2, 2), [(0, 0), (0, 0), (1, 1), (1, 1)])
    else:
        x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                                  (1, 2, 2, 1), [(0, 0), (1, 1), (1, 1), (0, 0)])
    i = 1
    for n, mid, out, stride in CFG:
        for j in range(n):
            p = params[i]
            i += 1
            s = stride if j == 0 else 1
            sc = x
            y = conv(x, cast(p["w1"]), 1, layout)
            y = bn_relu(y, p["g1"], p["b1"], layout)
            y = conv(y, cast(p["w2"]), s, layout)
            y = bn_relu(y, p["g2"], p["b2"], layout)
            y = conv(y, cast(p["w3"]), 1, layout)
            y = bn_relu(y, p["g3"], p["b3"], layout, relu=False)
            if "wp" in p:
                sc = conv(sc, cast(p["wp"]), s, layout)
                sc = bn_relu(sc, p["gp"], p["bp"], layout, relu=False)
            x = jnp.maximum(y + sc, 0.0)
    red = (2, 3) if layout == "NCHW" else (1, 2)
    x = jnp.mean(x.astype(jnp.float32), axis=red)
    logits = x @ params[-1]["fc"]
    return logits


def loss_fn(params, x, labels, layout, cdtype):
    logits = forward(params, x, layout, cdtype)
    lp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(lp, labels[:, None], axis=1))


@functools.partial(jax.jit, static_argnames=("layout", "cdtype"))
def fwd_only(params, x, labels, layout, cdtype):
    return loss_fn(params, x, labels, layout, cdtype)


@functools.partial(jax.jit, static_argnames=("layout", "cdtype"),
                   donate_argnums=(0, 1))
def step(params, vel, x, labels, layout, cdtype):
    loss, grads = jax.value_and_grad(loss_fn)(params, x, labels, layout,
                                              cdtype)
    new_p, new_v = [], []
    for p, v in zip(params, vel):
        np_, nv_ = {}, {}
        for k in p:
            nv_[k] = 0.9 * v[k] + grads[len(new_p)][k]
            np_[k] = p[k] - 1e-3 * nv_[k]
        new_p.append(np_)
        new_v.append(nv_)
    return loss, new_p, new_v


def run(layout, cdtype_name, batch, iters):
    cdtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[cdtype_name]
    rng = jax.random.key(0)
    params = init_params(rng, cdtype)
    vel = [{k: jnp.zeros_like(v) for k, v in p.items()} for p in params]
    shape = (batch, 3, 224, 224) if layout == "NCHW" else (batch, 224, 224, 3)
    x = jax.random.normal(jax.random.key(1), shape, jnp.float32)
    labels = jax.random.randint(jax.random.key(2), (batch,), 0, 1000)
    # warmup
    for _ in range(3):
        loss, params, vel = step(params, vel, x, labels, layout, cdtype)
    float(loss)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, params, vel = step(params, vel, x, labels, layout, cdtype)
        float(loss)  # fetch-sync
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    ips = batch / best
    # forward-only split
    lossf = fwd_only(params, x, labels, layout, cdtype)
    float(lossf)
    fbest = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            lossf = fwd_only(params, x, labels, layout, cdtype)
        float(lossf)
        dt = (time.perf_counter() - t0) / iters
        fbest = dt if fbest is None else min(fbest, dt)
    print("%s %s b%d: %.1f img/s (%.2f ms/step, fwd %.2f ms)  vs2610=%.3f" %
          (layout, cdtype_name, batch, ips, best * 1e3, fbest * 1e3,
           ips / 2610.0))
    return ips


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--configs", default="NCHW:bf16,NHWC:bf16,NCHW:f32,NHWC:f32")
    ap.add_argument("--onepass", action="store_true")
    ap.add_argument("--dot1x1", action="store_true")
    args = ap.parse_args()
    ONEPASS = args.onepass
    DOT1X1 = args.dot1x1
    for cfg in args.configs.split(","):
        layout, dt = cfg.split(":")
        run(layout, dt, args.batch, args.iters)
