"""The same model through the v2 API dialect (reference v2 book style:
layer DSL -> parameters -> trainer.SGD with events).

Run: JAX_PLATFORMS=cpu python examples/v2_mnist.py
"""
import numpy as np

from paddle_tpu import v2 as paddle


def main():
    paddle.init(use_gpu=False)
    img = paddle.layer.data(name="img",
                            type=paddle.data_type.dense_vector(784))
    hidden = paddle.layer.fc(input=img, size=128,
                             act=paddle.activation.Relu())
    pred = paddle.layer.fc(input=hidden, size=10,
                           act=paddle.activation.Softmax())
    lbl = paddle.layer.data(name="lbl",
                            type=paddle.data_type.integer_value(10))
    cost = paddle.layer.classification_cost(input=pred, label=lbl)

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost, params, paddle.optimizer.Adam(learning_rate=1e-3))

    rng = np.random.RandomState(0)
    centers = rng.randn(10, 784).astype("float32")

    def reader():
        for _ in range(512):
            y = int(rng.randint(0, 10))
            yield (centers[y] + 0.3 * rng.randn(784)).astype("float32"), y

    def handler(e):
        if isinstance(e, paddle.event.EndPass):
            print("pass", e.pass_id, "done")

    trainer.train(paddle.batch(reader, 64), num_passes=4,
                  event_handler=handler)

    probs = paddle.infer(output_layer=pred, parameters=params,
                         input=[(centers[i],) for i in range(10)])
    acc = np.mean(np.argmax(probs, 1) == np.arange(10))
    print("center acc %.2f" % acc)
    assert acc > 0.9
    print("OK")


if __name__ == "__main__":
    main()
