"""The same model through the v1 trainer-config DSL (reference
trainer_config_helpers usage: settings + *_layer + mixed_layer +
outputs, parsed by trainer.config_parser), executed by the shared
engine via the v2 trainer.

Run: JAX_PLATFORMS=cpu python examples/v1_config_mnist.py
"""
import numpy as np

import paddle_tpu.trainer_config_helpers as tch
from paddle_tpu import v2 as paddle
from paddle_tpu.trainer import config_parser


def network():
    tch.settings(batch_size=64, learning_rate=1e-3,
                 learning_method=tch.AdamOptimizer())
    img = tch.data_layer("img", size=784)
    with tch.mixed_layer(size=128, bias_attr=True,
                         act=tch.ReluActivation()) as m:
        m += tch.full_matrix_projection(img)
    pred = tch.fc_layer(m, size=10, act=tch.SoftmaxActivation())
    lbl = tch.data_layer("lbl", size=0,
                         type=paddle.data_type.integer_value(10))
    cost = tch.classification_cost(input=pred, label=lbl)
    tch.outputs(cost)
    return cost


def main():
    tc = config_parser.parse_config(network)
    print("parsed config:", tc.to_dict()["opt_config"])

    # the parse left the built graph live: train it with the v2 trainer
    cost = tc.model_config.output_layers[0]
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(cost, params,
                                 tch.current_settings().to_v2())

    rng = np.random.RandomState(0)
    centers = rng.randn(10, 784).astype("float32")

    def reader():
        for _ in range(512):
            y = int(rng.randint(0, 10))
            yield (centers[y] + 0.3 * rng.randn(784)).astype("float32"), y

    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    trainer.train(paddle.batch(reader, 64), num_passes=4,
                  event_handler=handler)
    print("first %.3f last %.3f" % (costs[0], costs[-1]))
    assert costs[-1] < costs[0] * 0.3
    print("OK")


if __name__ == "__main__":
    main()
