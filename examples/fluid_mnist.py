"""MNIST MLP through the fluid-parity dialect (reference
tests/book/test_recognize_digits.py usage) — build a Program with
layers, train with Executor, save/load an inference model.

Run: JAX_PLATFORMS=cpu python examples/fluid_mnist.py  (or on TPU,
drop the env var and use fluid.TPUPlace(0))
"""
import numpy as np

import paddle_tpu as fluid


def main():
    img = fluid.layers.data("img", shape=[784])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    hidden = fluid.layers.fc(img, size=128, act="relu")
    pred = fluid.layers.fc(hidden, size=10, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    acc = fluid.layers.accuracy(input=pred, label=label)
    test_program = fluid.default_main_program().clone(for_test=True)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    centers = rng.randn(10, 784).astype("float32")
    for step in range(60):
        ys = rng.randint(0, 10, 64)
        xs = (centers[ys] + 0.3 * rng.randn(64, 784)).astype("float32")
        lv, av = exe.run(feed={"img": xs, "label": ys[:, None]},
                         fetch_list=[loss, acc])
        if step % 20 == 0:
            print("step %d loss %.4f acc %.2f" % (step, lv[0], av[0]))

    ys = rng.randint(0, 10, 256)
    xs = (centers[ys] + 0.3 * rng.randn(256, 784)).astype("float32")
    lv, av = exe.run(test_program, feed={"img": xs, "label": ys[:, None]},
                     fetch_list=[loss, acc])
    print("eval loss %.4f acc %.2f" % (lv[0], av[0]))
    assert av[0] > 0.9
    print("OK")


if __name__ == "__main__":
    main()
