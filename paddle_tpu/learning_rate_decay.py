"""Top-level ``learning_rate_decay`` module name (the reference exports
it in ``fluid.__all__``; the implementations live in
``layers/learning_rate_scheduler.py`` there and here)."""

from .layers.learning_rate_scheduler import *  # noqa: F401,F403
from .layers import learning_rate_scheduler as _lrs

__all__ = list(_lrs.__all__)
