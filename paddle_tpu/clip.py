"""Gradient / error clipping.

Parity: reference ``python/paddle/fluid/clip.py`` (359 LoC):
``ErrorClipByValue``, ``GradientClipByValue``, ``GradientClipByNorm``,
``GradientClipByGlobalNorm`` — clip ops appended between backward and
optimizer ops, attached per-param via ParamAttr.gradient_clip or globally
via ``set_gradient_clip``.
"""

from .core import VarType
from .framework import default_main_program
from .layer_helper import LayerHelper


def _propagate_sparse(src, dst):
    """Clip products of a SELECTED_ROWS gradient are themselves sparse
    (the kernels keep the rows); the var type must follow so downstream
    build-time consumers (the regularizer's lazy-decay branch) see it."""
    if getattr(src, "type", None) == VarType.SELECTED_ROWS:
        dst.type = VarType.SELECTED_ROWS
    return dst

__all__ = [
    "ErrorClipByValue",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "set_gradient_clip",
    "append_gradient_clip_ops",
]


class BaseErrorClipAttr:
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    """Clip an activation's backward error signal (reference clip.py:
    ErrorClipByValue)."""

    def __init__(self, max, min=None):
        if min is None:
            min = -max
        self.max = float(max)
        self.min = float(min)

    def _append_clip_op(self, block, grad_name):
        block.append_op(
            type="clip", inputs={"X": [grad_name]},
            outputs={"Out": [grad_name]},
            attrs={"min": self.min, "max": self.max},
        )


def error_clip_callback(block, op):
    for grad_n in op.output_arg_names:
        if not grad_n.endswith("@GRAD"):
            continue
        fwd_var = block._find_var_recursive(grad_n[: -len("@GRAD")])
        if fwd_var is None:
            continue
        error_clip = getattr(fwd_var, "error_clip", None)
        if error_clip is not None:
            error_clip._append_clip_op(block, grad_n)


class BaseGradientClipAttr:
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        if min is None:
            min = -max
        self.max = float(max)
        self.min = float(min)

    def _create_operators(self, param, grad):
        helper = LayerHelper("clip_grad")
        new_grad = helper.create_variable_for_type_inference(dtype=grad.dtype)
        grad.block.append_op(
            type="clip", inputs={"X": [grad]}, outputs={"Out": [new_grad]},
            attrs={"min": self.min, "max": self.max},
        )
        return param, _propagate_sparse(grad, new_grad)


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        helper = LayerHelper("clip_grad_norm")
        new_grad = helper.create_variable_for_type_inference(dtype=grad.dtype)
        grad.block.append_op(
            type="clip_by_norm", inputs={"X": [grad]},
            outputs={"Out": [new_grad]},
            attrs={"max_norm": self.clip_norm},
        )
        return param, _propagate_sparse(grad, new_grad)


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Scale all grads by clip_norm/max(global_norm, clip_norm)
    (reference clip.py:GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        elif context[self.group_name + "_clip_value"] != self.clip_norm:
            raise ValueError(
                "all parameters in a group should share one clip_norm")
        helper = LayerHelper("global_norm_part")
        sq = helper.create_variable_for_type_inference(dtype=grad.dtype)
        grad.block.append_op(
            type="squared_l2_norm", inputs={"X": [grad]},
            outputs={"Out": [sq]},
        )
        context[self.group_name].append(sq)
        context[self.group_name + "_scale_computed"] = None

    def _create_operators(self, param, grad):
        # the scale var is computed once per group lazily
        raise NotImplementedError(
            "handled by append_gradient_clip_ops group logic")


def set_gradient_clip(clip, param_list=None, program=None):
    """Set a per-program default gradient clip (reference clip.py:
    set_gradient_clip).  Without ``param_list`` the clip attaches to the
    *program* (not process-global state, which would leak into unrelated
    programs built later in the same process)."""
    program = program or default_main_program()
    if param_list is not None:
        for p in param_list:
            if isinstance(p, str):
                p = program.global_block().var(p)
            p.gradient_clip_attr = clip
    else:
        program._gradient_clip_attr = clip


def append_gradient_clip_ops(param_grads):
    """Apply per-param / global clip attrs to gradients (reference
    clip.py:append_gradient_clip_ops)."""
    context = {}
    clips = []
    for p, g in param_grads:
        if g is None:
            clips.append((p, g, None))
            continue
        prog_clip = getattr(p.block.program, "_gradient_clip_attr", None)
        clip_attr = getattr(p, "gradient_clip_attr", None) or \
            prog_clip or NullGradientClipAttr()
        clip_attr._process_context(context, p, g)
        clips.append((p, g, clip_attr))

    # resolve global-norm groups: compute scale per group
    group_scales = {}
    for group_name, sq_list in list(context.items()):
        if not isinstance(sq_list, list):
            continue
        clip_value = context[group_name + "_clip_value"]
        helper = LayerHelper("global_norm")
        block = sq_list[0].block
        total = helper.create_variable_for_type_inference(dtype=sq_list[0].dtype)
        block.append_op(type="sum", inputs={"X": sq_list},
                        outputs={"Out": [total]})
        norm = helper.create_variable_for_type_inference(dtype=total.dtype)
        block.append_op(type="sqrt", inputs={"X": [total]},
                        outputs={"Out": [norm]})
        # scale = clip / max(norm, clip)
        maxed = helper.create_variable_for_type_inference(dtype=total.dtype)
        clip_var = helper.create_variable_for_type_inference(dtype=total.dtype)
        block.append_op(
            type="fill_constant", outputs={"Out": [clip_var]},
            attrs={"shape": [1], "value": clip_value,
                   "dtype": str(total.dtype)},
        )
        block.append_op(
            type="elementwise_max", inputs={"X": [norm], "Y": [clip_var]},
            outputs={"Out": [maxed]},
        )
        scale = helper.create_variable_for_type_inference(dtype=total.dtype)
        block.append_op(
            type="elementwise_div", inputs={"X": [clip_var], "Y": [maxed]},
            outputs={"Out": [scale]},
        )
        group_scales[group_name] = scale

    result = []
    for p, g, clip_attr in clips:
        if g is None:
            result.append((p, g))
            continue
        if isinstance(clip_attr, GradientClipByGlobalNorm):
            scale = group_scales[clip_attr.group_name]
            helper = LayerHelper("global_clip_grad")
            new_grad = helper.create_variable_for_type_inference(dtype=g.dtype)
            g.block.append_op(
                type="elementwise_mul", inputs={"X": [g], "Y": [scale]},
                outputs={"Out": [new_grad]},
            )
            result.append((p, _propagate_sparse(g, new_grad)))
        else:
            result.append(clip_attr._create_operators(p, g))
    return result
