"""Pipeline parallelism: GPipe-style microbatched stage execution over
the ``pp`` mesh axis.

The reference has NO pipeline parallelism (SURVEY.md §2.4: absent);
this module is the TPU-native capability extension that makes the
``pp`` axis real: layers are grouped into S stages whose parameters are
stacked on a leading stage dim and sharded over ``pp`` (each device
holds one stage), the batch splits into M microbatches, and activations
flow stage-to-stage with ``ppermute`` — the classic GPipe schedule run
as a single ``lax.fori_loop`` of M + S - 1 ticks where every device
computes every tick (bubble fraction (S-1)/(M+S-1)).

Surface:

* ``pipeline(stage_fn, stage_params, x, mesh, axis='pp',
  microbatches=M)`` — ``stage_fn(params, x) -> y`` is ONE stage's
  computation (inter-stage activations must share x's shape);
  ``stage_params`` is a pytree whose leaves have leading dim S.
  Returns the pipelined equivalent of folding all S stages over x.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import AXIS_PP, shard_map_norep

__all__ = ["pipeline"]


def _pipeline_shard(params, x, axis_name, stage_fn, microbatches):
    """Per-device body: params [1, ...] (this stage's slice), x [B, ...]
    (full batch, replicated).  Returns [B, ...] final-stage outputs,
    valid on every device (broadcast from the last stage)."""
    s = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    my_params = jax.tree_util.tree_map(lambda p: p[0], params)

    m = microbatches
    b = x.shape[0]
    mb = b // m
    # carries run in the stage output dtype (may differ from x, e.g.
    # fp32 params over bf16 activations promote)
    out_dtype = jax.eval_shape(
        stage_fn, my_params,
        jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype)).dtype
    x_mb = x.reshape((m, mb) + x.shape[1:]).astype(out_dtype)

    # send each stage's output to the next stage (ring without wrap: the
    # last stage's output would wrap to stage 0, which ignores it)
    perm = [(j, (j + 1) % s) for j in range(s)]

    def tick(t, carry):
        cur_in, outs = carry
        # stage 0 ingests microbatch t (zeros past the schedule tail)
        mb_idx = jnp.clip(t, 0, m - 1)
        fresh = x_mb[mb_idx]
        cur_in = jnp.where(stage == 0, fresh, cur_in)
        out = stage_fn(my_params, cur_in)
        # the last stage completes microbatch t-(s-1) at tick t
        done_idx = t - (s - 1)
        take = (stage == s - 1) & (done_idx >= 0) & (done_idx < m)
        updated = lax.dynamic_update_index_in_dim(
            outs, out, jnp.clip(done_idx, 0, m - 1), 0)
        outs = jnp.where(take, updated, outs)
        nxt = lax.ppermute(out, axis_name, perm)
        return nxt, outs

    outs0 = jnp.zeros((m, mb) + x.shape[1:], out_dtype)
    cur0 = jnp.zeros((mb,) + x.shape[1:], out_dtype)
    _, outs = lax.fori_loop(0, m + s - 1, tick, (cur0, outs0))
    # broadcast the last stage's collected outputs to every device
    mask = (stage == s - 1).astype(outs.dtype)
    outs = lax.psum(outs * mask, axis_name)
    return outs.reshape((b,) + x.shape[1:])


def pipeline(stage_fn, stage_params, x, mesh, axis=AXIS_PP,
             microbatches=None):
    """Run ``stage_fn`` as an S-stage GPipe pipeline over ``mesh``'s
    ``axis``.  ``stage_params`` leaves carry a leading stage dim equal
    to the axis size; returns stage_{S-1}(... stage_0(x))."""
    if axis not in mesh.axis_names:
        raise ValueError("mesh has no axis %r (axes: %s)"
                         % (axis, mesh.axis_names))
    s = mesh.devices.shape[mesh.axis_names.index(axis)]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != s:
            raise ValueError(
                "stage_params leading dim %d must equal the %r axis "
                "size %d (one stage per device)"
                % (leaf.shape[0], axis, s))
    microbatches = microbatches or s
    if x.shape[0] % microbatches != 0:
        raise ValueError(
            "microbatches (%d) must divide the batch (%d)"
            % (microbatches, x.shape[0]))
    mb_shape = (x.shape[0] // microbatches,) + tuple(x.shape[1:])
    stage0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    out_shape = jax.eval_shape(
        stage_fn, stage0, jax.ShapeDtypeStruct(mb_shape, x.dtype)).shape
    if tuple(out_shape) != mb_shape:
        raise ValueError(
            "stage_fn must preserve the activation shape so microbatches "
            "can flow stage-to-stage: input %s -> output %s. Reshape "
            "inside the stage (or use heterogeneous stages via "
            "program_pipeline)" % (mb_shape, tuple(out_shape)))
    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis), stage_params)
    # replicate x; stage params shard their leading stage dim over pp
    fn = shard_map_norep(
        functools.partial(_pipeline_shard, axis_name=axis,
                          stage_fn=stage_fn, microbatches=microbatches),
        mesh, in_specs=(param_specs, P()), out_specs=P())
    stage_params = jax.tree_util.tree_map(
        lambda p, sp: jax.device_put(p, NamedSharding(mesh, sp)),
        stage_params, param_specs)
    return fn(stage_params, x)
