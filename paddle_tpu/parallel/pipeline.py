"""Pipeline parallelism: microbatched stage execution over the ``pp``
mesh axis, under a selectable schedule.

The reference has NO pipeline parallelism (SURVEY.md §2.4: absent);
this module is the TPU-native capability extension that makes the
``pp`` axis real: layers are grouped into stages whose parameters are
stacked on a leading stage dim and sharded over ``pp`` (each device
holds one stage — or ``v`` stage *chunks* under the interleaved
schedule), the batch splits into M microbatches, and activations flow
stage-to-stage with ``ppermute`` inside a single ``lax.fori_loop``.

Schedules (PAPERS.md: GPipe, Huang et al.; 1F1B/interleaved, Narayanan
et al. Megatron-LM):

``gpipe``
    the classic fill-drain schedule: M + S - 1 ticks, bubble fraction
    (S-1)/(M+S-1).  Autodiff of the loop stashes every tick's
    residuals, so backward memory grows with M.
``1f1b``
    one-forward-one-backward: the forward pass is the same fill-drain
    loop run *stash-free* (a ``custom_vjp`` saves only the region
    inputs), and the backward pass is a combined schedule that
    recomputes each stage's forward just-in-time and interleaves it
    with the cotangent wave — each device holds at most
    ``min(M, 2S-1)`` in-flight microbatch input activations
    (M-independent), vs GPipe's M stashed residual sets.  That bounded
    memory is what lets M grow, which is the real bubble lever; the
    cost is one extra forward recompute (the classic GPipe-remat
    trade, made explicit).
``interleaved``
    circular/virtual-stage schedule: each device hosts ``v = S_total/S``
    stage chunks and microbatches go around the ring v times in groups
    of S, shrinking the fill/drain bubble to (S-1)/(vM+S-1) at equal
    (S, M).  Requires the stage count to be a multiple of the mesh axis
    size and M a multiple of S.

Surface:

* ``pipeline(stage_fn, stage_params, x, mesh, axis='pp',
  microbatches=M, schedule='gpipe')`` — ``stage_fn(params, x) -> y``
  is ONE stage's computation (inter-stage activations must share x's
  shape); ``stage_params`` is a pytree whose leaves have leading dim
  S_total (== axis size, or v * axis size under ``interleaved``).
  Returns the pipelined equivalent of folding all stages over x.
* ``schedule_stats(schedule, stages, microbatches, virtual=1)`` — the
  per-tick stage-idle accounting shared by the lowerings, the
  ParallelExecutor's ``pipeline_bubble`` goodput attribution, and the
  autotuner's ``tune_pipeline``.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import AXIS_PP, shard_map_norep

__all__ = ["pipeline", "SCHEDULES", "schedule_stats",
           "normalize_schedule", "make_1f1b", "interleaved_loop",
           "interleaved_order"]

SCHEDULES = ("gpipe", "1f1b", "interleaved")


def normalize_schedule(schedule):
    """``None`` -> the default ``gpipe``; anything else must name a
    known schedule."""
    if schedule is None:
        return "gpipe"
    if schedule not in SCHEDULES:
        raise ValueError("unknown pipeline schedule %r (choose from %s)"
                         % (schedule, list(SCHEDULES)))
    return schedule


def schedule_stats(schedule, stages, microbatches, virtual=1):
    """Per-device slot accounting for one fwd+bwd step of a schedule —
    the number source for the goodput ledger's ``pipeline_bubble``
    bucket and for ``autotune.tune_pipeline``.

    Unit model: one forward stage application = 1 unit, one backward
    (vjp) application = 2 units.  Every SPMD tick costs every device
    the same wall clock (idle stages compute masked garbage), so
    ``idle_units / total_units`` is the exact fraction of device time
    the executed schedule wastes — per-tick stage-idle accounting, not
    the closed-form estimate (they coincide for GPipe).  1F1B's
    just-in-time forward recompute is counted BUSY (it burns cycles but
    is remat overhead, not bubble); it is reported separately as
    ``remat_units``.
    """
    schedule = normalize_schedule(schedule)
    s = int(stages)
    m = int(microbatches)
    v = int(virtual or 1)
    if s < 1 or m < 1 or v < 1:
        raise ValueError("stages/microbatches/virtual must be >= 1")
    remat = 0
    if schedule == "gpipe":
        # fwd loop M+S-1 ticks @1; autodiff reverse M+S-1 ticks @2
        total = 3 * (m + s - 1)
        idle = 3 * (s - 1)
        in_flight = m + s - 1          # per-tick residual stashes
        ticks = m + s - 1
    elif schedule == "interleaved":
        # chunk ticks: fwd vM+S-1 @1, autodiff reverse vM+S-1 @2
        ticks = v * m + s - 1
        total = 3 * ticks
        idle = 3 * (s - 1)
        in_flight = ticks
    else:  # 1f1b
        # stash-free fwd loop (M+S-1 @1) + combined bwd loop of
        # M+2(S-1) ticks, each tick one fwd-recompute slot (@1) and
        # one bwd slot (@2)
        bwd_ticks = m + 2 * (s - 1)
        total = (m + s - 1) + 3 * bwd_ticks
        idle = (s - 1) + 3 * 2 * (s - 1)
        remat = m                      # one fwd recompute per microbatch
        in_flight = min(m, 2 * s - 1)  # input-activation stash slots
        ticks = (m + s - 1) + bwd_ticks
    return {"schedule": schedule, "stages": s, "microbatches": m,
            "virtual": v, "ticks": ticks, "total_units": total,
            "idle_units": idle, "remat_units": remat,
            "in_flight": in_flight,
            "bubble_fraction": idle / total if total else 0.0}


# ---------------------------------------------------------------------------
# per-device schedule bodies (run under shard_map; each returns the
# collected outputs with a leading per-stage dim [1, M, mb, ...] so the
# caller's out_specs P(axis) makes GSPMD deliver the last stage's slice
# as a true single-source broadcast — no psum over a masked all-stage
# buffer, and the slice transpose routes cotangents exactly)
# ---------------------------------------------------------------------------

def _gpipe_shard(params, x, axis_name, stage_fn, microbatches):
    """Classic GPipe: params [1, ...] (this stage's slice), x [B, ...]
    (full batch, replicated).  Returns [1, M, mb, ...] — valid on the
    last stage's shard."""
    s = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    my_params = jax.tree_util.tree_map(lambda p: p[0], params)

    m = microbatches
    b = x.shape[0]
    mb = b // m
    # carries run in the stage output dtype (may differ from x, e.g.
    # fp32 params over bf16 activations promote)
    out_dtype = jax.eval_shape(
        stage_fn, my_params,
        jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype)).dtype
    x_mb = x.reshape((m, mb) + x.shape[1:]).astype(out_dtype)

    # send each stage's output to the next stage only: the wrap-around
    # (S-1 -> 0) edge is dead on every tick (stage 0 always ingests a
    # fresh microbatch), so it is dropped from the permutation entirely
    perm = [(j, j + 1) for j in range(s - 1)]
    total = m + s - 1

    def tick(t, carry):
        cur_in, outs = carry
        # stage 0 ingests microbatch t (zeros past the schedule tail)
        mb_idx = jnp.clip(t, 0, m - 1)
        fresh = x_mb[mb_idx]
        cur_in = jnp.where(stage == 0, fresh, cur_in)
        out = stage_fn(my_params, cur_in)
        # the last stage completes microbatch t-(s-1) at tick t
        done_idx = t - (s - 1)
        take = (stage == s - 1) & (done_idx >= 0) & (done_idx < m)
        updated = lax.dynamic_update_index_in_dim(
            outs, out, jnp.clip(done_idx, 0, m - 1), 0)
        outs = jnp.where(take, updated, outs)
        # the final tick's rotation is discarded with the loop carry:
        # skip the ICI transfer entirely (ring_attention precedent)
        nxt = lax.cond(
            t < total - 1,
            lambda o: lax.ppermute(o, axis_name, perm),
            lambda o: o, out)
        return nxt, outs

    outs0 = jnp.zeros((m, mb) + x.shape[1:], out_dtype)
    cur0 = jnp.zeros((mb,) + x.shape[1:], out_dtype)
    _, outs = lax.fori_loop(0, total, tick, (cur0, outs0))
    return outs[None]


def interleaved_order(s, v):
    """Device-major restack order for the interleaved schedule: slot
    ``d*v + r`` holds virtual stage ``r*s + d`` (device d's chunk r —
    the Megatron round-robin assignment)."""
    return [r * s + d for d in range(s) for r in range(v)]


def interleaved_loop(axis_name, s, m, v, x_mb, apply_fn):
    """Per-device driver of the circular/interleaved schedule — THE
    single implementation shared by the functional surface and the
    ``pipeline_region`` lowering.  Groups of S microbatches ride the
    S-device ring v times; vM + S - 1 ticks.  At tick t this device's
    stream position is q = t - d; the microbatch here is
    ``(q // (S*v)) * S + (q % S)`` in round ``(q // S) % v`` (group g
    enters device 0 at tick g*S*v).  ``apply_fn(rnd, vs_idx, cur,
    midx) -> out`` applies this device's chunk ``rnd`` (program stage
    ``vs_idx``) to the carry for microbatch ``midx``.  Returns the
    collected final-round outputs with a leading per-stage dim
    [1, M, mb, ...]."""
    d = lax.axis_index(axis_name)
    vs_total = s * v
    total = v * m + s - 1
    perm = [(j, (j + 1) % s) for j in range(s)]

    def tick(t, carry):
        cur, outs = carry
        q = t - d
        r_mb = jnp.mod(q, s)
        rnd = jnp.mod(jnp.floor_divide(q, s), v)
        grp = jnp.floor_divide(q, vs_total)
        midx = jnp.clip(grp * s + r_mb, 0, m - 1)
        active = (q >= 0) & (grp < m // s)
        # device 0 ingests fresh microbatches on round 0; later rounds
        # arrive through the wrap-around ppermute edge
        cur = jnp.where((d == 0) & (rnd == 0), x_mb[midx], cur)
        out = apply_fn(rnd, rnd * s + d, cur, midx)
        done = active & (rnd == v - 1) & (d == s - 1)
        updated = lax.dynamic_update_index_in_dim(outs, out, midx, 0)
        outs = jnp.where(done, updated, outs)
        nxt = lax.cond(
            t < total - 1,
            lambda o: lax.ppermute(o, axis_name, perm),
            lambda o: o, out)
        return nxt, outs

    outs0 = jnp.zeros_like(x_mb)
    cur0 = jnp.zeros_like(x_mb[0])
    _, outs = lax.fori_loop(0, total, tick, (cur0, outs0))
    return outs[None]


def _interleaved_shard(params, x, axis_name, stage_fn, microbatches,
                       virtual):
    """Functional-surface adapter over :func:`interleaved_loop`:
    params [1, v, ...] (this device's chunks, device-major restacked by
    the caller), x [B, ...] replicated."""
    s = lax.psum(1, axis_name)
    m = microbatches
    mb = x.shape[0] // m
    my_chunks = jax.tree_util.tree_map(lambda p: p[0], params)
    chunk0 = jax.tree_util.tree_map(lambda p: p[0], my_chunks)
    out_dtype = jax.eval_shape(
        stage_fn, chunk0,
        jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype)).dtype
    x_mb = x.reshape((m, mb) + x.shape[1:]).astype(out_dtype)

    def apply_fn(rnd, vs_idx, cur, midx):
        chunk = jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, rnd, 0,
                                               keepdims=False),
            my_chunks)
        return stage_fn(chunk, cur)

    return interleaved_loop(axis_name, s, m, virtual, x_mb, apply_fn)


def make_1f1b(axis_name, s, m, run_factory, dp_extra_fn=None):
    """The 1F1B schedule as a ``custom_vjp`` — THE single
    implementation shared by the functional surface below and the
    ``pipeline_region`` lowering (``ops/pipeline_region.py``), so the
    intricate stash/ring math lives in one place.

    Returns ``f(params, x_mb, fsides, isides, consts, key_data) ->
    [1, M, mb, ...]`` to run under an existing shard_map:

    * ``params`` — pytree whose leaves carry the sharded leading stage
      dim (local ``[1, ...]``);
    * ``fsides`` / ``isides`` — lists of per-microbatch ``[M, mb, ...]``
      side inputs (floating ones receive cotangents, the rest get
      float0 zeros);
    * ``consts`` / ``key_data`` — opaque lists threaded verbatim to
      ``run_factory`` (explicit args because custom_vjp functions must
      not close over outer-trace tracers — PRNG keys ride as
      ``jax.random.key_data``);
    * ``run_factory(consts, key_data) -> run(stage_idx, stage_params,
      carry, sides, extra, mb_idx)`` applies ONE stage;
    * ``dp_extra_fn()`` — per-device decorrelation fold index for
      dp-sharded runs (None to disable).

    fwd: the fill-drain loop run stash-free (residuals = the region
    inputs only).  bwd: a combined loop of M + 2(S-1) ticks; each tick
    recomputes one stage forward just-in-time (stashing its INPUT in a
    min(M, 2S-1)-slot circular buffer — the M-independent memory
    bound) and runs one stage backward via per-stage ``jax.vjp``,
    cotangents flowing down-ring while activations flow up-ring."""
    import numpy as onp

    K = min(m, 2 * s - 1) if s > 1 else 1
    perm_fwd = [(j, j + 1) for j in range(s - 1)]
    perm_bwd = [(j + 1, j) for j in range(s - 1)]

    def _dyn(v, i):
        return lax.dynamic_index_in_dim(v, i, 0, keepdims=False)

    def _extra():
        return dp_extra_fn() if dp_extra_fn is not None else None

    def _fwd_loop(params, x_mb, fsides, isides, consts, key_data):
        run = run_factory(consts, key_data)
        d = lax.axis_index(axis_name)
        extra = _extra()
        my = jax.tree_util.tree_map(lambda p: p[0], params)
        total = m + s - 1

        def tick(t, carry):
            cur, outs = carry
            cur = jnp.where(d == 0, x_mb[jnp.clip(t, 0, m - 1)], cur)
            my_mb = jnp.clip(t - d, 0, m - 1)
            sides_t = [_dyn(v, my_mb) for v in fsides + isides]
            out = run(d, my, cur, sides_t, extra, my_mb)
            done = t - (s - 1)
            take = (d == s - 1) & (done >= 0) & (done < m)
            updated = lax.dynamic_update_index_in_dim(
                outs, out, jnp.clip(done, 0, m - 1), 0)
            outs = jnp.where(take, updated, outs)
            nxt = lax.cond(
                t < total - 1,
                lambda o: lax.ppermute(o, axis_name, perm_fwd),
                lambda o: o, out)
            return nxt, outs

        outs0 = jnp.zeros_like(x_mb)
        cur0 = jnp.zeros_like(x_mb[0])
        _, outs = lax.fori_loop(0, total, tick, (cur0, outs0))
        return outs[None]

    @jax.custom_vjp
    def f(params, x_mb, fsides, isides, consts, key_data):
        return _fwd_loop(params, x_mb, fsides, isides, consts, key_data)

    def f_fwd(params, x_mb, fsides, isides, consts, key_data):
        # stash-free forward: residuals are the region INPUTS only
        out = _fwd_loop(params, x_mb, fsides, isides, consts, key_data)
        return out, (params, x_mb, fsides, isides, consts, key_data)

    def f_bwd(res, g):
        params, x_mb, fsides, isides, consts, key_data = res
        run = run_factory(consts, key_data)
        d = lax.axis_index(axis_name)
        extra = _extra()
        my = jax.tree_util.tree_map(lambda p: p[0], params)
        total = m + 2 * (s - 1)

        def tick(t, carry):
            fcar, bcar, stash, dparams, dx, dfs = carry
            # forward slot: recompute microbatch t-d's stage forward
            # just-in-time and stash its input for the backward wave
            fidx = t - d
            fval = (fidx >= 0) & (fidx < m)
            f_mb = jnp.clip(fidx, 0, m - 1)
            finp = jnp.where(d == 0, x_mb[f_mb], fcar)
            sides_f = [_dyn(v, f_mb) for v in fsides + isides]
            fout = run(d, my, finp, sides_f, extra, f_mb)
            # write only live microbatches: an unguarded drain-phase
            # write would wrap onto a slot a pending backward still
            # needs when M < 2S-1
            stash = jnp.where(
                fval,
                lax.dynamic_update_index_in_dim(
                    stash, finp, jnp.mod(fidx, K), 0), stash)
            # the forward wave's last useful delivery lands at tick
            # m+s-2 (microbatch m-1 at the last stage): the drain
            # phase's rotations carry garbage — skip the transfers
            fcar_n = lax.cond(
                t < m + s - 2,
                lambda o: lax.ppermute(o, axis_name, perm_fwd),
                lambda o: o, fout)
            # backward slot: microbatch t - 2(S-1) + d retires here
            bidx = t - 2 * (s - 1) + d
            bval = (bidx >= 0) & (bidx < m)
            b_mb = jnp.clip(bidx, 0, m - 1)
            ct_in = jnp.where(d == s - 1, g[0, b_mb], bcar)
            saved_in = stash[jnp.mod(bidx, K)]
            sides_bf = [_dyn(v, b_mb) for v in fsides]
            sides_bi = [_dyn(v, b_mb) for v in isides]

            def stage_call(mp, c, sf):
                return run(d, mp, c, list(sf) + sides_bi, extra, b_mb)

            _, vjp_fn = jax.vjp(stage_call, my, saved_in, sides_bf)
            dp, dxx, dsf = vjp_fn(ct_in.astype(x_mb.dtype))
            dparams = jax.tree_util.tree_map(
                lambda a, inc: a + jnp.where(bval, inc, 0.0),
                dparams, dp)
            dx_upd = lax.dynamic_update_index_in_dim(dx, dxx, b_mb, 0)
            dx = jnp.where(bval & (d == 0), dx_upd, dx)
            dfs = [jnp.where(
                bval, lax.dynamic_update_index_in_dim(a, inc, b_mb, 0),
                a) for a, inc in zip(dfs, dsf)]
            # the final tick's cotangent rotation is discarded with
            # the loop carry — skip it like the forward loops do
            bcar_n = lax.cond(
                t < total - 1,
                lambda o: lax.ppermute(o, axis_name, perm_bwd),
                lambda o: o, jnp.where(bval, dxx, jnp.zeros_like(dxx)))
            return fcar_n, bcar_n, stash, dparams, dx, dfs

        mb_shape = tuple(x_mb.shape[1:])
        fcar0 = jnp.zeros(mb_shape, x_mb.dtype)
        bcar0 = jnp.zeros(mb_shape, x_mb.dtype)
        stash0 = jnp.zeros((K,) + mb_shape, x_mb.dtype)
        dp0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p[0]), params)
        dx0 = jnp.zeros_like(x_mb)
        dfs0 = [jnp.zeros_like(v) for v in fsides]
        _, _, _, dparams, dx, dfs = lax.fori_loop(
            0, total, tick, (fcar0, bcar0, stash0, dp0, dx0, dfs0))
        dparams = jax.tree_util.tree_map(lambda a: a[None], dparams)
        # each cotangent is valid on the stage that produced it; the
        # shard_map boundary transpose psums per-device partials for
        # replicated inputs, so zeros elsewhere make the sums exact
        dx = jnp.where(d == 0, dx, jnp.zeros_like(dx))
        f0 = jax.dtypes.float0
        d_isides = [onp.zeros(onp.shape(v), f0) for v in isides]
        d_consts = [onp.zeros(onp.shape(v), f0) for v in consts]
        d_key = [onp.zeros(onp.shape(v), f0) for v in key_data]
        return dparams, dx, dfs, d_isides, d_consts, d_key

    f.defvjp(f_fwd, f_bwd)
    return f


def _make_1f1b(axis_name, stage_fn, m, s):
    """Functional-surface adapter over :func:`make_1f1b`: no sides, no
    consts, no PRNG — one stage is just ``stage_fn(params, x)``."""

    def run_factory(consts, key_data):
        def run(stage_idx, my, carry, sides, extra, mb_idx):
            return stage_fn(my, carry)

        return run

    f = make_1f1b(axis_name, s, m, run_factory)

    def g(params, x_mb):
        return f(params, x_mb, [], [], [], [])

    return g


def pipeline(stage_fn, stage_params, x, mesh, axis=AXIS_PP,
             microbatches=None, schedule="gpipe"):
    """Run ``stage_fn`` as an S-stage pipeline over ``mesh``'s ``axis``
    under ``schedule``.  ``stage_params`` leaves carry a leading stage
    dim (== axis size; ``v *`` axis size for ``interleaved``); returns
    stage_{S-1}(... stage_0(x))."""
    schedule = normalize_schedule(schedule)
    if axis not in mesh.axis_names:
        raise ValueError("mesh has no axis %r (axes: %s)"
                         % (axis, mesh.axis_names))
    s = mesh.devices.shape[mesh.axis_names.index(axis)]
    leaves = jax.tree_util.tree_leaves(stage_params)
    s_total = leaves[0].shape[0] if leaves else s
    for leaf in leaves:
        if leaf.shape[0] != s_total:
            raise ValueError(
                "stage_params leaves disagree on the leading stage dim "
                "(%d vs %d)" % (leaf.shape[0], s_total))
    if schedule == "interleaved":
        if s_total % s:
            raise ValueError(
                "interleaved schedule: stage count %d must be a "
                "multiple of the %r axis size %d" % (s_total, axis, s))
        v = s_total // s
    else:
        v = 1
        if s_total != s:
            raise ValueError(
                "stage_params leading dim %d must equal the %r axis "
                "size %d (one stage per device; use "
                "schedule='interleaved' for v stages per device)"
                % (s_total, axis, s))
    microbatches = microbatches or s
    if x.shape[0] % microbatches != 0:
        raise ValueError(
            "microbatches (%d) must divide the batch (%d)"
            % (microbatches, x.shape[0]))
    if schedule == "interleaved" and microbatches % s:
        raise ValueError(
            "interleaved schedule: microbatches (%d) must be a "
            "multiple of the %r axis size %d (groups of S go around "
            "the ring together)" % (microbatches, axis, s))
    m = microbatches
    mb_shape = (x.shape[0] // m,) + tuple(x.shape[1:])
    stage0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    out_shape = jax.eval_shape(
        stage_fn, stage0, jax.ShapeDtypeStruct(mb_shape, x.dtype)).shape
    if tuple(out_shape) != mb_shape:
        raise ValueError(
            "stage_fn must preserve the activation shape so microbatches "
            "can flow stage-to-stage: input %s -> output %s. Reshape "
            "inside the stage (or use heterogeneous stages via "
            "program_pipeline)" % (mb_shape, tuple(out_shape)))

    if schedule == "interleaved":
        # device-major restack: device d hosts virtual stages
        # {r*S + d : r < v} as its chunk array [v, ...]
        order = jnp.asarray(interleaved_order(s, v))
        stage_params = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p)[order].reshape(
                (s, v) + tuple(p.shape[1:])),
            stage_params)
        body = functools.partial(
            _interleaved_shard, axis_name=axis, stage_fn=stage_fn,
            microbatches=m, virtual=v)
    elif schedule == "1f1b":
        def body(params, xx):
            my0 = jax.tree_util.tree_map(lambda p: p[0], params)
            mb = xx.shape[0] // m
            out_dtype = jax.eval_shape(
                stage_fn, my0,
                jax.ShapeDtypeStruct((mb,) + xx.shape[1:],
                                     xx.dtype)).dtype
            x_mb = xx.reshape((m, mb) + xx.shape[1:]).astype(out_dtype)
            return _make_1f1b(axis, stage_fn, m, int(s))(params, x_mb)
    else:
        body = functools.partial(
            _gpipe_shard, axis_name=axis, stage_fn=stage_fn,
            microbatches=m)

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis), stage_params)
    # replicate x; stage params shard their leading stage dim over the
    # pipeline axis; outputs come back with a leading per-stage dim and
    # only the LAST stage's slice is read — GSPMD inserts the
    # single-source broadcast (satellite fix: no psum over a masked
    # all-stage-sized buffer)
    fn = shard_map_norep(
        body, mesh, in_specs=(param_specs, P()), out_specs=P(axis))
    stage_params = jax.tree_util.tree_map(
        lambda p, sp: jax.device_put(p, NamedSharding(mesh, sp)),
        stage_params, param_specs)
    staged = fn(stage_params, x)           # [S, M, mb, ...]
    out = staged[s - 1]
    return out.reshape((x.shape[0],) + tuple(out.shape[2:]))
