"""Mesh runtime: the TPU-native replacement for the reference's
multi-device/multi-node stack (ParallelExecutor + MultiDevSSAGraphBuilder +
NCCL op handles + DistributeTranspiler; SURVEY.md §2.4).

Instead of replicating the program per device and inserting allreduce
handles, a Program is traced once (executor.trace_program) and pjit-
compiled over a ``jax.sharding.Mesh``; XLA GSPMD inserts the ICI
collectives the reference hand-schedules through NCCL.
"""

from .mesh import make_mesh  # noqa: F401
from .spec_layout import SpecLayout  # noqa: F401
from .strategy import BuildStrategy, ExecutionStrategy  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from .embedding import distributed_embedding_sharding_fn  # noqa: F401
from . import checkpoint  # noqa: F401
from .ring_attention import ring_attention, ring_attention_shard  # noqa: F401,E501
from .pipeline import pipeline  # noqa: F401
