"""Sharded embedding tables on the mesh.

Parity: the reference's distributed lookup tables — params sliced across
pservers with remote prefetch (``transpiler/distribute_transpiler.py``
lookup-table handling, ``operators/lookup_table_op.cc`` remote_prefetch,
``split_ids_op.cc`` / ``merge_ids_op.cc``) — re-designed TPU-first:
a table marked ``is_distributed`` by ``layers.embedding`` is row-sharded
over a mesh axis (``distributed_embedding_sharding_fn``), and the
``is_sparse`` lookup + lazy optimizer update run as EXPLICIT shard_map
lowerings (``sharded_sparse_lookup`` / ``sharded_sparse_update``): the
forward gathers only local rows and psums the [N, D] activations over
the table axis; the backward exchanges the O(batch·seq) SelectedRows
(ids + value slices) over the batch axes — never an all-gathered
[vocab, D] table, never a dense [vocab, D] gradient collective — and
each shard's lazy update touches only its local rows.  There is no
server role, no RPC, and no prefetch op: the "remote" rows are one
row-slice exchange away (the split_ids/merge_ids pair re-expressed as
mesh collectives).
"""

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import AXIS_DP, AXIS_EP, AXIS_FSDP, shard_map_norep

__all__ = ["distributed_embedding_sharding_fn", "sharded_sparse_lookup",
           "sharded_sparse_update", "dim0_axes"]


def distributed_embedding_sharding_fn(program, mesh, axis=None):
    """Build a BuildStrategy.param_sharding_fn that row-shards every
    ``is_distributed`` embedding table over ``axis`` (default: the mesh's
    ``ep`` axis if present, else ``dp``).

    Optimizer slot vars of a sharded table (``<table>_moment1_0`` etc.,
    recognized by the ``<table>_`` name prefix plus a leading dim equal
    to the table height) INHERIT the row sharding: a lazy sparse Adam
    over a 1e6-row table must not keep replicated [vocab, D] moments —
    they dominate state exactly like the table does.

    Compose with another policy by chaining: the returned fn yields None
    for non-table params so a wrapper can fall through.
    """
    if axis is None:
        axis = AXIS_EP if AXIS_EP in mesh.axis_names else AXIS_DP
    if axis not in mesh.axis_names:
        raise ValueError(
            "mesh %r has no %r axis to shard embedding tables over; pass "
            "axis= naming one of its axes" % (tuple(mesh.axis_names), axis))
    size = mesh.devices.shape[mesh.axis_names.index(axis)]
    from ..ops.selected_rows import is_row_slot_of, sparse_lookup_tables

    heights = {w: int(v.shape[0]) for w, v in sparse_lookup_tables(
        program, "is_distributed").items()}
    tables = set(heights)

    def fn(name, shape):
        if name in tables and shape and shape[0] % size == 0:
            return P(axis)
        for t, h in heights.items():
            if is_row_slot_of(name, t) and shape and len(shape) >= 1 \
                    and shape[0] == h and h % size == 0:
                return P(axis)     # optimizer slot var of a sharded table
        return None

    return fn


# ---------------------------------------------------------------------------
# Sharded sparse lookup / update lowerings (the pserver prefetch +
# sparse-update pair as explicit shard_map collectives)
# ---------------------------------------------------------------------------

def dim0_axes(spec):
    """The mesh axes sharding dim 0 of ``spec`` as a flat tuple
    (() = unsharded/replicated)."""
    entries = tuple(spec) if spec is not None else ()
    if not entries or entries[0] is None:
        return ()
    e = entries[0]
    return tuple(e) if isinstance(e, tuple) else (e,)


def _extent(mesh, axes):
    n = 1
    for a in axes:
        n *= mesh.devices.shape[mesh.axis_names.index(a)]
    return n


def _shard_offset(mesh, axes, local_rows):
    """This shard's first global row, from inside a shard_map: the
    combined (major-to-minor per the P((a, b)) convention) index over
    ``axes`` times the local row count."""
    r = jnp.int32(0)
    for a in axes:
        r = r * mesh.devices.shape[mesh.axis_names.index(a)] \
            + lax.axis_index(a)
    return r * local_rows


def _data_axes(ctx):
    """The mesh axes the PE shards batches over (dp x fsdp, populated
    only) — the axes a flat [N]-per-batch tensor is sharded along."""
    mesh = ctx.mesh
    return tuple(a for a in (AXIS_DP, AXIS_FSDP)
                 if a in mesh.axis_names
                 and mesh.devices.shape[mesh.axis_names.index(a)] > 1)


def _table_partition(ctx, name, height):
    """(table_axes, batch_axes) when ``name`` is row-sharded on this
    trace's mesh and the height divides; None otherwise (caller falls
    back to the unsharded lowering).  ``batch_axes`` are the data axes
    NOT used by the table — the axes the SelectedRows exchange gathers
    over; a table sharded over a data axis simply sees the ids
    replicated at the shard_map boundary (the gather happens there)."""
    if ctx is None or ctx.mesh is None or not ctx.state_specs:
        return None
    axes = dim0_axes(ctx.state_specs.get(name))
    if not axes:
        return None
    k = _extent(ctx.mesh, axes)
    if k <= 1 or height % k != 0:
        return None
    batch_axes = tuple(a for a in _data_axes(ctx) if a not in axes)
    return axes, batch_axes


def _narrow_batch_axes(ctx, batch_axes, n):
    """Drop batch axes (rightmost first) until their extent divides the
    flat id count ``n`` — an indivisible exchange degrades toward
    replication, never to an invalid spec."""
    axes = tuple(batch_axes)
    while axes and n % _extent(ctx.mesh, axes) != 0:
        axes = axes[:-1]
    return axes


def sharded_sparse_lookup(ctx, w, flat_ids, w_name):
    """Row-sharded embedding gather: each shard reads ONLY its local
    rows and the [N, D] results psum over the table axes — the
    remote-prefetch collective.  Returns the [N, D] lookup, or None when
    ``w_name`` is not row-sharded on this trace's mesh."""
    part = _table_partition(ctx, w_name, int(w.shape[0]))
    if part is None:
        return None
    table_axes, batch_axes = part
    batch_axes = _narrow_batch_axes(ctx, batch_axes, int(flat_ids.shape[0]))
    mesh = ctx.mesh
    local_rows = int(w.shape[0]) // _extent(mesh, table_axes)
    w_spec = ctx.state_specs.get(w_name)
    bspec = batch_axes if len(batch_axes) != 1 else batch_axes[0]
    ids_spec = P(bspec) if batch_axes else P()
    out_spec = P(bspec, None) if batch_axes else P()

    def gather(w_local, ids_local):
        lo = _shard_offset(mesh, table_axes, local_rows)
        loc = ids_local.astype(jnp.int32) - lo
        ok = (loc >= 0) & (loc < local_rows)
        out = jnp.take(w_local, jnp.where(ok, loc, 0), axis=0)
        out = out * ok[:, None].astype(out.dtype)
        return lax.psum(out, table_axes)

    return shard_map_norep(
        gather, mesh, in_specs=(w_spec, ids_spec),
        out_specs=out_spec)(w, flat_ids)


def sharded_sparse_update(ctx, names, tables, sr, scalars, row_update):
    """Row-sharded lazy optimizer update: the SelectedRows gradient's
    (rows, values) are exchanged over the BATCH axes (an O(batch·seq·D)
    all-gather — ids bucket to their owner by the in-shard range mask),
    then each table shard applies ``row_update`` to its local rows only.
    Never materializes an all-gathered table or a dense [vocab, D]
    gradient.

    ``names``/``tables``: the param + its row-wise slot vars (all must
    share the param's dim-0 sharding; scalar-shaped slots belong in
    ``scalars``).  ``row_update(sr_local, scalars, *tables_local)``
    returns the updated local tables in order.  Returns the updated
    (sharded) tables, or None when the param is not row-sharded here
    (caller runs the single-device lazy kernel)."""
    from ..ops.selected_rows import SelectedRows

    height = int(tables[0].shape[0])
    part = _table_partition(ctx, names[0], height)
    if part is None:
        return None
    mesh = ctx.mesh
    table_axes, batch_axes = part
    # every row-wise operand must ride the SAME dim-0 sharding — a
    # replicated moment var would force pjit to all-gather a [vocab, D]
    # buffer right back; fall back loudly-by-structure instead
    specs = []
    for n, t in zip(names, tables):
        ax = dim0_axes(ctx.state_specs.get(n))
        if ax != table_axes or int(t.shape[0]) != height:
            return None
        specs.append(ctx.state_specs.get(n))
    batch_axes = _narrow_batch_axes(ctx, batch_axes, int(sr.rows.shape[0]))
    local_rows = height // _extent(mesh, table_axes)
    bspec = batch_axes if len(batch_axes) != 1 else batch_axes[0]
    rows_spec = P(bspec) if batch_axes else P()
    vals_spec = P(*((bspec,) + (None,) * (sr.values.ndim - 1))) \
        if batch_axes else P(*((None,) * sr.values.ndim))

    def upd(rows, vals, scal, *tabs):
        if batch_axes:
            rows = lax.all_gather(rows, batch_axes, axis=0, tiled=True)
            vals = lax.all_gather(vals, batch_axes, axis=0, tiled=True)
        lo = _shard_offset(mesh, table_axes, local_rows)
        loc = rows.astype(jnp.int32) - lo
        ok = (loc >= 0) & (loc < local_rows)
        # foreign/sentinel rows -> the local height sentinel with zeroed
        # values: merge_rows collapses them and the scatter drops them
        loc = jnp.where(ok, loc, local_rows).astype(jnp.int32)
        vals = vals * ok.reshape((-1,) + (1,) * (vals.ndim - 1)) \
            .astype(vals.dtype)
        return row_update(SelectedRows(loc, vals, local_rows), scal, *tabs)

    out = shard_map_norep(
        upd, mesh,
        in_specs=(rows_spec, vals_spec, P()) + tuple(specs),
        out_specs=tuple(specs))(sr.rows, sr.values, scalars, *tables)
    return out if isinstance(out, tuple) else (out,)
