"""Sharded embedding tables on the mesh.

Parity: the reference's distributed lookup tables — params sliced across
pservers with remote prefetch (``transpiler/distribute_transpiler.py``
lookup-table handling, ``operators/lookup_table_op.cc`` remote_prefetch,
``split_ids_op.cc`` / ``merge_ids_op.cc``) — re-designed TPU-first:
a table marked ``is_distributed`` by ``layers.embedding`` is row-sharded
over a mesh axis and GSPMD turns the lookups into gather collectives over
ICI; there is no server role, no RPC, and no prefetch op — the "remote"
rows are one all-gather away.
"""

from jax.sharding import PartitionSpec as P

from .mesh import AXIS_DP, AXIS_EP

__all__ = ["distributed_embedding_sharding_fn"]


def _distributed_tables(program):
    """Names of lookup_table W params marked is_distributed."""
    names = set()
    for blk in program.blocks:
        for op in blk.ops:
            if op.type == "lookup_table" and \
                    op.attrs.get("is_distributed", False):
                names.update(op.inputs.get("W", []))
    return names


def distributed_embedding_sharding_fn(program, mesh, axis=None):
    """Build a BuildStrategy.param_sharding_fn that row-shards every
    ``is_distributed`` embedding table over ``axis`` (default: the mesh's
    ``ep`` axis if present, else ``dp``).

    Compose with another policy by chaining: the returned fn yields None
    for non-table params so a wrapper can fall through.
    """
    if axis is None:
        axis = AXIS_EP if AXIS_EP in mesh.axis_names else AXIS_DP
    if axis not in mesh.axis_names:
        raise ValueError(
            "mesh %r has no %r axis to shard embedding tables over; pass "
            "axis= naming one of its axes" % (tuple(mesh.axis_names), axis))
    size = mesh.devices.shape[mesh.axis_names.index(axis)]
    tables = _distributed_tables(program)

    def fn(name, shape):
        if name in tables and shape and shape[0] % size == 0:
            return P(axis)
        return None

    return fn
