"""Execution/Build strategy objects (reference
``framework/details/build_strategy.h:55`` and ``execution_strategy.h``).

The reference's BuildStrategy selects how gradients are combined across
devices (kAllReduce: replicate optimizer everywhere; kReduce: shard the
optimizer work per device, then broadcast params).  The TPU translation:

* kAllReduce -> params/opt-state replicated on the mesh; XLA psums grads.
* kReduce    -> params/opt-state dim-0 sharded over the mesh's ``fsdp``
  axis when it has one, else ``dp`` (ZeRO-style); XLA reduce-scatters
  grads and all-gathers params, which is exactly the reduce+broadcast
  pair the reference schedules by hand.

Declarative model parallelism layers on top via ``sharding_rules``
(spec_layout.py): per-parameter-class canonical PartitionSpecs over the
``(dp, fsdp, tp)`` axes, resolved from the Program structure.
"""

__all__ = ["BuildStrategy", "ExecutionStrategy"]


class BuildStrategy:
    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0   # scale loss grad by 1/num_devices
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        # TPU-specific knobs
        self.donate_state = True          # in-place param updates (XLA)
        self.remat = False                # jax.checkpoint the whole step
        # sharding-policy hooks (the DistributeTranspiler analog: decide
        # where each tensor lives on the mesh; GSPMD inserts collectives)
        #   param_sharding_fn(name, shape) -> PartitionSpec or None
        #   feed_sharding_fn(name, shape)  -> PartitionSpec or None
        # None falls back to sharding_rules (below), then the built-in
        # rule (params: Reduce-strategy ZeRO sharding or replicate;
        # feeds: batch dim over the data axes).
        self.param_sharding_fn = None
        self.feed_sharding_fn = None
        # declarative model parallelism (spec_layout.py): a SpecLayout
        # (or True for the default table) classifies every persistable
        # var from the Program structure and resolves canonical
        # PartitionSpecs onto the mesh's (dp, fsdp, tp) axes — params
        # AND optimizer slot vars fsdp-shard (ZeRO), attention/ffn
        # weights tp-shard, feeds batch-shard over dp x fsdp.  Resolution
        # degrades per-dim when an axis is absent/size-1 or does not
        # divide.  Precedence per param: param_sharding_fn (when it
        # returns a spec) > sharding_rules > reduce_strategy fallback.
        self.sharding_rules = None
        # sp: lower fused_attention ops to ring attention (context
        # parallelism) when the mesh has a populated `sp` axis.  On by
        # default — it only activates when an sp axis exists.  Gates ONLY
        # the attention ring lowering; other mesh-aware lowerings
        # (pipeline_region over pp) always see the mesh.
        self.sequence_parallel = True
        # pipeline schedule for pipeline_region lowerings on pp meshes
        # (parallel/pipeline.py): 'gpipe' (fill-drain), '1f1b'
        # (bounded-memory one-forward-one-backward), 'interleaved'
        # (v stage chunks per device, smaller bubble).  None means the
        # gpipe default AND marks the knob untouched, so
        # autotune.tune_pipeline may choose; an explicit value is a
        # user pin the tuner respects.
        self.pipeline_schedule = None
        # override the pipeline_region ops' microbatch attr (None =
        # honor the program; the tune_pipeline knob lands here)
        self.pipeline_microbatches = None
        # Ragged epoch-end batches (reference
        # details/data_balance_op_handle.cc redistributes them): under
        # SPMD the step's shapes are static, so an indivisible global
        # batch is instead REPLICATED whole, r = dp/gcd(B, dp) times —
        # exact (not approximate) for mean-normalized objectives and BN
        # batch statistics, so the loss/update trajectory matches the
        # single-device run bit-for-bit.  False restores the r3-era
        # ValueError.
        self.pad_uneven_batches = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0              # XLA owns scheduling; kept for API
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100
        self.use_experimental_executor = False
