"""ParallelExecutor: pjit a Program over a device mesh.

Capability parity with the reference's single-process multi-GPU runtime
(``framework/parallel_executor.cc:58-247``: per-device scopes, NCCL
context map, SSA-graph replication with allreduce handles, threaded
dataflow executor) — re-designed TPU-first:

* The program is traced ONCE into a pure step function
  (executor.trace_program) and jit-compiled with
  ``in_shardings``/``out_shardings`` over a named Mesh.  XLA GSPMD
  partitions the computation and inserts ICI collectives — the psum of
  data-parallel gradients replaces ``all_reduce_op_handle.cc``; the
  reduce-scatter/all-gather pair of the kReduce strategy replaces
  ``reduce_op_handle.cc`` + ``broadcast_op_handle.cc``.
* Gradient averaging needs no explicit scale_loss_grad op: the batch is
  sharded over ``dp`` and mean-reduced losses psum partial means, which
  is exactly CoeffNumDevice semantics.
* Feeds: one global batch dict (sharded on dim 0 over ``dp``), or the
  reference's per-device list-of-dicts form (concatenated).
* State lives in the Scope as global jax Arrays; between steps sharded
  params stay resident on their devices (no host round-trip) — the analog
  of the reference's persistent per-device scopes.
* Multi-host ("NCCL2 mode", ``num_trainers``/``trainer_id``): initialize
  ``jax.distributed`` first; the same mesh then spans hosts and XLA
  routes collectives over ICI/DCN (replaces gen_nccl_id + flat NCCL
  world, parallel_executor.cc:94-103).
"""

import time

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import compile_cache, fault, flags, guardian, monitor, registry  # noqa: F401  (op registry must be loaded)
from ..executor import (AsyncDispatchQueue, trace_program, Executor,
                        _batch_examples, _check_finite,
                        _sparse_step_extras, _with_provenance)
from ..monitor import program_profile
from ..profiler import RecordEvent, is_profiling
from ..framework import Variable, default_main_program
from ..scope import global_scope
from .mesh import make_mesh, AXIS_DP, AXIS_FSDP
from .spec_layout import SpecLayout
from .strategy import BuildStrategy, ExecutionStrategy

__all__ = ["ParallelExecutor"]

# sharding_rules=True resolves to this shared table (SpecLayout hashes
# by value, so a per-call instance would also cache correctly — one
# object just keeps the intent obvious)
_DEFAULT_SPEC_LAYOUT = SpecLayout()


class _Compiled:
    def __init__(self, fn, feed_names, state_in, state_out, fetch_names,
                 feed_shardings, state_shardings, out_state_shardings,
                 partition_key=None, guarded=False, probe=None):
        self.fn = fn
        self.feed_names = feed_names
        self.state_in = state_in
        self.state_out = state_out
        self.fetch_names = fetch_names
        self.feed_shardings = feed_shardings
        self.state_shardings = state_shardings
        self.out_state_shardings = out_state_shardings
        # mesh/sharding identity for the program-profile registry: the
        # same program compiled replicated vs fsdp-sharded has ~N-times
        # different per-device memory analyses — separate profile slots
        self.partition_key = partition_key
        # lowered with the guardian's in-graph skip guard (trailing ok
        # fetch; see executor._CompiledProgram)
        self.guarded = guarded
        # lowered with the model-health probe (FLAGS_health): the (L, 4)
        # per-layer stats array rides between user fetches and ok; None
        # means run() performs zero health calls
        self.probe = probe
        self.warm = False      # first dispatch = trace+compile (see Executor)
        # schedule accounting for the program's pipeline regions on this
        # mesh (set by PE._compile; None = nothing runs pipelined)
        self.pipeline_stats = None
        # AOT-captured executable (one per entry: the trace-cache key
        # already pins the feed signature + mesh); set by profile
        # capture at the cold dispatch and used for every later step
        self.aot_exec = None


class ParallelExecutor:
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None, mesh=None):
        self._mesh = mesh if mesh is not None else make_mesh()
        if AXIS_DP not in self._mesh.axis_names and \
                AXIS_FSDP not in self._mesh.axis_names:
            raise ValueError(
                "mesh must have a data axis (%r or %r)"
                % (AXIS_DP, AXIS_FSDP))
        self._program = main_program
        self._scope = scope
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._loss_name = loss_name
        self._num_trainers = num_trainers
        self._trainer_id = trainer_id
        self._cache = {}
        self._run_counter = 0
        self._warned_unobserved_guard = False
        self._auto_seed_val = None
        self._dispatch_queue = AsyncDispatchQueue(name="parallel_executor")
        # observability: how many ragged batches were replication-padded
        # (the data_balance_op_handle capability — see _pad_uneven)
        self.uneven_batches_padded = 0
        if share_vars_from is not None:
            # parity with PE(share_vars_from=train_exe): same scope object
            self._scope = share_vars_from._actual_scope()

    # ------------------------------------------------------------------
    @property
    def device_count(self):
        return int(np.prod(self._mesh.devices.shape))

    def _actual_scope(self):
        return self._scope if self._scope is not None else global_scope()

    def _dp_size(self):
        """Total batch-sharding extent: dp x fsdp.  Both axes shard the
        batch (fsdp is a data-parallel axis for activations; it
        additionally ZeRO-shards params/optimizer state — spec_layout)."""
        return self._axis_size(AXIS_DP) * self._axis_size(AXIS_FSDP)

    def _data_axes(self):
        """The mesh axes the batch dim shards over, in (dp, fsdp) order.
        When both are size 1 (or absent), fall back to whichever data
        axis the mesh actually HAS — naming an absent axis in a spec is
        a jax error even at size 1."""
        axes = tuple(a for a in (AXIS_DP, AXIS_FSDP)
                     if self._axis_size(a) > 1)
        if axes:
            return axes
        return (AXIS_DP,) if AXIS_DP in self._mesh.axis_names \
            else (AXIS_FSDP,)

    def _zero_axis(self):
        """The axis ZeRO-style state sharding targets: ``fsdp`` when the
        mesh has a populated one (the kReduce strategy generalized off
        pure-dp), else ``dp`` (the original kReduce behavior) — always
        an axis the mesh actually has."""
        if self._axis_size(AXIS_FSDP) > 1 or \
                AXIS_DP not in self._mesh.axis_names:
            return AXIS_FSDP
        return AXIS_DP

    # ------------------------------------------------------------------
    def _axis_size(self, axis):
        if axis not in self._mesh.axis_names:
            return 1
        return self._mesh.devices.shape[self._mesh.axis_names.index(axis)]

    def _spec_fits(self, spec, shape, local_batch=False):
        """True iff every named axis in ``spec`` divides its dim of shape.
        With ``local_batch`` (multi-host feeds), dim 0 holds only this
        process's slice, so its divisor shrinks by the process count."""
        entries = tuple(spec)
        if len(entries) > len(shape):
            return False
        for i, (dim, entry) in enumerate(zip(shape, entries)):
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            total = 1
            for ax in axes:
                if ax not in self._mesh.axis_names:
                    return False
                total *= self._axis_size(ax)
            if local_batch and i == 0:
                total = max(1, total // jax.process_count())
            if total > 1 and (dim <= 0 or dim % total != 0):
                return False
        return True

    def _sharding_layout(self):
        """The BuildStrategy's sharding_rules normalized to a SpecLayout
        (``True`` selects the shared default table — the user's strategy
        object is read, never mutated), or None."""
        rules = self._build_strategy.sharding_rules
        if rules is True:
            return _DEFAULT_SPEC_LAYOUT
        return rules

    def _state_spec(self, name, val, rule_specs):
        """Sharding spec for a persistable state array.  Precedence:
        the param_sharding_fn hook (when it returns a spec), then the
        resolved sharding_rules table, then the reduce-strategy
        fallback (ZeRO dim-0 over the fsdp/dp axis under kReduce,
        replicate under kAllReduce)."""
        custom = self._build_strategy.param_sharding_fn
        if custom is not None:
            spec = custom(name, tuple(getattr(val, "shape", ())))
            if spec is not None:
                if not self._spec_fits(spec, tuple(val.shape)):
                    raise ValueError(
                        "param_sharding_fn spec %r does not divide %r of "
                        "shape %s on mesh %s"
                        % (spec, name, tuple(val.shape),
                           dict(zip(self._mesh.axis_names,
                                    self._mesh.devices.shape))))
                return spec
        rule = rule_specs.get(name)
        if rule is not None and rule != P():
            return rule
        # a rules resolution that degraded all the way to "replicate"
        # (e.g. sharding_rules on a mesh with no populated fsdp/tp axis)
        # falls THROUGH to the reduce-strategy tier, so kReduce ZeRO
        # sharding on a pure-dp mesh survives enabling the table; use
        # param_sharding_fn to force-replicate a var against kReduce.
        strat = self._build_strategy.reduce_strategy
        if strat == BuildStrategy.ReduceStrategy.Reduce:
            # ZeRO-style: shard dim 0 over the zero axis when it divides
            # evenly.  Read shape only — np.asarray here would download
            # every param from device HBM at compile time.
            shape = tuple(getattr(val, "shape", ()))
            ax = self._zero_axis()
            if len(shape) >= 1 and shape[0] > 0 \
                    and shape[0] % self._axis_size(ax) == 0:
                return P(ax)
        return P()

    def _compile(self, program, feed_names, fetch_names, scope, feed_vals,
                 feed_sig):
        exe = Executor.__new__(Executor)  # reuse its analyzer only
        state_names, writeback = Executor._analyze(
            exe, program, feed_names, scope)
        bs = self._build_strategy
        # process-global trace cache: key everything this lowering bakes
        # in — program structure + signatures (fingerprint/feed/state/
        # fetch), mesh identity, and the sharding policy knobs
        state_sig = tuple(
            (n, tuple(getattr(scope.var(n), "shape", ())),
             str(getattr(scope.var(n), "dtype", "")))
            for n in state_names)
        mesh_key = (tuple(self._mesh.axis_names),
                    tuple(self._mesh.devices.shape),
                    tuple(int(d.id) for d in self._mesh.devices.flat))
        tkey = compile_cache.trace_key(
            program, feed_sig, state_sig, fetch_names,
            "pjit", mesh_key, bs.reduce_strategy, bs.param_sharding_fn,
            bs.feed_sharding_fn, self._sharding_layout(),
            bs.sequence_parallel, bs.remat,
            bs.donate_state, jax.process_count(),
            bs.pipeline_schedule, bs.pipeline_microbatches,
            compile_cache.trace_flag_values())
        cached = compile_cache.lookup(tkey)
        if cached is not None:
            return cached

        mesh = self._mesh
        # resolve the state placement BEFORE tracing: sharded-op
        # lowerings (sparse embedding lookup/update over row-sharded
        # tables) read their operands' specs from the trace context, so
        # the placement is an input of the trace, not an afterthought.
        # state_in below == state_names (trace_program's contract).
        pre_state_vals = [scope.var(n) for n in state_names]
        layout = self._sharding_layout()
        rule_specs = {}
        if layout is not None:
            rule_specs = layout.resolve(
                program, mesh,
                [(n, tuple(getattr(v, "shape", ())))
                 for n, v in zip(state_names, pre_state_vals)])
        spec_by_name = {
            n: self._state_spec(n, v, rule_specs)
            for n, v in zip(state_names, pre_state_vals)
        }

        # FLAGS_health: grad vars join the traced fetch list, the fused
        # per-layer stats reduction rides as one extra fetch (see
        # executor._lower); enablement re-keys via trace_flag_values
        probe = monitor.health.build_probe(program, state_names) \
            if monitor.health.probe_enabled() else None
        traced_fetches = list(fetch_names) + \
            (list(probe.grad_names) if probe is not None else [])
        with RecordEvent("parallel_executor/trace"):
            fn, state_in, state_out = trace_program(
                program, feed_names, state_names, writeback, traced_fetches,
                platform=self._mesh.devices.flat[0].platform,
                mesh=self._mesh,
                sequence_parallel=self._build_strategy.sequence_parallel,
                pipeline_schedule=bs.pipeline_schedule,
                pipeline_microbatches=bs.pipeline_microbatches,
                state_specs=spec_by_name)
        data_axes = self._data_axes()
        batch_spec = P(data_axes if len(data_axes) > 1 else data_axes[0])
        feed_shardings = []
        dp = self._dp_size()
        # multi-host: each process feeds its local slice, so the local
        # batch only needs to cover this process's share of the dp axis
        dp = max(1, dp // jax.process_count())
        custom_feed = self._build_strategy.feed_sharding_fn
        for n, v in zip(feed_names, feed_vals):
            arr = np.asarray(v) if not isinstance(v, jax.Array) else v
            spec = None
            if custom_feed is not None:
                spec = custom_feed(n, tuple(arr.shape))
            if spec is not None:
                if not self._spec_fits(spec, tuple(arr.shape),
                                       local_batch=jax.process_count() > 1):
                    raise ValueError(
                        "feed_sharding_fn spec %r does not divide feed %r "
                        "of shape %s" % (spec, n, tuple(arr.shape)))
                feed_shardings.append(NamedSharding(mesh, spec))
            elif arr.ndim >= 1 and arr.shape[0] % dp == 0 \
                    and arr.shape[0] > 0:
                feed_shardings.append(NamedSharding(mesh, batch_spec))
            else:
                raise ValueError(
                    "feed %r batch dim %s is not divisible by the "
                    "data-parallel mesh extent %d (dp x fsdp)"
                    % (n, arr.shape[:1], dp)
                )

        state_shardings = [
            NamedSharding(mesh, spec_by_name[n]) for n in state_in
        ]
        out_state_shardings = [
            NamedSharding(mesh, spec_by_name.get(n, P()))
            for n in state_out
        ]

        if self._build_strategy.remat:
            fn = jax.checkpoint(fn)

        guarded = guardian.skip_guard_enabled()
        if guarded:
            # in-graph sentinel + skip (see executor._lower); wrapped
            # OUTSIDE remat so the guard's select is not rematerialized.
            # n_watch keeps the probe's grad fetches off the sentinel
            fn = guardian.wrap_step_guard(fn, state_in, state_out,
                                          n_watch=len(fetch_names))
        if probe is not None:
            fn = monitor.health.wrap_step_probe(
                fn, probe, len(fetch_names), guarded, state_in, state_out)

        donate = (1,) if self._build_strategy.donate_state else ()
        # multi-host: fetches are forced replicated so every process can
        # read them (np.asarray on a non-addressable array would throw)
        fetch_shardings = None
        if jax.process_count() > 1:
            # +1s: the guard's trailing ok fetch and the probe's stats
            # array are scalars/small every process must read too
            fetch_shardings = [NamedSharding(mesh, P())] \
                * (len(fetch_names) + (1 if probe is not None else 0)
                   + (1 if guarded else 0))
        # jax.jit here is lazy (tracing deferred to the first call): no
        # span — the real jaxpr cost is the trace_program above
        jitted = jax.jit(
            fn,
            in_shardings=(feed_shardings, state_shardings, None),
            out_shardings=(fetch_shardings, out_state_shardings),
            donate_argnums=donate,
        )
        partition_key = (mesh_key[0], mesh_key[1], tuple(
            (n, str(spec_by_name[n])) for n in state_in
            if spec_by_name[n] != P()))
        compiled = _Compiled(
            jitted, feed_names, state_in, state_out,
            fetch_names, feed_shardings, state_shardings,
            out_state_shardings, partition_key=partition_key,
            guarded=guarded, probe=probe)
        compiled.pipeline_stats = self._pipeline_stats(program)
        return compile_cache.store(tkey, compiled)

    def _pipeline_stats(self, program):
        """Per-tick stage-idle accounting for the program's
        pipeline_region ops under this executor's mesh + schedule — the
        numbers behind the goodput ledger's ``pipeline_bubble`` bucket.
        Mirrors the lowering's engagement test (ops/pipeline_region.py);
        None when no region runs pipelined on this mesh."""
        from .mesh import AXIS_PP
        from .pipeline import normalize_schedule, schedule_stats

        pp = self._axis_size(AXIS_PP)
        if pp <= 1:
            return None
        schedule = normalize_schedule(
            self._build_strategy.pipeline_schedule)
        override = self._build_strategy.pipeline_microbatches
        regions = []
        for op in program.global_block().ops:
            if op.type != "pipeline_region":
                continue
            s_count = int(op.attrs["stages"])
            if schedule == "interleaved":
                if s_count % pp or s_count <= 1:
                    continue
                v = s_count // pp
            else:
                if s_count != pp or s_count <= 1:
                    continue
                v = 1
            m = int(override or op.attrs.get("microbatches") or s_count)
            regions.append(schedule_stats(schedule, pp, m, v))
        if not regions:
            return None
        total = sum(r["total_units"] for r in regions)
        idle = sum(r["idle_units"] for r in regions)
        return {"schedule": schedule,
                "bubble_fraction": idle / total if total else 0.0,
                "regions": regions}

    # ------------------------------------------------------------------
    @staticmethod
    def _global_state(val, sharding):
        """Lift a host-local state value (identical on every process, by
        deterministic seeded startup) into a global array on ``sharding``."""
        if isinstance(val, jax.Array) and len(val.sharding.device_set) > 1:
            return val          # already global (previous step's output)
        host = np.asarray(val)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    # ------------------------------------------------------------------
    def _pad_uneven(self, feed_vals):
        """Ragged-batch handling (reference
        ``details/data_balance_op_handle.cc:1`` redistributes uneven
        epoch-end batches across devices): SPMD-jitted steps have static
        shapes, so the ragged global batch is replicated WHOLE,
        r = dp / gcd(B, dp) times, making dim 0 divisible.  Replication
        (unlike zero-pad-and-mask) is EXACT: means over the batch,
        per-sample gradients of a mean loss, and BN batch statistics are
        all invariant under whole-batch replication, so the training
        trajectory matches the single-device run bit-for-bit; per-sample
        fetches are trimmed back to the true batch.  Costs r x compute
        for the one ragged batch per epoch."""
        import math

        dp = max(1, self._dp_size() // jax.process_count())
        bs = {v.shape[0] for v in feed_vals if getattr(v, "ndim", 0) >= 1}
        if len(bs) != 1:
            return feed_vals, 1
        b = bs.pop()
        if b <= 0 or b % dp == 0:
            return feed_vals, 1
        r = dp // math.gcd(b, dp)
        if self.uneven_batches_padded == 0:
            import warnings
            warnings.warn(
                "ragged batch %d replicated x%d to fit the dp=%d mesh: "
                "exact for mean-normalized losses and BN stats; a "
                "sum-reduced objective would scale by the replication "
                "factor — set BuildStrategy.pad_uneven_batches=False to "
                "reject ragged batches instead" % (b, r, dp),
                stacklevel=3)
        self.uneven_batches_padded += 1
        return [np.concatenate([np.asarray(v)] * r, axis=0)
                for v in feed_vals], r

    # ------------------------------------------------------------------
    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        program = self._program or default_main_program()
        scope = self._actual_scope()
        mon_t0 = time.perf_counter() if monitor.enabled() else None
        feed = feed if feed is not None else feed_dict
        if isinstance(feed, (list, tuple)):
            # reference per-device feed list: concatenate along batch
            merged = {}
            for k in feed[0]:
                merged[k] = np.concatenate(
                    [np.asarray(d[k]) for d in feed], axis=0)
            feed = merged
        feed = dict(feed or {})

        fetch_names = [
            v.name if isinstance(v, Variable) else v for v in fetch_list
        ]
        feed_names = sorted(feed.keys())
        block = program.global_block()
        feed_vals = []
        for n in feed_names:
            v = feed[n]
            if not isinstance(v, jax.Array):
                v = np.asarray(v)
            pv = block._find_var_recursive(n)
            if pv is not None and pv.dtype is not None and \
                    np.dtype(v.dtype) != np.dtype(pv.dtype):
                v = v.astype(pv.dtype)
            feed_vals.append(v)

        # this run's step index (before the PRNG fold-in counter bumps):
        # fault schedules and guardian records key on it
        step_idx = self._run_counter
        if fault.active():
            fault.fire("executor/feed", step_idx,
                       feed_names=feed_names, feed_vals=feed_vals)

        # the guardian quarantines the batch AS FED (post-drill, pre-pad):
        # a replayed quarantine artifact must match what the reader
        # yielded, not the mesh-padded copy
        user_feed_vals = feed_vals
        pad_r = 1
        if self._build_strategy.pad_uneven_batches:
            feed_vals, pad_r = self._pad_uneven(feed_vals)

        feed_sig = tuple(
            (n, tuple(v.shape), str(v.dtype))
            for n, v in zip(feed_names, feed_vals)
        )
        # policy fns go in the key as objects (kept alive by the cache, so
        # no id()-reuse aliasing after GC)
        key = (id(program), program._version, feed_sig, tuple(fetch_names),
               id(scope), getattr(program, '_amp_policy', None),
               # trace-time flag choices, matching _compile's trace_key
               compile_cache.trace_flag_values(),
               self._build_strategy.reduce_strategy,
               self._build_strategy.param_sharding_fn,
               self._build_strategy.feed_sharding_fn,
               self._sharding_layout(),
               self._build_strategy.pipeline_schedule,
               self._build_strategy.pipeline_microbatches)
        compiled = self._cache.get(key)
        if compiled is None:
            with RecordEvent("parallel_executor/compile"):
                compiled = self._compile(program, feed_names, fetch_names,
                                         scope, feed_vals, feed_sig)
            self._cache[key] = compiled

        multihost = jax.process_count() > 1
        with RecordEvent("parallel_executor/h2d_transfer"):
            if multihost:
                # NCCL2-mode parity: each trainer process feeds its LOCAL
                # shard of the global batch; the global array spans hosts
                # (parallel_executor.cc:102 flat world of trainer ranks)
                feed_dev = [
                    v if isinstance(v, jax.Array)
                    and len(v.sharding.device_set)
                    > 1 else jax.make_array_from_process_local_data(s, v)
                    for v, s in zip(feed_vals, compiled.feed_shardings)
                ]
                state_dev = [
                    self._global_state(scope.var(n), s)
                    for n, s in zip(compiled.state_in,
                                    compiled.state_shardings)
                ]
            else:
                feed_dev = [
                    jax.device_put(v, s)
                    for v, s in zip(feed_vals, compiled.feed_shardings)
                ]
                state_dev = [
                    jax.device_put(scope.var(n), s)
                    for n, s in zip(compiled.state_in,
                                    compiled.state_shardings)
                ]
        seed = program.random_seed or 0
        rng = jax.random.key(
            np.uint32(seed) if seed else self._auto_seed(),
            impl="rbg" if flags.flag("fast_prng") else None)
        rng = jax.random.fold_in(rng, self._run_counter)
        self._run_counter += 1

        step_span = "parallel_executor/dispatch" if compiled.warm \
            else "parallel_executor/compile"
        fp = compile_cache.program_fingerprint(program) \
            if (mon_t0 is not None or is_profiling()) else None
        # bucket hint for the goodput ledger / offline trace_summary
        # (same contract as the single-device Executor)
        span_args = {"run_id": monitor.run_id(), "fingerprint": fp[:12],
                     "step": self._run_counter - 1,
                     "bucket": "compute" if compiled.warm
                     else "trace_compile"} if fp else None
        if fault.active():
            fault.fire("executor/dispatch", step_idx)
        with RecordEvent("parallel_executor/run"):
            with RecordEvent(step_span, args=span_args):
                if not compiled.warm and program_profile.capture_enabled() \
                        and not flags.flag("debug_nans"):
                    # AOT-compile + profile + HBM-preflight the pjit'd
                    # module before its first dispatch; the captured
                    # executable serves every later step (one compile
                    # total).  SPMD analyses are per-device, which is
                    # the granularity the preflight compares against.
                    compiled.aot_exec = program_profile.capture(
                        fp if fp is not None else
                        compile_cache.program_fingerprint(program),
                        feed_sig, compiled.fn, (feed_dev, state_dev, rng),
                        device=self._mesh.devices.flat[0],
                        kind="parallel_executor",
                        fetch_names=tuple(fetch_names),
                        partition=compiled.partition_key)
                fn = compiled.aot_exec \
                    if compiled.aot_exec is not None \
                    and not flags.flag("debug_nans") else compiled.fn
                try:
                    fetches, new_state = fn(feed_dev, state_dev, rng)
                except (TypeError, ValueError):
                    if fn is compiled.fn:
                        raise
                    # AOT executable rejected the args: permanent
                    # fallback to the jit path for this entry
                    compiled.aot_exec = None
                    fetches, new_state = compiled.fn(feed_dev, state_dev,
                                                     rng)
        compiled.warm = True

        ok_flag = None
        if compiled.guarded:
            # the in-graph sentinel's verdict rides as a trailing fetch
            ok_flag = fetches[-1]
            fetches = fetches[:-1]
        if compiled.probe is not None:
            # per-layer health stats ride second-to-last (before ok);
            # the replay context stashes the batch AS FED (pre-pad), the
            # same artifact the guardian quarantines — so provenance
            # replays reproduce the quarantined step exactly
            health_stats = fetches[-1]
            fetches = fetches[:-1]
            monitor.health.note_step(
                "parallel_executor", step_idx, compiled.probe,
                health_stats, program=program, scope=scope, rng=rng,
                feed_names=feed_names, feed_vals=user_feed_vals,
                platform=self._mesh.devices.flat[0].platform)

        for n, v in zip(compiled.state_out, new_state):
            scope.set_var(n, v)

        if fault.active():
            fetches = list(fetches)
            fault.fire("executor/step_done", step_idx, scope=scope,
                       state_names=compiled.state_out,
                       fetch_names=compiled.fetch_names, fetches=fetches)
        if pad_r > 1:
            # trim per-sample fetches (e.g. predictions [B*r, ...]) back
            # to the true batch; scalars/means are replication-invariant.
            # Only BATCH-dim vars trim (program shape[0] == -1): a
            # parameter whose leading dim coincidentally equals the
            # padded batch must come back whole.
            padded_b = next((v.shape[0] for v in feed_vals
                             if getattr(v, "ndim", 0) >= 1), 0)
            true_b = padded_b // pad_r

            def _is_batch_var(name):
                v = block._find_var_recursive(name)
                return (v is not None and v.shape is not None
                        and len(v.shape) >= 1 and v.shape[0] in (-1, None))

            fetches = [
                f[:true_b] if getattr(f, "ndim", 0) >= 1
                and f.shape[0] == padded_b and _is_batch_var(n) else f
                for n, f in zip(compiled.fetch_names, fetches)
            ]
        np_fetches = None
        if flags.flag("check_nan_inf"):
            # fetches only: state may span hosts (not fully addressable).
            # Convert into a side copy so return_numpy=False still hands
            # back device arrays (the check implies a per-step sync, not
            # a type change).
            np_fetches = [self._fetch_to_np(f) for f in fetches]
            try:
                _check_finite(
                    zip(compiled.fetch_names, np_fetches),
                    context=lambda: "run_id=%s fp12=%s step=%d" % (
                        monitor.run_id(),
                        compile_cache.program_fingerprint(program)[:12],
                        step_idx))
            except RuntimeError as e:
                raise _with_provenance(e, compiled.probe, step_idx) \
                    from None
        if return_numpy:
            with RecordEvent("parallel_executor/fetch_sync"):
                fetches = np_fetches if np_fetches is not None else \
                    [self._fetch_to_np(f) for f in fetches]
        else:
            # async fast path (matches single-device Executor semantics):
            # fetches stay (possibly sharded) device arrays, no per-step
            # sync — the dispatch window blocks only at its edge
            self._dispatch_queue.push_step(fetches, new_state)
        if mon_t0 is not None:
            warm_step = step_span == "parallel_executor/dispatch"
            ps = compiled.pipeline_stats
            if ps is not None and warm_step:
                # measured bubble attribution: the executed schedule's
                # per-tick stage-idle fraction (exact, from the
                # lowering's own schedule tables) carved out of this
                # step's measured wall clock.  Warm steps only — a cold
                # step's wall is compile, already attributed.  The
                # whole step is treated as pipelined time (the regions
                # dominate deep models; documented in README).
                step_s = time.perf_counter() - mon_t0
                monitor.observe_span(
                    "pipeline/bubble",
                    step_s * ps["bubble_fraction"] * 1e6,
                    args={"bucket": "pipeline_bubble",
                          "schedule": ps["schedule"],
                          "fraction": round(ps["bubble_fraction"], 4),
                          "run_id": monitor.run_id(),
                          "fingerprint": fp[:12] if fp else None})
            # // pad_r: a replication-padded ragged batch still trained
            # on its true example count
            examples = _batch_examples(block, feed_names,
                                       feed_vals) // pad_r
            monitor.record_step(
                "parallel_executor", time.perf_counter() - mon_t0,
                examples, len(self._dispatch_queue),
                device=self._mesh.devices.flat[0],
                warm=warm_step,
                fingerprint=fp,
                extras=_sparse_step_extras(program, feed_names,
                                           user_feed_vals))
            # per-device memory/step gauges for the whole local mesh
            # (the single-device sample above covers only device 0)
            monitor.sample_device_gauges(
                [d for d in self._mesh.devices.flat
                 if d.process_index == jax.process_index()])
        # guardian hook LAST (after telemetry); one module-global read
        # when no guardian is installed
        g = guardian.active()
        if g is not None:
            g.note_step("parallel_executor", step_idx, ok=ok_flag,
                        fetch_names=compiled.fetch_names, fetches=fetches,
                        feed=(feed_names, user_feed_vals),
                        sync=return_numpy)
        elif ok_flag is not None:
            guardian.warn_unobserved_skip_guard(self)
        return fetches

    def sync(self):
        """Retire every in-flight async-dispatched step (see
        ``Executor.sync``)."""
        self._dispatch_queue.drain()

    def state_shardings(self, program=None, scope=None):
        """``{name: NamedSharding}`` for every persistable var of
        ``program`` as this executor's policy would place it on its mesh
        — the ``shardings=`` argument for TrainState/orbax restores, so
        a checkpoint written on any topology lands directly sharded on
        this one instead of replicating through host memory first."""
        from jax.sharding import NamedSharding as NS

        from ..framework import default_main_program
        from .checkpoint import _persistable_state

        program = program if program is not None else (
            self._program or default_main_program())
        scope = scope if scope is not None else self._actual_scope()
        state = _persistable_state(scope, program)
        layout = self._sharding_layout()
        rule_specs = {}
        if layout is not None:
            rule_specs = layout.resolve(
                program, self._mesh,
                [(n, tuple(getattr(v, "shape", ())))
                 for n, v in state.items()])
        return {n: NS(self._mesh, self._state_spec(n, v, rule_specs))
                for n, v in state.items()}

    def state_dict(self):
        """Exact-resume host state (see ``Executor.state_dict``): the
        PRNG fold-in counter plus the once-per-executor auto seed for
        seedless programs (drawn at first run, broadcast across hosts —
        restoring it keeps the resumed random stream identical)."""
        st = {"run_counter": int(self._run_counter)}
        if self._auto_seed_val is not None:
            st["auto_seed"] = int(self._auto_seed_val)
        return st

    def load_state_dict(self, state):
        self._run_counter = int(state["run_counter"])
        if state.get("auto_seed") is not None:
            self._auto_seed_val = np.uint32(state["auto_seed"])

    def _auto_seed(self):
        """Seed for programs with no explicit random_seed.  Drawn once
        per executor and, on multi-host jobs, broadcast from process 0:
        SPMD requires every process to feed the *same* rng key or
        nominally-replicated state silently diverges across hosts."""
        if self._auto_seed_val is None:
            seed = np.random.randint(0, 2**31 - 1)
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                seed = int(multihost_utils.broadcast_one_to_all(
                    np.int64(seed)))
            self._auto_seed_val = np.uint32(seed)
        return self._auto_seed_val

    @staticmethod
    def _fetch_to_np(f):
        if isinstance(f, jax.Array) and not f.is_fully_addressable:
            # multi-host: fetches are compiled with replicated
            # out_shardings, so the local shard IS the global value
            return np.asarray(f.addressable_shards[0].data)
        return np.asarray(f)
