"""Ring attention: sequence/context parallelism over the mesh.

The reference's long-sequence story is LoD + RNN (SURVEY.md §5); the
2026-scale equivalent this framework makes first-class is context
parallelism: the sequence axis is sharded over a mesh axis (``sp``) and
attention runs as a RING — each device holds its local Q block
resident and streams the K/V blocks around the ring with ``ppermute``
(one ICI hop per step), accumulating the softmax online (flash-style
running max/denominator).  Peak memory per device is O(T/n * T/n)
instead of O(T^2), and the K/V transfer overlaps compute on real ICI.

Public surface:

* ``ring_attention(q, k, v, mesh, axis='sp', causal=False)`` — jittable;
  q/k/v are [B, H, T, D] global arrays (or host arrays) that get
  time-sharded over ``axis`` via shard_map.
* ``ring_attention_shard(...)`` — the per-device body, usable inside an
  existing shard_map (e.g. a pjit'ed training step that already runs
  under the mesh).

Design refs: the blockwise/ring formulation in PAPERS.md; collectives
per pallas_guide.md "Ring Collectives" (ppermute ring pattern) — here
expressed at the XLA level (lax.ppermute) so GSPMD schedules ICI DMAs;
a Pallas RDMA variant can slot in later without changing the surface.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import AXIS_SP, shard_map_norep

__all__ = ["ring_attention", "ring_attention_shard"]

_NEG_INF = -1e30


def ring_attention_shard(q, k, v, axis_name, causal=False, scale=None,
                         k_len=None, dropout_rate=0.0, seed=None,
                         batch_axis_name=None, head_axis_name=None):
    """Per-device ring attention body (run under shard_map).

    q [B, H, Tq, D] local query block; k/v [B, H, Tk, D] local key/value
    blocks.  Streams K/V around the ``axis_name`` ring; returns the
    local attention output [B, H, Tq, D].

    ``k_len`` [B] masks padded key positions (global valid-key counts for
    this shard's batch rows); ``dropout_rate``/``seed`` apply the same
    counter-hash weight dropout as the single-chip fused_attention op
    (``ops/pallas/flash_attention._keep_mask`` on GLOBAL positions, so a
    ring run reproduces a single-chip run's mask bit-for-bit —
    downgrade_in_infer semantics: masked, not upscaled).
    ``batch_axis_name`` names the mesh axis the batch is sharded over, so
    the hash's global (batch*head) index stays correct under dp.
    ``head_axis_name`` likewise names the axis the HEAD dim is sharded
    over (tensor parallelism composing with the sequence ring): heads
    attend independently, so tp sharding is transparent to the math, and
    the head offset keeps dropout masks identical to a single-chip run.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, tq, d = q.shape
    tk = k.shape[2]
    in_dtype = q.dtype
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    # scores and online-softmax statistics accumulate in fp32: bf16
    # inputs (the AMP path) would drift across the n ring steps
    q = q.astype(jnp.float32) * scale

    # ring: at step i we hold the K/V block originally owned by shard
    # (idx + i) mod n; send to the previous neighbor each step so the
    # blocks rotate forward through every device exactly once
    perm = [(j, (j - 1) % n) for j in range(n)]

    q_pos = idx * tq + jnp.arange(tq)             # global query positions
    masked = causal or k_len is not None
    if dropout_rate:
        from ..ops.pallas.flash_attention import _keep_mask
        if seed is None:
            seed = jnp.zeros((), jnp.uint32)
        b_off = 0
        if batch_axis_name is not None:
            b_off = lax.axis_index(batch_axis_name) * b
        h_off = 0
        h_total = h
        if head_axis_name is not None:
            h_off = lax.axis_index(head_axis_name) * h
            h_total = h * lax.psum(1, head_axis_name)
        # global (batch*head) index per row, same layout as single-chip
        bh_idx = ((b_off + jnp.arange(b))[:, None] * h_total +
                  (h_off + jnp.arange(h))[None, :])[:, :, None, None]

    def step(i, carry):
        k_blk, v_blk, m, l, o = carry
        kv_owner = (idx + i) % n
        k_pos = kv_owner * tk + jnp.arange(tk)    # global key positions
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32)
        if masked:
            valid = jnp.ones((b, 1, tq, tk), bool)
            if k_len is not None:
                valid = k_pos[None, None, None, :] < \
                    k_len.astype(jnp.int32)[:, None, None, None]
            if causal:
                valid = valid & \
                    (q_pos[:, None] >= k_pos[None, :])[None, None]
            s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if masked:
            # a fully-masked row keeps m_new == _NEG_INF, so exp(s - m_new)
            # is 1.0 per masked key; zero them explicitly rather than rely
            # on the diagonal block (tq == tk at step 0) being seen first —
            # ring_attention guarantees that, standalone shard use may not
            p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate:
            keep = _keep_mask(seed.astype(jnp.uint32), bh_idx,
                              q_pos[:, None], k_pos[None, :], dropout_rate)
            p = jnp.where(keep, p, 0.0)
        o = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk,
                                  preferred_element_type=jnp.float32)

        def rotate(blks):
            return tuple(lax.ppermute(x, axis_name, perm) for x in blks)

        # the final iteration's rotation would be discarded: skip the
        # two ICI transfers (n-1 hops move every block everywhere)
        k_blk, v_blk = lax.cond(i < n - 1, rotate,
                                lambda blks: blks, (k_blk, v_blk))
        return k_blk, v_blk, m_new, l, o

    m0 = jnp.full((b, h, tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq, 1), jnp.float32)
    o0 = jnp.zeros((b, h, tq, d), jnp.float32)
    _, _, m, l, o = lax.fori_loop(0, n, step, (k, v, m0, l0, o0))
    return (o / jnp.maximum(l, 1e-20)).astype(in_dtype)


def ring_attention(q, k, v, mesh, axis=AXIS_SP, causal=False,
                   scale=None, batch_axis=None):
    """Context-parallel attention over ``mesh``'s ``axis``.

    q/k/v: [B, H, T, D] with T divisible by the axis size.  Returns
    [B, H, T, D] sharded the same way (time over ``axis``).
    ``batch_axis`` optionally shards the batch dim over another mesh
    axis (dp composition); without it the batch replicates across the
    non-sp axes."""
    if axis not in mesh.axis_names:
        raise ValueError("mesh has no axis %r (axes: %s)"
                         % (axis, mesh.axis_names))
    if batch_axis is not None:
        if batch_axis not in mesh.axis_names:
            raise ValueError("mesh has no axis %r (axes: %s)"
                             % (batch_axis, mesh.axis_names))
        if batch_axis == axis:
            raise ValueError(
                "batch_axis must differ from the sequence axis %r" % axis)
    spec = P(batch_axis, None, axis, None)
    body = functools.partial(ring_attention_shard, axis_name=axis,
                             causal=causal, scale=scale)
    fn = shard_map_norep(body, mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(q, k, v)
