"""Sharded / async checkpointing for the mesh runtime (orbax-backed).

Parity: the reference's checkpoint tier at distributed scale —
``save_op``/``load_op`` + ``fluid/io.py`` handle host tensors
(mirrored by ``paddle_tpu.io``); the *distributed* story there is
pserver-side shard checkpoints triggered by ``checkpoint_notify_op.cc``
and the Go pserver's periodic shard snapshots
(``go/pserver/service.go:346 checkpoint``, ``:175 LoadCheckpoint``).
TPU-native redesign: parameters live sharded on the mesh, so the
checkpoint IS the sharded artifact — orbax writes each host's shards in
parallel (OCDBT), restore re-shards onto the current mesh (even a mesh
of a different shape/size, the elastic-resume case), and saves can be
async so the train loop overlaps the write (the pserver's
"snapshot while serving" behavior).

Works with the Scope/Program model: persistable vars are the pytree.

Exact-resume elastic training (ISSUE 6 tentpole) lives in the second
half of this module: ``TrainState`` captures params *and* optimizer
slot vars, LR/step counters, executor PRNG counters, and reader
position as ONE atomic artifact; ``TrainStateCheckpointManager`` writes
it asynchronously (snapshot at the step boundary, write under the next
interval's compute, ``checkpoint/save`` monitor span), commits
atomically (tmp dir + rename) with a sha256 manifest, and on restore
validates the manifest and FALLS BACK to the previous checkpoint when
the latest is partial or corrupt — the production pattern of CheckFreq
(FAST'21) / Check-N-Run (NSDI'22), see PAPERS.md.
"""

import collections
import hashlib
import json
import os
import shutil
import threading
import time
import warnings

import jax
import numpy as np

from .. import fault, monitor
from ..profiler import RecordEvent
from ..scope import global_scope

__all__ = [
    "save_sharded", "load_sharded", "ShardedCheckpointManager",
    "TrainState", "TrainStateCheckpointManager", "CheckpointCorruptError",
    "CheckpointMismatchError", "capture_train_state", "apply_train_state",
    "save_train_state", "load_train_state", "save_train_state_sharded",
    "write_train_state_shards", "commit_sharded_train_state",
    "partition_shards", "sparse_table_state_vars", "row_delta",
]


def _persistable_state(scope, program=None):
    """dict name -> array of the checkpointable vars."""
    from ..framework import default_main_program

    program = program or default_main_program()
    state = {}
    for var in program.global_block().vars.values():
        if getattr(var, "persistable", False) and scope.has_var(var.name):
            state[var.name] = scope.var(var.name)
    return state


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _require_state(state, action):
    if not state:
        raise ValueError(
            "no persistable state in scope to %s: run the startup "
            "program first so the var set and shapes/dtypes exist"
            % action)


def _abstract_state(state, shardings):
    """ShapeDtypeStruct restore targets (optionally mesh-placed)."""

    def one(name, v):
        arr = np.asarray(v) if not isinstance(v, jax.Array) else v
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype,
                                    sharding=(shardings or {}).get(name))

    return {n: one(n, v) for n, v in state.items()}


def save_sharded(dirname, scope=None, program=None):
    """Write the persistable state as a sharded orbax checkpoint.
    Each process writes only its addressable shards (multi-host safe)."""
    scope = scope or global_scope()
    state = _persistable_state(scope, program)
    _require_state(state, "save")
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(dirname), state, force=True)
    ckptr.wait_until_finished()
    return sorted(state)


def load_sharded(dirname, scope=None, program=None, shardings=None):
    """Restore a sharded checkpoint into the scope.

    ``shardings``: optional dict name -> jax.sharding.Sharding to place
    restored arrays directly onto the current mesh (possibly a different
    topology than the one that saved — the elastic-resume case).
    Without it arrays restore as host-local numpy."""
    import orbax.checkpoint as ocp

    scope = scope or global_scope()
    state = _persistable_state(scope, program)
    _require_state(state, "restore into")
    ckptr = _checkpointer()
    restored = ckptr.restore(os.path.abspath(dirname),
                             _abstract_state(state, shardings))
    for name, val in restored.items():
        scope.set_var(name, val)
    return sorted(restored)


class ShardedCheckpointManager:
    """Step-indexed async checkpoint rotation (CheckpointConfig's
    epoch/step-interval + max_num_checkpoints at mesh scale;
    go/pserver periodic-shard-checkpoint parity)."""

    def __init__(self, dirname, max_to_keep=3, save_interval_steps=1,
                 async_save=True):
        import orbax.checkpoint as ocp

        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(dirname), options=self._options)

    def save(self, step, scope=None, program=None):
        """Maybe-save (interval-gated) at ``step``; async by default."""
        import orbax.checkpoint as ocp

        if not self._mgr.should_save(step):
            return False  # interval-gated: skip the state walk entirely
        state = _persistable_state(scope or global_scope(), program)
        _require_state(state, "save")
        return self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore(self, scope=None, program=None, step=None,
                shardings=None):
        """Restore ``step`` (default: latest). Returns the step or None
        if no checkpoint exists."""
        import orbax.checkpoint as ocp

        scope = scope or global_scope()
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        state = _persistable_state(scope, program)
        _require_state(state, "restore into")
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(
                _abstract_state(state, shardings)))
        for name, val in restored.items():
            scope.set_var(name, val)
        return step

    def save_now(self, step, scope=None, program=None):
        """Forced synchronous save, ignoring the interval gate — the
        flush-before-exit path (preemption / SIGTERM).

        Callers decide WHEN this is safe: flush at a step boundary, and
        in a multi-process world agree on ``step`` first (the
        ``distributed.any_process_flagged`` vote) since every host must
        join this collective write.  ``contrib.Trainer`` wires the
        single-process flow (signal -> finish step -> flush);
        ``tests/dist_runner.py`` shows the multi-process protocol."""
        import orbax.checkpoint as ocp

        # drain any in-flight async periodic save before starting the
        # forced one (CheckpointManager.save is not reentrant)
        self._mgr.wait_until_finished()
        state = _persistable_state(scope or global_scope(), program)
        _require_state(state, "save")
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                               force=True)
        self._mgr.wait_until_finished()
        return saved

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


# ---------------------------------------------------------------------------
# Exact-resume TrainState checkpoints (ISSUE 6)
# ---------------------------------------------------------------------------

TRAIN_STATE_FORMAT = 1

# Fault-injection points for the kill-and-resume drills live in the
# process-wide registry (``paddle_tpu.fault``): the write protocol
# fires ``checkpoint/before_write`` / ``checkpoint/after_write`` /
# ``checkpoint/before_commit`` with the artifact's step — e.g.
# ``fault.kill_mid_save(FaultSchedule(steps=[11]))`` simulates
# preemption mid-save, leaving only a .tmp dir the restore must ignore
# (tests/test_elastic_drill.py).

_ARRAYS_FILE = "arrays.npz"
_HOST_FILE = "train_state.json"
_MANIFEST_FILE = "MANIFEST.json"
_STEP_PREFIX = "step_"
_TMP_PREFIX = ".tmp."


class CheckpointCorruptError(RuntimeError):
    """A checkpoint artifact failed manifest/checksum validation."""


class CheckpointMismatchError(CheckpointCorruptError):
    """The artifact is intact but does not FIT: different model var set
    or executor naming.  Distinct from corruption so restore() can stop
    and surface a configuration error instead of silently falling back
    past every (structurally identical) older artifact to a fresh
    start."""


def _npz_encode(arr):
    """(encodable array, logical dtype name or None): dtypes the npy
    format cannot describe (ml_dtypes bfloat16 etc. round-trip as raw
    void) are stored as same-width uints + the logical name."""
    arr = np.ascontiguousarray(arr)
    try:
        descr = np.lib.format.dtype_to_descr(arr.dtype)
        if np.dtype(descr) == arr.dtype:
            return arr, None
    except (ValueError, TypeError):
        pass
    raw = np.dtype("u%d" % arr.dtype.itemsize)
    return arr.view(raw), arr.dtype.name


def _npz_decode(arr, dtype_name):
    if not dtype_name:
        return arr
    import ml_dtypes

    return arr.view(np.dtype(getattr(ml_dtypes, dtype_name)))


def _named(objs, what):
    """Normalize the executors=/readers= argument: None, a single
    object, a sequence (auto-named by position), or a {name: obj} dict."""
    if objs is None:
        return {}
    if isinstance(objs, dict):
        return dict(objs)
    if isinstance(objs, (list, tuple)):
        return {"%s%d" % (what, i): o for i, o in enumerate(objs)}
    return {what + "0": objs}


def sparse_table_state_vars(program, names):
    """The state vars an incremental checkpoint should delta-encode for
    ``program``: every ``is_sparse`` lookup table plus its row-wise
    optimizer slot vars (``<table>_moment1_0``... — recognized by the
    name prefix; the shape gate — leading dim == table height — is
    applied against the live arrays at save time).  These are exactly
    the vars the lazy SelectedRows update keeps bit-stable on untouched
    rows, which is what makes row deltas small."""
    from ..ops.selected_rows import is_row_slot_of, sparse_lookup_tables

    tables = {w: int(v.shape[0])
              for w, v in sparse_lookup_tables(program).items()}
    out = {}
    for n in names:
        for t, h in tables.items():
            if n == t or is_row_slot_of(n, t):
                out[n] = h
                break
    return out


def row_delta(base, new):
    """(rows int64[K], values[K, ...]) of the dim-0 slices of ``new``
    that differ from ``base`` — BITWISE comparison (a NaN row that
    stayed bit-identical is not re-written; a row that moved by one ULP
    is), so base + delta replay is bit-identical by construction."""
    if base.shape != new.shape or base.dtype != new.dtype:
        raise ValueError("row_delta needs same-shape/dtype arrays, got "
                         "%s/%s vs %s/%s" % (base.shape, base.dtype,
                                             new.shape, new.dtype))
    a = np.ascontiguousarray(new).view(np.uint8).reshape(new.shape[0], -1)
    b = np.ascontiguousarray(base).view(np.uint8).reshape(base.shape[0], -1)
    rows = np.nonzero((a != b).any(axis=1))[0].astype(np.int64)
    return rows, np.ascontiguousarray(new[rows])


def _apply_delta_ops(target, ops):
    """Apply one var's delta ops onto a (private, mutable) array."""
    for op in ops:
        kind, sel, data = op[0], op[1], op[2]
        if kind == "rows":
            target[np.asarray(sel, dtype=np.int64)] = data.reshape(
                (len(sel),) + target.shape[1:])
        elif kind == "range":
            view = target[tuple(slice(int(a), int(b)) for a, b in sel)]
            view[...] = data.reshape(view.shape)
        else:
            raise CheckpointCorruptError("unknown delta op kind %r" % kind)
    return target


_DELTA_ROWS_SUFFIX = "@DELTA_ROWS"
_DELTA_VALUES_SUFFIX = "@DELTA_VALUES"


def _gather_host(v):
    """One state value as a FULL host numpy array, copied out of any
    device buffer.  Fully-addressable jax Arrays (single-host meshes —
    sharded or not) gather through ``np.array``; multi-host global
    arrays all-gather across processes first (every process then writes
    an identical, complete artifact — restorable anywhere)."""
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(v, tiled=True))
    return np.array(v, copy=True)


class TrainState:
    """One atomic snapshot of a training run at a step boundary:
    ``arrays`` (host numpy: params, optimizer slots, LR, in-graph step
    counters) + ``host`` (JSON-able: step index, executor PRNG counters,
    reader positions, caller extras).

    A SHARDED capture (``capture_train_state(..., sharded=True)``)
    carries ``shards`` instead of ``arrays``: the entries this process
    owns (``[{"name", "index", "data"}]`` with global index ranges) plus
    ``array_meta`` — the global shape/dtype of EVERY var, which is what
    the elected saver writes into the manifest.  Loaded artifacts always
    come back with full ``arrays`` (the loader assembles shards), so
    everything downstream — ``apply_train_state``, the guardian's
    poisoned-checkpoint scan — sees one representation.

    An INCREMENTAL delta artifact (Check-N-Run style, written by
    ``TrainStateCheckpointManager(incremental=...)``) additionally
    carries ``delta``: ``{name: [("rows", int64[K], values[K, ...]) |
    ("range", [[a, b], ...], values)]}`` — only the rows that changed
    since the previous artifact.  A delta TrainState read straight off
    disk is NOT self-contained; the manager's ``load(step)`` replays
    base+deltas and returns full arrays."""

    def __init__(self, step, arrays, host, shards=None, array_meta=None,
                 delta=None):
        self.step = int(step)
        self.arrays = arrays
        self.host = host
        self.shards = shards
        self.array_meta = array_meta
        self.delta = delta

    def __repr__(self):
        if self.arrays is None:
            return ("TrainState(step=%d, shards=%d of %d vars, "
                    "executors=%s)"
                    % (self.step, len(self.shards or ()),
                       len(self.array_meta or ()),
                       sorted(self.host.get("executors", {}))))
        return "TrainState(step=%d, arrays=%d, executors=%s, readers=%s)" % (
            self.step, len(self.arrays),
            sorted(self.host.get("executors", {})),
            sorted(self.host.get("readers", {})))


def _shard_index(shape, index):
    """Normalize a jax ``Shard.index`` (tuple of slices) to JSON-able
    ``[[start, stop], ...]`` over the global ``shape``."""
    out = []
    for dim, sl in zip(shape, index):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _owned_shards(name, v):
    """The shard entries THIS process owns for one state value: the
    addressable replica-0 shards of a jax Array (each unique piece of
    the global array is written by exactly one process, no all-gather),
    or — for host numpy / non-jax values, which every process holds
    identically — one full-array entry owned by process 0."""
    if isinstance(v, jax.Array):
        shape = tuple(v.shape)
        out = []
        for s in v.addressable_shards:
            if s.replica_id != 0:
                continue           # a replica: some other shard owns it
            out.append({"name": name,
                        "index": _shard_index(shape, s.index),
                        "data": np.array(s.data, copy=True)})
        return out
    if jax.process_index() != 0:
        return []
    arr = np.array(v, copy=True)
    return [{"name": name,
             "index": [[0, d] for d in arr.shape],
             "data": arr}]


def _array_meta(state):
    """Global ``{name: {"shape", "dtype"}}`` of every state value —
    identical on every process (shapes/dtypes are program facts), so the
    elected saver's copy is THE manifest schema."""
    meta = {}
    for n, v in state.items():
        dtype = v.dtype if hasattr(v, "dtype") else np.asarray(v).dtype
        meta[n] = {"shape": [int(d) for d in
                             getattr(v, "shape", np.shape(v))],
                   "dtype": np.dtype(dtype).name}
    return meta


def _dtype_from_name(name):
    """Inverse of ``np.dtype(...).name``, covering the ml_dtypes names
    (bfloat16, float8_*) the npy format cannot describe."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def capture_train_state(step, scope=None, program=None, executors=None,
                        readers=None, extra=None, sharded=False):
    """Snapshot the train state at a step boundary.

    Blocks only for the device->host copy of the persistable vars (the
    cheap part); serialization happens in whoever writes the snapshot —
    under the next interval's compute on the async save path.
    ``executors``/``readers`` are objects exposing ``state_dict()``
    (Executor/ParallelExecutor PRNG run counters, reader positions);
    pass the same names to the restoring side so state re-applies to
    the matching object.

    ``sharded=False`` (the single-host full-artifact path): every value
    gathers to a FULL host array — on a multi-host mesh that is a
    process allgather, every host then writing an identical complete
    artifact.  ``sharded=True`` (the per-host path): this process copies
    out only the shards it OWNS (addressable replica-0 shards — no
    gather, no cross-host traffic), so per-host checkpoint bytes scale
    as 1/N of the state and stay flat as the mesh grows; write with
    ``save_train_state_sharded`` / the manager's sharded mode."""
    with RecordEvent("checkpoint/snapshot"):
        scope = scope or global_scope()
        state = _persistable_state(scope, program)
        _require_state(state, "snapshot")
        host = {
            "format": TRAIN_STATE_FORMAT,
            "step": int(step),
            "time": time.time(),
            "executors": {n: dict(e.state_dict())
                          for n, e in _named(executors, "executor").items()},
            "readers": {n: dict(r.state_dict())
                        for n, r in _named(readers, "reader").items()},
            "extra": dict(extra or {}),
        }
        if sharded:
            shards = []
            for n in sorted(state):
                shards.extend(_owned_shards(n, state[n]))
            return TrainState(step, None, host, shards=shards,
                              array_meta=_array_meta(state))
        # _gather_host: np.array(copy=True), NOT np.asarray — on the CPU
        # backend np.asarray(jax.Array) is a ZERO-COPY view of the
        # device buffer, and the next dispatched step DONATES that
        # buffer — XLA reuses the memory while the background writer
        # serializes it, tearing the snapshot (found by the kill-at-step
        # drill: warm-cache runs dispatch fast enough to hit the
        # window).  Mesh-sharded state (fsdp/tp params under
        # sharding_rules) gathers to the FULL logical array, so the
        # artifact is topology-free: restore re-shards onto whatever
        # mesh (or single device) the resuming process runs.
        arrays = {n: _gather_host(v) for n, v in state.items()}
    return TrainState(step, arrays, host)


def apply_train_state(ts, scope=None, program=None, executors=None,
                      readers=None, shardings=None, strict=True):
    """Apply a restored ``TrainState``: arrays into the scope (optionally
    ``device_put`` onto ``shardings``), PRNG counters into the executors,
    positions into the readers.  ``strict`` requires every persistable
    var of the current program to be present in the artifact (exact
    resume must not silently half-restore a model)."""
    scope = scope or global_scope()
    current = _persistable_state(scope, program)
    _require_state(current, "restore into")
    missing = sorted(set(current) - set(ts.arrays))
    if missing and strict:
        raise CheckpointMismatchError(
            "checkpoint (step %d) lacks persistable vars %s of the "
            "current program — not the same model (strict=False to "
            "restore the intersection)" % (ts.step, missing))
    if strict:
        # names matching is not enough: a smaller model whose var names
        # are a SUBSET of the saved one must still be rejected, so
        # shapes/dtypes are part of the fit check
        for name in current:
            if name not in ts.arrays:
                continue
            want, got = ts.arrays[name], current[name]
            if tuple(np.shape(got)) != tuple(want.shape):
                raise CheckpointMismatchError(
                    "checkpoint (step %d) var %r has shape %s but the "
                    "current model declares %s — not the same model"
                    % (ts.step, name, tuple(want.shape),
                       tuple(np.shape(got))))
    # validate the executor-name mapping BEFORE touching the scope: a
    # rejected checkpoint must not leave its params half-applied
    named_ex = _named(executors, "executor")
    if strict and ts.host.get("executors"):
        for name in named_ex:
            if name not in ts.host["executors"]:
                raise CheckpointMismatchError(
                    "checkpoint has no executor state named %r "
                    "(saved: %s)" % (name, sorted(ts.host["executors"])))
    for name in current:
        if name not in ts.arrays:
            continue
        val = ts.arrays[name]
        sh = (shardings or {}).get(name)
        scope.set_var(name, jax.device_put(val, sh) if sh is not None
                      else val)
    for name, ex in named_ex.items():
        st = ts.host.get("executors", {}).get(name)
        if st is not None:
            ex.load_state_dict(st)
    for name, r in _named(readers, "reader").items():
        st = ts.host.get("readers", {}).get(name)
        if st is not None:
            r.load_state_dict(st)
    return ts.step


def _sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


# one shared commit-idiom helper: cloud.store is the dependency-light
# canonical home (importing this jax-heavy module from cloud would
# invert the layering)
from ..cloud.store import fsync_dir as _fsync_dir  # noqa: E402


def save_train_state(dirname, ts):
    """Write ``ts`` as one atomic artifact: arrays.npz + train_state.json
    + a sha256 MANIFEST, assembled in a ``.tmp`` sibling and committed
    with a single directory rename.  A crash at ANY point leaves either
    the previous artifact set intact or a .tmp dir restores ignore."""
    if ts.arrays is None:
        raise ValueError(
            "this TrainState was captured sharded (shards, not full "
            "arrays): write it with save_train_state_sharded")
    dirname = os.path.abspath(dirname)
    parent = os.path.dirname(dirname)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, _TMP_PREFIX + "%s.%d"
                       % (os.path.basename(dirname), os.getpid()))
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    try:
        fault.fire("checkpoint/before_write", ts.step)
        to_encode = dict(ts.arrays)
        for n, ops in (ts.delta or {}).items():
            # one rows-op per var on the write side (the manager's diff);
            # range-ops only arise when assembling sharded artifacts
            (kind, rows, values), = ops
            if kind != "rows":
                raise ValueError(
                    "save_train_state only encodes single rows-op "
                    "deltas; var %r carries a %r op (a TrainState "
                    "assembled from a sharded artifact must be re-saved "
                    "through the manager, not directly)" % (n, kind))
            to_encode[n + _DELTA_ROWS_SUFFIX] = np.asarray(rows, np.int64)
            to_encode[n + _DELTA_VALUES_SUFFIX] = values
        encoded, raw_dtypes = {}, {}
        for n, a in to_encode.items():
            encoded[n], logical = _npz_encode(a)
            if logical:
                raw_dtypes[n] = logical
        host = dict(ts.host)
        host["raw_dtypes"] = raw_dtypes
        # npz member names can't carry '/' etc. reliably across numpy
        # versions -> positional members + an ordered name list
        names = sorted(encoded)
        arrays_path = os.path.join(tmp, _ARRAYS_FILE)
        with open(arrays_path, "wb") as f:
            np.savez(f, **{"arr_%d" % i: encoded[n]
                           for i, n in enumerate(names)})
            f.flush()
            os.fsync(f.fileno())
        host["array_names"] = names
        host_path = os.path.join(tmp, _HOST_FILE)
        with open(host_path, "w") as f:
            json.dump(host, f)
            f.flush()
            os.fsync(f.fileno())
        fault.fire("checkpoint/after_write", ts.step)
        manifest = {
            "format": TRAIN_STATE_FORMAT,
            "step": ts.step,
            "files": {
                _ARRAYS_FILE: {"sha256": _sha256(arrays_path),
                               "bytes": os.path.getsize(arrays_path)},
                _HOST_FILE: {"sha256": _sha256(host_path),
                             "bytes": os.path.getsize(host_path)},
            },
        }
        if ts.host.get("incremental"):
            # chain pointers in the manifest too: rotation walks chains
            # without opening (and re-hashing) the arrays payloads
            manifest["incremental"] = {
                k: ts.host["incremental"][k]
                for k in ("base_step", "prev_step")}
        with open(os.path.join(tmp, _MANIFEST_FILE), "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        fault.fire("checkpoint/before_commit", ts.step)
        _commit_artifact_dir(dirname, tmp)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return dirname


def _commit_artifact_dir(dirname, tmp):
    """The commit point: everything before it is invisible to restores.
    Re-saving an existing step renames the old artifact aside first (as
    a .tmp sibling, reclaimed by the next manager init) —
    rmtree-then-replace would hold a destroyed-artifact window open for
    the whole delete; the rename pair shrinks it to two directory
    entries."""
    parent = os.path.dirname(dirname)
    if os.path.isdir(dirname):
        old = tmp + ".replaced"
        shutil.rmtree(old, ignore_errors=True)
        os.replace(dirname, old)
        os.replace(tmp, dirname)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.replace(tmp, dirname)
    _fsync_dir(parent or ".")


def load_train_state(dirname):
    """Read + VALIDATE one TrainState artifact; raises
    ``CheckpointCorruptError`` on a missing/partial/garbled artifact
    (manifest absent, checksum mismatch, undecodable payload)."""
    dirname = os.path.abspath(dirname)
    mpath = os.path.join(dirname, _MANIFEST_FILE)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            "checkpoint %s: unreadable manifest (%s) — likely a partial "
            "write" % (dirname, e))
    try:
        for fname, meta in manifest["files"].items():
            fpath = os.path.join(dirname, fname)
            if not os.path.exists(fpath):
                raise CheckpointCorruptError(
                    "checkpoint %s: missing %s" % (dirname, fname))
            if _sha256(fpath) != meta["sha256"]:
                raise CheckpointCorruptError(
                    "checkpoint %s: %s fails its sha256 — corrupt"
                    % (dirname, fname))
        if manifest.get("sharded"):
            # per-host artifact: assemble the shard files back into
            # full host arrays (same downstream representation)
            return _load_sharded_train_state(dirname, manifest)
        with open(os.path.join(dirname, _HOST_FILE)) as f:
            host = json.load(f)
        raw_dtypes = host.pop("raw_dtypes", {})
        names = host.pop("array_names")
        with np.load(os.path.join(dirname, _ARRAYS_FILE)) as z:
            arrays = {n: _npz_decode(z["arr_%d" % i], raw_dtypes.get(n))
                      for i, n in enumerate(names)}
        delta = None
        if host.get("incremental"):
            delta = {}
            for n in host["incremental"].get("delta_vars", []):
                rows = arrays.pop(n + _DELTA_ROWS_SUFFIX)
                values = arrays.pop(n + _DELTA_VALUES_SUFFIX)
                delta[n] = [("rows", rows, values)]
        return TrainState(manifest["step"], arrays, host, delta=delta)
    except CheckpointCorruptError:
        raise
    except Exception as e:  # noqa: BLE001 — any decode failure = corrupt
        raise CheckpointCorruptError(
            "checkpoint %s: undecodable (%r)" % (dirname, e))


# ---------------------------------------------------------------------------
# Per-host sharded artifact IO (ISSUE 13): each host writes ONLY its
# addressable shards; the elected saver commits a global manifest.
# orbax-OCDBT-style layout (PAPERS.md):
#
#   step_0000000012/
#     shard_00000.npz    writer 0's shards, positional members
#     shard_00000.json   writer 0's index: per-entry (name, global range)
#     shard_00001.npz    writer 1's shards ...
#     train_state.json   host state + global array meta (saver-written)
#     MANIFEST.json      sharded: true, per-file sha256 + bytes,
#                        per-writer bytes, committed LAST by the saver
#
# Per-host bytes written therefore scale as 1/N of the full state; a
# restore (any process, any mesh size — even a single host) reads the
# shard files, assembles full host arrays, and re-shards through
# apply_train_state(shardings=pe.state_shardings()).
# ---------------------------------------------------------------------------

_SHARD_FILE = "shard_%05d.npz"
_SHARD_META = "shard_%05d.json"
_SHARED_TMP_SUFFIX = ".shared"


def partition_shards(ts, writers):
    """Split a sharded TrainState's LOCAL entries across ``writers``
    virtual hosts (the single-process bench/test path: one process
    standing in for N hosts).  Entries whose leading dim splits evenly
    enough are sliced along dim 0 — exact ~1/N bytes for the tensors
    that dominate state — the rest round-robin whole.  Returns a list
    of ``writers`` entry lists.  Real multi-host runs never call this:
    ownership already is the partition."""
    writers = max(1, int(writers))
    out = [[] for _ in range(writers)]
    rr = 0
    for e in ts.shards:
        data = e["data"]
        if data.ndim >= 1 and data.shape[0] >= writers:
            start = e["index"][0][0]
            off = 0
            for w, piece in enumerate(np.array_split(data, writers)):
                idx = [list(r) for r in e["index"]]
                idx[0] = [start + off, start + off + piece.shape[0]]
                off += piece.shape[0]
                out[w].append({"name": e["name"], "index": idx,
                               "data": piece})
        else:
            out[rr % writers].append(e)
            rr += 1
    return out


def _sharded_tmp(dirname):
    """The SHARED tmp dir every writer of one artifact assembles into
    (deterministic name — unlike the full path's pid-suffixed tmp, all
    hosts must agree on it)."""
    dirname = os.path.abspath(dirname)
    return os.path.join(os.path.dirname(dirname),
                        _TMP_PREFIX + os.path.basename(dirname)
                        + _SHARED_TMP_SUFFIX)


def write_train_state_shards(dirname, ts, writer_id, entries=None):
    """Write ONE writer's shard file + index sidecar into the artifact's
    shared tmp dir.  ``entries`` defaults to the TrainState's own owned
    shards (pass a ``partition_shards`` slice in virtual-host mode).
    The sidecar lands via atomic rename LAST — it is the signal the
    committing saver polls for.  Returns the bytes written."""
    if ts.shards is None:
        raise ValueError("TrainState was not captured sharded "
                         "(capture_train_state(..., sharded=True))")
    entries = ts.shards if entries is None else entries
    writer_id = int(writer_id)
    tmp = _sharded_tmp(dirname)
    os.makedirs(tmp, exist_ok=True)
    fault.fire("checkpoint/before_write", ts.step)
    npz_path = os.path.join(tmp, _SHARD_FILE % writer_id)
    members = {}
    for i, e in enumerate(entries):
        members["arr_%d" % i] = _npz_encode(e["data"])[0]
        if e.get("rows") is not None:
            # incremental entry: only this writer's CHANGED local rows
            # ("rows" are GLOBAL row indices; "data" their values)
            members["rows_%d" % i] = np.asarray(e["rows"], np.int64)
    with open(npz_path, "wb") as f:
        np.savez(f, **members)
        f.flush()
        os.fsync(f.fileno())
    sidecar = {
        "writer": writer_id,
        "step": ts.step,
        "entries": [{"name": e["name"], "index": e["index"],
                     **({"delta": True} if e.get("rows") is not None
                        else {})}
                    for e in entries],
        "bytes": os.path.getsize(npz_path),
        "sha256": _sha256(npz_path),
    }
    side_path = os.path.join(tmp, _SHARD_META % writer_id)
    with open(side_path + ".part", "w") as f:
        json.dump(sidecar, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(side_path + ".part", side_path)
    _fsync_dir(tmp)
    fault.fire("checkpoint/after_write", ts.step)
    return sidecar["bytes"]


def commit_sharded_train_state(dirname, ts, expected_writers,
                               timeout=120.0, poll=0.05):
    """The ELECTED SAVER's half: wait until every expected writer's
    sidecar landed in the shared tmp dir, then write train_state.json +
    the global MANIFEST and commit the directory rename.  Raises
    ``CheckpointCorruptError`` when the writers don't all arrive within
    ``timeout`` (the tmp dir is left for the next manager init to
    reclaim — restores never see it)."""
    dirname = os.path.abspath(dirname)
    tmp = _sharded_tmp(dirname)
    expected = list(range(int(expected_writers)))
    deadline = time.monotonic() + float(timeout)
    missing = expected
    while True:
        missing = [w for w in expected
                   if not os.path.exists(os.path.join(tmp,
                                                      _SHARD_META % w))]
        if not missing:
            break
        if time.monotonic() > deadline:
            raise CheckpointCorruptError(
                "sharded checkpoint step %d: writers %s never delivered "
                "their shards within %.0fs — commit abandoned"
                % (ts.step, missing, timeout))
        time.sleep(poll)
    try:
        host = dict(ts.host)
        meta = {}
        for n, m in (ts.array_meta or {}).items():
            entry = {"shape": list(m["shape"]), "dtype": m["dtype"]}
            enc, logical = _npz_encode(
                np.empty(0, dtype=_dtype_from_name(m["dtype"])))
            if logical:
                entry["raw_dtype"] = enc.dtype.name
            meta[n] = entry
        host["array_meta"] = meta
        host_path = os.path.join(tmp, _HOST_FILE)
        with open(host_path, "w") as f:
            json.dump(host, f)
            f.flush()
            os.fsync(f.fileno())
        files = {_HOST_FILE: {"sha256": _sha256(host_path),
                              "bytes": os.path.getsize(host_path)}}
        per_writer = {}
        for w in expected:
            # each writer already hashed its own (fsynced) shard npz
            # into the sidecar — re-hashing all N files here would make
            # the commit O(total state) read IO on the saver, undoing
            # half the per-host 1/N win; the saver hashes only the
            # sidecars (tiny), chaining trust: manifest -> sidecar ->
            # shard payload
            side_path = os.path.join(tmp, _SHARD_META % w)
            with open(side_path) as f:
                side = json.load(f)
            if not ts.host.get("incremental") and \
                    any(e.get("delta") for e in side["entries"]):
                # incremental cadence desync: a peer wrote touched-row
                # deltas while this (e.g. freshly restarted) saver
                # decided on a full artifact — committing would land a
                # mixed artifact no loader can interpret AND hand later
                # deltas a broken chain base.  Refuse loudly; the failed
                # save costs one interval, the existing chain stays
                # intact.  (A peer shipping FULL entries under a delta
                # manifest is fine — the loader folds those as range
                # ops.)
                raise CheckpointCorruptError(
                    "sharded checkpoint step %d: writer %d delivered "
                    "delta entries but the committing saver encoded a "
                    "full artifact — incremental cadence desynchronized "
                    "across hosts; commit refused" % (ts.step, w))
            files[_SHARD_FILE % w] = {"sha256": side["sha256"],
                                      "bytes": side["bytes"]}
            files[_SHARD_META % w] = {
                "sha256": _sha256(side_path),
                "bytes": os.path.getsize(side_path)}
            per_writer[str(w)] = side["bytes"]
        manifest = {
            "format": TRAIN_STATE_FORMAT,
            "sharded": True,
            "step": ts.step,
            "writers": len(expected),
            "per_writer_bytes": per_writer,
            "files": files,
        }
        if ts.host.get("incremental"):
            manifest["incremental"] = {
                k: ts.host["incremental"][k]
                for k in ("base_step", "prev_step")}
        with open(os.path.join(tmp, _MANIFEST_FILE), "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        fault.fire("checkpoint/before_commit", ts.step)
        _commit_artifact_dir(dirname, tmp)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return dirname


def save_train_state_sharded(dirname, ts, writer_id=0, writers=1,
                             saver=True, commit_timeout=120.0):
    """One host's leg of a sharded TrainState save: write this writer's
    shards, and — when this host is the elected ``saver`` — wait for the
    peers and commit the manifest.  Returns the committed dirname
    (saver) or the bytes this writer contributed (non-saver)."""
    written = write_train_state_shards(dirname, ts, writer_id)
    if not saver:
        return written
    return commit_sharded_train_state(dirname, ts, writers,
                                      timeout=commit_timeout)


def _load_sharded_train_state(dirname, manifest):
    """Assemble a sharded artifact back into full host arrays (manifest
    and per-file sha256 already partially validated by the caller):
    every var gets an empty global buffer filled from the shard entries;
    incomplete coverage is corruption, not a silent zero-filled restore.

    INCREMENTAL sharded artifacts carry delta entries (per-writer
    changed rows): those vars come back as delta OPS, not arrays — a
    full shard entry of a delta var (a writer that lost its base)
    becomes a range op, a delta entry a rows op.  The manager's chain
    replay applies them onto the base."""
    with open(os.path.join(dirname, _HOST_FILE)) as f:
        host = json.load(f)
    meta = host.pop("array_meta")
    incremental = bool(host.get("incremental"))
    # pass 1: read every writer's entries (decoded to logical dtypes)
    entries = {n: [] for n in meta}   # name -> [(index, rows|None, data)]
    for w in range(int(manifest["writers"])):
        with open(os.path.join(dirname, _SHARD_META % w)) as f:
            sidecar = json.load(f)
        with np.load(os.path.join(dirname, _SHARD_FILE % w)) as z:
            for i, e in enumerate(sidecar["entries"]):
                n = e["name"]
                m = meta[n]
                data = _npz_decode(
                    z["arr_%d" % i],
                    m["dtype"] if m.get("raw_dtype") else None)
                rows = z["rows_%d" % i] if e.get("delta") else None
                entries[n].append((e["index"], rows, data))
    # pass 2: vars fully covered by full entries assemble to arrays; in
    # an incremental artifact everything else becomes delta ops (full
    # pieces from writers that lost their base ride along as range ops,
    # applied before the rows ops)
    buffers, delta = {}, {}
    for n, m in meta.items():
        total = int(np.prod(m["shape"], dtype=np.int64))
        full = [(idx, data) for idx, rows, data in entries[n]
                if rows is None]
        covered = sum(int(data.size) for _, data in full)
        if covered == total and len(full) == len(entries[n]):
            buf = np.empty(tuple(m["shape"]),
                           dtype=_dtype_from_name(m["dtype"]))
            for idx, data in full:
                sel = tuple(slice(a, b) for a, b in idx)
                buf[sel] = data.reshape(buf[sel].shape)
            buffers[n] = buf
            continue
        if not incremental:
            raise CheckpointCorruptError(
                "sharded checkpoint %s: var %r covered %d of %d "
                "elements — shard set incomplete"
                % (dirname, n, covered, total))
        # delta entries carry changed rows, not their whole shard, so
        # coverage is checked over the declared INDEX extents: every
        # writer still owes an entry (full or delta) for its slice — a
        # missing writer entry is corruption here exactly as it is on
        # the full path, never a silent partial restore
        idx_cov = sum(
            int(np.prod([b - a for a, b in idx], dtype=np.int64))
            for idx, _rows, _data in entries[n])
        if idx_cov != total:
            raise CheckpointCorruptError(
                "sharded checkpoint %s: var %r shard index coverage %d "
                "of %d elements — shard set incomplete"
                % (dirname, n, idx_cov, total))
        ops = [("range", idx, data) for idx, data in full]
        ops += [("rows", rows, data)
                for idx, rows, data in entries[n] if rows is not None]
        delta[n] = ops
    return TrainState(manifest["step"], buffers, host,
                      delta=delta or None)


class TrainStateCheckpointManager:
    """Step-indexed TrainState checkpoints with async writes overlapped
    under compute and corruption-safe fallback restore.

    Save protocol (the CheckFreq split): ``save(step)`` snapshots the
    state synchronously at the step boundary (a device->host copy), then
    hands the WRITE to a background thread — the serialization +
    fsync + atomic commit runs under the next interval's compute and
    shows up as a ``checkpoint/save`` monitor span, not step time.  A
    still-inflight write is drained before the next snapshot (and by
    ``save_now``/``wait_until_finished``/``close``); a failed background
    write re-raises at the next call into the manager rather than
    dying silently.

    Restore protocol: newest artifact first; an artifact failing
    manifest/sha256 validation is logged and SKIPPED, falling back to
    the previous one — a torn or corrupt latest checkpoint costs one
    interval of work, never the job.

    Sharded mode (``sharded=True``, or the default ``None`` = auto on
    multi-process runs): saves go through the per-host sharded artifact
    path — this process captures and writes ONLY its addressable shards
    (1/N of the state), and the host elected by ``saver_elect(step)``
    (default: process 0; wire ``ClusterMember.request_save`` for
    master-arbitrated election) waits for the peers' shard files and
    commits the manifest.  ``writer_id``/``writers`` default to the jax
    process identity.  Restores are format-agnostic: the loader
    assembles shard files back into full host arrays, so a sharded
    artifact restores on any topology — including a single host —
    through the same ``apply_train_state`` path.

    Incremental mode (``incremental=``, Check-N-Run style): the state
    vars named (or, with ``'auto'``, every ``is_sparse`` lookup table +
    its row-wise optimizer slots) are written as per-interval
    TOUCHED-ROW DELTAS against a periodic full base — artifact bytes
    scale with rows touched since the last save, not with vocab.  The
    diff is BITWISE against the previous artifact's values (kept as a
    host-side base copy — budget one extra host copy of the tables), so
    base + delta replay is bit-identical by construction; the lazy
    SelectedRows optimizer update is what keeps untouched rows
    bit-stable and the deltas small.  Every ``incremental_full_every``-th
    artifact is a full base (bounds the replay chain); ``load``/
    ``restore`` replay the chain transparently and rotation never
    deletes an artifact a kept delta still needs.  In sharded mode each
    host diffs and writes only its own shards' touched rows."""

    def __init__(self, dirname, max_to_keep=3, save_interval_steps=1,
                 async_save=True, sharded=None, saver_elect=None,
                 writer_id=None, writers=None, commit_timeout=120.0,
                 incremental=None, incremental_full_every=8):
        self._dir = os.path.abspath(dirname)
        os.makedirs(self._dir, exist_ok=True)
        self._max_to_keep = max(1, int(max_to_keep)) \
            if max_to_keep is not None else None
        self._interval = max(1, int(save_interval_steps))
        self._async = bool(async_save)
        self._sharded = sharded
        self._saver_elect = saver_elect
        self._writer_id = writer_id
        self._writers = writers
        self._commit_timeout = float(commit_timeout)
        self._last_saved = None
        self._inflight = None            # (thread, step)
        self._error = None
        # incremental (delta) mode: None/False off; True/'auto' =
        # sparse-table autodetect from the save-time program; or an
        # explicit iterable of var names
        self._incremental = incremental
        self._full_every = max(1, int(incremental_full_every))
        self._incr_base = {}         # full path: {name: host array}
        self._incr_shard_base = {}   # sharded: {(name, index key): array}
        self._incr_full_base = {}    # restore-seeded full arrays (sliced
        #                              lazily into shard bases)
        self._incr_base_step = None  # step of the generation's full base
        self._incr_prev_step = None  # step of the last written artifact
        self._deltas_since_full = 0
        # rolling measured costs (autotune.tune_checkpoint_interval's
        # evidence): the synchronous device->host snapshot span and the
        # background write span, most recent samples
        self._snapshot_s = collections.deque(maxlen=16)
        self._save_s = collections.deque(maxlen=16)
        self._mu = threading.Lock()
        self.last_restored = None        # TrainState of the last restore
        # a dead process's .tmp dirs (kill mid-save) are garbage — but
        # a SHARED sharded tmp may be a live peer's in-flight write (a
        # rejoining host constructs its manager while survivors are
        # mid-save), so those are reclaimed only once older than the
        # commit timeout: nothing waits longer than that for a commit,
        # so an older one is provably abandoned
        now = time.time()
        for entry in os.listdir(self._dir):
            if not entry.startswith(_TMP_PREFIX):
                continue
            path = os.path.join(self._dir, entry)
            if entry.endswith(_SHARED_TMP_SUFFIX):
                try:
                    age = now - os.path.getmtime(path)
                except OSError:
                    continue
                if age <= self._commit_timeout:
                    continue
            shutil.rmtree(path, ignore_errors=True)

    # -- paths / listing ----------------------------------------------
    def _step_dir(self, step):
        return os.path.join(self._dir, "%s%010d" % (_STEP_PREFIX, step))

    def all_steps(self):
        """Committed step indices, sorted ascending (no validation)."""
        out = []
        for entry in os.listdir(self._dir):
            if entry.startswith(_STEP_PREFIX):
                try:
                    out.append(int(entry[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def should_save(self, step):
        last = self._last_saved
        if last is None:
            last = self.latest_step()
        return last is None or step >= last + self._interval

    @property
    def save_interval_steps(self):
        return self._interval

    def set_interval(self, save_interval_steps):
        """Re-gate the save cadence (the auto-tuner's checkpoint-
        interval decision lands here; a mid-run retune is safe — the
        gate compares against the last SAVED step either way)."""
        self._interval = max(1, int(save_interval_steps))

    def measured_costs(self):
        """Mean measured costs of this manager's own saves — the
        snapshot (synchronous device->host copy, the only on-step cost
        of an async save) and the write (serialize+fsync+commit) —
        plus the sample count.  The evidence
        ``autotune.tune_checkpoint_interval`` consumes; empty dict
        before the first save."""
        # deque snapshots are atomic enough (GIL) for a mean; the
        # writer thread appends, this reads
        snaps, saves = list(self._snapshot_s), list(self._save_s)
        out = {}
        if snaps:
            out["snapshot_s"] = sum(snaps) / len(snaps)
        if saves:
            out["save_s"] = sum(saves) / len(saves)
        if out:
            out["n"] = max(len(snaps), len(saves))
        return out

    # -- sharded-mode identity -----------------------------------------
    def sharded_mode(self):
        """Whether saves go through the per-host sharded path: the
        explicit ``sharded=`` setting, else auto — sharded iff this is
        a multi-process run (the case the all-gather used to pay for)."""
        if self._sharded is not None:
            return bool(self._sharded)
        return jax.process_count() > 1

    def _writer_identity(self):
        wid = self._writer_id if self._writer_id is not None \
            else jax.process_index()
        n = self._writers if self._writers is not None \
            else jax.process_count()
        return int(wid), max(1, int(n))

    def _is_saver(self, step):
        """Exactly-one-committer election for sharded artifacts: the
        ``saver_elect`` hook (``ClusterMember.request_save`` under a
        cluster master), else writer 0."""
        if self._saver_elect is not None:
            return bool(self._saver_elect(step))
        return self._writer_identity()[0] == 0

    # -- incremental (delta) encoding ----------------------------------
    def _resolve_incr_names(self, program, ts):
        """{var name: table height or None} of the vars THIS artifact
        may delta-encode; resolved on the save path (needs the program
        for 'auto')."""
        if not self._incremental:
            return None
        if ts.arrays is not None:
            names = set(ts.arrays)
        else:
            names = set(ts.array_meta or ())
        if self._incremental in (True, "auto"):
            from ..framework import default_main_program

            program = program if program is not None \
                else default_main_program()
            return sparse_table_state_vars(program, names)
        return {n: None for n in self._incremental if n in names}

    def _delta_eligible(self, arr, height):
        if getattr(arr, "ndim", 0) < 1 or arr.size == 0:
            return False
        return height is None or arr.shape[0] == int(height)

    def _encode_incremental(self, ts):
        """Rewrite ``ts`` in place into a delta artifact when a base is
        available and the generation isn't due for a full one.  Always
        refreshes the in-memory base to this artifact's values — the
        next diff is against the LAST WRITTEN state, so base + deltas
        replay bit-identically."""
        names = getattr(ts, "_incr_names", None)
        if not names:
            return
        if ts.shards is not None:
            return self._encode_incremental_shards(ts, names)
        eligible = {n: ts.arrays[n] for n, h in names.items()
                    if n in ts.arrays
                    and self._delta_eligible(ts.arrays[n], h)}
        want_full = (self._incr_prev_step is None
                     or self._deltas_since_full >= self._full_every - 1
                     or not eligible)
        if not want_full:
            delta, rows_count = {}, {}
            for n, a in eligible.items():
                base = self._incr_base.get(n)
                if base is None or base.shape != a.shape \
                        or base.dtype != a.dtype:
                    continue        # ships full in this artifact
                rows, values = row_delta(base, a)
                delta[n] = [("rows", rows, values)]
                rows_count[n] = int(rows.shape[0])
            if delta:
                for n in delta:
                    del ts.arrays[n]
                ts.delta = delta
                ts.host["incremental"] = {
                    "base_step": self._incr_base_step,
                    "prev_step": self._incr_prev_step,
                    "delta_vars": sorted(delta),
                    "delta_rows": rows_count,
                }
                self._deltas_since_full += 1
            else:
                want_full = True
        if want_full:
            self._incr_base_step = ts.step
            self._deltas_since_full = 0
        self._incr_base = dict(eligible)     # capture's private copies
        self._incr_prev_step = ts.step

    def _encode_incremental_shards(self, ts, names):
        """The per-host leg: each writer diffs ONLY its own shard
        entries against its shard base and writes only local touched
        rows.  An entry without a base (fresh host, resized shard)
        ships full — the loader folds mixed full/delta entries."""
        want_full = (self._incr_prev_step is None
                     or self._deltas_since_full >= self._full_every - 1)
        new_entries, delta_vars, rows_count = [], set(), {}
        new_base = {}
        for e in ts.shards:
            n, a = e["name"], e["data"]
            # shard shapes are local slices, so the height gate does not
            # apply here — membership + non-scalar is the eligibility
            track = n in names and getattr(a, "ndim", 0) >= 1 \
                and a.size > 0
            key = (n, tuple(tuple(int(x) for x in r)
                            for r in e["index"]))
            base = self._incr_shard_base.get(key)
            if base is None and n in self._incr_full_base:
                # restore-seeded full array: slice this shard's piece
                sel = tuple(slice(x, y) for x, y in e["index"])
                cand = self._incr_full_base[n][sel]
                if cand.shape == a.shape:
                    base = np.ascontiguousarray(cand)
            if track:
                new_base[key] = a
            if want_full or not track or base is None \
                    or base.shape != a.shape or base.dtype != a.dtype:
                new_entries.append(e)
                continue
            start = int(e["index"][0][0])
            rows, values = row_delta(base, a)
            new_entries.append({"name": n, "index": e["index"],
                                "rows": rows + start, "data": values})
            delta_vars.add(n)
            rows_count[n] = rows_count.get(n, 0) + int(rows.shape[0])
        self._incr_shard_base = new_base
        self._incr_full_base = {}
        ts.shards = new_entries
        if delta_vars:
            ts.host["incremental"] = {
                "base_step": self._incr_base_step,
                "prev_step": self._incr_prev_step,
                "delta_vars": sorted(delta_vars),
                "delta_rows": rows_count,
            }
            self._deltas_since_full += 1
        else:
            self._incr_base_step = ts.step
            self._deltas_since_full = 0
        self._incr_prev_step = ts.step

    def _chain_prev(self, step):
        """prev_step pointer of an artifact (manifest read only), or
        None for a full artifact / unreadable manifest."""
        try:
            with open(os.path.join(self._step_dir(step),
                                   _MANIFEST_FILE)) as f:
                m = json.load(f)
        except (OSError, ValueError):
            return None
        inc = m.get("incremental")
        return int(inc["prev_step"]) if inc else None

    def _seed_incremental_base(self, ts):
        """After a restore: the restored full arrays ARE the state at
        ``ts.step`` — seed the diff base for the chain's delta vars so
        the next save continues the delta chain instead of paying a
        full write."""
        if not self._incremental or ts.arrays is None:
            return
        inc = ts.host.get("incremental")
        if not inc:
            self._incr_base_step = None
            self._incr_prev_step = None
            self._deltas_since_full = 0
            self._incr_base, self._incr_shard_base = {}, {}
            self._incr_full_base = {}
            return
        dv = list(inc.get("delta_vars", []))
        seeded = {n: np.array(ts.arrays[n], copy=True)
                  for n in dv if n in ts.arrays}
        self._incr_base = seeded
        self._incr_shard_base = {}
        self._incr_full_base = dict(seeded)
        self._incr_base_step = int(inc["base_step"])
        self._incr_prev_step = int(ts.step)
        # conservative: restart the full cadence from here (the replay
        # chain stays bounded by rotation's chain tracking either way)
        self._deltas_since_full = 1

    # -- save ----------------------------------------------------------
    def save(self, step, scope=None, program=None, executors=None,
             readers=None, extra=None):
        """Interval-gated async save at ``step``.  Returns False when
        gated; True once the snapshot is taken and the write is running
        (or, sync mode, committed)."""
        self._reraise()
        if not self.should_save(step):
            return False
        self.wait_until_finished()       # drain the previous write
        t0 = time.perf_counter()
        ts = capture_train_state(step, scope=scope, program=program,
                                 executors=executors, readers=readers,
                                 extra=extra, sharded=self.sharded_mode())
        # resolved on the main thread (needs the program); the diff
        # itself runs in the writer thread, off the step path
        ts._incr_names = self._resolve_incr_names(program, ts)
        self._snapshot_s.append(time.perf_counter() - t0)
        self._last_saved = int(step)
        if not self._async:
            self._write(ts)
            return True
        t = threading.Thread(target=self._write_guarded, args=(ts,),
                             name="ckpt-write-%d" % step, daemon=True)
        with self._mu:
            self._inflight = (t, int(step))
        t.start()
        return True

    def save_now(self, step, scope=None, program=None, executors=None,
                 readers=None, extra=None):
        """Forced SYNCHRONOUS save ignoring the interval gate — the
        preemption/SIGTERM flush path.  Drains any in-flight async write
        first; returns only once the artifact is committed.  If this
        exact step already committed (the periodic save landed at the
        same boundary), the flush is a no-op: the state at one step
        boundary is one state, and re-writing it would only re-open the
        replace window during a shutdown deadline."""
        self._reraise()
        self.wait_until_finished()
        if self._last_saved == int(step) and \
                os.path.exists(os.path.join(self._step_dir(step),
                                            _MANIFEST_FILE)):
            return True
        t0 = time.perf_counter()
        ts = capture_train_state(step, scope=scope, program=program,
                                 executors=executors, readers=readers,
                                 extra=extra, sharded=self.sharded_mode())
        ts._incr_names = self._resolve_incr_names(program, ts)
        self._snapshot_s.append(time.perf_counter() - t0)
        self._last_saved = int(step)
        self._write(ts)
        return True

    def _write_guarded(self, ts):
        try:
            self._write(ts)
        except BaseException as e:  # noqa: BLE001 — surfaced on next call
            with self._mu:
                self._error = e

    def _write(self, ts):
        t0 = time.perf_counter()
        step_dir = self._step_dir(ts.step)
        # delta-encode BEFORE serializing: the diff runs in this (write)
        # thread, overlapped under the next interval's compute like the
        # rest of the serialization
        self._encode_incremental(ts)
        inc = ts.host.get("incremental")
        if ts.shards is not None:
            wid, writers = self._writer_identity()
            saver = self._is_saver(ts.step)
            nbytes = sum(e["data"].nbytes for e in ts.shards)
            with RecordEvent("checkpoint/save"):
                save_train_state_sharded(
                    step_dir, ts, writer_id=wid, writers=writers,
                    saver=saver, commit_timeout=self._commit_timeout)
            path = step_dir
            extra = {"sharded": True, "writer_id": wid,
                     "writers": writers, "saver": saver}
        else:
            nbytes = sum(a.nbytes for a in ts.arrays.values())
            nbytes += sum(rows.nbytes + values.nbytes
                          for ops in (ts.delta or {}).values()
                          for _, rows, values in ops)
            with RecordEvent("checkpoint/save"):
                path = save_train_state(step_dir, ts)
            saver = True
            extra = {}
        if inc:
            extra = dict(extra, incremental=True,
                         base_step=inc["base_step"],
                         delta_rows=inc.get("delta_rows"))
            monitor.count("checkpoint/incremental_saves")
            monitor.count("checkpoint/incremental_rows",
                          sum((inc.get("delta_rows") or {}).values()))
        self._save_s.append(time.perf_counter() - t0)
        if saver:
            # non-elected hosts never rotate: racing rmtrees against
            # the committer's rename would re-open the torn-artifact
            # window the commit protocol exists to close
            self._rotate()
        monitor.mark("checkpoint/saved")
        monitor.log_event(dict({
            "event": "checkpoint_saved", "ts": time.time(),
            "step": ts.step, "path": path,
            "seconds": round(time.perf_counter() - t0, 6),
            "bytes": nbytes,
            "async": self._async}, **extra))
        return path

    def _rotate(self):
        if self._max_to_keep is None:
            return
        steps = self.all_steps()
        keep = set(steps[-self._max_to_keep:])
        # a kept DELTA artifact is only restorable through its chain:
        # every artifact back to its full base is load-bearing
        need = set()
        for s in keep:
            cur, guard = s, 0
            while cur is not None and cur not in need and guard < 65536:
                need.add(cur)
                cur = self._chain_prev(cur)
                guard += 1
        for s in steps:
            if s not in keep and s not in need:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def _reraise(self):
        with self._mu:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                "previous async checkpoint write failed") from err

    def wait_until_finished(self):
        with self._mu:
            inflight = self._inflight
        if inflight is not None:
            inflight[0].join()
            with self._mu:
                if self._inflight is inflight:
                    self._inflight = None
        self._reraise()

    # -- restore -------------------------------------------------------
    def load(self, step):
        """Read + VALIDATE the artifact at ``step`` without applying it
        — pre-restore inspection (the guardian's poisoned-checkpoint
        scan rejects artifacts before they touch live state).  Raises
        ``CheckpointCorruptError`` on a corrupt/partial artifact.

        Incremental artifacts are replayed transparently: the chain
        walks back to the full base and applies each delta's touched
        rows in order, so the returned TrainState always carries FULL
        arrays — bit-identical to the uninterrupted state at ``step``
        (the diff was bitwise against exactly this replay's input)."""
        ts = load_train_state(self._step_dir(step))
        if not ts.host.get("incremental"):
            return ts
        chain, seen = [ts], {ts.step}
        cur = ts
        while cur.host.get("incremental"):
            prev = int(cur.host["incremental"]["prev_step"])
            if prev in seen:
                raise CheckpointCorruptError(
                    "incremental chain at step %d cycles through step %d"
                    % (step, prev))
            seen.add(prev)
            cur = load_train_state(self._step_dir(prev))
            chain.append(cur)
        arrays = dict(cur.arrays)      # the full base artifact
        private = set()     # delta vars already copied out of their npz
        for d in reversed(chain[:-1]):
            for n, v in (d.arrays or {}).items():
                arrays[n] = v          # full vars in a delta artifact
                private.discard(n)
            for n, ops in (d.delta or {}).items():
                if n not in arrays:
                    raise CheckpointCorruptError(
                        "incremental chain: delta var %r (step %d) has "
                        "no base value" % (n, d.step))
                if n not in private:
                    # privatize ONCE per var (the base npz view must not
                    # be mutated) — not once per chain link: replaying a
                    # long chain over a [vocab, D] table would otherwise
                    # pay O(chain · vocab · D) in copies
                    arrays[n] = np.array(arrays[n], copy=True)
                    private.add(n)
                arrays[n] = _apply_delta_ops(arrays[n], ops)
        host = dict(chain[0].host)
        return TrainState(step, arrays, host)

    def restore(self, scope=None, program=None, executors=None,
                readers=None, step=None, shardings=None, strict=True,
                train_state=None):
        """Restore ``step`` (default: newest VALID artifact, falling
        back past corrupt/partial ones with a warning).  Returns the
        restored step index, or None when no usable checkpoint exists;
        the full ``TrainState`` stays readable as ``last_restored``
        (the Trainer applies executor/reader state from it after it
        builds those objects).  ``train_state``: a TrainState already
        read by ``load(step)`` — skips the second disk read/checksum of
        that exact artifact (requires ``step``; the guardian's restore
        scan pre-validates artifacts this way)."""
        self.wait_until_finished()
        candidates = [step] if step is not None \
            else list(reversed(self.all_steps()))
        for s in candidates:
            try:
                ts = train_state if (train_state is not None
                                     and step is not None) \
                    else self.load(s)
                restored = apply_train_state(
                    ts, scope=scope, program=program, executors=executors,
                    readers=readers, shardings=shardings, strict=strict)
            except CheckpointMismatchError:
                # a structural misfit (different model / executor
                # naming) is a CONFIGURATION error every older artifact
                # shares — falling back would silently end in a fresh
                # start; surface it instead
                raise
            except CheckpointCorruptError as e:
                if step is not None:
                    raise
                warnings.warn(
                    "skipping corrupt checkpoint step %d (%s); falling "
                    "back to the previous one" % (s, e))
                monitor.mark("checkpoint/corrupt_skipped")
                continue
            self.last_restored = ts
            # save cadence restarts from the RESTORED step, not from
            # whatever newer (possibly corrupt, just skipped) artifact
            # sits on disk: replayed steps re-checkpoint on schedule,
            # and the next save at a skipped step's index overwrites
            # the corrupt artifact instead of warning forever
            self._last_saved = restored
            self._seed_incremental_base(ts)
            monitor.log_event({"event": "checkpoint_restored",
                               "ts": time.time(), "step": restored})
            return restored
        return None

    def close(self):
        self.wait_until_finished()
