"""Sharded / async checkpointing for the mesh runtime (orbax-backed).

Parity: the reference's checkpoint tier at distributed scale —
``save_op``/``load_op`` + ``fluid/io.py`` handle host tensors
(mirrored by ``paddle_tpu.io``); the *distributed* story there is
pserver-side shard checkpoints triggered by ``checkpoint_notify_op.cc``
and the Go pserver's periodic shard snapshots
(``go/pserver/service.go:346 checkpoint``, ``:175 LoadCheckpoint``).
TPU-native redesign: parameters live sharded on the mesh, so the
checkpoint IS the sharded artifact — orbax writes each host's shards in
parallel (OCDBT), restore re-shards onto the current mesh (even a mesh
of a different shape/size, the elastic-resume case), and saves can be
async so the train loop overlaps the write (the pserver's
"snapshot while serving" behavior).

Works with the Scope/Program model: persistable vars are the pytree.

Exact-resume elastic training (ISSUE 6 tentpole) lives in the second
half of this module: ``TrainState`` captures params *and* optimizer
slot vars, LR/step counters, executor PRNG counters, and reader
position as ONE atomic artifact; ``TrainStateCheckpointManager`` writes
it asynchronously (snapshot at the step boundary, write under the next
interval's compute, ``checkpoint/save`` monitor span), commits
atomically (tmp dir + rename) with a sha256 manifest, and on restore
validates the manifest and FALLS BACK to the previous checkpoint when
the latest is partial or corrupt — the production pattern of CheckFreq
(FAST'21) / Check-N-Run (NSDI'22), see PAPERS.md.
"""

import collections
import hashlib
import json
import os
import shutil
import threading
import time
import warnings

import jax
import numpy as np

from .. import fault, monitor
from ..profiler import RecordEvent
from ..scope import global_scope

__all__ = [
    "save_sharded", "load_sharded", "ShardedCheckpointManager",
    "TrainState", "TrainStateCheckpointManager", "CheckpointCorruptError",
    "CheckpointMismatchError", "capture_train_state", "apply_train_state",
    "save_train_state", "load_train_state",
]


def _persistable_state(scope, program=None):
    """dict name -> array of the checkpointable vars."""
    from ..framework import default_main_program

    program = program or default_main_program()
    state = {}
    for var in program.global_block().vars.values():
        if getattr(var, "persistable", False) and scope.has_var(var.name):
            state[var.name] = scope.var(var.name)
    return state


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _require_state(state, action):
    if not state:
        raise ValueError(
            "no persistable state in scope to %s: run the startup "
            "program first so the var set and shapes/dtypes exist"
            % action)


def _abstract_state(state, shardings):
    """ShapeDtypeStruct restore targets (optionally mesh-placed)."""

    def one(name, v):
        arr = np.asarray(v) if not isinstance(v, jax.Array) else v
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype,
                                    sharding=(shardings or {}).get(name))

    return {n: one(n, v) for n, v in state.items()}


def save_sharded(dirname, scope=None, program=None):
    """Write the persistable state as a sharded orbax checkpoint.
    Each process writes only its addressable shards (multi-host safe)."""
    scope = scope or global_scope()
    state = _persistable_state(scope, program)
    _require_state(state, "save")
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(dirname), state, force=True)
    ckptr.wait_until_finished()
    return sorted(state)


def load_sharded(dirname, scope=None, program=None, shardings=None):
    """Restore a sharded checkpoint into the scope.

    ``shardings``: optional dict name -> jax.sharding.Sharding to place
    restored arrays directly onto the current mesh (possibly a different
    topology than the one that saved — the elastic-resume case).
    Without it arrays restore as host-local numpy."""
    import orbax.checkpoint as ocp

    scope = scope or global_scope()
    state = _persistable_state(scope, program)
    _require_state(state, "restore into")
    ckptr = _checkpointer()
    restored = ckptr.restore(os.path.abspath(dirname),
                             _abstract_state(state, shardings))
    for name, val in restored.items():
        scope.set_var(name, val)
    return sorted(restored)


class ShardedCheckpointManager:
    """Step-indexed async checkpoint rotation (CheckpointConfig's
    epoch/step-interval + max_num_checkpoints at mesh scale;
    go/pserver periodic-shard-checkpoint parity)."""

    def __init__(self, dirname, max_to_keep=3, save_interval_steps=1,
                 async_save=True):
        import orbax.checkpoint as ocp

        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(dirname), options=self._options)

    def save(self, step, scope=None, program=None):
        """Maybe-save (interval-gated) at ``step``; async by default."""
        import orbax.checkpoint as ocp

        if not self._mgr.should_save(step):
            return False  # interval-gated: skip the state walk entirely
        state = _persistable_state(scope or global_scope(), program)
        _require_state(state, "save")
        return self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore(self, scope=None, program=None, step=None,
                shardings=None):
        """Restore ``step`` (default: latest). Returns the step or None
        if no checkpoint exists."""
        import orbax.checkpoint as ocp

        scope = scope or global_scope()
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        state = _persistable_state(scope, program)
        _require_state(state, "restore into")
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(
                _abstract_state(state, shardings)))
        for name, val in restored.items():
            scope.set_var(name, val)
        return step

    def save_now(self, step, scope=None, program=None):
        """Forced synchronous save, ignoring the interval gate — the
        flush-before-exit path (preemption / SIGTERM).

        Callers decide WHEN this is safe: flush at a step boundary, and
        in a multi-process world agree on ``step`` first (the
        ``distributed.any_process_flagged`` vote) since every host must
        join this collective write.  ``contrib.Trainer`` wires the
        single-process flow (signal -> finish step -> flush);
        ``tests/dist_runner.py`` shows the multi-process protocol."""
        import orbax.checkpoint as ocp

        # drain any in-flight async periodic save before starting the
        # forced one (CheckpointManager.save is not reentrant)
        self._mgr.wait_until_finished()
        state = _persistable_state(scope or global_scope(), program)
        _require_state(state, "save")
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                               force=True)
        self._mgr.wait_until_finished()
        return saved

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


# ---------------------------------------------------------------------------
# Exact-resume TrainState checkpoints (ISSUE 6)
# ---------------------------------------------------------------------------

TRAIN_STATE_FORMAT = 1

# Fault-injection points for the kill-and-resume drills live in the
# process-wide registry (``paddle_tpu.fault``): the write protocol
# fires ``checkpoint/before_write`` / ``checkpoint/after_write`` /
# ``checkpoint/before_commit`` with the artifact's step — e.g.
# ``fault.kill_mid_save(FaultSchedule(steps=[11]))`` simulates
# preemption mid-save, leaving only a .tmp dir the restore must ignore
# (tests/test_elastic_drill.py).

_ARRAYS_FILE = "arrays.npz"
_HOST_FILE = "train_state.json"
_MANIFEST_FILE = "MANIFEST.json"
_STEP_PREFIX = "step_"
_TMP_PREFIX = ".tmp."


class CheckpointCorruptError(RuntimeError):
    """A checkpoint artifact failed manifest/checksum validation."""


class CheckpointMismatchError(CheckpointCorruptError):
    """The artifact is intact but does not FIT: different model var set
    or executor naming.  Distinct from corruption so restore() can stop
    and surface a configuration error instead of silently falling back
    past every (structurally identical) older artifact to a fresh
    start."""


def _npz_encode(arr):
    """(encodable array, logical dtype name or None): dtypes the npy
    format cannot describe (ml_dtypes bfloat16 etc. round-trip as raw
    void) are stored as same-width uints + the logical name."""
    arr = np.ascontiguousarray(arr)
    try:
        descr = np.lib.format.dtype_to_descr(arr.dtype)
        if np.dtype(descr) == arr.dtype:
            return arr, None
    except (ValueError, TypeError):
        pass
    raw = np.dtype("u%d" % arr.dtype.itemsize)
    return arr.view(raw), arr.dtype.name


def _npz_decode(arr, dtype_name):
    if not dtype_name:
        return arr
    import ml_dtypes

    return arr.view(np.dtype(getattr(ml_dtypes, dtype_name)))


def _named(objs, what):
    """Normalize the executors=/readers= argument: None, a single
    object, a sequence (auto-named by position), or a {name: obj} dict."""
    if objs is None:
        return {}
    if isinstance(objs, dict):
        return dict(objs)
    if isinstance(objs, (list, tuple)):
        return {"%s%d" % (what, i): o for i, o in enumerate(objs)}
    return {what + "0": objs}


def _gather_host(v):
    """One state value as a FULL host numpy array, copied out of any
    device buffer.  Fully-addressable jax Arrays (single-host meshes —
    sharded or not) gather through ``np.array``; multi-host global
    arrays all-gather across processes first (every process then writes
    an identical, complete artifact — restorable anywhere)."""
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(v, tiled=True))
    return np.array(v, copy=True)


class TrainState:
    """One atomic snapshot of a training run at a step boundary:
    ``arrays`` (host numpy: params, optimizer slots, LR, in-graph step
    counters) + ``host`` (JSON-able: step index, executor PRNG counters,
    reader positions, caller extras)."""

    def __init__(self, step, arrays, host):
        self.step = int(step)
        self.arrays = arrays
        self.host = host

    def __repr__(self):
        return "TrainState(step=%d, arrays=%d, executors=%s, readers=%s)" % (
            self.step, len(self.arrays),
            sorted(self.host.get("executors", {})),
            sorted(self.host.get("readers", {})))


def capture_train_state(step, scope=None, program=None, executors=None,
                        readers=None, extra=None):
    """Snapshot the FULL train state at a step boundary.

    Blocks only for the device->host copy of the persistable vars (the
    cheap part); serialization happens in whoever writes the snapshot —
    under the next interval's compute on the async save path.
    ``executors``/``readers`` are objects exposing ``state_dict()``
    (Executor/ParallelExecutor PRNG run counters, reader positions);
    pass the same names to the restoring side so state re-applies to
    the matching object."""
    with RecordEvent("checkpoint/snapshot"):
        scope = scope or global_scope()
        state = _persistable_state(scope, program)
        _require_state(state, "snapshot")
        # _gather_host: np.array(copy=True), NOT np.asarray — on the CPU
        # backend np.asarray(jax.Array) is a ZERO-COPY view of the
        # device buffer, and the next dispatched step DONATES that
        # buffer — XLA reuses the memory while the background writer
        # serializes it, tearing the snapshot (found by the kill-at-step
        # drill: warm-cache runs dispatch fast enough to hit the
        # window).  Mesh-sharded state (fsdp/tp params under
        # sharding_rules) gathers to the FULL logical array, so the
        # artifact is topology-free: restore re-shards onto whatever
        # mesh (or single device) the resuming process runs.
        arrays = {n: _gather_host(v) for n, v in state.items()}
        host = {
            "format": TRAIN_STATE_FORMAT,
            "step": int(step),
            "time": time.time(),
            "executors": {n: dict(e.state_dict())
                          for n, e in _named(executors, "executor").items()},
            "readers": {n: dict(r.state_dict())
                        for n, r in _named(readers, "reader").items()},
            "extra": dict(extra or {}),
        }
    return TrainState(step, arrays, host)


def apply_train_state(ts, scope=None, program=None, executors=None,
                      readers=None, shardings=None, strict=True):
    """Apply a restored ``TrainState``: arrays into the scope (optionally
    ``device_put`` onto ``shardings``), PRNG counters into the executors,
    positions into the readers.  ``strict`` requires every persistable
    var of the current program to be present in the artifact (exact
    resume must not silently half-restore a model)."""
    scope = scope or global_scope()
    current = _persistable_state(scope, program)
    _require_state(current, "restore into")
    missing = sorted(set(current) - set(ts.arrays))
    if missing and strict:
        raise CheckpointMismatchError(
            "checkpoint (step %d) lacks persistable vars %s of the "
            "current program — not the same model (strict=False to "
            "restore the intersection)" % (ts.step, missing))
    if strict:
        # names matching is not enough: a smaller model whose var names
        # are a SUBSET of the saved one must still be rejected, so
        # shapes/dtypes are part of the fit check
        for name in current:
            if name not in ts.arrays:
                continue
            want, got = ts.arrays[name], current[name]
            if tuple(np.shape(got)) != tuple(want.shape):
                raise CheckpointMismatchError(
                    "checkpoint (step %d) var %r has shape %s but the "
                    "current model declares %s — not the same model"
                    % (ts.step, name, tuple(want.shape),
                       tuple(np.shape(got))))
    # validate the executor-name mapping BEFORE touching the scope: a
    # rejected checkpoint must not leave its params half-applied
    named_ex = _named(executors, "executor")
    if strict and ts.host.get("executors"):
        for name in named_ex:
            if name not in ts.host["executors"]:
                raise CheckpointMismatchError(
                    "checkpoint has no executor state named %r "
                    "(saved: %s)" % (name, sorted(ts.host["executors"])))
    for name in current:
        if name not in ts.arrays:
            continue
        val = ts.arrays[name]
        sh = (shardings or {}).get(name)
        scope.set_var(name, jax.device_put(val, sh) if sh is not None
                      else val)
    for name, ex in named_ex.items():
        st = ts.host.get("executors", {}).get(name)
        if st is not None:
            ex.load_state_dict(st)
    for name, r in _named(readers, "reader").items():
        st = ts.host.get("readers", {}).get(name)
        if st is not None:
            r.load_state_dict(st)
    return ts.step


def _sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:       # platforms without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save_train_state(dirname, ts):
    """Write ``ts`` as one atomic artifact: arrays.npz + train_state.json
    + a sha256 MANIFEST, assembled in a ``.tmp`` sibling and committed
    with a single directory rename.  A crash at ANY point leaves either
    the previous artifact set intact or a .tmp dir restores ignore."""
    dirname = os.path.abspath(dirname)
    parent = os.path.dirname(dirname)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, _TMP_PREFIX + "%s.%d"
                       % (os.path.basename(dirname), os.getpid()))
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    try:
        fault.fire("checkpoint/before_write", ts.step)
        encoded, raw_dtypes = {}, {}
        for n, a in ts.arrays.items():
            encoded[n], logical = _npz_encode(a)
            if logical:
                raw_dtypes[n] = logical
        host = dict(ts.host)
        host["raw_dtypes"] = raw_dtypes
        # npz member names can't carry '/' etc. reliably across numpy
        # versions -> positional members + an ordered name list
        names = sorted(encoded)
        arrays_path = os.path.join(tmp, _ARRAYS_FILE)
        with open(arrays_path, "wb") as f:
            np.savez(f, **{"arr_%d" % i: encoded[n]
                           for i, n in enumerate(names)})
            f.flush()
            os.fsync(f.fileno())
        host["array_names"] = names
        host_path = os.path.join(tmp, _HOST_FILE)
        with open(host_path, "w") as f:
            json.dump(host, f)
            f.flush()
            os.fsync(f.fileno())
        fault.fire("checkpoint/after_write", ts.step)
        manifest = {
            "format": TRAIN_STATE_FORMAT,
            "step": ts.step,
            "files": {
                _ARRAYS_FILE: {"sha256": _sha256(arrays_path),
                               "bytes": os.path.getsize(arrays_path)},
                _HOST_FILE: {"sha256": _sha256(host_path),
                             "bytes": os.path.getsize(host_path)},
            },
        }
        with open(os.path.join(tmp, _MANIFEST_FILE), "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        fault.fire("checkpoint/before_commit", ts.step)
        # the commit point: everything before it is invisible to
        # restores.  Re-saving an existing step renames the old
        # artifact aside first (as a .tmp sibling, reclaimed by the
        # next manager init) — rmtree-then-replace would hold a
        # destroyed-artifact window open for the whole delete; the
        # rename pair shrinks it to two directory entries.
        if os.path.isdir(dirname):
            old = tmp + ".replaced"
            shutil.rmtree(old, ignore_errors=True)
            os.replace(dirname, old)
            os.replace(tmp, dirname)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.replace(tmp, dirname)
        _fsync_dir(parent or ".")
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return dirname


def load_train_state(dirname):
    """Read + VALIDATE one TrainState artifact; raises
    ``CheckpointCorruptError`` on a missing/partial/garbled artifact
    (manifest absent, checksum mismatch, undecodable payload)."""
    dirname = os.path.abspath(dirname)
    mpath = os.path.join(dirname, _MANIFEST_FILE)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            "checkpoint %s: unreadable manifest (%s) — likely a partial "
            "write" % (dirname, e))
    try:
        for fname, meta in manifest["files"].items():
            fpath = os.path.join(dirname, fname)
            if not os.path.exists(fpath):
                raise CheckpointCorruptError(
                    "checkpoint %s: missing %s" % (dirname, fname))
            if _sha256(fpath) != meta["sha256"]:
                raise CheckpointCorruptError(
                    "checkpoint %s: %s fails its sha256 — corrupt"
                    % (dirname, fname))
        with open(os.path.join(dirname, _HOST_FILE)) as f:
            host = json.load(f)
        raw_dtypes = host.pop("raw_dtypes", {})
        names = host.pop("array_names")
        with np.load(os.path.join(dirname, _ARRAYS_FILE)) as z:
            arrays = {n: _npz_decode(z["arr_%d" % i], raw_dtypes.get(n))
                      for i, n in enumerate(names)}
        return TrainState(manifest["step"], arrays, host)
    except CheckpointCorruptError:
        raise
    except Exception as e:  # noqa: BLE001 — any decode failure = corrupt
        raise CheckpointCorruptError(
            "checkpoint %s: undecodable (%r)" % (dirname, e))


class TrainStateCheckpointManager:
    """Step-indexed TrainState checkpoints with async writes overlapped
    under compute and corruption-safe fallback restore.

    Save protocol (the CheckFreq split): ``save(step)`` snapshots the
    state synchronously at the step boundary (a device->host copy), then
    hands the WRITE to a background thread — the serialization +
    fsync + atomic commit runs under the next interval's compute and
    shows up as a ``checkpoint/save`` monitor span, not step time.  A
    still-inflight write is drained before the next snapshot (and by
    ``save_now``/``wait_until_finished``/``close``); a failed background
    write re-raises at the next call into the manager rather than
    dying silently.

    Restore protocol: newest artifact first; an artifact failing
    manifest/sha256 validation is logged and SKIPPED, falling back to
    the previous one — a torn or corrupt latest checkpoint costs one
    interval of work, never the job."""

    def __init__(self, dirname, max_to_keep=3, save_interval_steps=1,
                 async_save=True):
        self._dir = os.path.abspath(dirname)
        os.makedirs(self._dir, exist_ok=True)
        self._max_to_keep = max(1, int(max_to_keep)) \
            if max_to_keep is not None else None
        self._interval = max(1, int(save_interval_steps))
        self._async = bool(async_save)
        self._last_saved = None
        self._inflight = None            # (thread, step)
        self._error = None
        # rolling measured costs (autotune.tune_checkpoint_interval's
        # evidence): the synchronous device->host snapshot span and the
        # background write span, most recent samples
        self._snapshot_s = collections.deque(maxlen=16)
        self._save_s = collections.deque(maxlen=16)
        self._mu = threading.Lock()
        self.last_restored = None        # TrainState of the last restore
        # a dead process's .tmp dirs (kill mid-save) are garbage
        for entry in os.listdir(self._dir):
            if entry.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self._dir, entry),
                              ignore_errors=True)

    # -- paths / listing ----------------------------------------------
    def _step_dir(self, step):
        return os.path.join(self._dir, "%s%010d" % (_STEP_PREFIX, step))

    def all_steps(self):
        """Committed step indices, sorted ascending (no validation)."""
        out = []
        for entry in os.listdir(self._dir):
            if entry.startswith(_STEP_PREFIX):
                try:
                    out.append(int(entry[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def should_save(self, step):
        last = self._last_saved
        if last is None:
            last = self.latest_step()
        return last is None or step >= last + self._interval

    @property
    def save_interval_steps(self):
        return self._interval

    def set_interval(self, save_interval_steps):
        """Re-gate the save cadence (the auto-tuner's checkpoint-
        interval decision lands here; a mid-run retune is safe — the
        gate compares against the last SAVED step either way)."""
        self._interval = max(1, int(save_interval_steps))

    def measured_costs(self):
        """Mean measured costs of this manager's own saves — the
        snapshot (synchronous device->host copy, the only on-step cost
        of an async save) and the write (serialize+fsync+commit) —
        plus the sample count.  The evidence
        ``autotune.tune_checkpoint_interval`` consumes; empty dict
        before the first save."""
        # deque snapshots are atomic enough (GIL) for a mean; the
        # writer thread appends, this reads
        snaps, saves = list(self._snapshot_s), list(self._save_s)
        out = {}
        if snaps:
            out["snapshot_s"] = sum(snaps) / len(snaps)
        if saves:
            out["save_s"] = sum(saves) / len(saves)
        if out:
            out["n"] = max(len(snaps), len(saves))
        return out

    # -- save ----------------------------------------------------------
    def save(self, step, scope=None, program=None, executors=None,
             readers=None, extra=None):
        """Interval-gated async save at ``step``.  Returns False when
        gated; True once the snapshot is taken and the write is running
        (or, sync mode, committed)."""
        self._reraise()
        if not self.should_save(step):
            return False
        self.wait_until_finished()       # drain the previous write
        t0 = time.perf_counter()
        ts = capture_train_state(step, scope=scope, program=program,
                                 executors=executors, readers=readers,
                                 extra=extra)
        self._snapshot_s.append(time.perf_counter() - t0)
        self._last_saved = int(step)
        if not self._async:
            self._write(ts)
            return True
        t = threading.Thread(target=self._write_guarded, args=(ts,),
                             name="ckpt-write-%d" % step, daemon=True)
        with self._mu:
            self._inflight = (t, int(step))
        t.start()
        return True

    def save_now(self, step, scope=None, program=None, executors=None,
                 readers=None, extra=None):
        """Forced SYNCHRONOUS save ignoring the interval gate — the
        preemption/SIGTERM flush path.  Drains any in-flight async write
        first; returns only once the artifact is committed.  If this
        exact step already committed (the periodic save landed at the
        same boundary), the flush is a no-op: the state at one step
        boundary is one state, and re-writing it would only re-open the
        replace window during a shutdown deadline."""
        self._reraise()
        self.wait_until_finished()
        if self._last_saved == int(step) and \
                os.path.exists(os.path.join(self._step_dir(step),
                                            _MANIFEST_FILE)):
            return True
        t0 = time.perf_counter()
        ts = capture_train_state(step, scope=scope, program=program,
                                 executors=executors, readers=readers,
                                 extra=extra)
        self._snapshot_s.append(time.perf_counter() - t0)
        self._last_saved = int(step)
        self._write(ts)
        return True

    def _write_guarded(self, ts):
        try:
            self._write(ts)
        except BaseException as e:  # noqa: BLE001 — surfaced on next call
            with self._mu:
                self._error = e

    def _write(self, ts):
        t0 = time.perf_counter()
        with RecordEvent("checkpoint/save"):
            path = save_train_state(self._step_dir(ts.step), ts)
        self._save_s.append(time.perf_counter() - t0)
        self._rotate()
        monitor.mark("checkpoint/saved")
        monitor.log_event({
            "event": "checkpoint_saved", "ts": time.time(),
            "step": ts.step, "path": path,
            "seconds": round(time.perf_counter() - t0, 6),
            "bytes": sum(a.nbytes for a in ts.arrays.values()),
            "async": self._async})
        return path

    def _rotate(self):
        if self._max_to_keep is None:
            return
        steps = self.all_steps()
        for s in steps[:-self._max_to_keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def _reraise(self):
        with self._mu:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                "previous async checkpoint write failed") from err

    def wait_until_finished(self):
        with self._mu:
            inflight = self._inflight
        if inflight is not None:
            inflight[0].join()
            with self._mu:
                if self._inflight is inflight:
                    self._inflight = None
        self._reraise()

    # -- restore -------------------------------------------------------
    def load(self, step):
        """Read + VALIDATE the artifact at ``step`` without applying it
        — pre-restore inspection (the guardian's poisoned-checkpoint
        scan rejects artifacts before they touch live state).  Raises
        ``CheckpointCorruptError`` on a corrupt/partial artifact."""
        return load_train_state(self._step_dir(step))

    def restore(self, scope=None, program=None, executors=None,
                readers=None, step=None, shardings=None, strict=True,
                train_state=None):
        """Restore ``step`` (default: newest VALID artifact, falling
        back past corrupt/partial ones with a warning).  Returns the
        restored step index, or None when no usable checkpoint exists;
        the full ``TrainState`` stays readable as ``last_restored``
        (the Trainer applies executor/reader state from it after it
        builds those objects).  ``train_state``: a TrainState already
        read by ``load(step)`` — skips the second disk read/checksum of
        that exact artifact (requires ``step``; the guardian's restore
        scan pre-validates artifacts this way)."""
        self.wait_until_finished()
        candidates = [step] if step is not None \
            else list(reversed(self.all_steps()))
        for s in candidates:
            try:
                ts = train_state if (train_state is not None
                                     and step is not None) \
                    else load_train_state(self._step_dir(s))
                restored = apply_train_state(
                    ts, scope=scope, program=program, executors=executors,
                    readers=readers, shardings=shardings, strict=strict)
            except CheckpointMismatchError:
                # a structural misfit (different model / executor
                # naming) is a CONFIGURATION error every older artifact
                # shares — falling back would silently end in a fresh
                # start; surface it instead
                raise
            except CheckpointCorruptError as e:
                if step is not None:
                    raise
                warnings.warn(
                    "skipping corrupt checkpoint step %d (%s); falling "
                    "back to the previous one" % (s, e))
                monitor.mark("checkpoint/corrupt_skipped")
                continue
            self.last_restored = ts
            # save cadence restarts from the RESTORED step, not from
            # whatever newer (possibly corrupt, just skipped) artifact
            # sits on disk: replayed steps re-checkpoint on schedule,
            # and the next save at a skipped step's index overwrites
            # the corrupt artifact instead of warning forever
            self._last_saved = restored
            monitor.log_event({"event": "checkpoint_restored",
                               "ts": time.time(), "step": restored})
            return restored
        return None

    def close(self):
        self.wait_until_finished()
