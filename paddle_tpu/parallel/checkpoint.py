"""Sharded / async checkpointing for the mesh runtime (orbax-backed).

Parity: the reference's checkpoint tier at distributed scale —
``save_op``/``load_op`` + ``fluid/io.py`` handle host tensors
(mirrored by ``paddle_tpu.io``); the *distributed* story there is
pserver-side shard checkpoints triggered by ``checkpoint_notify_op.cc``
and the Go pserver's periodic shard snapshots
(``go/pserver/service.go:346 checkpoint``, ``:175 LoadCheckpoint``).
TPU-native redesign: parameters live sharded on the mesh, so the
checkpoint IS the sharded artifact — orbax writes each host's shards in
parallel (OCDBT), restore re-shards onto the current mesh (even a mesh
of a different shape/size, the elastic-resume case), and saves can be
async so the train loop overlaps the write (the pserver's
"snapshot while serving" behavior).

Works with the Scope/Program model: persistable vars are the pytree.
"""

import os

import jax
import numpy as np

from ..scope import global_scope

__all__ = ["save_sharded", "load_sharded", "ShardedCheckpointManager"]


def _persistable_state(scope, program=None):
    """dict name -> array of the checkpointable vars."""
    from ..framework import default_main_program

    program = program or default_main_program()
    state = {}
    for var in program.global_block().vars.values():
        if getattr(var, "persistable", False) and scope.has_var(var.name):
            state[var.name] = scope.var(var.name)
    return state


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _require_state(state, action):
    if not state:
        raise ValueError(
            "no persistable state in scope to %s: run the startup "
            "program first so the var set and shapes/dtypes exist"
            % action)


def _abstract_state(state, shardings):
    """ShapeDtypeStruct restore targets (optionally mesh-placed)."""

    def one(name, v):
        arr = np.asarray(v) if not isinstance(v, jax.Array) else v
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype,
                                    sharding=(shardings or {}).get(name))

    return {n: one(n, v) for n, v in state.items()}


def save_sharded(dirname, scope=None, program=None):
    """Write the persistable state as a sharded orbax checkpoint.
    Each process writes only its addressable shards (multi-host safe)."""
    scope = scope or global_scope()
    state = _persistable_state(scope, program)
    _require_state(state, "save")
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(dirname), state, force=True)
    ckptr.wait_until_finished()
    return sorted(state)


def load_sharded(dirname, scope=None, program=None, shardings=None):
    """Restore a sharded checkpoint into the scope.

    ``shardings``: optional dict name -> jax.sharding.Sharding to place
    restored arrays directly onto the current mesh (possibly a different
    topology than the one that saved — the elastic-resume case).
    Without it arrays restore as host-local numpy."""
    import orbax.checkpoint as ocp

    scope = scope or global_scope()
    state = _persistable_state(scope, program)
    _require_state(state, "restore into")
    ckptr = _checkpointer()
    restored = ckptr.restore(os.path.abspath(dirname),
                             _abstract_state(state, shardings))
    for name, val in restored.items():
        scope.set_var(name, val)
    return sorted(restored)


class ShardedCheckpointManager:
    """Step-indexed async checkpoint rotation (CheckpointConfig's
    epoch/step-interval + max_num_checkpoints at mesh scale;
    go/pserver periodic-shard-checkpoint parity)."""

    def __init__(self, dirname, max_to_keep=3, save_interval_steps=1,
                 async_save=True):
        import orbax.checkpoint as ocp

        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(dirname), options=self._options)

    def save(self, step, scope=None, program=None):
        """Maybe-save (interval-gated) at ``step``; async by default."""
        import orbax.checkpoint as ocp

        if not self._mgr.should_save(step):
            return False  # interval-gated: skip the state walk entirely
        state = _persistable_state(scope or global_scope(), program)
        _require_state(state, "save")
        return self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore(self, scope=None, program=None, step=None,
                shardings=None):
        """Restore ``step`` (default: latest). Returns the step or None
        if no checkpoint exists."""
        import orbax.checkpoint as ocp

        scope = scope or global_scope()
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        state = _persistable_state(scope, program)
        _require_state(state, "restore into")
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(
                _abstract_state(state, shardings)))
        for name, val in restored.items():
            scope.set_var(name, val)
        return step

    def save_now(self, step, scope=None, program=None):
        """Forced synchronous save, ignoring the interval gate — the
        flush-before-exit path (preemption / SIGTERM).

        Callers decide WHEN this is safe: flush at a step boundary, and
        in a multi-process world agree on ``step`` first (the
        ``distributed.any_process_flagged`` vote) since every host must
        join this collective write.  ``contrib.Trainer`` wires the
        single-process flow (signal -> finish step -> flush);
        ``tests/dist_runner.py`` shows the multi-process protocol."""
        import orbax.checkpoint as ocp

        # drain any in-flight async periodic save before starting the
        # forced one (CheckpointManager.save is not reentrant)
        self._mgr.wait_until_finished()
        state = _persistable_state(scope or global_scope(), program)
        _require_state(state, "save")
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                               force=True)
        self._mgr.wait_until_finished()
        return saved

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
