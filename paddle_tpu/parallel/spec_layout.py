"""SpecLayout: canonical parameter shardings + t5x-style logical-axis
rules for the ``(data, fsdp, tp)`` hardware mesh.

This is the declarative half of the mesh engine (the
``MultiDevSSAGraphBuilder`` analog done the GSPMD way): instead of the
reference's hand-built per-device SSA graph with reduce/broadcast op
handles, each *parameter class* gets a canonical
:class:`~jax.sharding.PartitionSpec` — the annotate side of XLA's
annotate-and-propagate sharding — and XLA inserts the ICI collectives
(reduce-scatter of grads / all-gather of params around each use for
``fsdp``; all-reduce of partial matmuls for ``tp``).

Three layers, each usable on its own:

* :class:`SpecLayout` — the table of canonical specs per parameter
  class (embeddings, qkv/ffn projections, norm scales, batch), plus the
  logical-axis rules mapping *model* axes (``vocab``, ``embed``,
  ``mlp``, ``norm``, ``batch``) onto *mesh* axes (``dp``, ``fsdp``,
  ``tp``) — the t5x ``LogicalAxisRules`` pattern.
* :func:`classify_params` / :func:`optimizer_slot_params` — derive each
  persistable var's parameter class from the Program structure (which
  ops consume it), so the rules apply to any layers-DSL model without
  per-model spec tables.  Optimizer slot vars (Adam moments, Momentum
  velocity, ...) inherit their parameter's class; scalar slots
  (beta-pow counters, LR) replicate.
* :meth:`SpecLayout.resolve` — bind the table to a concrete
  (program, mesh, shapes): returns ``{name: PartitionSpec}`` with
  graceful degradation — a mesh axis that is absent or size 1 drops out
  of the spec, a dim a rule does not divide sheds axes until it fits
  (replicating as the last resort), and no mesh axis is used twice in
  one spec.

``BuildStrategy.sharding_rules`` carries a SpecLayout (or ``True`` for
the default one) into ``ParallelExecutor._compile``; the older
``param_sharding_fn`` hook still wins per-param when it returns a spec,
so policies can layer (see strategy.py).
"""

import numpy as np

from jax.sharding import PartitionSpec as P

from .mesh import AXIS_DP, AXIS_FSDP, AXIS_TP

__all__ = ["SpecLayout", "DEFAULT_RULES", "classify_params",
           "optimizer_slot_params"]


# Logical (model) axes -> mesh axes; tuple values shard one dim over
# several mesh axes (dim size must divide their product).  The t5x
# convention: first matching rule wins, one mesh axis at most once per
# spec.
DEFAULT_RULES = (
    ("batch", (AXIS_DP, AXIS_FSDP)),   # dp AND fsdp both shard the batch
    ("vocab", (AXIS_FSDP, AXIS_TP)),   # embedding rows over fsdp x tp
    ("embed", AXIS_FSDP),              # model dim: ZeRO-sharded
    ("mlp", AXIS_TP),                  # projection out-columns / heads
    ("norm", AXIS_FSDP),               # 1-D scales/biases: ZeRO-sharded
)

# ops that keep their main input's hidden-dim lineage (used by the
# program scan below to tell column-parallel producers from the
# row-parallel consumers that follow them)
_PASSTHROUGH_OPS = {
    "relu", "gelu", "tanh", "sigmoid", "dropout", "scale", "reshape",
    "transpose", "fused_attention", "softmax", "cast",
}


def classify_params(program):
    """Map each parameter to its class as logical dim axes, from the ops
    that consume it:

    * ``lookup_table`` W                     -> ``("vocab", "embed")``
    * ``layer_norm`` Scale/Bias              -> ``("norm",)``
    * ``mul``/``matmul`` weights [in, out]   -> ``("embed", "mlp")``
      (column-parallel), or ``("mlp", "embed")`` (row-parallel) when the
      op's data input descends from a column-parallel output — the
      Megatron pairing: qkv/ffn-up shard columns, attn-out/ffn-down
      shard rows, so the pair needs one all-reduce, not two.
    * 1-D biases added onto a column-parallel output -> ``("mlp",)``;
      other 1-D biases -> ``("norm",)``.

    Returns ``{param_name: tuple_of_logical_axes}``; unlisted
    persistables (counters, tables of odd rank) resolve to replicated.
    """
    classes = {}
    # vars whose LAST dim is currently "mlp"-sharded (output of a
    # column-parallel projection, propagated through elementwise ops)
    mlp_vars = set()
    for blk in program.blocks:
        for op in blk.ops:
            ins, outs = op.inputs, op.outputs
            if op.type == "lookup_table":
                for w in ins.get("W", ()):
                    classes[w] = ("vocab", "embed")
            elif op.type == "layer_norm":
                for slot in ("Scale", "Bias"):
                    for nm in ins.get(slot, ()):
                        classes[nm] = ("norm",)
            elif op.type in ("mul", "matmul"):
                xs = ins.get("X", ())
                for w in ins.get("Y", ()):
                    v = blk._find_var_recursive(w)
                    if v is None or not getattr(v, "persistable", False):
                        continue
                    row_par = any(x in mlp_vars for x in xs)
                    classes.setdefault(
                        w, ("mlp", "embed") if row_par else ("embed", "mlp"))
                    if classes[w] == ("embed", "mlp"):
                        mlp_vars.update(outs.get("Out", ()))
            elif op.type == "elementwise_add":
                xs = ins.get("X", ())
                col = any(x in mlp_vars for x in xs)
                for b in ins.get("Y", ()):
                    v = blk._find_var_recursive(b)
                    if v is not None and getattr(v, "persistable", False) \
                            and v.shape is not None and len(v.shape) == 1:
                        classes.setdefault(b, ("mlp",) if col else ("norm",))
                if col:
                    mlp_vars.update(outs.get("Out", ()))
            elif op.type in _PASSTHROUGH_OPS:
                if any(x in mlp_vars for x in
                       list(ins.get("X", ())) + list(ins.get("Q", ()))):
                    for names in outs.values():
                        mlp_vars.update(names)
    return classes


def optimizer_slot_params(program):
    """Map optimizer slot vars to the parameter they accumulate for, by
    op structure: any op with a ``Param`` input slot (momentum, adam,
    adamax, ...) binds its other persistable inputs — Moment1/Moment2/
    Velocity/beta-pow counters — to that parameter.  Slot vars inherit
    the parameter's sharding when shapes match (resolve() replicates
    the scalar counters)."""
    out = {}
    for blk in program.blocks:
        for op in blk.ops:
            ins = op.inputs
            pnames = ins.get("Param", ())
            if not pnames:
                continue
            for slot, names in ins.items():
                if slot in ("Param", "Grad", "LearningRate"):
                    continue
                for nm in names:
                    v = blk._find_var_recursive(nm)
                    if v is not None and getattr(v, "persistable", False):
                        out.setdefault(nm, pnames[0])
    return out


class SpecLayout:
    """Canonical PartitionSpecs per parameter class on a named
    ``(data, fsdp, tp)`` mesh (SNIPPETS [1] pattern), plus the
    logical->mesh rules and the resolver that binds them to a Program.

    ``rules`` override :data:`DEFAULT_RULES` (same shape: a sequence of
    ``(logical_axis, mesh_axis_or_tuple_or_None)``).  Axis names are
    configurable so the same table drives e.g. a pure-dp ZeRO layout
    (``fsdp_axis="dp"``)."""

    def __init__(self, data_axis=AXIS_DP, fsdp_axis=AXIS_FSDP,
                 tp_axis=AXIS_TP, rules=None):
        self.data_axis = data_axis
        self.fsdp_axis = fsdp_axis
        self.tp_axis = tp_axis
        if rules is None:
            sub = {AXIS_DP: data_axis, AXIS_FSDP: fsdp_axis,
                   AXIS_TP: tp_axis}
            rules = tuple(
                (ln, tuple(sub.get(a, a) for a in m)
                 if isinstance(m, tuple) else sub.get(m, m))
                for ln, m in DEFAULT_RULES)
        self.rules = tuple(rules)
        # first matching rule wins (the t5x convention) — keep the
        # FIRST occurrence of a duplicated logical axis, not dict()'s
        # last-wins
        self._rule_map = {}
        for ln, m in self.rules:
            self._rule_map.setdefault(ln, m)

    # -- the canonical table (documentation + direct use) ---------------
    def batch(self):
        """Feeds/activations: batch dim over data x fsdp."""
        return P((self.data_axis, self.fsdp_axis))

    def embeddings(self):
        """[vocab, embed] tables: rows over fsdp x tp, embed replicated."""
        return P((self.fsdp_axis, self.tp_axis), None)

    def qkv_projection(self):
        """[embed, heads*d_head] attention in-projections: rows fsdp,
        columns tp (column-parallel)."""
        return P(self.fsdp_axis, self.tp_axis)

    def attn_output(self):
        """[heads*d_head, embed] out-projection: rows tp (row-parallel,
        pairing with qkv's column split), columns fsdp."""
        return P(self.tp_axis, self.fsdp_axis)

    def ffn_up(self):
        return P(self.fsdp_axis, self.tp_axis)

    def ffn_down(self):
        return P(self.tp_axis, self.fsdp_axis)

    def norm_scale(self):
        """layer_norm scales/shifts and other 1-D params: ZeRO-sharded
        over fsdp (XLA all-gathers around the one use)."""
        return P(self.fsdp_axis)

    # -- logical -> mesh resolution -------------------------------------
    def spec_for_logical(self, logical_axes, shape, mesh, rules=None):
        """PartitionSpec for one array: per-dim logical axes through the
        rules (default: this layout's rule map), degraded to whatever
        ``mesh``/``shape`` support."""
        rule_map = self._rule_map if rules is None else rules
        entries, used = [], set()
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        for dim, logical in zip(shape, logical_axes):
            mapped = rule_map.get(logical)
            axes = mapped if isinstance(mapped, tuple) else \
                (mapped,) if mapped else ()
            # keep only live, unused axes; shed from the right until the
            # dim divides the product (replicate the dim as last resort)
            axes = [a for a in axes
                    if sizes.get(a, 1) > 1 and a not in used]
            while axes:
                total = int(np.prod([sizes[a] for a in axes]))
                if dim > 0 and dim % total == 0:
                    break
                axes = axes[:-1]
            if axes:
                used.update(axes)
                entries.append(tuple(axes) if len(axes) > 1 else axes[0])
            else:
                entries.append(None)
        while entries and entries[-1] is None:
            entries.pop()
        return P(*entries)

    def resolve(self, program, mesh, names_shapes):
        """Bind the table to a concrete (program, mesh): returns
        ``{name: PartitionSpec}`` for every (name, shape) pair.

        Parameter classes come from :func:`classify_params`; optimizer
        slot vars inherit their parameter's class when shapes match and
        replicate otherwise (beta-pow counters); unclassified arrays
        fall back to ZeRO dim-0 fsdp sharding when it divides, else
        replicate."""
        classes = classify_params(program)
        slots = optimizer_slot_params(program)
        fallback_rules = {**self._rule_map, "zero0": self.fsdp_axis}
        out = {}
        for name, shape in names_shapes:
            shape = tuple(shape)
            owner = slots.get(name, name)
            logical = classes.get(owner)
            if logical is not None and owner is not name:
                owner_v = program.global_block()._find_var_recursive(owner)
                owner_shape = tuple(getattr(owner_v, "shape", ()) or ()) \
                    if owner_v is not None else ()
                if len(owner_shape) != len(shape):
                    logical = None      # scalar slot of a tensor param
            if logical is None:
                # ZeRO fallback: shard dim 0 of anything unclassified
                # and non-scalar over fsdp (optimizer state and params
                # alike must not replicate on an fsdp mesh)
                if shape and int(np.prod(shape)) > 1:
                    logical = ("zero0",) + (None,) * (len(shape) - 1)
                else:
                    out[name] = P()
                    continue
            out[name] = self.spec_for_logical(logical, shape, mesh,
                                              rules=fallback_rules)
        return out

    def _identity(self):
        return (self.data_axis, self.fsdp_axis, self.tp_axis, self.rules)

    def __eq__(self, other):
        """Value equality: two default tables are THE SAME policy, so
        executors built with separate ``sharding_rules=True`` strategies
        share one process-global trace-cache entry (the cache keys the
        layout object; identity hashing would recompile per executor)."""
        return isinstance(other, SpecLayout) and \
            self._identity() == other._identity()

    def __hash__(self):
        return hash(self._identity())

    def __repr__(self):
        return "SpecLayout(data=%r, fsdp=%r, tp=%r)" % (
            self.data_axis, self.fsdp_axis, self.tp_axis)
