"""Device-mesh helpers (the TPU analog of the reference's
``platform/nccl_helper.h`` NCCLContextMap: which devices participate and
how they are wired).

On TPU the wiring is the ICI torus; ``jax.sharding.Mesh`` names its axes
and XLA routes collectives over it.  Axis convention used throughout:

* ``dp``  — data parallel (batch sharding, gradient psum)
* ``fsdp`` — fully-sharded data parallel (batch sharding AND ZeRO-style
  parameter/optimizer-state sharding: XLA reduce-scatters grads and
  all-gathers params around each use — see spec_layout.py)
* ``tp``  — tensor/model parallel (weight-column sharding)
* ``pp``  — pipeline stages (scan-over-stages layer sharding)
* ``sp``  — sequence/context parallel (ring attention)
* ``ep``  — expert parallel (MoE / sharded embeddings)
"""

import numpy as np

import jax
from jax.sharding import Mesh

try:
    from jax import shard_map as _shard_map    # jax >= 0.8
    _REP_KW = {"check_vma": False}
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map
    _REP_KW = {"check_rep": False}


def shard_map_norep(f, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across the jax >= 0.8
    (check_vma) and older (check_rep) spellings of the flag."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **_REP_KW)

__all__ = ["make_mesh", "shard_map_norep", "AXIS_DP", "AXIS_FSDP",
           "AXIS_TP", "AXIS_PP", "AXIS_SP", "AXIS_EP"]

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_EP = "ep"


def make_mesh(shape=None, axis_names=None, devices=None):
    """Build a Mesh.

    ``make_mesh()``                  -> 1-D dp mesh over all devices
    ``make_mesh(8)``                 -> dp mesh over 8 devices
    ``make_mesh((4, 2))``            -> (dp, tp) mesh
    ``make_mesh((2, 2, 2), ("dp", "tp", "sp"))``
    ``make_mesh((1, 2, 2), ("dp", "fsdp", "tp"))``  -> the model-parallel
    mesh spec_layout.py's sharding rules target (dp and fsdp both shard
    the batch; fsdp additionally ZeRO-shards params/optimizer state; tp
    column-shards attention/ffn weights)
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),)
    elif isinstance(shape, int):
        shape = (shape,)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(
            "mesh shape %r needs %d devices, have %d"
            % (shape, n, len(devices))
        )
    if axis_names is None:
        axis_names = (AXIS_DP, AXIS_TP, AXIS_PP, AXIS_SP, AXIS_EP)[:len(shape)]
    if len(axis_names) != len(shape):
        raise ValueError("axis_names length must match mesh shape rank")
    arr = np.asarray(devices[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))
