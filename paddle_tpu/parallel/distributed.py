"""Multi-host bootstrap: the TPU replacement for the reference's NCCL2
rendezvous and pserver role wiring.

Parity: ``operators/distributed/gen_nccl_id_op.cc:31`` (rank 0 creates an
NCCL unique id and serves it to peers over gRPC) and the cluster role env
vars consumed by ``contrib/trainer.py:324`` / ``benchmark/fluid/README``
(PADDLE_TRAINERS, PADDLE_TRAINER_ID, PADDLE_CURRENT_IP...) — re-designed
TPU-first: ``jax.distributed.initialize`` IS the rendezvous (a gRPC
coordination service exactly like gen_nccl_id's exchange); after it, the
same Mesh spans every host's devices and XLA routes collectives over
ICI/DCN.  There is no pserver role: parameters live sharded on the mesh.
"""

import os

import jax

__all__ = ["init_distributed", "is_initialized", "process_count",
           "process_id", "barrier", "any_process_flagged"]

_initialized = False


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, local_device_ids=None):
    """Join the multi-host world.  Arguments fall back to the reference's
    cluster env vars, then to JAX's own:

    * coordinator_address <- PADDLE_COORDINATOR (host:port; the analog of
      the pserver endpoint the reference serves the NCCL id from)
    * num_processes       <- PADDLE_TRAINERS
    * process_id          <- PADDLE_TRAINER_ID

    Call before any jax computation, once per process.  On real TPU pods
    with a TPU runtime the arguments are auto-detected and may be None.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or \
        os.getenv("PADDLE_COORDINATOR")
    if num_processes is None and os.getenv("PADDLE_TRAINERS"):
        num_processes = int(os.environ["PADDLE_TRAINERS"])
    if process_id is None and os.getenv("PADDLE_TRAINER_ID"):
        process_id = int(os.environ["PADDLE_TRAINER_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized = True
    from .. import compile_cache

    # the persistent XLA cache must not mix executables across world
    # shapes: an N-process executable embeds cross-process collective
    # wiring, and a process of a DIFFERENT world (the elastic-resume
    # survivor, a resized job) deserializing it computes silent garbage
    # — found by the cluster drill, where the resumed solo world read
    # the 2-process world's entries and NaN'd within three steps
    compile_cache.rescope_persistent_cache()


def is_initialized():
    """Whether init_distributed ran in THIS process.  Deliberately does
    NOT query jax.process_count(): that would initialize the XLA backend
    and make a later init_distributed() impossible."""
    return _initialized


def process_count():
    return jax.process_count()


def process_id():
    return jax.process_index()


def barrier(name="paddle_tpu_barrier"):
    """Host barrier over the coordination service (the analog of the
    reference's send_barrier/fetch_barrier RPC round)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def any_process_flagged(flag):
    """Collective OR over processes — the preemption vote.

    Each process passes its local signal flag; every process learns, at
    the SAME point in its step loop, whether any host was signaled.
    This is the coordination that makes checkpoint-on-signal safe for
    sharded state: the actual save is a collective (every host writes
    its shards for one step id), so hosts must agree on the flush step
    rather than each flushing whenever its own handler fired.  Analog:
    the reference pserver exits its serve loop on a barriered condition
    (listen_and_serv_op.cc rpc_service_->IsExit), not mid-RPC.
    """
    import numpy as np
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return bool(flag)
    gathered = multihost_utils.process_allgather(
        np.asarray([bool(flag)]))
    return bool(np.asarray(gathered).any())
