"""Thread-local default-scope stack (reference
python/paddle/fluid/default_scope_funcs.py:1).

The reference keeps a thread-local stack of C++ Scopes; here the stack
holds the framework's Python ``Scope`` objects (scope.py — name ->
host/device array store).  ``var``/``find_var`` address the current
scope; ``scoped_function`` runs a function inside a fresh kid scope and
drops it afterwards.
"""

import threading

from .scope import Scope, global_scope

__all__ = [
    "get_cur_scope",
    "enter_local_scope",
    "leave_local_scope",
    "var",
    "find_var",
    "scoped_function",
]

__tl_scope__ = threading.local()


class _Unset(object):
    """Placeholder for a declared-but-unassigned variable slot (the
    reference's Scope::Var creates an empty Variable holder; this
    scope stores values directly, so declaration needs a sentinel)."""

    def __repr__(self):
        return "<unset var>"


_UNSET = _Unset()


def get_cur_scope():
    """The scope on top of this thread's stack (the bottom is the
    process-global scope, matching the reference's root scope)."""
    cur_scope_stack = getattr(__tl_scope__, "cur_scope", None)
    if cur_scope_stack is None:
        __tl_scope__.cur_scope = [global_scope()]
    return __tl_scope__.cur_scope[-1]


def enter_local_scope():
    """Push a new kid scope of the current scope."""
    cur_scope = get_cur_scope()
    new_scope = cur_scope.new_scope()
    __tl_scope__.cur_scope.append(new_scope)
    return new_scope


def leave_local_scope():
    """Pop and destroy the current local scope."""
    if len(__tl_scope__.cur_scope) <= 1:
        raise RuntimeError("cannot leave the root scope")
    __tl_scope__.cur_scope.pop()
    get_cur_scope().drop_kids()


def var(name):
    """Create (or get) a variable slot in the current scope."""
    scope = get_cur_scope()
    if not scope.has_var(name):
        scope.set_var(name, _UNSET)
    return scope.find_var(name)


def find_var(name):
    """Find a variable in the current scope or its parents."""
    return get_cur_scope().find_var(name)


def scoped_function(func):
    """Run ``func`` inside a fresh local scope (reference
    default_scope_funcs.scoped_function)."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
