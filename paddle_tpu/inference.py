"""Deployment predictor API.

Parity: reference ``paddle/fluid/inference/api/paddle_inference_api.h``
— ``PaddleTensor`` (:95), ``NativeConfig`` (:183), ``AnalysisConfig``
(:255), ``PaddlePredictor::Run``/``Clone`` (:141) and the
``CreatePaddlePredictor`` factory; implementations
``api_impl.cc`` (NativePaddlePredictor over NaiveExecutor) and
``analysis_predictor.cc`` (ir passes then execute).

TPU-native redesign: the predictor wraps a saved inference model
(``io.save_inference_model``'s pruned program + params) in a dedicated
scope and runs it through the jit Executor — the first Run compiles one
fused HLO per input signature, after which Run is a single device
dispatch.  ``AnalysisConfig``'s ir-pass pipeline maps to the
InferenceTranspiler's inference-mode rewrite (numeric fusions are XLA's
job).  ``Clone()`` shares the immutable weights but gets its own
executor cache, matching the reference's clone-per-thread deployment
pattern.
"""

import threading

import numpy as np

from . import io as fluid_io
from .executor import CPUPlace, Executor, TPUPlace
from .scope import Scope

__all__ = ["PaddleTensor", "NativeConfig", "AnalysisConfig",
           "PaddlePredictor", "create_paddle_predictor"]


class PaddleTensor:
    """In/out tensor of the predictor ABI (paddle_inference_api.h:95).
    ``data`` is a numpy array; ``name`` must match a feed/fetch var for
    inputs (outputs are filled by Run).  ``lod`` carries per-sequence
    lengths for lod_level>=1 inputs (the @LEN companion)."""

    def __init__(self, name="", data=None, shape=None, dtype=None,
                 lod=None):
        self.name = name
        if data is not None:
            data = np.asarray(data, dtype=dtype)
            if shape:
                data = data.reshape(shape)
        self.data = data
        self.shape = tuple(data.shape) if data is not None else \
            tuple(shape or ())
        self.dtype = str(data.dtype) if data is not None else dtype
        self.lod = lod

    def __repr__(self):
        return "PaddleTensor(name=%r, shape=%s, dtype=%s)" % (
            self.name, self.shape, self.dtype)


class NativeConfig:
    """paddle_inference_api.h:183 — where the model lives and on what
    device it runs.  ``use_gpu``/``fraction_of_gpu_memory`` are accepted
    for parity; the accelerator here is the TPU (XLA manages memory)."""

    def __init__(self, model_dir="", prog_file=None, param_file=None,
                 use_gpu=True, device=0, fraction_of_gpu_memory=-1.0):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.param_file = param_file
        self.use_gpu = use_gpu
        self.device = device
        self.fraction_of_gpu_memory = fraction_of_gpu_memory

    def _place(self):
        import jax

        accel = any(d.platform != "cpu" for d in jax.local_devices())
        if self.use_gpu and accel:
            return TPUPlace(self.device)
        return CPUPlace()


class AnalysisConfig(NativeConfig):
    """paddle_inference_api.h:255 — NativeConfig + the ir-optimization
    pipeline.  On this framework the pipeline is inherently applied:
    save_inference_model already writes an inference-mode (for_test)
    program and XLA performs the numeric fusions the reference's ir
    passes hand-roll, so AnalysisConfig is API parity with identical
    runtime behavior; ``enable_ir_optim`` is recorded but has nothing
    left to do.  ``enable_serving`` routes ``Run`` through the
    continuous-batching :class:`~.serving.InferenceEngine` instead of a
    private dispatch — concurrent predictors/clones then share one
    admission queue and fixed slot batches."""

    def __init__(self, *args, enable_ir_optim=True, **kwargs):
        super().__init__(*args, **kwargs)
        self.enable_ir_optim = enable_ir_optim
        self.serving = None
        self.quantize_mode = None

    def enable_serving(self, slots=8, timeout_s=30.0, bucket_bounds=None,
                       tuned_config=None, quarantine_dir=None):
        """Opt this config's predictors into engine-backed Run (keyword
        args mirror :class:`~.serving.InferenceEngine`)."""
        self.serving = {"slots": slots, "timeout_s": timeout_s,
                        "bucket_bounds": bucket_bounds,
                        "tuned_config": tuned_config,
                        "quarantine_dir": quarantine_dir}
        return self

    def enable_quantization(self, mode="weight_only"):
        """int8 execution (the reference's EnableTensorRtEngine-with-
        int8 analog): the predictor rewrites the loaded program through
        ``transpiler.quantize_inference`` — int8 weights with
        per-channel dequant scales, fused dequant-matmul kernels.
        Clones (and an ``enable_serving`` engine) share the rewritten
        program.  Artifacts saved ALREADY quantized need no opt-in —
        they load cold."""
        self.quantize_mode = mode
        return self


class PaddlePredictor:
    """paddle_inference_api.h:141 — Run(inputs) -> outputs, Clone()."""

    def __init__(self, config, _shared=None):
        self._config = config
        self._place = config._place()
        # no state donation: clones run concurrently over shared weights
        self._exe = Executor(self._place, donate_state=False)
        if _shared is not None:
            # Clone(): share program + weights (and the serving engine
            # holder — all clones feed ONE admission queue), own
            # executor cache
            self._program, self._feed_names, self._fetch_vars, \
                self._scope, self._engine_holder = _shared
        else:
            self._scope = Scope()
            from .scope import scope_guard

            with scope_guard(self._scope):
                self._program, self._feed_names, self._fetch_vars = \
                    fluid_io.load_inference_model(
                        config.model_dir, self._exe,
                        model_filename=config.prog_file,
                        params_filename=config.param_file)
            qmode = getattr(config, "quantize_mode", None)
            if qmode:
                # enable_quantization(): rewrite once here; clones
                # share the quantized program + int8 scope vars
                from .transpiler.quantize_pass import quantize_inference

                self._program = quantize_inference(
                    self._program, scope=self._scope, mode=qmode)
                blk = self._program.global_block()
                self._fetch_vars = [blk.var(v.name)
                                    for v in self._fetch_vars]
            # the holder carries its own lock: clones share the holder
            # but not self._mu, and two first-calls racing from a base
            # and its clone must not build two engines
            self._engine_holder = [None, threading.Lock()]
        self._mu = threading.Lock()

    # ------------------------------------------------------------------
    def serving_engine(self, **overrides):
        """The continuous-batching engine over this predictor's loaded
        program + shared weights — built lazily, shared by every clone
        (the delegation target of ``enable_serving`` configs; also
        usable directly for request-level ``submit``)."""
        holder = self._engine_holder
        with holder[1]:
            if holder[0] is None:
                from .serving import InferenceEngine

                kw = dict(getattr(self._config, "serving", None) or {})
                kw.update(overrides)
                holder[0] = InferenceEngine(
                    program=self._program,
                    feed_names=self._feed_names,
                    fetch_vars=self._fetch_vars, scope=self._scope,
                    place=self._place, **kw)
        return holder[0]

    # ------------------------------------------------------------------
    def run(self, inputs):
        """List of PaddleTensor (or name->array dict) in, list of
        PaddleTensor out, ordered like the saved fetch targets."""
        feed = {}
        if isinstance(inputs, dict):
            items = inputs.items()
        else:
            items = [(t.name, t.data) for t in inputs]
            for t in inputs:
                if t.lod is not None:
                    feed[t.name + "@LEN"] = np.asarray(t.lod, "int32")
        for name, data in items:
            if name not in self._feed_names and \
                    not name.endswith("@LEN"):
                raise ValueError(
                    "input %r is not a feed target of this model "
                    "(expected %s)" % (name, self._feed_names))
            if data is None:
                raise ValueError(
                    "input %r has no data (PaddleTensor.data is None)"
                    % name)
            feed[name] = data
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise ValueError("missing inputs: %s" % missing)
        if getattr(self._config, "serving", None) is not None:
            return self._run_serving(feed)
        # scope passed explicitly — scope_guard's global stack is not
        # thread-safe and clones run concurrently
        with self._mu:
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_vars,
                                 scope=self._scope)
        return [PaddleTensor(name=v.name, data=o)
                for v, o in zip(self._fetch_vars, outs)]

    def _run_serving(self, feed):
        """Engine-backed Run: the whole call becomes one micro-batch
        request (fixed-shape models) or one request per example
        (variable-length sequence models) through the shared
        continuous-batching engine — same outputs as the direct
        dispatch (deterministic inference program), but concurrent
        callers' work co-batches instead of serializing on the
        predictor lock."""
        engine = self.serving_engine()
        batch = max(int(np.shape(v)[0]) for n, v in feed.items()
                    if not n.endswith("@LEN"))
        # block until the engine decides: expiry is the engine's job
        # (every queued request is either served or timed out by it)
        if not engine._seq_feeds:
            # one micro-batch request per slot-capacity chunk
            step = engine.slots
            reqs = []
            for lo in range(0, batch, step):
                chunk = {n: np.asarray(v)[lo:lo + step]
                         for n, v in feed.items()}
                rows = min(step, batch - lo)
                if rows == 1:
                    chunk = {n: v[0] for n, v in chunk.items()}
                reqs.append(engine.submit(chunk, rows=rows))
            parts = [r.result() for r in reqs]
            outs = [np.concatenate(
                [p[j] if r.rows > 1 else np.asarray(p[j])[None]
                 for p, r in zip(parts, reqs)])
                for j in range(len(self._fetch_vars))]
            return [PaddleTensor(name=v.name, data=o)
                    for v, o in zip(self._fetch_vars, outs)]
        requests = []
        for i in range(batch):
            one = {}
            for n, v in feed.items():
                one[n] = np.asarray(v)[i] if not n.endswith("@LEN") \
                    else int(np.asarray(v)[i])
            requests.append(engine.submit(one))
        rows = [r.result() for r in requests]
        outs = [np.stack([row[j] for row in rows])
                for j in range(len(self._fetch_vars))]
        return [PaddleTensor(name=v.name, data=o)
                for v, o in zip(self._fetch_vars, outs)]

    # reference spells it Run/Clone; keep both casings
    Run = run

    def clone(self):
        """Per-thread copy sharing the immutable weights
        (api_impl.cc Clone)."""
        return PaddlePredictor(
            self._config,
            _shared=(self._program, self._feed_names, self._fetch_vars,
                     self._scope, self._engine_holder))

    Clone = clone

    @property
    def feed_names(self):
        return list(self._feed_names)

    @property
    def fetch_names(self):
        return [v.name for v in self._fetch_vars]


def create_paddle_predictor(config):
    """CreatePaddlePredictor<Config> factory."""
    return PaddlePredictor(config)
