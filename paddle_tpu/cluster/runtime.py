"""ClusterMember: one host's live session against the ClusterMaster.

Wraps the transport (a ``cloud.MasterClient`` over TCP, a raw
``host:port`` address, or a direct in-process ``ClusterMaster`` — the
unit-test path), keeps the lease alive from a daemon heartbeat thread,
and exposes the control-plane verbs the training loop needs:

* ``enter_step(step)`` — the lockstep dispatch gate; blocks (polling)
  until the master says ``go``, or returns the ``reshape`` /
  ``command`` decision the member must apply BEFORE dispatching;
* ``propose_verdict`` / ``ack_command`` — guardian arbitration
  (``cluster.ClusterGuardian`` drives these);
* ``request_save(step)`` — saver election for sharded-checkpoint
  manifest commits (plugs into
  ``TrainStateCheckpointManager(saver_elect=member.request_save)``).

The constructed member registers itself as the PROCESS-LOCAL member
(``local_member()``/``local_context()``): guardian events and watchdog
stall escalations stamp ``member_id`` + ``membership_epoch`` into their
JSONL records so cluster-level post-mortems correlate across host logs.
"""

import threading
import time

from ..monitor import tracing

__all__ = ["ClusterMember", "ClusterTimeout",
           "local_member", "local_context", "set_local_member"]


class ClusterTimeout(RuntimeError):
    """A barrier/poll deadline expired with no master decision."""


def _transport(t):
    """Normalize the transport to an object with ``call(method, *args)``:
    a MasterClient already has it; a direct service object gets a thin
    adapter; a ``host:port`` string builds a MasterClient."""
    if isinstance(t, str):
        from ..cloud.server import MasterClient

        t = MasterClient(t)
    if callable(getattr(t, "call", None)):
        return t

    class _Direct:
        def __init__(self, svc):
            self._svc = svc

        def call(self, method, *args):
            return getattr(self._svc, method)(*args)

        def close(self):
            pass

    return _Direct(t)


class ClusterMember:
    """One host's membership session.  ``auto_heartbeat`` (default)
    runs a daemon thread renewing the lease every ``lease_timeout/3``
    seconds; with it off the caller heartbeats explicitly (every
    ``enter_step`` also renews)."""

    def __init__(self, transport, host_id, meta=None,
                 auto_heartbeat=True, poll_interval=0.05,
                 register_local=True, heartbeat_meta=None):
        self._t = _transport(transport)
        self.host_id = str(host_id)
        self._poll = float(poll_interval)
        # optional provider of per-heartbeat meta (a serving replica's
        # live load report rides the lease renewal this way)
        self._hb_meta = heartbeat_meta
        self._mu = threading.Lock()
        self._closed = False
        self._expelled = False
        # the membership session's trace root: barrier/heartbeat spans
        # (and the rpc spans nested under them) all join this trace, so
        # a cross-host post-mortem assembles one tree per session.  The
        # open-anchor is emitted NOW — a killed host leaves a rooted
        # tree behind, not orphan spans.
        self._trace = (tracing.Span("cluster_session",
                                    attrs={"host_id": self.host_id})
                       if tracing.enabled() else None)
        if self._trace is not None:
            self._trace.emit_open()
        with tracing.use_span(self._trace):
            view = self._t.call("join", self.host_id, dict(meta or {}))
        self._epoch = int(view["epoch"])
        # the epoch of the world this host has BUILT (mesh, executors).
        # Distinct from _epoch (latest observed): the daemon heartbeat
        # may observe a death first and absorb the new epoch, but the
        # barrier must keep presenting the world the member actually
        # runs — otherwise the master sees matching epochs and answers
        # "go" into a dead world (a hung collective, the exact failure
        # the barrier exists to prevent).  accept_world() advances it
        # after the caller reshapes.
        self._world_epoch = int(view["epoch"])
        self._members = list(view["members"])
        self._lease = float(view.get("lease_timeout", 10.0))
        self.last_command_seq = 0
        # fleet telemetry (monitor.aggregate): lazily-built digest
        # builder; the disabled path pays one module-global bool read
        # per heartbeat and nothing else
        self._digest = None
        self._hb_stop = threading.Event()
        self._hb_thread = None
        if auto_heartbeat:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True,
                name="cluster-heartbeat-%s" % self.host_id)
            self._hb_thread.start()
        if register_local:
            set_local_member(self)

    # -- views ----------------------------------------------------------
    @property
    def epoch(self):
        return self._epoch

    @property
    def world_epoch(self):
        """The membership epoch this host's CURRENT world (mesh,
        executors) was built for — what the barrier presents."""
        return self._world_epoch

    @property
    def members(self):
        return list(self._members)

    def accept_world(self, epoch=None):
        """Mark a membership view as the world this host now runs: call
        after rebuilding the mesh for a reshape (or when a benign epoch
        move — a join at world formation — needs no rebuild).  Pass the
        EPOCH OF THE VIEW ACTED ON (the reshape response's): adopting
        the latest observed epoch instead would race the heartbeat
        daemon — a death absorbed during the rebuild must still surface
        as a fresh ``reshape``, not be accepted blind."""
        self._world_epoch = int(self._epoch if epoch is None else epoch)

    def _absorb(self, view):
        """Record a membership view; returns True when the epoch moved."""
        with self._mu:
            changed = int(view["epoch"]) != self._epoch
            self._epoch = int(view["epoch"])
            self._members = list(view.get("members", self._members))
            return changed

    # -- liveness -------------------------------------------------------
    @property
    def expelled(self):
        """True once the master reported this member's lease expired
        (``rejoin``): the host was expelled from the run and must not
        keep training/committing as a zombie — ``ClusterGuardian``
        turns this into a typed abort at the next step."""
        return self._expelled

    def heartbeat(self, step=None):
        """Renew the lease; returns the view (absorbing it).  A
        ``rejoin`` response latches ``expelled`` instead of being
        silently absorbed.  With a ``heartbeat_meta`` provider, its
        dict rides the renewal (merged master-side into the member's
        meta); without one the wire call keeps its two-arg shape.
        With fleet telemetry on (``FLAGS_fleet_telemetry``) a
        MetricDigest rides the same renewal under meta["digest"] — the
        digest baseline advances only after the master confirmed
        delivery, so a failed RPC just re-ships the delta."""
        from ..monitor import aggregate

        extra = self._hb_meta() if self._hb_meta is not None else None
        digest = None
        if aggregate._ENABLED:
            if self._digest is None:
                self._digest = aggregate.DigestBuilder(self.host_id)
            digest = self._digest.build()
            extra = dict(extra or {})
            extra["digest"] = digest
        with tracing.span("cluster/heartbeat", parent=self._trace,
                          attrs={"host_id": self.host_id}):
            if extra is not None:
                view = self._t.call("heartbeat", self.host_id, step,
                                    extra)
            else:
                view = self._t.call("heartbeat", self.host_id, step)
        if view.get("rejoin"):
            self._expelled = True
        elif digest is not None:
            self._digest.committed(digest["seq"])
        self._absorb(view)
        return view

    def fleet_view(self):
        """The master's one-pane fleet view (telemetry RPC verb)."""
        return self._t.call("fleet_view")

    def _hb_loop(self):
        interval = max(0.05, self._lease / 3.0)
        while not self._hb_stop.wait(interval):
            try:
                self.heartbeat()
            except Exception:  # noqa: BLE001 — transient master outages
                pass           # ride the client's own backoff next time

    # -- the lockstep dispatch gate ------------------------------------
    def enter_step(self, step, timeout=None):
        """Block (polling the master) until the cluster decides what
        this member does about ``step``:

        * ``{"action": "go"}`` — dispatch it;
        * ``{"action": "reshape", ...}`` — membership changed: the view
          is absorbed first, so ``self.epoch``/``members`` already
          describe the NEW world;
        * ``{"action": "command", "command": {...}}`` — apply the
          arbitration verdict at this boundary (then ack).

        Raises ``ClusterTimeout`` after ``timeout`` seconds of "wait"
        (None = poll forever)."""
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        # one barrier span covers the WHOLE poll (every enter_step rpc
        # nests under it): the span's duration IS the barrier wait
        with tracing.span("cluster/barrier", parent=self._trace,
                          attrs={"step": int(step),
                                 "epoch": self._world_epoch}) as bs:
            polls = 0
            while True:
                # present the WORLD epoch, not the latest observed one:
                # an epoch change first noticed by the heartbeat thread
                # must still surface here as "reshape" (_world_epoch)
                res = self._t.call("enter_step", self.host_id,
                                   int(step), self._world_epoch)
                polls += 1
                action = res.get("action")
                if action in ("reshape", "go", "command"):
                    if bs is not None:
                        bs.attrs.update(action=action, polls=polls)
                    if action == "reshape":
                        if res.get("rejoin"):
                            self._expelled = True
                        self._absorb(res)
                    return res
                if deadline is not None and time.monotonic() > deadline:
                    raise ClusterTimeout(
                        "member %s: no barrier decision for step %d "
                        "within %.1fs" % (self.host_id, step, timeout))
                time.sleep(self._poll)

    # -- arbitration ----------------------------------------------------
    def propose_verdict(self, step, kind, reason, quarantined=False):
        cmd = self._t.call("propose_verdict", self.host_id, int(step),
                           kind, str(reason), bool(quarantined))
        self.last_command_seq = max(self.last_command_seq,
                                    int(cmd["seq"]))
        return cmd

    def poll_command(self):
        cmd = self._t.call("poll_command", self.host_id,
                           self.last_command_seq)
        return cmd

    def ack_command(self, seq):
        self.last_command_seq = max(self.last_command_seq, int(seq))
        return self._t.call("ack_command", self.host_id, int(seq))

    # -- saver election -------------------------------------------------
    def request_save(self, step, block_secs=None):
        """True iff THIS member commits the sharded manifest for
        ``step`` — the ``saver_elect`` hook of
        ``TrainStateCheckpointManager``."""
        return bool(self._t.call("request_save", self.host_id,
                                 int(step), block_secs))

    # -- lifecycle ------------------------------------------------------
    def leave(self):
        """Graceful departure (bumps the epoch for the survivors)."""
        try:
            return self._t.call("leave", self.host_id)
        finally:
            self.close()

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._trace is not None:
            # terminal re-emit of the open-anchored session root:
            # assembly prefers it, a SIGKILLed host keeps the anchor
            self._trace.finish("ok", epoch=self._epoch)
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
        if local_member() is self:
            set_local_member(None)
        close = getattr(self._t, "close", None)
        if callable(close):
            try:
                close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# process-local member registration (guardian/monitor event stamping)
# ---------------------------------------------------------------------------

_LOCAL = None


def set_local_member(member):
    """Install ``member`` as the process's cluster identity (None
    clears).  Constructed members self-register."""
    global _LOCAL
    _LOCAL = member


def local_member():
    """The process's ClusterMember, or None outside a cluster run."""
    return _LOCAL


def local_context():
    """``{"member_id", "membership_epoch"}`` for JSONL correlation, or
    ``{}`` outside a cluster run — guardian events and watchdog stall
    escalations merge this in so cluster-level post-mortems can join
    per-host logs."""
    m = _LOCAL
    if m is None:
        return {}
    return {"member_id": m.host_id, "membership_epoch": m.epoch}
