"""Cluster runtime (ROADMAP item 3): the control-plane layer over the
multi-host mesh — run membership, cluster-scope guardian verdicts, and
elastic resume — the TPU rebuild of the reference's Go/etcd ``go/master``
control plane (task leases live in ``paddle_tpu.cloud``; this package
adds the layer that knows WHO is in the run).

* ``ClusterMaster`` (``membership.py``) — per-host heartbeat leases
  with membership **epochs**, verdict arbitration (one host's guardian
  escalation becomes ONE cluster-wide rollback/abort command), saver
  election for sharded-checkpoint manifest commits, and the lockstep
  step barrier that turns a host death into a ``reshape`` decision
  instead of a hung collective.  Snapshots ride any ``cloud.store``
  Store and are served by the unmodified ``cloud.MasterServer``.
* ``ClusterMember`` (``runtime.py``) — a host's live session: join,
  heartbeat thread, ``enter_step`` barrier, verdict propose/ack, saver
  election; registers the process-local identity that guardian events
  and watchdog stall escalations stamp into JSONL records.
* ``ClusterGuardian`` (``guardian_bridge.py``) — a ``Guardian`` whose
  rollback/abort verdicts are arbitrated by the master and whose
  ``note_step`` applies remote members' verdicts at the next step
  boundary.

Elastic resume is the composition: TrainState artifacts are
topology-free (PR 5) and sharded per host (this PR), so when the epoch
moves the survivors rebuild the mesh at the new size, restore the last
committed step through ``ParallelExecutor.state_shardings()``, and
continue — ``tests/cluster_runner.py`` is the reference harness.
"""

from .membership import ClusterMaster, Member  # noqa: F401
from .runtime import (  # noqa: F401
    ClusterMember,
    ClusterTimeout,
    local_member,
    local_context,
    set_local_member,
)
from .guardian_bridge import ClusterGuardian  # noqa: F401

__all__ = [
    "ClusterMaster", "Member",
    "ClusterMember", "ClusterTimeout",
    "local_member", "local_context", "set_local_member",
    "ClusterGuardian",
]
