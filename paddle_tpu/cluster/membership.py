"""ClusterMaster: run membership, cluster-scope verdicts, and saver
election — the control-plane half of the reference's Go/etcd cloud
layer (ROADMAP item 3).

The data-plane half already exists (``cloud.MasterService`` task
leases); what was missing is the layer that knows WHO is in the run and
arbitrates decisions that must win cluster-wide:

* **membership** — each host joins with a heartbeat lease; a lease that
  expires removes the member and bumps the **membership epoch**.  The
  epoch is the elastic-resume trigger: survivors that observe an epoch
  change rebuild the mesh at the new size and resume from the last
  committed checkpoint (``cluster.runtime`` / the drill harness).
  Deadlines live in the snapshotted state and are enforced lazily under
  the lock (``_sweep``), exactly like ``MasterService._expire_stale`` —
  a recovered master (new process, same Store) keeps honoring the leases
  the dead one granted.
* **verdict arbitration** — one host's guardian escalation
  (NaN/stall -> rollback/abort) becomes ONE cluster-wide command: the
  first proposal wins and every later proposal (or poll) returns the
  same command, so all members apply the same decision at the same
  committed-step boundary instead of each process deciding alone (the
  PR-6 follow-up).  Commands retire when every live member acked.
* **saver election** — ``request_save(host, step)`` elects exactly one
  committer per checkpoint step (the ``RequestSaveModel`` idiom),
  gating the manifest commit of a multi-host sharded artifact.
* **step barrier** — ``enter_step(host, step, epoch)`` is the dispatch
  gate for lockstep SPMD members: "go" only once every live member
  reached the step, "reshape" when the membership epoch moved while
  waiting, "command" when an arbitration verdict is pending.  The
  barrier is what keeps a survivor from dispatching a collective into a
  dead peer: the death is observed as a lease expiry at the barrier,
  never as a hung all-reduce.

State snapshots ride any ``cloud.store`` Store (InMemStore, FileStore —
the etcd analog); the service is served by the unmodified
``cloud.MasterServer`` via its ``rpc_methods()`` allowlist.
"""

import json
import threading
import time

__all__ = ["ClusterMaster", "Member"]


class Member:
    """One host's membership record: lease deadline + step progress."""

    __slots__ = ("host_id", "deadline", "joined_epoch", "last_step",
                 "meta")

    def __init__(self, host_id, deadline, joined_epoch=0, last_step=-1,
                 meta=None):
        self.host_id = str(host_id)
        self.deadline = float(deadline)
        self.joined_epoch = int(joined_epoch)
        self.last_step = int(last_step)
        self.meta = dict(meta or {})

    def to_dict(self):
        return {"host_id": self.host_id, "deadline": self.deadline,
                "joined_epoch": self.joined_epoch,
                "last_step": self.last_step, "meta": self.meta}

    @classmethod
    def from_dict(cls, d):
        return cls(d["host_id"], d["deadline"], d["joined_epoch"],
                   d["last_step"], d.get("meta"))

    def __repr__(self):
        return ("Member(%s, step=%d, epoch=%d)"
                % (self.host_id, self.last_step, self.joined_epoch))


class ClusterMaster:
    """Single-coordinator membership + arbitration service.

    ``lease_timeout`` bounds how long a silent host stays a member;
    heartbeats (and ``enter_step`` calls, which imply liveness) renew
    it.  The ``clock`` must be WALL time — deadlines are persisted in
    the snapshot and must stay comparable after a master restart."""

    def __init__(self, store=None, lease_timeout=10.0, clock=time.time,
                 save_block_secs=300.0):
        from ..cloud.store import InMemStore

        self.store = store or InMemStore()
        self.lease_timeout = float(lease_timeout)
        self.save_block_secs = float(save_block_secs)
        self._clock = clock
        self._mu = threading.RLock()

        self._members = {}         # host_id -> Member
        self._epoch = 0            # bumps on ANY membership change
        self._command = None       # active arbitration command (dict)
        self._command_seq = 0      # last issued command sequence number
        self._acks = set()         # host_ids that acked the active cmd
        self._savers = {}          # step -> {"host_id", "until"}
        self._last_snap = -1e18    # clock of the last persisted snapshot
        # optional fleet telemetry plane (monitor.aggregate): heartbeat
        # digests are popped from meta and fed here; lock ordering is
        # strictly master lock -> aggregator lock, never the reverse
        self._telemetry = None

        snap = self.store.load()
        if snap:
            self._restore(snap)

    # -- the server-side allowlist (cloud.server.service_methods) ------
    @staticmethod
    def rpc_methods():
        return ("join", "heartbeat", "leave", "membership", "enter_step",
                "propose_verdict", "poll_command", "ack_command",
                "request_save", "stats", "fleet_view")

    # -- fleet telemetry (ISSUE 19) ------------------------------------
    def attach_telemetry(self, aggregator):
        """Attach a ``monitor.aggregate.FleetAggregator``: heartbeat
        meta digests flow into it and membership exits notify it."""
        self._telemetry = aggregator

    def fleet_view(self):
        """The aggregator's one-pane fleet view (RPC verb), or a
        minimal membership-only view when no aggregator is attached."""
        agg = self._telemetry
        if agg is not None:
            return agg.fleet_view()
        with self._mu:
            self._sweep()
            return {"hosts": {}, "alerts": [],
                    "members": sorted(self._members),
                    "epoch": self._epoch}

    def _notify_expired(self, dead):
        """Lock held (caller is _sweep): tombstone expired members in
        the telemetry plane.  Never raises into the control plane."""
        agg = self._telemetry
        if agg is not None:
            try:
                agg.note_expired(dead)
            except Exception:
                pass

    # -- snapshot / recover --------------------------------------------
    def _snapshot(self, material=False):
        """Persist state to the Store.  ``material`` changes
        (membership/epoch/command/saver) always persist; pure deadline
        RENEWALS (every heartbeat and barrier poll is one) are
        rate-limited to once per lease_timeout/4 — with a FileStore
        that is otherwise two fsyncs per poll per member under the
        service lock, and recovery only needs deadlines fresh to well
        within one heartbeat interval (members renew every
        lease_timeout/3)."""
        now = self._clock()
        if not material and now - self._last_snap \
                < self.lease_timeout / 4.0:
            return
        self._last_snap = now
        state = {
            "members": {h: m.to_dict() for h, m in self._members.items()},
            "epoch": self._epoch,
            "command": self._command,
            "command_seq": self._command_seq,
            "acks": sorted(self._acks),
            "savers": {str(s): dict(e)
                       for s, e in self._savers.items()},
        }
        self.store.save(json.dumps(state).encode("utf-8"))

    def _restore(self, blob):
        state = json.loads(blob.decode("utf-8"))
        self._members = {h: Member.from_dict(d)
                         for h, d in state["members"].items()}
        self._epoch = int(state["epoch"])
        self._command = state.get("command")
        self._command_seq = int(state.get("command_seq", 0))
        self._acks = set(state.get("acks", ()))
        self._savers = {int(s): dict(e) for s, e in
                        state.get("savers", {}).items()}

    # -- membership -----------------------------------------------------
    def _sweep(self):
        """Expire members whose lease deadline passed.  Must hold the
        lock.  Returns True when the sweep changed membership (the
        epoch bumped)."""
        now = self._clock()
        dead = [h for h, m in self._members.items() if m.deadline <= now]
        for h in dead:
            del self._members[h]
        if dead:
            self._epoch += 1
            self._drop_member_state(dead)
            self._notify_expired(dead)
            self._count("cluster/lease_expired", len(dead))
            self._event({"event": "cluster_member_expired",
                         "members": dead, "epoch": self._epoch})
            self._snapshot(material=True)
        return bool(dead)

    def _drop_member_state(self, gone):
        """Release per-member side state held by departed hosts (lock
        held): a saver election pinned by a dead member would otherwise
        block EVERY survivor's commit for the whole block window — the
        step's checkpoint would silently never commit; and a command
        missing only dead members' acks must retire."""
        self._savers = {s: e for s, e in self._savers.items()
                        if e["host_id"] not in gone}
        self._retire_if_acked()

    def _view(self):
        """The membership view members act on (lock held)."""
        return {"epoch": self._epoch,
                "members": sorted(self._members),
                "lease_timeout": self.lease_timeout,
                "command_seq": self._command_seq}

    def join(self, host_id, meta=None):
        """Register (or re-register) ``host_id``; a NEW member bumps the
        membership epoch.  Returns the membership view."""
        host_id = str(host_id)
        if not host_id:
            raise ValueError("host id is empty")
        with self._mu:
            self._sweep()
            fresh = host_id not in self._members
            if fresh:
                self._epoch += 1
            self._members[host_id] = Member(
                host_id, self._clock() + self.lease_timeout,
                joined_epoch=self._epoch, meta=meta)
            if fresh:
                self._event({"event": "cluster_member_joined",
                             "member_id": host_id, "epoch": self._epoch})
            self._snapshot(material=fresh)
            return self._view()

    def heartbeat(self, host_id, step=None, meta=None):
        """Renew ``host_id``'s lease.  An expired (unknown) member gets
        ``{"rejoin": True}`` — its lease died, it must ``join`` again
        and treat the run as a fresh epoch.  ``meta`` (a serving
        replica's load report) MERGES into the member's meta — join-time
        identity keys (data-plane address, kind) survive load-only
        renewals.  A ``digest`` key in meta is the member's fleet
        telemetry payload (monitor.aggregate): it is popped OUT of the
        merge (digests must not bloat the persisted snapshot) and fed
        to the attached aggregator after the lease work — outside the
        service lock, so a slow merge never delays another member's
        renewal."""
        host_id = str(host_id)
        digest = meta.pop("digest", None) if meta else None
        with self._mu:
            self._sweep()
            m = self._members.get(host_id)
            if m is None:
                return dict(self._view(), rejoin=True)
            m.deadline = self._clock() + self.lease_timeout
            if step is not None:
                m.last_step = max(m.last_step, int(step))
            if meta:
                m.meta.update(meta)
            self._snapshot()
            view = self._view()
        agg = self._telemetry
        if agg is not None and digest is not None:
            try:
                agg.ingest(host_id, digest, meta=meta)
            except Exception:
                # telemetry must never break lease renewal
                pass
        return view

    def leave(self, host_id):
        """Graceful departure: removes the member, bumps the epoch."""
        with self._mu:
            self._sweep()
            if self._members.pop(str(host_id), None) is not None:
                self._epoch += 1
                self._drop_member_state([str(host_id)])
                if self._telemetry is not None:
                    try:
                        self._telemetry.drop_host(str(host_id))
                    except Exception:
                        pass
                self._event({"event": "cluster_member_left",
                             "member_id": str(host_id),
                             "epoch": self._epoch})
                self._snapshot(material=True)
            return self._view()

    def membership(self):
        with self._mu:
            self._sweep()
            return {"epoch": self._epoch,
                    "members": {h: m.to_dict()
                                for h, m in self._members.items()}}

    # -- step barrier ---------------------------------------------------
    def enter_step(self, host_id, step, epoch):
        """The lockstep dispatch gate.  ``epoch`` is the caller's known
        membership epoch.  Returns one of:

        * ``{"action": "reshape", ...view}`` — membership changed since
          the caller's epoch: rebuild the mesh before dispatching;
        * ``{"action": "command", "command": {...}}`` — an arbitration
          verdict is pending that this member has not acked: apply it
          at this boundary;
        * ``{"action": "go"}`` — every live member reached ``step``;
        * ``{"action": "wait"}`` — peers are still behind: poll again.

        Entering a step renews the lease (progress is liveness)."""
        host_id = str(host_id)
        step = int(step)
        with self._mu:
            self._sweep()
            m = self._members.get(host_id)
            if m is None:
                return dict(self._view(), action="reshape", rejoin=True)
            m.deadline = self._clock() + self.lease_timeout
            m.last_step = max(m.last_step, step)
            self._snapshot()
            if int(epoch) != self._epoch:
                return dict(self._view(), action="reshape")
            cmd = self._command
            if cmd is not None and host_id not in self._acks:
                return {"action": "command", "command": dict(cmd)}
            if all(p.last_step >= step for p in self._members.values()):
                return {"action": "go"}
            return {"action": "wait"}

    # -- verdict arbitration --------------------------------------------
    def propose_verdict(self, host_id, step, kind, reason,
                        quarantined=False):
        """One host's guardian escalation.  The FIRST proposal while no
        command is active wins and becomes the cluster command; any
        later proposal returns the active command unchanged — so every
        member, including late proposers, applies ONE decision.  The
        proposer is auto-acked (it applies its own verdict locally)."""
        host_id = str(host_id)
        if kind not in ("rollback", "abort"):
            raise ValueError("verdict kind must be rollback or abort, "
                             "got %r" % (kind,))
        with self._mu:
            self._sweep()
            if host_id not in self._members:
                # same guard as request_save: an expelled zombie's
                # escalation (raised before its heartbeat latched the
                # rejoin) must not roll every healthy member back
                raise ValueError(
                    "verdict from %r rejected: not a cluster member "
                    "(lease expired?) — the run has moved on without "
                    "this host" % host_id)
            if self._command is None:
                self._command_seq += 1
                self._command = {
                    "seq": self._command_seq, "step": int(step),
                    "kind": kind, "reason": str(reason),
                    "origin": host_id, "epoch": self._epoch,
                    "quarantined": bool(quarantined),
                }
                self._acks = set()
                self._count("cluster/verdicts")
                self._event({"event": "cluster_verdict",
                             "member_id": host_id, "step": int(step),
                             "kind": kind, "reason": str(reason),
                             "seq": self._command_seq,
                             "epoch": self._epoch})
            cmd = dict(self._command)
            self._ack(host_id)
            self._snapshot(material=True)
            return cmd

    def poll_command(self, host_id, last_seq=0):
        """The active command if ``host_id`` has not acked it and it is
        newer than ``last_seq``, else None."""
        with self._mu:
            self._sweep()
            cmd = self._command
            if cmd is None or cmd["seq"] <= int(last_seq) \
                    or str(host_id) in self._acks:
                return None
            return dict(cmd)

    def ack_command(self, host_id, seq):
        """Member ``host_id`` applied command ``seq``.  When every live
        member acked, the command retires (a new incident can then be
        arbitrated)."""
        with self._mu:
            self._sweep()
            cmd = self._command
            if cmd is None or int(seq) != cmd["seq"]:
                return False
            self._ack(str(host_id))
            self._snapshot(material=True)
            return True

    def _ack(self, host_id):
        """Lock held: record the ack, retire the command when all live
        members have applied it."""
        self._acks.add(host_id)
        self._retire_if_acked()

    def _retire_if_acked(self):
        cmd = self._command
        if cmd is None:
            return
        if all(h in self._acks for h in self._members):
            self._event({"event": "cluster_verdict_retired",
                         "seq": cmd["seq"], "kind": cmd["kind"],
                         "step": cmd["step"]})
            self._command = None
            self._acks = set()

    # -- saver election -------------------------------------------------
    def request_save(self, host_id, step, block_secs=None):
        """True iff ``host_id`` is the elected committer for checkpoint
        ``step`` (the RequestSaveModel idiom): the first requester of a
        step wins a ``block_secs`` window; everyone else writes shards
        but does NOT commit the manifest.  Elections are tracked PER
        STEP (async writer threads of different hosts can lag steps
        apart — a request for another step must not evict a live
        election, or two hosts end up committing the same artifact);
        expired entries are pruned on every call."""
        host_id = str(host_id)
        if not host_id:
            raise ValueError("host id is empty")
        step = int(step)
        block = float(block_secs if block_secs is not None
                      else self.save_block_secs)
        with self._mu:
            self._sweep()
            if host_id not in self._members:
                # an expelled (or never-joined) host must not win a
                # commit election: a zombie committing a manifest for a
                # world that reshaped without it corrupts the artifact
                return False
            now = self._clock()
            self._savers = {s: e for s, e in self._savers.items()
                            if e["until"] > now}
            cur = self._savers.get(step)
            elected = cur is None or cur["host_id"] == host_id
            if elected:
                self._savers[step] = {"host_id": host_id,
                                      "until": now + block}
                self._snapshot(material=True)
            return elected

    # -- observability --------------------------------------------------
    def stats(self):
        with self._mu:
            self._sweep()
            return {"epoch": self._epoch, "members": len(self._members),
                    "command_seq": self._command_seq,
                    "active_command": None if self._command is None
                    else dict(self._command),
                    "savers": {s: dict(e)
                               for s, e in self._savers.items()}}

    # master-side telemetry: enabled-gated counters/events through the
    # process monitor (a no-op unless the master's process monitors)
    @staticmethod
    def _count(name, amount=1):
        from .. import monitor

        monitor.count(name, amount)

    @staticmethod
    def _event(rec):
        from .. import monitor

        rec.setdefault("ts", time.time())
        monitor.log_event(rec)
