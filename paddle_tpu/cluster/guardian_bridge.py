"""ClusterGuardian: guardian escalations arbitrated cluster-wide.

The per-process ``guardian.Guardian`` decides alone — correct for one
host, wrong for a mesh: if host 3's spike detector fires and rolls back
while hosts 0-2 keep training, the run is corrupt (the PR-6 follow-up).
The ClusterGuardian closes that hole:

* a LOCAL escalation first proposes the verdict to the ClusterMaster;
  the master's arbitration (first proposal wins) returns THE cluster
  command — possibly another host's earlier verdict — and the ladder
  raises that command, not the local opinion;
* every ``note_step`` polls the master (every ``poll_every`` steps) so
  a REMOTE host's verdict reaches this member's training loop at its
  next step boundary as the same ``GuardianRollback``/abort the origin
  raised — all members recover to the same committed checkpoint;
* commands are acked after being raised, so the master retires them
  once every live member applied the decision.

The in-graph NaN/Inf skip needs no arbitration: the verdict is computed
on-device inside the SPMD program, so every host already skips the same
update deterministically.  Only host-side decisions (rollback ladders,
stall aborts) go through the master.
"""

from .. import guardian as _g

__all__ = ["ClusterGuardian"]


class ClusterGuardian(_g.Guardian):
    """A ``Guardian`` whose rollback/abort verdicts are cluster
    commands.  ``member`` is the host's ``ClusterMember``;
    ``poll_every`` sets how many completed steps may pass between
    remote-verdict polls (1 = every step; the poll is one tiny
    control-plane RPC, never a collective)."""

    def __init__(self, member, poll_every=1, **kwargs):
        super().__init__(**kwargs)
        self._member = member
        self._poll_every = max(1, int(poll_every))
        self._steps_since_poll = 0

    @property
    def member(self):
        return self._member

    # -- remote verdicts ------------------------------------------------
    def note_step(self, executor_name, step, **kwargs):
        if self._member.expelled:
            # the master expired this host's lease: the cluster has
            # already reshaped without it, so training on would commit
            # zombie updates — a typed exit, not a silent divergence
            raise _g.GuardianAbortError(
                "guardian: member %r was expelled from the cluster "
                "(lease expired; membership moved on) — aborting this "
                "host instead of training as a zombie"
                % self._member.host_id)
        self._steps_since_poll += 1
        if self._steps_since_poll >= self._poll_every:
            self._steps_since_poll = 0
            cmd = self._member.poll_command()
            if cmd is not None:
                self.apply_command(cmd)
        super().note_step(executor_name, step, **kwargs)

    def apply_command(self, cmd):
        """Raise the cluster command through the local ladder (acking it
        first — the raise IS this member applying the decision).  Also
        the entry point for commands delivered by the step barrier
        (``enter_step`` -> ``{"action": "command"}``)."""
        self._member.ack_command(cmd["seq"])
        self._event({"event": "guardian_cluster_command",
                     "seq": cmd["seq"], "kind": cmd["kind"],
                     "step": cmd["step"], "origin": cmd["origin"],
                     "reason": cmd["reason"]})
        reason = "cluster[%s]: %s" % (cmd["origin"], cmd["reason"])
        if cmd["kind"] == "rollback":
            raise _g.GuardianRollback(cmd["step"], reason,
                                      quarantined=cmd.get("quarantined",
                                                          False))
        raise _g.GuardianAbortError(
            "guardian: cluster abort at step %d (%s)"
            % (cmd["step"], reason))

    # -- local escalations route through the master ---------------------
    def _escalate(self, step, reason, quarantined):
        kind = "rollback" if "rollback" in self.policy else "abort"
        cmd = self._member.propose_verdict(step, kind, reason,
                                           quarantined=quarantined)
        # the master may hand back ANOTHER host's earlier verdict for
        # this incident — the cluster decision wins over the local one
        self.apply_command(cmd)
