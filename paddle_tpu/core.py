"""Core scalar/dtype definitions shared by the whole framework.

Plays the role of the reference's ``paddle/fluid/framework/framework.proto``
VarType/data-type enums (framework.proto:104) plus ``platform/float16.h`` —
but TPU-native: dtypes are numpy/jax dtypes, bfloat16 is first-class (the MXU
native format), and there is no protobuf in the hot path (programs serialize
to a plain-dict format in ``framework.py``).
"""

import numpy as np

try:  # jax's bfloat16 comes from ml_dtypes
    import ml_dtypes

    bfloat16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    bfloat16 = None


class VarType:
    """Variable kinds, mirroring the capability of VarDesc.VarType
    (reference framework.proto:104): dense tensors, parameter-like
    persistables, readers and step scopes are represented; LoD is replaced by
    packed segment metadata carried in ``Variable.lod_level`` plus explicit
    segment-id companions (see SURVEY.md §5 long-context notes)."""

    DENSE_TENSOR = "dense_tensor"
    SELECTED_ROWS = "selected_rows"  # sparse row-slice gradients
    READER = "reader"
    STEP_SCOPES = "step_scopes"
    RAW = "raw"


_DTYPE_ALIASES = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "int32": np.int32,
    "int64": np.int64,
    "int16": np.int16,
    "int8": np.int8,
    "uint8": np.uint8,
    "bool": np.bool_,
}
if bfloat16 is not None:
    _DTYPE_ALIASES["bfloat16"] = bfloat16


def convert_dtype(dtype):
    """Normalize user-provided dtype (str / np.dtype / jax dtype) to np.dtype."""
    if isinstance(dtype, str):
        if dtype not in _DTYPE_ALIASES:
            raise ValueError("unsupported dtype string: %r" % dtype)
        return np.dtype(_DTYPE_ALIASES[dtype])
    return np.dtype(dtype)


def long_dtype():
    """The canonical wide-integer dtype for in-graph index/count outputs.

    The reference emits int64 everywhere (framework.proto VarType INT64);
    under JAX with x64 disabled an explicit int64 request silently truncates
    to int32 and raises a UserWarning per call.  Policy: declared program
    dtype stays ``int64`` for API parity, but compute paths materialize
    ``int64`` only when x64 is enabled and ``int32`` otherwise — explicit,
    warning-free, and exact for every in-range value (ids/counts < 2^31).
    """
    import jax
    import jax.numpy as jnp

    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def materialize_dtype(dtype):
    """Dtype to materialize arrays with under the current x64 mode.

    64-bit requests (declared program dtypes keep int64/float64 for API
    parity with the reference) degrade explicitly to their 32-bit siblings
    when x64 is disabled, instead of relying on JAX's warn-and-truncate."""
    import jax

    d = convert_dtype(dtype)
    if not jax.config.jax_enable_x64:
        degrade = {np.dtype(np.int64): np.dtype(np.int32),
                   np.dtype(np.uint64): np.dtype(np.uint32),
                   np.dtype(np.float64): np.dtype(np.float32)}
        return degrade.get(d, d)
    return d


def dtype_is_floating(dtype):
    d = convert_dtype(dtype)
    if bfloat16 is not None and d == bfloat16:
        return True
    return np.issubdtype(d, np.floating)


def dtype_is_integer(dtype):
    d = convert_dtype(dtype)
    return np.issubdtype(d, np.integer) or d == np.bool_


class Tensor(object):
    """Host tensor shim (reference pybind ``core.Tensor`` surface:
    ``set``/``shape``/buffer protocol).  Device residency belongs to
    XLA; this stages a numpy array for feeding."""

    def __init__(self, array=None):
        self._array = None if array is None else np.asarray(array)

    def set(self, array, place=None):
        self._array = np.asarray(array)

    def shape(self):
        return () if self._array is None else tuple(self._array.shape)

    def _dtype(self):
        return None if self._array is None else self._array.dtype

    def __array__(self, dtype=None):
        if self._array is None:
            raise ValueError("Tensor is unset; call set() first")
        return (self._array.astype(dtype) if dtype is not None
                else self._array)
