"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid 0.15.0 (see SURVEY.md for the full capability map).

Public API mirrors the reference's ``paddle.fluid`` surface: Program/Block
graph building, layers DSL, program-level autodiff, optimizers, Executor,
ParallelExecutor (mesh runtime), io save/load, Trainer.  The implementation
is JAX/XLA/Pallas/pjit from the ground up.
"""

import jax as _jax

# Sharding-invariant in-graph PRNG.  With the legacy (non-partitionable)
# threefry lowering, jax.random bits generated INSIDE a computation that
# GSPMD partitions over a multi-axis mesh depend on the mesh shape: the
# same program/seed produced different dropout masks on a (2, 4) mesh
# than on one device or a 1-D dp mesh (reproduced at the raw-jax level;
# this was the long-standing sp/pp transformer loss-parity drift in
# tests/test_program_sp_pp.py).  The partitionable implementation makes
# random values a pure function of (key, shape) regardless of sharding —
# required for the mesh executor's single-device loss-parity contract.
# It is a different (still seed-deterministic) stream than the legacy
# one; nothing in this framework pins exact values across streams.
_jax.config.update("jax_threefry_partitionable", True)

from . import core, unique_name  # noqa: E402
from .framework import (
    Program,
    Block,
    Operator,
    Variable,
    Parameter,
    default_main_program,
    default_startup_program,
    program_guard,
    name_scope,
)
from . import ops  # registers the op library
from . import layers
from . import initializer
from . import regularizer
from . import clip
from . import optimizer
from . import metrics
from . import evaluator
from .evaluator import Evaluator
from . import nets
from .backward import append_backward, calc_gradient
from .executor import (Executor, CPUPlace, TPUPlace, CUDAPlace,
                       CUDAPinnedPlace)
from .scope import Scope, global_scope, scope_guard, _switch_scope
from .core import Tensor
from . import learning_rate_decay
from .param_attr import ParamAttr, WeightNormParamAttr
from .data_feeder import DataFeeder
from . import io
from . import monitor
from . import profiler
from . import parallel
from . import reader
from . import dataset
from . import contrib
from .reader import batch
from . import compat  # noqa: F401
from . import utils    # noqa: F401
from .parallel import ParallelExecutor, BuildStrategy, ExecutionStrategy
from .parallel.mesh import make_mesh
from . import transpiler
from .transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
    InferenceTranspiler,
    memory_optimize,
    release_memory,
)
from . import cloud
from . import inference
from . import debugger
from . import average
from . import lod_tensor
from . import net_drawer
from .lod_tensor import (create_lod_tensor, create_random_int_lodtensor,
                         LoDTensor, LoDTensorArray)
from . import recordio
from . import recordio_writer
from . import fault
from . import guardian
from . import autotune
from . import serving
from .flags import set_flags, get_flags

__version__ = "0.1.0"

__all__ = [
    "Program", "Block", "Operator", "Variable", "Parameter",
    "default_main_program", "default_startup_program", "program_guard",
    "name_scope", "layers", "initializer", "regularizer", "clip",
    "optimizer", "metrics", "nets", "append_backward", "calc_gradient",
    "Executor", "CPUPlace", "TPUPlace", "CUDAPlace", "CUDAPinnedPlace",
    "Scope", "Tensor", "LoDTensor", "LoDTensorArray",
    "learning_rate_decay",
    "global_scope", "scope_guard", "ParamAttr", "WeightNormParamAttr",
    "DataFeeder", "io", "monitor", "profiler", "parallel",
    "ParallelExecutor",
    "BuildStrategy", "ExecutionStrategy", "make_mesh", "reader",
    "dataset", "batch", "compat", "utils", "transpiler", "DistributeTranspiler",
    "DistributeTranspilerConfig", "InferenceTranspiler",
    "memory_optimize", "release_memory", "cloud", "set_flags", "get_flags",
    "fault", "guardian", "autotune", "serving",
    "recordio", "recordio_writer", "inference", "debugger",
    "average", "lod_tensor", "net_drawer", "create_lod_tensor",
    "create_random_int_lodtensor",
]
