"""v2 trainer (reference python/paddle/v2/trainer.py:37 SGD).

The reference SGD drives a swig GradientMachine + ParameterUpdater;
here it compiles the v2 graph's Program with the fluid-parity Executor
(one jit-compiled step function) and runs the same
pass/batch/event loop.  Updates land in the Parameters' scope, so the
user's Parameters object always reflects the trained weights."""

import collections.abc

import numpy as np

from ..clip import GradientClipByGlobalNorm, set_gradient_clip
from ..data_feeder import DataFeeder
from ..executor import Executor
from . import config as cfg
from . import event as v2_event
from . import optimizer as v2_optimizer
from . import parameters as v2_parameters
from .topology import Topology

__all__ = ["SGD"]


def default_event_handler(event):
    pass


class SGD(object):
    """Trainer combining data reader, topology and update rule
    (reference v2/trainer.py:37).  ``is_local=False`` pserver modes are
    a fold into the mesh runtime — use paddle_tpu.ParallelExecutor /
    the distribute transpiler for multi-host training (SURVEY §2.4)."""

    def __init__(self, cost, parameters, update_equation, extra_layers=None,
                 is_local=True, pserver_spec=None, use_etcd=True,
                 place=None):
        if not isinstance(parameters, v2_parameters.Parameters):
            raise TypeError("parameters should be parameters")
        if not isinstance(update_equation, v2_optimizer.Optimizer):
            raise TypeError("update equation parameter must be "
                            "paddle_tpu.v2.optimizer.Optimizer")
        if not is_local:
            raise NotImplementedError(
                "pserver mode is folded into the mesh runtime; see "
                "transpiler.DistributeTranspiler (SURVEY §2.4)")

        topology = Topology(cost, extra_layers=extra_layers)
        self.__topology__ = topology
        self.__parameters__ = parameters
        self.__optimizer__ = update_equation
        if place is None:
            from . import default_place
            place = default_place()
        self.__place__ = place

        # snapshot the forward graph for test()/infer before optimizer ops
        self.__test_program__ = topology.program.clone(for_test=True)

        if update_equation.gradient_clipping_threshold:
            set_gradient_clip(
                GradientClipByGlobalNorm(
                    update_equation.gradient_clipping_threshold),
                program=topology.program)
        opt = update_equation.to_optimizer()
        from ..framework import program_guard
        with program_guard(topology.program, topology.startup):
            opt.minimize(cost.var, startup_program=topology.startup)

        # startup now also initializes optimizer state; fill missing vars
        parameters.attach(topology, place=self.__place__)
        self.__exe__ = Executor(self.__place__)
        self.__cost__ = cost

    def get_topology_proto(self):
        return self.__topology__.proto()

    # -- feeding ----------------------------------------------------------

    def __feed_plan__(self, feeding):
        """[(data_layer, column_index)] ordered by column index."""
        layers = self.__topology__.data_layers
        if feeding is None:
            plan = list(zip(layers, range(len(layers))))
        else:
            by_name = {l.name: l for l in layers}
            plan = []
            for name, idx in feeding.items():
                if name not in by_name:
                    raise KeyError("feeding names unknown data layer %r"
                                   % name)
                plan.append((by_name[name], idx))
            plan.sort(key=lambda p: p[1])
        return plan

    def __make_feeder__(self, plan):
        return DataFeeder(
            feed_list=[l.var for l in plan_layers(plan)],
            place=self.__place__, program=self.__topology__.program)

    @staticmethod
    def __make_feed__(feeder, plan, data_batch):
        rows = [tuple(row[idx] for _, idx in plan) for row in data_batch]
        return feeder.feed(rows)

    def __evaluator_fetches__(self):
        return [(name, var, tr) for name, var, tr
                in self.__topology__.graph.evaluators]

    # -- training loop (reference trainer.py:137) --------------------------

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        if event_handler is None:
            event_handler = default_event_handler
        __check_train_args__(reader, event_handler)

        plan = self.__feed_plan__(feeding)
        feeder = self.__make_feeder__(plan)
        evals = self.__evaluator_fetches__()
        fetch_list = [self.__cost__.var.name] + [v.name for _, v, _ in evals]
        scope = self.__parameters__.scope

        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            pass_metrics, pass_n = {}, 0
            for batch_id, data_batch in enumerate(reader()):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                feed = self.__make_feed__(feeder, plan, data_batch)
                outs = self.__exe__.run(
                    self.__topology__.program, feed=feed,
                    fetch_list=fetch_list, scope=scope)
                cost = float(np.mean(np.asarray(outs[0])))
                metrics = {}
                for (name, _, tr), val in zip(evals, outs[1:]):
                    v = float(np.mean(np.asarray(val)))
                    metrics[name] = 1.0 - v if tr == "one_minus" else v
                event_handler(v2_event.EndForwardBackward(pass_id, batch_id))
                n = len(data_batch)
                pass_n += n
                for k, v in metrics.items():
                    pass_metrics[k] = pass_metrics.get(k, 0.0) + v * n
                event_handler(v2_event.EndIteration(
                    pass_id, batch_id, cost, metrics))
            event_handler(v2_event.EndPass(
                pass_id,
                metrics={k: v / max(pass_n, 1)
                         for k, v in pass_metrics.items()}))

    # -- evaluation (reference trainer.py:test) ----------------------------

    def test(self, reader, feeding=None):
        plan = self.__feed_plan__(feeding)
        feeder = self.__make_feeder__(plan)
        evals = self.__evaluator_fetches__()
        fetch_list = [self.__cost__.var.name] + [v.name for _, v, _ in evals]
        scope = self.__parameters__.scope

        total_cost, total_metrics, num_samples = 0.0, {}, 0
        for data_batch in reader():
            feed = self.__make_feed__(feeder, plan, data_batch)
            outs = self.__exe__.run(
                self.__test_program__, feed=feed, fetch_list=fetch_list,
                scope=scope)
            n = len(data_batch)
            num_samples += n
            total_cost += float(np.mean(np.asarray(outs[0]))) * n
            for (name, _, tr), val in zip(evals, outs[1:]):
                v = float(np.mean(np.asarray(val)))
                v = 1.0 - v if tr == "one_minus" else v
                total_metrics[name] = total_metrics.get(name, 0.0) + v * n
        num_samples = max(num_samples, 1)
        return v2_event.TestResult(
            metrics={k: v / num_samples for k, v in total_metrics.items()},
            cost=total_cost / num_samples)

    def save_parameter_to_tar(self, f):
        self.__parameters__.to_tar(f)


def plan_layers(plan):
    return [l for l, _ in plan]


def __check_train_args__(reader, event_handler):
    if not callable(reader) or not isinstance(
            reader(), collections.abc.Iterator):
        raise TypeError("train_data_reader should be a function "
                        "which returns an iterator")
    if not callable(event_handler):
        raise TypeError("event handler should be a function")
