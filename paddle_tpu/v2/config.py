"""Internal model-config state for the v2 API.

The reference v2 stack parses layer configs into a global ``ModelConfig``
proto (``python/paddle/trainer/config_parser.py`` ``g_config`` /
``python/paddle/v2/layer.py:1``).  Here the "config" IS the Program IR:
every ``paddle_tpu.v2.layer`` call appends ops to one process-global
Program pair, and ``Topology``/``Trainer``/``infer`` prune or clone it.
This replaces the v2 proto + GradientMachine pipeline with the same
Program objects the fluid-parity stack executes — one engine, two API
dialects (the fold README.md documents).
"""

import contextlib

from .. import framework


class Graph:
    """The v2 analog of config_parser's ``g_config``: one main+startup
    Program pair, the ordered data layers, and registered evaluators."""

    def __init__(self):
        self.main = framework.Program()
        self.startup = framework.Program()
        self.data_layers = []    # Layer objects for data inputs, in order
        self.evaluators = []     # (metric_name, Variable, transform) tuples


_graph = None


def graph():
    global _graph
    if _graph is None:
        _graph = Graph()
    return _graph


def reset():
    """Drop the global graph (tests / building a second model)."""
    global _graph
    _graph = None


@contextlib.contextmanager
def build():
    """Route fluid-parity layer calls into the v2 graph's programs."""
    g = graph()
    with framework.program_guard(g.main, g.startup):
        yield g


class Layer:
    """What every ``paddle_tpu.v2.layer.*`` call returns: a handle on the
    Variable the layer produced (reference ``v2/config_base.py`` Layer).
    ``v2_dim`` carries the logical width (data-type dim for data layers,
    output size for computed layers) so e.g. ``embedding`` can read its
    vocabulary size off its input, as the v2 API requires."""

    def __init__(self, var, data_type=None, v2_dim=None, parents=()):
        self.__var__ = var
        self.name = var.name
        self.data_type = data_type
        self.v2_dim = v2_dim
        self.parents = list(parents)

    @property
    def var(self):
        return self.__var__

    def __repr__(self):
        return "<v2.Layer %s>" % self.name


def unwrap(x):
    """Layer -> Variable (lists map elementwise)."""
    if isinstance(x, (list, tuple)):
        return [unwrap(i) for i in x]
    return x.var if isinstance(x, Layer) else x


def as_layers(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]
