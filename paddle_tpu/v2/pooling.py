"""v2 pooling objects (reference python/paddle/v2/pooling.py:1 wrapping
trainer_config_helpers/poolings.py).  Used both for sequence pooling
(``layer.pooling``) and image pooling (``layer.img_pool``)."""

__all__ = ["BasePool", "Max", "Avg", "Sum", "CudnnMax", "CudnnAvg"]


class BasePool(object):
    seq_type = None   # sequence_pool pooltype
    img_type = None   # pool2d pool_type

    def __repr__(self):
        return "pooling.%s()" % type(self).__name__


class Max(BasePool):
    seq_type = "max"
    img_type = "max"


class Avg(BasePool):
    seq_type = "average"
    img_type = "avg"


class Sum(BasePool):
    seq_type = "sum"
    img_type = "avg"  # no sum image pooling; reference maps via avg*N


CudnnMax = Max
CudnnAvg = Avg


def seq_pool_type(p):
    if isinstance(p, type) and issubclass(p, BasePool):
        p = p()
    if not isinstance(p, BasePool):
        raise TypeError("expected a paddle_tpu.v2.pooling object, got %r" % p)
    return p.seq_type


def img_pool_type(p):
    if isinstance(p, type) and issubclass(p, BasePool):
        p = p()
    if not isinstance(p, BasePool):
        raise TypeError("expected a paddle_tpu.v2.pooling object, got %r" % p)
    return p.img_type
