"""v2 parameter attributes (reference python/paddle/v2/attr.py:1).

``Param``/``ParamAttr`` forward onto the fluid-parity ``ParamAttr``;
``Extra``/``ExtraAttr`` carries layer-level extras (only ``drop_rate``
is meaningful on this stack — the rest were GPU scheduling hints)."""

from ..param_attr import ParamAttr as _FluidParamAttr

__all__ = ["Param", "ParamAttr", "Extra", "ExtraAttr", "Hook", "HookAttr"]


def ParamAttr(name=None, initial_std=None, initial_mean=None, is_static=None,
              l1_rate=None, l2_rate=None, learning_rate=None, momentum=None,
              gradient_clipping_threshold=None, sparse_update=None,
              initializer=None):
    """Build a fluid-parity ParamAttr from v2 keyword names.

    initial_mean/initial_std -> Normal initializer; l2_rate -> L2 decay;
    is_static -> trainable=False; sparse_update -> marks the consuming
    embedding for the SelectedRows sparse-grad path (the layer reads it).
    """
    from .. import initializer as init_mod
    from .. import regularizer

    kw = {}
    if momentum is not None:
        raise NotImplementedError(
            "per-parameter momentum override is a v1 updater feature with "
            "no fluid-parity analog; set momentum on the optimizer")
    if gradient_clipping_threshold is not None:
        # v1 clipped each gradient element into [-t, t] (legacy updater
        # clipping); the per-param GradientClipByValue hook is the analog
        from .. import clip as clip_mod
        kw["gradient_clip"] = clip_mod.GradientClipByValue(
            max=gradient_clipping_threshold,
            min=-gradient_clipping_threshold)
    if name is not None:
        kw["name"] = name
    if initializer is not None:
        kw["initializer"] = initializer
    elif initial_std is not None or initial_mean is not None:
        kw["initializer"] = init_mod.NormalInitializer(
            loc=initial_mean or 0.0, scale=initial_std
            if initial_std is not None else 0.01)
    if learning_rate is not None:
        kw["learning_rate"] = learning_rate
    if l2_rate is not None:
        kw["regularizer"] = regularizer.L2DecayRegularizer(l2_rate)
    elif l1_rate is not None:
        kw["regularizer"] = regularizer.L1DecayRegularizer(l1_rate)
    if is_static:
        kw["trainable"] = False
    attr = _FluidParamAttr(**kw)
    attr.sparse_update = bool(sparse_update)
    return attr


Param = ParamAttr


class ExtraAttr(object):
    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device


Extra = ExtraAttr


class HookAttr(object):
    """Parameter hook (reference attr.py HookAttribute) — pruning hooks
    are not supported on this stack; kept for signature parity."""

    def __init__(self, type=None, sparsity_ratio=None):
        self.type = type
        self.sparsity_ratio = sparsity_ratio


Hook = HookAttr
