"""v2 input-type declarations (reference python/paddle/v2/data_type.py:1
re-exporting trainer/PyDataProvider2.py InputType).

A data type = (dim, seq_type, value kind).  On TPU, sequence inputs
become padded ``[batch, time, ...]`` arrays with an ``@LEN`` companion
(see layers/io.py data); the InputType here records which conversion
``DataFeeder`` must apply and which shape/dtype the data layer declares.
"""

DENSE = 0
SPARSE_BINARY = 1
SPARSE_FLOAT = 2
INDEX = 3

NO_SEQUENCE = 0
SEQUENCE = 1
SUB_SEQUENCE = 2

__all__ = [
    "InputType", "dense_vector", "dense_array", "sparse_binary_vector",
    "sparse_float_vector", "integer_value", "dense_vector_sequence",
    "integer_value_sequence", "sparse_binary_vector_sequence",
    "sparse_float_vector_sequence",
]


class InputType(object):
    def __init__(self, dim, seq_type, tp):
        self.dim = dim
        self.seq_type = seq_type
        self.type = tp

    def __repr__(self):
        return "InputType(dim=%d, seq_type=%d, type=%d)" % (
            self.dim, self.seq_type, self.type)


def dense_vector(dim, seq_type=NO_SEQUENCE):
    return InputType(dim, seq_type, DENSE)


dense_array = dense_vector


def sparse_binary_vector(dim, seq_type=NO_SEQUENCE):
    return InputType(dim, seq_type, SPARSE_BINARY)


def sparse_float_vector(dim, seq_type=NO_SEQUENCE):
    return InputType(dim, seq_type, SPARSE_FLOAT)


def integer_value(value_range, seq_type=NO_SEQUENCE):
    return InputType(value_range, seq_type, INDEX)


def dense_vector_sequence(dim):
    return dense_vector(dim, seq_type=SEQUENCE)


def integer_value_sequence(value_range):
    return integer_value(value_range, seq_type=SEQUENCE)


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, seq_type=SEQUENCE)


def sparse_float_vector_sequence(dim):
    return sparse_float_vector(dim, seq_type=SEQUENCE)
