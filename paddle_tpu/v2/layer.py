"""The v2 layer DSL (reference python/paddle/v2/layer.py:1, which
auto-wraps trainer_config_helpers/layers.py).

Each function appends fluid-parity ops to the process-global v2 graph
(see config.py) and returns a ``Layer`` handle — the TPU-native redesign
of the v2 proto-config pipeline: instead of emitting a ``ModelConfig``
proto interpreted by the legacy GradientMachine
(``legacy/gserver/gradientmachines/GradientMachine.h:75``), the calls
build the same Program IR the rest of this framework jit-compiles.

The surface is the curated subset the v2 book/demo models use; layer
math (``+``/``-``/``*``) works through the underlying Variables.
"""

import math

from .. import layers as fl
from . import config as cfg
from .activation import act_name
from .data_type import INDEX, NO_SEQUENCE, SPARSE_BINARY, SPARSE_FLOAT
from .pooling import Max as _MaxPool
from .pooling import img_pool_type, seq_pool_type

__all__ = [
    "data", "fc", "embedding", "img_conv", "img_pool", "batch_norm",
    "dropout", "concat", "addto", "pooling", "first_seq", "last_seq",
    "cos_sim", "max_id", "classification_cost", "cross_entropy_cost",
    "multi_binary_label_cross_entropy_cost", "square_error_cost",
    "mse_cost", "regression_cost", "nce", "hsigmoid", "crf",
    "crf_decoding", "ctc", "lstmemory", "grumemory",
    "parse_network", "reset",
]

reset = cfg.reset


def _seq(dt):
    return dt is not None and dt.seq_type != NO_SEQUENCE


def data(name, type, height=None, width=None, **kwargs):
    """Input layer (reference v2/layer.py:105 __data_layer__).

    ``height``/``width`` hint the image geometry for ``img_conv`` on
    flat dense vectors (the v1 config carried them on the proto)."""
    if type.type in (SPARSE_BINARY, SPARSE_FLOAT):
        raise NotImplementedError(
            "sparse input vectors are a pserver-era format; feed dense "
            "vectors (SURVEY.md §2.4 sparse-input ruling)")
    with cfg.build() as g:
        if type.type == INDEX:
            var = fl.data(name, shape=[1], dtype="int64",
                          lod_level=1 if _seq(type) else 0)
        else:
            var = fl.data(name, shape=[type.dim], dtype="float32",
                          lod_level=1 if _seq(type) else 0)
        layer = cfg.Layer(var, data_type=type, v2_dim=type.dim)
        layer.height, layer.width = height, width
        g.data_layers.append(layer)
    return layer


def fc(input, size, act=None, param_attr=None, bias_attr=None, name=None,
       layer_attr=None):
    """reference trainer_config_helpers fc_layer -> fluid-parity fc."""
    inputs = cfg.as_layers(input)
    # v1 fc flattens everything after the batch axis — except sequence
    # inputs [B, T, D], where the projection applies per timestep
    nfd = 2 if _any_seq(inputs) else 1
    with cfg.build():
        var = fl.fc([l.var for l in inputs], size=size,
                    num_flatten_dims=nfd,
                    act=act_name(act), param_attr=param_attr,
                    bias_attr=bias_attr, name=name)
    return cfg.Layer(var, v2_dim=size, parents=inputs)


def _any_seq(layers):
    return any(getattr(l.var, "lod_level", 0) or
               getattr(l.var, "_seq_len_name", None) for l in layers)


def embedding(input, size, param_attr=None, name=None, layer_attr=None):
    """Table lookup; vocabulary = the input data layer's integer range
    (reference v2 embedding reads dim off the input's data type)."""
    if input.v2_dim is None:
        raise ValueError("embedding input must be an integer_value(_sequence)"
                         " data layer carrying its vocabulary size")
    sparse = bool(getattr(param_attr, "sparse_update", False))
    with cfg.build():
        var = fl.embedding(input.var, size=[input.v2_dim, size],
                           is_sparse=sparse, param_attr=param_attr)
    return cfg.Layer(var, v2_dim=size, parents=[input])


def _as_image(layer, num_channels):
    """Reshape a flat dense-vector layer to NCHW for conv/pool layers.
    Uses the data layer's height/width hints, else assumes square."""
    var = layer.var
    if len(var.shape) == 4:
        return var, var.shape[1]
    dim = layer.v2_dim
    h = getattr(layer, "height", None)
    w = getattr(layer, "width", None)
    # channel count: explicit, else derived from known h/w hints — which
    # must actually divide the layer's dim (a stale hint would otherwise
    # produce a wrong channel count and a confusing downstream reshape)
    if not num_channels and h and w:
        if dim % (h * w) != 0:
            raise ValueError(
                "height/width hints (%d x %d) do not divide the layer "
                "dim %d; fix the data layer's height=/width= or pass "
                "num_channels" % (h, w, dim))
        c = dim // (h * w)
    else:
        c = num_channels or 1
    if h and w and c * h * w != dim:
        raise ValueError(
            "channels x height x width = %d x %d x %d != layer dim %d"
            % (c, h, w, dim))
    if not (h and w):
        hw = int(round(math.sqrt(dim // c)))
        if c * hw * hw != dim:
            raise ValueError(
                "cannot infer image shape from dim=%d channels=%d; pass "
                "height=/width= to layer.data" % (dim, c))
        h = w = hw
    return fl.reshape(var, shape=[-1, c, h, w]), c


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, act=None, param_attr=None, bias_attr=None,
             groups=1, name=None, layer_attr=None):
    """reference img_conv_layer -> conv2d (NCHW surface; XLA lays out)."""
    with cfg.build():
        img, _c = _as_image(input, num_channels)
        var = fl.conv2d(img, num_filters=num_filters,
                        filter_size=filter_size, stride=stride,
                        padding=padding, groups=groups, act=act_name(act),
                        param_attr=param_attr, bias_attr=bias_attr,
                        name=name)
    out = cfg.Layer(var, parents=[input])
    out.v2_dim = None
    return out


def img_pool(input, pool_size, num_channels=None, pool_type=None, stride=1,
             padding=0, name=None, layer_attr=None):
    with cfg.build():
        img, _c = _as_image(input, num_channels)
        var = fl.pool2d(img, pool_size=pool_size,
                        pool_type=img_pool_type(pool_type or _MaxPool()),
                        pool_stride=stride, pool_padding=padding, name=name)
    return cfg.Layer(var, parents=[input])


def batch_norm(input, act=None, name=None, num_channels=None,
               param_attr=None, bias_attr=None, use_global_stats=None,
               moving_average_fraction=0.9, layer_attr=None):
    with cfg.build():
        var = fl.batch_norm(input.var, act=act_name(act), name=name,
                            param_attr=param_attr, bias_attr=bias_attr,
                            momentum=moving_average_fraction,
                            use_global_stats=bool(use_global_stats))
    return cfg.Layer(var, v2_dim=input.v2_dim, parents=[input])


def dropout(input, dropout_rate, name=None):
    with cfg.build():
        var = fl.dropout(input.var, dropout_prob=dropout_rate, name=name)
    return cfg.Layer(var, v2_dim=input.v2_dim, parents=[input])


def concat(input, act=None, name=None, layer_attr=None):
    inputs = cfg.as_layers(input)
    with cfg.build():
        var = fl.concat([l.var for l in inputs], axis=-1)
        if act_name(act):
            var = getattr(fl, act_name(act))(var)
    dims = [l.v2_dim for l in inputs]
    return cfg.Layer(var, v2_dim=sum(dims) if all(dims) else None,
                     parents=inputs)


def addto(input, act=None, bias_attr=None, name=None, layer_attr=None):
    if bias_attr:
        raise NotImplementedError("addto bias is not supported; add a "
                                  "fc(size=same, bias_attr=...) instead")
    inputs = cfg.as_layers(input)
    with cfg.build():
        var = fl.sums([l.var for l in inputs]) if len(inputs) > 1 \
            else inputs[0].var
        if act_name(act):
            var = getattr(fl, act_name(act))(var)
    return cfg.Layer(var, v2_dim=inputs[0].v2_dim, parents=inputs)


def pooling(input, pooling_type=None, agg_level=None, name=None,
            layer_attr=None):
    """Sequence pooling over the padded time axis (reference
    pooling_layer; LoD-free — the @LEN companion masks padding)."""
    with cfg.build():
        var = fl.sequence_pool(
            input.var, pool_type=seq_pool_type(pooling_type or _MaxPool()))
    return cfg.Layer(var, v2_dim=input.v2_dim, parents=[input])


def first_seq(input, name=None, **kwargs):
    with cfg.build():
        var = fl.sequence_first_step(input.var)
    return cfg.Layer(var, v2_dim=input.v2_dim, parents=[input])


def last_seq(input, name=None, **kwargs):
    with cfg.build():
        var = fl.sequence_last_step(input.var)
    return cfg.Layer(var, v2_dim=input.v2_dim, parents=[input])


def cos_sim(a, b, scale=1, name=None, layer_attr=None):
    """Cosine similarity (reference cos_sim layer; the v2 recommender
    demo's matching score)."""
    with cfg.build():
        var = fl.cos_sim(a.var, b.var)
        if scale != 1:
            var = var * float(scale)
    return cfg.Layer(var, v2_dim=1, parents=[a, b])


def max_id(input, name=None, layer_attr=None):
    """reference maxid_layer -> argmax over the class axis."""
    with cfg.build():
        var = fl.argmax(input.var, axis=-1)
    return cfg.Layer(var, parents=[input])


def lstmemory(input, size=None, reverse=False, act=None, gate_act=None,
              state_act=None, bias_attr=None, param_attr=None, name=None,
              layer_attr=None):
    """reference lstmemory (legacy hl_cuda_lstm.cu fused kernel) ->
    scan-based dynamic_lstm.  v2 feeds it a pre-projected input of
    4*size width (the mixed/fc layer before it)."""
    size = size or (input.v2_dim // 4 if input.v2_dim else None)
    if size is None:
        raise ValueError("lstmemory needs size= or a sized input layer")
    with cfg.build():
        h, _c = fl.dynamic_lstm(
            input.var, size=size * 4, is_reverse=reverse,
            param_attr=param_attr, bias_attr=bias_attr,
            candidate_activation=act_name(act) or "tanh",
            gate_activation=act_name(gate_act) or "sigmoid",
            cell_activation=act_name(state_act) or "tanh")
    return cfg.Layer(h, v2_dim=size, parents=[input])


def grumemory(input, size=None, reverse=False, act=None, gate_act=None,
              bias_attr=None, param_attr=None, name=None, layer_attr=None):
    size = size or (input.v2_dim // 3 if input.v2_dim else None)
    if size is None:
        raise ValueError("grumemory needs size= or a sized input layer")
    with cfg.build():
        h = fl.dynamic_gru(
            input.var, size=size, is_reverse=reverse,
            param_attr=param_attr, bias_attr=bias_attr,
            candidate_activation=act_name(act) or "tanh",
            gate_activation=act_name(gate_act) or "sigmoid")
    return cfg.Layer(h, v2_dim=size, parents=[input])


# ---- cost layers ----------------------------------------------------------

def _register_classification_error(g, input, label, name):
    name = name or "classification_error_evaluator"
    acc = fl.accuracy(input=input.var, label=label.var)
    # last registration under a name wins (re-registering the same metric
    # must not fetch two accuracy subgraphs per step)
    g.evaluators = [e for e in g.evaluators if e[0] != name]
    g.evaluators.append((name, acc, "one_minus"))
    return acc


def classification_cost(input, label, weight=None, name=None,
                        evaluator=None, layer_attr=None):
    """Softmax-input cross entropy + auto-registered classification-error
    evaluator (reference trainer_config_helpers classification_cost)."""
    if weight is not None:
        raise NotImplementedError("weighted classification_cost")
    with cfg.build() as g:
        ce = fl.cross_entropy(input=input.var, label=label.var)
        cost = fl.mean(ce)
        _register_classification_error(g, input, label, None)
    return cfg.Layer(cost, parents=[input, label])


def cross_entropy_cost(input, label, name=None, coeff=1.0, weight=None,
                       layer_attr=None):
    with cfg.build():
        ce = fl.cross_entropy(input=input.var, label=label.var)
        cost = fl.mean(ce)
        if coeff != 1.0:
            cost = cost * coeff
    return cfg.Layer(cost, parents=[input, label])


def multi_binary_label_cross_entropy_cost(input, label, name=None,
                                          coeff=1.0, layer_attr=None):
    from ..layer_helper import LayerHelper
    with cfg.build():
        helper = LayerHelper("multi_binary_label_cross_entropy")
        ce = helper.create_variable_for_type_inference(input.var.dtype)
        helper.append_op(
            type="sigmoid_cross_entropy_with_logits",
            inputs={"X": [input.var], "Label": [label.var]},
            outputs={"Out": [ce]},
        )
        cost = fl.mean(ce)
        if coeff != 1.0:
            cost = cost * coeff
    return cfg.Layer(cost, parents=[input, label])


def square_error_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    with cfg.build():
        sq = fl.square_error_cost(input=input.var, label=label.var)
        cost = fl.mean(sq)
        if coeff != 1.0:
            cost = cost * coeff
    return cfg.Layer(cost, parents=[input, label])


mse_cost = square_error_cost
regression_cost = square_error_cost


def nce(input, label, num_classes, param_attr=None, weight=None,
        num_neg_samples=10, neg_distribution=None, bias_attr=None,
        name=None, layer_attr=None):
    if weight is not None or neg_distribution is not None:
        raise NotImplementedError(
            "nce weight=/neg_distribution= (uniform sampling only, as "
            "ops/sampled_loss.py implements)")
    inputs = cfg.as_layers(input)
    with cfg.build():
        x = fl.concat([l.var for l in inputs], axis=-1) \
            if len(inputs) > 1 else inputs[0].var
        cost = fl.nce(input=x, label=label.var, num_total_classes=num_classes,
                      param_attr=param_attr, bias_attr=bias_attr,
                      num_neg_samples=num_neg_samples)
        cost = fl.mean(cost)
    return cfg.Layer(cost, parents=inputs + [label])


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, layer_attr=None):
    inputs = cfg.as_layers(input)
    with cfg.build():
        x = fl.concat([l.var for l in inputs], axis=-1) \
            if len(inputs) > 1 else inputs[0].var
        cost = fl.hsigmoid(input=x, label=label.var,
                           num_classes=num_classes, param_attr=param_attr,
                           bias_attr=bias_attr)
        cost = fl.mean(cost)
    return cfg.Layer(cost, parents=inputs + [label])


def crf(input, label, size=None, param_attr=None, name=None,
        layer_attr=None):
    with cfg.build():
        ll = fl.linear_chain_crf(input=input.var, label=label.var,
                                 param_attr=param_attr)
        cost = fl.mean(ll)
    return cfg.Layer(cost, parents=[input, label])


def crf_decoding(input, size=None, label=None, param_attr=None, name=None,
                 layer_attr=None):
    with cfg.build():
        path = fl.crf_decoding(
            input=input.var, param_attr=param_attr,
            label=None if label is None else label.var)
    return cfg.Layer(path, parents=[input] + ([label] if label else []))


def ctc(input, label, size=None, name=None, norm_by_times=False,
        layer_attr=None):
    with cfg.build():
        cost = fl.warpctc(input=input.var, label=label.var,
                          norm_by_times=norm_by_times)
        cost = fl.mean(cost)
    return cfg.Layer(cost, parents=[input, label])


def parse_network(*outputs):
    """Return the Program holding the given output layers (reference
    v2/layer.py parse_network returns the pruned ModelConfig proto)."""
    from .topology import Topology
    return Topology(list(outputs)).program
