"""v2 activation objects (reference python/paddle/v2/activation.py:1
wrapping trainer_config_helpers/activations.py).  Each maps to the
activation-op name the fluid-parity LayerHelper appends."""

__all__ = [
    "Base", "Tanh", "Sigmoid", "Softmax", "Identity", "Linear", "Relu",
    "BRelu", "SoftRelu", "STanh", "Abs", "Square", "Exp", "Log",
]


class Base(object):
    name = None  # fluid-parity activation op type; None = linear

    def __repr__(self):
        return "activation.%s()" % type(self).__name__


class Tanh(Base):
    name = "tanh"


class Sigmoid(Base):
    name = "sigmoid"


class Softmax(Base):
    name = "softmax"


class Identity(Base):
    name = None


Linear = Identity


class Relu(Base):
    name = "relu"


class BRelu(Base):
    name = "brelu"


class SoftRelu(Base):
    name = "soft_relu"


class STanh(Base):
    name = "stanh"


class Abs(Base):
    name = "abs"


class Square(Base):
    name = "square"


class Exp(Base):
    name = "exp"


class Log(Base):
    name = "log"


def act_name(act):
    """activation object (or None / raw string) -> op-type string."""
    if act is None or isinstance(act, str):
        return act
    if isinstance(act, Base):
        return act.name
    if isinstance(act, type) and issubclass(act, Base):
        return act.name
    raise TypeError("expected a paddle_tpu.v2.activation object, got %r"
                    % (act,))
