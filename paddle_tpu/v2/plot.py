"""Training-curve plotter (reference python/paddle/v2/plot/plot.py:1).

Collects (step, value) series per title; ``plot()`` renders with
matplotlib when available and DISABLE_PLOT is unset, else is a no-op
(the reference gates identically for headless CI)."""

import os

__all__ = ["Ploter"]


class PlotData(object):
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter(object):
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {t: PlotData() for t in args}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT", "False")
        try:
            if not self.__plot_is_disabled__():
                import matplotlib.pyplot as plt
                from IPython import display
                self.plt = plt
                self.display = display
        except ImportError:
            self.__disable_plot__ = "True"

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == "True"

    def append(self, title, step, value):
        assert isinstance(title, str)
        assert title in self.__plot_data__
        self.__plot_data__[title].append(step, value)

    def data(self, title):
        return self.__plot_data__[title]

    def plot(self, path=None):
        if self.__plot_is_disabled__():
            return
        titles = []
        for title in self.__args__:
            data = self.__plot_data__[title]
            if len(data.step) > 0:
                self.plt.plot(data.step, data.value)
                titles.append(title)
        self.plt.legend(titles, loc="upper left")
        if path is None:
            self.display.clear_output(wait=True)
            self.display.display(self.plt.gcf())
        else:
            self.plt.savefig(path)
        self.plt.gcf().clear()

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
