"""v2 evaluators (reference python/paddle/v2/evaluator.py:1 wrapping
trainer_config_helpers/evaluators.py).  An evaluator registers an
in-graph metric op whose per-batch value the trainer surfaces through
``event.metrics``."""

from .. import layers as fl
from . import config as cfg

__all__ = ["classification_error", "auc", "value_printer"]


def classification_error(input, label, name=None, **kwargs):
    """Error rate = 1 - accuracy (reference
    classification_error_evaluator)."""
    from .layer import _register_classification_error

    with cfg.build() as g:
        acc = _register_classification_error(g, input, label, name)
    return cfg.Layer(acc, parents=[input, label])


def auc(input, label, name=None, **kwargs):
    with cfg.build() as g:
        auc_var, _ = fl.auc(input=input.var, label=label.var)
        g.evaluators.append((name or "auc_evaluator", auc_var, None))
    return cfg.Layer(auc_var, parents=[input, label])


def value_printer(input, name=None):
    """Register a layer's mean value as a metric (reference
    value_printer_evaluator prints activations; here it reports the
    batch mean through event.metrics)."""
    with cfg.build() as g:
        m = fl.mean(input.var)
        g.evaluators.append((name or ("value_printer_" + input.name), m,
                             None))
    return cfg.Layer(m, parents=[input])
