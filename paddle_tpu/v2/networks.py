"""v2 composite networks (reference python/paddle/v2/networks.py:1
wrapping trainer_config_helpers/networks.py)."""

from .. import layers as fl
from .. import nets as fnets
from . import config as cfg
from . import layer as v2_layer
from .activation import act_name

__all__ = [
    "simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
    "simple_lstm", "simple_gru", "bidirectional_lstm",
    "simple_attention", "dot_product_attention",
]


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, num_channel=None,
                         pool_type="max", **kwargs):
    """conv + pool block (reference networks.py simple_img_conv_pool)."""
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channel)
        var = fnets.simple_img_conv_pool(
            img, num_filters=num_filters, filter_size=filter_size,
            pool_size=pool_size, pool_stride=pool_stride,
            act=act_name(act), pool_type=pool_type)
    return cfg.Layer(var, parents=[input])


def img_conv_group(input, conv_num_filter, conv_filter_size=3,
                   pool_size=2, pool_stride=2, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   num_channels=None, pool_type="max", **kwargs):
    with cfg.build():
        img, _c = v2_layer._as_image(input, num_channels)
        var = fnets.img_conv_group(
            img, conv_num_filter=conv_num_filter,
            conv_filter_size=conv_filter_size, pool_size=pool_size,
            pool_stride=pool_stride, conv_act=act_name(conv_act),
            conv_with_batchnorm=conv_with_batchnorm,
            conv_batchnorm_drop_rate=conv_batchnorm_drop_rate,
            pool_type=pool_type)
    return cfg.Layer(var, parents=[input])


def sequence_conv_pool(input, context_len, hidden_size, act=None,
                       pool_type="max", **kwargs):
    """text conv block (reference networks.py sequence_conv_pool);
    context_len/hidden_size follow the v1 argument names."""
    with cfg.build():
        var = fnets.sequence_conv_pool(
            input.var, num_filters=hidden_size, filter_size=context_len,
            act=act_name(act) or "tanh", pool_type=pool_type)
    return cfg.Layer(var, v2_dim=hidden_size, parents=[input])


def simple_lstm(input, size, reverse=False, act=None, gate_act=None,
                state_act=None, mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None, **kwargs):
    """fc projection + lstmemory (reference networks.py simple_lstm)."""
    mixed = v2_layer.fc(input, size=size * 4, act=None,
                        param_attr=mat_param_attr, bias_attr=False)
    return v2_layer.lstmemory(
        mixed, size=size, reverse=reverse, act=act, gate_act=gate_act,
        state_act=state_act, param_attr=inner_param_attr,
        bias_attr=bias_param_attr)


def simple_gru(input, size, reverse=False, act=None, gate_act=None,
               **kwargs):
    mixed = v2_layer.fc(input, size=size * 3, act=None, bias_attr=False)
    return v2_layer.grumemory(mixed, size=size, reverse=reverse, act=act,
                              gate_act=gate_act)


def bidirectional_lstm(input, size, return_seq=False, **kwargs):
    """fwd + bwd simple_lstm, concatenated (reference
    networks.py bidirectional_lstm)."""
    fwd = simple_lstm(input, size=size)
    bwd = simple_lstm(input, size=size, reverse=True)
    if return_seq:
        return v2_layer.concat([fwd, bwd])
    f_last = v2_layer.last_seq(fwd)
    b_last = v2_layer.first_seq(bwd)
    return v2_layer.concat([f_last, b_last])


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     decoder_size=None, **kwargs):
    """Bahdanau attention context (reference networks.py
    simple_attention; math in paddle_tpu.nets.simple_attention)."""
    size = decoder_size or decoder_state.v2_dim
    if size is None:
        raise ValueError("simple_attention needs decoder_size= or a "
                         "sized decoder_state layer")
    with cfg.build():
        var = fnets.simple_attention(encoded_sequence.var,
                                     encoded_proj.var,
                                     decoder_state.var, size)
    return cfg.Layer(var, v2_dim=encoded_sequence.v2_dim,
                     parents=[encoded_sequence, encoded_proj,
                              decoder_state])


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, **kwargs):
    """Dot-product attention context (reference networks.py
    dot_product_attention)."""
    with cfg.build():
        var = fnets.dot_product_attention(encoded_sequence.var,
                                          attended_sequence.var,
                                          transformed_state.var)
    return cfg.Layer(var, v2_dim=attended_sequence.v2_dim,
                     parents=[encoded_sequence, attended_sequence,
                              transformed_state])
