"""Topology: the set of layers reachable from the output layers
(reference python/paddle/v2/topology.py:1, which serializes a pruned
ModelConfig proto).  Here it is a view over the global v2 graph's
Program plus the ordered data layers — pruning happens lazily via
``Program.prune_feed_fetch`` when a trainer/inferencer compiles."""

from . import config as cfg

__all__ = ["Topology"]


class Topology(object):
    def __init__(self, layers, extra_layers=None):
        self.layers = cfg.as_layers(layers) + cfg.as_layers(extra_layers)
        if not self.layers:
            raise ValueError("Topology needs at least one output layer")
        g = cfg.graph()
        for l in self.layers:
            if l.var.block.program is not g.main:
                raise ValueError(
                    "layer %s belongs to a reset v2 graph; rebuild the "
                    "model after v2.layer.reset()" % l.name)
        self.graph = g
        self.program = g.main
        self.startup = g.startup
        self.data_layers = list(g.data_layers)

    def data_type(self):
        """[(name, InputType)] in declaration order (reference
        topology.py:data_type) — the default feeding order."""
        return [(l.name, l.data_type) for l in self.data_layers]

    def data_layer_names(self):
        return [l.name for l in self.data_layers]

    def get_layer(self, name):
        for l in self.layers + self.data_layers:
            if l.name == name:
                return l
        return None

    def proto(self):
        """Serializable form (the ProgramDesc JSON replaces the v2
        ModelConfig proto)."""
        return self.program.to_dict()
