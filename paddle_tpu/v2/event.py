"""Training/testing events (reference python/paddle/v2/event.py:1).

``metrics`` is a plain name->float dict computed from the in-graph
evaluator ops the cost layers registered (the reference reads them off
a swig Evaluator; there is no gm object on this stack, so ``gm`` is
kept as an attribute but is always None)."""

__all__ = [
    "EndIteration", "BeginIteration", "BeginPass", "EndPass", "TestResult",
    "EndForwardBackward",
]


class WithMetric(object):
    def __init__(self, metrics):
        self.metrics = dict(metrics or {})


class TestResult(WithMetric):
    """What trainer.test returns."""

    def __init__(self, metrics, cost):
        super(TestResult, self).__init__(metrics)
        self.cost = cost


class BeginPass(object):
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, metrics=None, gm=None):
        self.pass_id = pass_id
        self.gm = gm
        WithMetric.__init__(self, metrics)


class BeginIteration(object):
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndForwardBackward(object):
    def __init__(self, pass_id, batch_id, gm=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.gm = gm


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, metrics=None, gm=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.gm = gm
        WithMetric.__init__(self, metrics)
