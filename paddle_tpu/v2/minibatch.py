"""Batched reader (reference python/paddle/v2/minibatch.py:17)."""

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=True):
    """Group a sample reader into lists of ``batch_size`` samples.
    Delegates to the shared reader decorator; only the reference's
    surprising drop_last=True default differs."""
    from ..reader import batch as _batch

    return _batch(reader, batch_size, drop_last=drop_last)
