"""Batched reader (reference python/paddle/v2/minibatch.py:17)."""

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=True):
    """Group a sample reader into lists of ``batch_size`` samples.
    Note the reference's surprising default drop_last=True is kept."""

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if not drop_last and b:
            yield b

    return batch_reader
