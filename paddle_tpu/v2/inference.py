"""v2 inference (reference python/paddle/v2/inference.py:1).

``infer(output_layer=..., parameters=..., input=...)`` prunes the v2
graph to the requested outputs, feeds the batch, and returns numpy
results — the GradientMachine forward pass replaced by one jit-compiled
pruned Program."""

import numpy as np

from ..data_feeder import DataFeeder
from ..executor import Executor
from . import config as cfg
from .topology import Topology

__all__ = ["infer", "Inference"]


class Inference(object):
    def __init__(self, output_layer, parameters, place=None):
        self.outputs = cfg.as_layers(output_layer)
        topo = Topology(self.outputs)
        self.topology = topo
        self.parameters = parameters
        if place is None:
            from . import default_place
            place = default_place()
        self.place = place
        if parameters._topology is None:
            parameters.attach(topo, place=self.place)

        out_names = [l.name for l in self.outputs]
        all_feed = []
        for l in topo.data_layers:
            all_feed.append(l.name)
            if getattr(l.var, "_seq_len_name", None):
                all_feed.append(l.var._seq_len_name)
        pruned = topo.program.clone(for_test=True).prune_feed_fetch(
            all_feed, out_names)
        # only data layers some op in the pruned program actually consumes
        # are fed (prune keeps all feed vars in the block, even orphans)
        consumed = set()
        for op in pruned.global_block().ops:
            consumed.update(op.input_arg_names)
        self.data_layers = [
            l for l in topo.data_layers if l.name in consumed
        ]
        self.program = pruned
        self.exe = Executor(self.place)

    def infer(self, input, feeding=None, field="value"):
        if field not in ("value", None):
            raise NotImplementedError(
                "only field='value' is supported; take argmax of the "
                "returned probabilities for ids (reference field='id')")
        layers = self.data_layers
        if feeding is None:
            plan = list(zip(layers, range(len(layers))))
        else:
            known = {l.name for l in self.topology.data_layers}
            unknown = set(feeding) - known
            if unknown:
                raise KeyError("feeding names unknown data layer(s) %s"
                               % sorted(unknown))
            by_name = {l.name: l for l in layers}
            # names pruned away (e.g. the label column) are dropped
            plan = sorted(((by_name[n], i) for n, i in feeding.items()
                           if n in by_name), key=lambda p: p[1])
        feeder = DataFeeder(feed_list=[l.var for l, _ in plan],
                            place=self.place, program=self.topology.program)
        rows = [tuple(row[idx] for _, idx in plan) for row in input]
        feed = feeder.feed(rows)
        outs = self.exe.run(
            self.program, feed=feed,
            fetch_list=[l.name for l in self.outputs],
            scope=self.parameters.scope)
        outs = [np.asarray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs


def infer(output_layer, parameters, input, feeding=None, field="value"):
    return Inference(output_layer, parameters).infer(
        input, feeding=feeding, field=field)
