"""v2 Parameters: a dict-like view of the model's trainable parameters
(reference python/paddle/v2/parameters.py:44).

The reference object mirrors GradientMachine parameter buffers; here it
owns a Scope — the same store the executors run against — so trainer
updates are visible through it with no copying.  ``to_tar``/``from_tar``
keep the v2 archive workflow (one member per parameter; numpy .npy
replaces the v1 binary layout, documented in the archive's meta member).
"""

import io as _io
import json
import tarfile

import numpy as np

from ..executor import CPUPlace, Executor
from ..scope import Scope

__all__ = ["Parameters", "create"]

_META_MEMBER = "__meta__.json"


def create(*layers):
    """Create Parameters for the topology ending at ``layers`` (reference
    parameters.py:create): initializes every trainable parameter by
    running the topology's startup program."""
    from .topology import Topology

    topo = Topology(list(layers))
    params = Parameters()
    params.attach(topo)
    return params


class Parameters(object):
    def __init__(self):
        self._scope = Scope()
        self._topology = None
        self._param_names = []
        self._pending = {}   # values set/loaded before a topology attaches

    # -- wiring ------------------------------------------------------------

    def attach(self, topology, place=None):
        """Bind to a topology: run its startup program for any scope var
        not already present (so re-attaching after an optimizer added
        accumulators only fills the new ones), then apply pending values."""
        self._topology = topology
        self._param_names = [
            p.name for p in topology.program.global_block().all_parameters()
        ]
        exe = Executor(place or CPUPlace())
        tmp = Scope()
        exe.run(topology.startup, scope=tmp)
        for name, val in tmp.items():
            if self._scope.find_var(name) is None:
                self._scope.set_var(name, val)
        for name, val in list(self._pending.items()):
            if self._scope.find_var(name) is not None:
                del self._pending[name]
                self.set(name, val)   # same shape check / dtype cast
        return self

    @property
    def scope(self):
        return self._scope

    # -- dict surface ------------------------------------------------------

    def names(self):
        """Topology parameters plus any loaded values still awaiting a
        topology — so to_tar after a partial attach loses nothing."""
        extra = [n for n in sorted(self._pending) if n not in
                 self._param_names]
        return list(self._param_names) + extra

    keys = names

    def has_key(self, name):
        return name in self.names()

    def __iter__(self):
        return iter(self.names())

    def __len__(self):
        return len(self.names())

    def __contains__(self, name):
        return self.has_key(name)

    def get(self, name):
        v = self._scope.find_var(name)
        if v is not None:
            return np.asarray(v)
        if name in self._pending:
            return np.asarray(self._pending[name])
        raise KeyError("no parameter %r" % name)

    __getitem__ = get

    def get_shape(self, name):
        return tuple(self.get(name).shape)

    def set(self, name, value):
        value = np.asarray(value)
        if self._scope.find_var(name) is not None:
            cur = np.asarray(self._scope.find_var(name))
            if cur.shape != value.shape:
                raise ValueError("shape mismatch for %r: %s vs %s"
                                 % (name, cur.shape, value.shape))
            self._scope.set_var(name, value.astype(cur.dtype))
        else:
            self._pending[name] = value

    __setitem__ = set

    # -- tar archive (reference parameters.py to_tar/from_tar) -------------

    def to_tar(self, f):
        names = self.names()
        with tarfile.open(fileobj=f, mode="w") as tar:
            meta = json.dumps({"format": "paddle_tpu.v2", "version": 1,
                               "names": names}).encode()
            info = tarfile.TarInfo(_META_MEMBER)
            info.size = len(meta)
            tar.addfile(info, _io.BytesIO(meta))
            for name in names:
                buf = _io.BytesIO()
                np.save(buf, self.get(name), allow_pickle=False)
                data = buf.getvalue()
                info = tarfile.TarInfo(name + ".npy")
                info.size = len(data)
                tar.addfile(info, _io.BytesIO(data))

    @staticmethod
    def from_tar(f):
        params = Parameters()
        params.init_from_tar(f)
        return params

    def init_from_tar(self, f):
        """Merge values from an archive into this object (reference
        parameters.py:init_from_tar): unknown names are held pending
        until a topology with those parameters attaches."""
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                if member.name == _META_MEMBER:
                    continue
                if not member.name.endswith(".npy"):
                    continue
                name = member.name[:-len(".npy")]
                data = tar.extractfile(member).read()
                arr = np.load(_io.BytesIO(data), allow_pickle=False)
                self.set(name, arr)
