"""paddle_tpu.v2 — the legacy v2 API dialect, re-hosted on the TPU stack.

The reference ships two frameworks (SURVEY.md §2.5): Fluid and the older
v2 engine (`python/paddle/v2/` config DSL -> ModelConfig proto -> swig
GradientMachine + legacy C++ layers/Matrix/pserver,
`legacy/gserver/gradientmachines/GradientMachine.h:75`).  This package
is the deliberate TPU-first fold: the v2 *API* (layer DSL, Parameters,
trainer.SGD, events, infer) is preserved, but every call builds the same
Program IR the fluid-parity stack jit-compiles — there is one engine.
The 144k LoC of legacy CUDA/Matrix machinery is absorbed by XLA exactly
as the fluid C++ operator library is.

Usage (reference v2 book style)::

    from paddle_tpu import v2 as paddle
    paddle.init(use_gpu=False)
    img = paddle.layer.data(name='img',
                            type=paddle.data_type.dense_vector(784))
    fc = paddle.layer.fc(input=img, size=10,
                         act=paddle.activation.Softmax())
    lbl = paddle.layer.data(name='lbl',
                            type=paddle.data_type.integer_value(10))
    cost = paddle.layer.classification_cost(input=fc, label=lbl)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(cost, params,
                                 paddle.optimizer.Momentum(momentum=0.9))
    trainer.train(paddle.batch(reader, 128), num_passes=2,
                  event_handler=handler)
"""

from .. import dataset    # noqa: F401 — same dataset suite serves both APIs
from .. import reader     # noqa: F401 — reader decorators are shared
from ..dataset import image  # noqa: F401
from . import activation  # noqa: F401
from . import attr        # noqa: F401
from . import config      # noqa: F401
from . import data_type   # noqa: F401
from . import evaluator   # noqa: F401
from . import event       # noqa: F401
from . import inference   # noqa: F401
from . import layer       # noqa: F401
from . import minibatch   # noqa: F401
from . import networks    # noqa: F401
from . import optimizer   # noqa: F401
from . import parameters  # noqa: F401
from . import plot        # noqa: F401
from . import pooling     # noqa: F401
from . import topology    # noqa: F401
from . import trainer     # noqa: F401
from .inference import infer  # noqa: F401
from .minibatch import batch  # noqa: F401
# the reference v2 __init__ re-exports the fluid program singletons
from ..framework import (  # noqa: F401
    default_main_program, default_startup_program)

__all__ = [
    "init", "layer", "activation", "parameters", "trainer", "event",
    "data_type", "attr", "pooling", "topology", "networks", "evaluator",
    "inference", "infer", "batch", "minibatch", "optimizer", "plot",
    "reader", "dataset", "image", "master", "reset",
    "default_main_program", "default_startup_program",
]

reset = config.reset

# the Go master's task-lease machinery lives in cloud/; v2/master.py
# wraps it in the reference client surface (reference
# python/paddle/v2/master/client.py -> go/master/service.go)
from . import master  # noqa: F401,E402


_default_place = None


def init(use_gpu=False, trainer_count=1, **kwargs):
    """Process init (reference v2/__init__.py init -> swig initPaddle).

    ``use_gpu=True`` selects the accelerator (TPU here); trainer_count>1
    maps to the mesh runtime rather than per-thread trainers — use
    paddle_tpu.ParallelExecutor for data parallelism.
    """
    global _default_place
    from ..executor import CPUPlace, TPUPlace

    _default_place = TPUPlace() if use_gpu else CPUPlace()
    return _default_place


def default_place():
    from ..executor import CPUPlace

    return _default_place or CPUPlace()
