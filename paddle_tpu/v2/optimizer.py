"""v2 optimizers (reference python/paddle/v2/optimizer.py:1, wrapping the
legacy ``ParameterUpdater``/swig path).  Each maps onto the fluid-parity
optimizer op family; ``regularization`` and
``gradient_clipping_threshold`` translate to the regularizer/clip hooks
``Optimizer.minimize`` already applies."""

from .. import optimizer as fluid_opt
from .. import regularizer as fluid_reg

__all__ = [
    "Optimizer", "Momentum", "Adam", "Adamax", "AdaGrad", "DecayedAdaGrad",
    "AdaDelta", "RMSProp", "L1Regularization", "L2Regularization",
    "ModelAverage",
]


class L2Regularization(object):
    """settings(regularization=L2Regularization(rate)) analog."""

    def __init__(self, rate=0.0):
        self.rate = rate

    def to_regularizer(self):
        return fluid_reg.L2DecayRegularizer(self.rate)


class L1Regularization(object):
    def __init__(self, rate=0.0):
        self.rate = rate

    def to_regularizer(self):
        return fluid_reg.L1DecayRegularizer(self.rate)


class ModelAverage(object):
    """Marker matching reference ModelAverage(average_window=...); the
    trainer applies it via the fluid-parity contrib ModelAverage when
    requested (reference v2/optimizer.py ModelAverage settings)."""

    def __init__(self, average_window=0.15, max_average_window=None,
                 min_average_window=10000):
        self.average_window = average_window
        self.max_average_window = max_average_window
        self.min_average_window = min_average_window


class Optimizer(object):
    def __init__(self, learning_rate=1e-3, regularization=None,
                 model_average=None, gradient_clipping_threshold=None,
                 learning_rate_decay_a=None, learning_rate_decay_b=None,
                 learning_rate_schedule=None, **extra):
        self.learning_rate = learning_rate
        self.regularization = regularization
        self.model_average = model_average
        self.gradient_clipping_threshold = gradient_clipping_threshold
        self.extra = extra

    def _regularizer(self):
        if self.regularization is None:
            return None
        return self.regularization.to_regularizer()

    def to_optimizer(self):
        """Build the fluid-parity optimizer instance."""
        raise NotImplementedError

    # kept for signature parity with the reference (swig enable_types)
    def enable_types(self):
        return []


class Momentum(Optimizer):
    def __init__(self, momentum=0.9, sparse=False, **kw):
        super(Momentum, self).__init__(**kw)
        self.momentum = momentum

    def to_optimizer(self):
        return fluid_opt.MomentumOptimizer(
            learning_rate=self.learning_rate, momentum=self.momentum,
            regularization=self._regularizer())


class Adam(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super(Adam, self).__init__(**kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def to_optimizer(self):
        return fluid_opt.AdamOptimizer(
            learning_rate=self.learning_rate, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon,
            regularization=self._regularizer())


class Adamax(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, **kw):
        super(Adamax, self).__init__(**kw)
        self.beta1, self.beta2 = beta1, beta2

    def to_optimizer(self):
        return fluid_opt.AdamaxOptimizer(
            learning_rate=self.learning_rate, beta1=self.beta1,
            beta2=self.beta2, regularization=self._regularizer())


class AdaGrad(Optimizer):
    def to_optimizer(self):
        return fluid_opt.AdagradOptimizer(
            learning_rate=self.learning_rate,
            regularization=self._regularizer())


class DecayedAdaGrad(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super(DecayedAdaGrad, self).__init__(**kw)
        self.rho, self.epsilon = rho, epsilon

    def to_optimizer(self):
        return fluid_opt.DecayedAdagradOptimizer(
            learning_rate=self.learning_rate, decay=self.rho,
            epsilon=self.epsilon, regularization=self._regularizer())


class AdaDelta(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super(AdaDelta, self).__init__(**kw)
        self.rho, self.epsilon = rho, epsilon

    def to_optimizer(self):
        return fluid_opt.AdadeltaOptimizer(
            learning_rate=self.learning_rate, rho=self.rho,
            epsilon=self.epsilon, regularization=self._regularizer())


class RMSProp(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super(RMSProp, self).__init__(**kw)
        self.rho, self.epsilon = rho, epsilon

    def to_optimizer(self):
        return fluid_opt.RMSPropOptimizer(
            learning_rate=self.learning_rate, rho=self.rho,
            epsilon=self.epsilon, regularization=self._regularizer())
