"""v2 master client (reference python/paddle/v2/master/client.py:29).

The reference client is a cgo binding onto the Go master (etcd
discovery, record-level ``next_record`` over leased chunks,
save-model arbitration, ``go/master/service.go:368``).  Here the same
surface wraps the TPU stack's elastic coordinator (cloud/master.py
task-lease state machine + cloud/server.py TCP transport): etcd
endpoints become the master's TCP address (discovery is the
jax.distributed-era control plane; durability is the master's snapshot
store), and records stream from recordio chunks leased per task.
"""

from ..cloud.master import (AllTasksFailed, MasterService, NoMoreAvailable,
                            PassAfter, PassBefore)
from ..cloud.reader import master_reader

__all__ = ["client"]


def _chunk_records(chunk):
    """Materialize one leased chunk descriptor {'path', 'skip'}."""
    from .. import recordio
    with recordio.Scanner(chunk["path"], skip_chunks=chunk["skip"],
                          max_chunks=1) as sc:
        for rec in sc:
            yield rec


class client(object):
    """Trainer-side master client (reference client.py:29).

    ``addr`` is a ``host:port`` master address or an in-process
    ``MasterService`` (the transports share one surface — the dist
    tests drive both)."""

    def __init__(self, addr, timeout_sec=30.0, buf_size=0):
        if isinstance(addr, MasterService):
            self.c = addr
        else:
            from ..cloud.server import MasterClient
            self.c = MasterClient(addr, timeout=timeout_sec)
        self._records = None

    def release(self):
        close = getattr(self.c, "close", None)
        if close is not None:
            close()
        self.c = None

    def set_dataset(self, paths):
        """Register recordio files; each chunk becomes a lease unit
        (reference paddle_set_dataset; chunk-per-task matches the Go
        master's partition over recordio chunks)."""
        from .. import recordio
        chunks = []
        for path in paths:
            for i in range(recordio.num_chunks(path)):
                chunks.append({"path": path, "skip": i})
        self.c.set_dataset(chunks)

    def paddle_start_get_records(self, pass_id):
        """Begin streaming the given pass's records."""
        self._records = master_reader(self.c, _chunk_records,
                                      pass_id=pass_id)()

    def next_record(self):
        """(record, 0) per record; (None, -2) once the pass ends
        (reference next_record's size<0 convention)."""
        if self._records is None:
            return None, -1
        try:
            return next(self._records), 0
        except StopIteration:
            self._records = None
            return None, -2
        except (PassBefore, PassAfter, NoMoreAvailable, AllTasksFailed):
            self._records = None
            return None, -2

    def request_save_model(self, trainer_id, block_ms):
        """1 if this trainer should save, 0 if another holds the save
        lease (reference request_save_model's int convention)."""
        ok = self.c.request_save_model(trainer_id, block_ms / 1000.0)
        return 1 if ok else 0
