"""contrib package (reference ``python/paddle/fluid/contrib/``: the
high-level Trainer/Inferencer moved here at release 0.15)."""

from .trainer import (  # noqa: F401
    Trainer, CheckpointConfig,
    BeginEpochEvent, EndEpochEvent, BeginStepEvent, EndStepEvent,
)
from .inferencer import Inferencer  # noqa: F401
from . import mixed_precision  # noqa: F401
from . import memory_usage_calc, op_frequence  # noqa: F401,E402
from .memory_usage_calc import memory_usage  # noqa: F401,E402
from .op_frequence import op_freq_statistic  # noqa: F401,E402
from . import quantize  # noqa: F401,E402
from .quantize import QuantizeTranspiler  # noqa: F401,E402
from . import float16  # noqa: F401,E402
from .float16 import Bfloat16Transpiler, Float16Transpiler  # noqa: F401,E402
from . import decoder  # noqa: F401,E402
from .decoder import (  # noqa: F401,E402
    InitState, StateCell, TrainingDecoder, BeamSearchDecoder)
