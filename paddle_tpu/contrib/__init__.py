"""contrib package (reference ``python/paddle/fluid/contrib/``: the
high-level Trainer/Inferencer moved here at release 0.15)."""

from .trainer import (  # noqa: F401
    Trainer, CheckpointConfig,
    BeginEpochEvent, EndEpochEvent, BeginStepEvent, EndStepEvent,
)
from .inferencer import Inferencer  # noqa: F401
from . import mixed_precision  # noqa: F401
