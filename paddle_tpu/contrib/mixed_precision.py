"""Automatic mixed precision (bf16) — the TPU rebuild of the reference's
fp16 story (``contrib/float16/float16_transpiler.py``: rewrites a program
to fp16 by inserting casts and retyping vars).

TPU-first redesign: bfloat16 is the MXU's native input format and shares
float32's exponent range, so — unlike fp16 on GPUs — **no loss scaling is
required** and there is no transpiler pass inserting cast ops into the
program.  Instead the policy is applied at *trace time*: ops on the white
list (the MXU-bound FLOPs: matmuls/convs) compute in bf16, ops on the
black list (numerically sensitive: losses, norms, optimizer updates)
compute in fp32, everything else follows its inputs' promotion.  Master
weights stay fp32 automatically: parameters live fp32 in the scope and
only their *use* inside whitelisted ops is cast, while the (blacklisted)
optimizer ops update the fp32 originals.

API parity targets: ``fluid.contrib.mixed_precision.decorate(optimizer)``
and the float16 transpiler's program rewrite
(``contrib/float16/float16_transpiler.py``); ``init_loss_scaling`` is
accepted for signature parity and ignored (bf16 needs none — documented
SURVEY.md §2.6 float16 demo row).
"""

import jax.numpy as jnp

from ..core import bfloat16

__all__ = ["AutoMixedPrecisionLists", "AMPPolicy", "decorate",
           "bf16_program_guard", "cast_parameters_to_bf16"]


class AutoMixedPrecisionLists:
    """White/black op lists (the reference AMP concept; the float16
    transpiler's implicit op partition made explicit)."""

    # MXU-bound: cast fp32 inputs to bf16
    WHITE = {
        "matmul", "mul", "conv2d", "conv3d", "depthwise_conv2d",
        "conv2d_transpose", "bilinear_tensor_product", "fused_attention",
    }
    # numerically sensitive: force fp32 compute.  batch_norm/layer_norm
    # are NOT here: their kernels accumulate statistics in fp32
    # internally while activations pass through in bf16 — blacklisting
    # them would insert two full-activation cast passes around every
    # conv/sublayer (measured 20%+ of the ResNet step).
    BLACK = {
        "softmax_with_cross_entropy", "cross_entropy", "mean",
        "reduce_sum", "reduce_mean",
        "group_norm", "lrn", "norm", "exp", "log", "softmax",
        "log_softmax", "sigmoid_cross_entropy_with_logits",
        # optimizer updates read/write fp32 master weights
        "sgd", "momentum", "adam", "adamax", "adagrad", "adadelta",
        "rmsprop", "ftrl", "decayed_adagrad", "proximal_gd",
        "proximal_adagrad", "sum", "clip_by_norm", "squared_l2_norm",
        "isfinite",
    }

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = (set(self.WHITE) | set(custom_white_list or ())) \
            - set(custom_black_list or ())
        self.black_list = (set(self.BLACK) | set(custom_black_list or ())) \
            - set(custom_white_list or ())


class AMPPolicy:
    """Trace-time dtype policy consulted by registry.compute_op."""

    def __init__(self, amp_lists=None):
        self.lists = amp_lists or AutoMixedPrecisionLists()

    def cast_inputs(self, op_type, ins):
        """Return ``ins`` with float32<->bf16 casts applied per the lists.
        Grad ops follow their forward op's color (the generic auto-vjp
        grad re-runs the forward, so the same cast yields the same
        bf16 compute in the backward pass)."""
        if bfloat16 is None:  # pragma: no cover - ml_dtypes always present
            return ins
        base = op_type[:-5] if op_type.endswith("_grad") else op_type
        if base in self.lists.white_list:
            target, source = jnp.bfloat16, jnp.float32
        elif base in self.lists.black_list:
            target, source = jnp.float32, jnp.bfloat16
        else:
            return ins
        out = {}
        for slot, vals in ins.items():
            out[slot] = [
                v.astype(target)
                if hasattr(v, "dtype") and v.dtype == source else v
                for v in vals
            ]
        return out


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             use_dynamic_loss_scaling=False):
    """Wrap an optimizer so that ``minimize(loss)`` marks the loss's
    program for bf16 mixed-precision execution.

    ``init_loss_scaling``/``use_dynamic_loss_scaling`` are accepted for
    API parity with the GPU fp16 recipe and ignored: bf16 keeps fp32's
    exponent range, so gradients cannot underflow the way fp16's do.
    """

    class _AMPOptimizer:
        def __init__(self, inner):
            self._inner = inner
            self._amp_policy = AMPPolicy(amp_lists)

        def minimize(self, loss, startup_program=None, **kw):
            result = self._inner.minimize(
                loss, startup_program=startup_program, **kw)
            loss.block.program._amp_policy = self._amp_policy
            return result

        def __getattr__(self, name):
            return getattr(self._inner, name)

    return _AMPOptimizer(optimizer)


class bf16_program_guard:
    """Context manager marking ``program`` for bf16 execution without an
    optimizer — the inference-side analog of the float16 transpiler
    (``float16_transpiler.py`` rewrites inference programs)."""

    def __init__(self, program, amp_lists=None):
        self.program = program
        self.policy = AMPPolicy(amp_lists)
        self._prior = None

    def __enter__(self):
        self._prior = getattr(self.program, "_amp_policy", None)
        self.program._amp_policy = self.policy
        return self.program

    def __exit__(self, *exc):
        self.program._amp_policy = self._prior
        return False


def cast_parameters_to_bf16(program, scope):
    """Hard-cast persistable fp32 params in ``scope`` to bf16 — the
    float16 transpiler's var-retyping path, for inference deployments
    that want bf16 weights at rest (half the HBM footprint)."""
    import numpy as np

    for var in program.global_block().vars.values():
        if not getattr(var, "persistable", False):
            continue
        if scope.has_var(var.name):
            v = scope.var(var.name)
            if hasattr(v, "dtype") and np.dtype(v.dtype) == np.float32:
                scope.set_var(var.name, jnp.asarray(v, dtype=jnp.bfloat16))
