"""High-level seq2seq decoder API: ``InitState`` / ``StateCell`` /
``TrainingDecoder`` / ``BeamSearchDecoder`` (reference
python/paddle/fluid/contrib/decoder/beam_search_decoder.py:1).

The reference builds the search as a ``While`` loop over LoD beams whose
width shrinks as hypotheses finish, gathering beam parents implicitly
through ``sequence_expand`` on the LoD of the previous scores.  Dynamic
beam widths are a dynamic-shape design XLA cannot tile, so this is a
TPU-first redesign with the same public surface:

* beams are a FIXED ``[B, K]`` lane dimension for the whole search;
  finished beams are frozen by the ``beam_search`` op (they re-emit
  ``end_id`` at zero incremental score) instead of being pruned;
* hidden states ride the ``While`` loop as static-shape ``[B, K*S]``
  carries; beam-parent gathers are explicit one-hot matmuls (MXU work,
  not host reorders);
* per-step ids/backpointers land in preallocated ``[max_len, B, K]``
  arrays initialized to a frozen tail (``end_id`` tokens, identity
  parents), so an ``early_stop()`` exit leaves the arrays valid for
  ``beam_search_decode`` backtracking;
* ``topk_size`` is accepted for API parity and absorbed: the
  ``beam_search`` op top-ks the full vocabulary on device, so the
  reference's host-side topk pre-prune has nothing to prune.

``StateCell`` drives a ``DynamicRNN`` memory when entered by a
``TrainingDecoder`` and a loop carry when entered by a
``BeamSearchDecoder`` — the same updater function serves training and
search, which is the point of the API.
"""

import contextlib

import numpy as np

from ... import unique_name
from ...framework import Variable
from ...layer_helper import LayerHelper
from ... import layers

__all__ = ['InitState', 'StateCell', 'TrainingDecoder', 'BeamSearchDecoder']


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState(object):
    """Initial hidden state for one ``StateCell`` state (reference
    beam_search_decoder.py:42).  Either an explicit ``init`` Variable or
    a constant tensor shaped like ``init_boot``'s batch.

    ``need_reorder`` is accepted for API parity and ignored: the
    reference reorders inits by LoD rank when length-bucketing reorders
    the batch; the padded ``[B, T]`` design keeps batch order stable.
    """

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype='float32'):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                'init_boot must be provided to infer the shape of InitState')
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, shape=shape, value=value, dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState(object):
    """Training-side state: a DynamicRNN memory (reference _MemoryState)."""

    def __init__(self, state_name, rnn_obj, init_state):
        self._state_name = state_name
        self._rnn_obj = rnn_obj
        self._state_mem = self._rnn_obj.memory(init=init_state.value)

    def get_state(self):
        return self._state_mem

    def update_state(self, state):
        self._rnn_obj.update_memory(self._state_mem, state)


class _BeamState(object):
    """Search-side state: a ``[B, K*S]`` While-loop carry.

    ``get_state`` exposes the beam-flattened ``[B*K, S]`` view;
    ``update_state`` records the step's computed state, which the
    decoder reorders by the chosen beam parents and assigns back into
    the carry (the reference reaches the same effect implicitly via
    ``sequence_expand`` on LoD backpointers)."""

    def __init__(self, state_name, decoder, init_state):
        init = init_state.value
        if len(init.shape) != 2:
            raise ValueError(
                'BeamSearchDecoder states must be rank-2 [batch, size]; '
                'state %r has shape %s' % (state_name, (init.shape,)))
        self._state_name = state_name
        self._decoder = decoder
        self._size = int(init.shape[1])
        k = decoder._beam_size
        # The carry must be a loop-carried var: its init/tile ops belong
        # in the block that owns the While op, but _BeamState is built
        # lazily at the first in-loop state access — emit into the
        # decoder's parent block explicitly (the reference's _ArrayState
        # does the same via _parent_block()).
        program = decoder._helper.main_program
        saved = program.current_block_idx
        program.current_block_idx = decoder._parent_block.idx
        try:
            # [B, S] -> [B, K, S] -> [B, K*S]
            tiled = layers.expand(
                layers.unsqueeze(init, axes=[1]), expand_times=[1, k, 1])
            self._carry = layers.reshape(tiled, shape=[0, k * self._size])
        finally:
            program.current_block_idx = saved
        self._pending = None

    def get_state(self):
        # in-loop flattened view [B*K, S]
        return layers.reshape(self._carry, shape=[-1, self._size])

    def update_state(self, state):
        self._pending = state
        # when the beam parents for this step are already known (the
        # standard search-then-update order), gather immediately; a
        # custom update-then-search order is flushed by search_step
        if self._decoder._parent_onehot is not None:
            self.commit(self._decoder._parent_onehot)

    def commit(self, parent_onehot):
        """Gather the pending state by beam parent and write the carry."""
        if self._pending is None:
            return
        k = self._decoder._beam_size
        s3 = layers.reshape(self._pending, shape=[-1, k, self._size])
        sel = layers.matmul(parent_onehot, s3)            # [B, K, S]
        layers.assign(layers.reshape(sel, shape=[0, k * self._size]),
                      output=self._carry)
        self._pending = None


class StateCell(object):
    """Named hidden states + step inputs of an RNN cell (reference
    beam_search_decoder.py:158).  The cell's step function is installed
    with ``state_updater`` and runs identically under a
    ``TrainingDecoder`` (states are DynamicRNN memories) and a
    ``BeamSearchDecoder`` (states are beam-search loop carries)."""

    def __init__(self, inputs, states, out_state, name=None):
        self._helper = LayerHelper('state_cell', name=name)
        self._cur_states = {}
        self._init_states = {}   # preserved across decoders (a cell may
        self._state_names = []   # serve a TrainingDecoder then a search)
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError('state must be an InitState object.')
            self._cur_states[state_name] = state
            self._init_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = inputs
        self._cur_decoder_obj = None
        self._in_decoder = False
        self._states_holder = {}
        self._switched_decoder = False
        self._state_updater = None
        self._out_state = out_state
        if self._out_state not in self._cur_states:
            raise ValueError('out_state must be one state in states')

    def _enter_decoder(self, decoder_obj):
        if self._in_decoder or self._cur_decoder_obj is not None:
            raise ValueError('StateCell has already entered a decoder.')
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj
        self._switched_decoder = False

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder:
            raise ValueError('StateCell not in decoder, '
                             'invalid leaving operation.')
        if self._cur_decoder_obj is not decoder_obj:
            raise ValueError('Inconsistent decoder object in StateCell.')
        self._in_decoder = False
        self._cur_decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        """Lazily bind each InitState to the entered decoder's state
        mechanism (memory vs loop carry) on first access."""
        if not self._in_decoder:
            raise ValueError('StateCell must enter a decoder first.')
        if self._switched_decoder:
            raise ValueError('StateCell already done switching.')
        for state_name in self._state_names:
            holder = self._states_holder.setdefault(state_name, {})
            if id(self._cur_decoder_obj) not in holder:
                state = self._init_states[state_name]
                if self._cur_decoder_obj.type == _DecoderType.TRAINING:
                    holder[id(self._cur_decoder_obj)] = _MemoryState(
                        state_name, self._cur_decoder_obj.dynamic_rnn,
                        state)
                elif self._cur_decoder_obj.type == _DecoderType.BEAM_SEARCH:
                    holder[id(self._cur_decoder_obj)] = _BeamState(
                        state_name, self._cur_decoder_obj, state)
                else:
                    raise ValueError('Unknown decoder type, only support '
                                     '[TRAINING, BEAM_SEARCH]')
            self._cur_states[state_name] = holder[
                id(self._cur_decoder_obj)].get_state()
        self._switched_decoder = True

    def get_state(self, state_name):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        if state_name not in self._cur_states:
            raise ValueError('Unknown state %s.' % state_name)
        return self._cur_states[state_name]

    def get_input(self, input_name):
        if input_name not in self._inputs or \
                self._inputs[input_name] is None:
            raise ValueError('Invalid input %s.' % input_name)
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        """Install the cell step function (usable as a decorator).  The
        updater receives this StateCell and must ``set_state`` every
        state it advances."""
        self._state_updater = updater
        return updater

    def compute_state(self, inputs):
        """Bind this step's inputs and run the installed updater."""
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError(
                    'Unknown input %s. Please make sure %s is a declared '
                    'input placeholder.' % (input_name, input_name))
            self._inputs[input_name] = input_value
        if self._state_updater is None:
            raise ValueError('state_updater has not been installed.')
        self._state_updater(self)

    def update_states(self):
        """Record the step's computed states into the decoder's state
        mechanism (RNN memory update / beam carry commit)."""
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for state_name, decoder_state in self._states_holder.items():
            if id(self._cur_decoder_obj) not in decoder_state:
                raise ValueError('Unknown decoder object, please make sure '
                                 'switch_decoder has been invoked.')
            decoder_state[id(self._cur_decoder_obj)].update_state(
                self._cur_states[state_name])

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder(object):
    """Teacher-forced decoder: a DynamicRNN over the target sequence
    driving a StateCell (reference beam_search_decoder.py:385)."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper('training_decoder', name=name)
        self._status = TrainingDecoder.BEFORE_DECODER
        self._dynamic_rnn = layers.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError('decoder.block() can only be invoked once')
        self._status = TrainingDecoder.IN_DECODER
        with self._dynamic_rnn.block():
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    @property
    def state_cell(self):
        self._assert_in_decoder_block('state_cell')
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._dynamic_rnn

    @property
    def type(self):
        return self._type

    def step_input(self, x):
        self._assert_in_decoder_block('step_input')
        return self._dynamic_rnn.step_input(x)

    def static_input(self, x):
        self._assert_in_decoder_block('static_input')
        return self._dynamic_rnn.static_input(x)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError('Output of training decoder can only be visited '
                             'outside the block.')
        return self._dynamic_rnn(*args, **kwargs)

    def output(self, *outputs):
        self._assert_in_decoder_block('output')
        self._dynamic_rnn.output(*outputs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError('%s should be invoked inside block of '
                             'TrainingDecoder object.' % method)


class BeamSearchDecoder(object):
    """Beam-search generation driver (reference
    beam_search_decoder.py:522) — fixed ``[B, K]`` beams in a bounded
    ``While`` loop (see module docstring for the redesign rationale).

    Reference-parity args are positional; the trailing keyword-only
    ``*_attr`` args let the search share parameters with the training
    program by name (the reference relies on layer-creation order
    making auto-generated names line up, which only works when train
    and decode programs emit layers in lockstep — explicit attrs are
    the robust spelling).
    """

    BEFORE_BEAM_SEARCH_DECODER = 0
    IN_BEAM_SEARCH_DECODER = 1
    AFTER_BEAM_SEARCH_DECODER = 2

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict={}, topk_size=50, sparse_emb=True,
                 max_len=100, beam_size=1, end_id=1, name=None,
                 emb_param_attr=None, score_param_attr=None,
                 score_bias_attr=None):
        self._helper = LayerHelper('beam_search_decoder', name=name)
        self._parent_block = self._helper.main_program.current_block()
        self._type = _DecoderType.BEAM_SEARCH
        self._status = BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER
        self._state_cell = state_cell
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self._target_dict_dim = int(target_dict_dim)
        self._word_dim = int(word_dim)
        self._topk_size = int(topk_size)   # parity only; see module doc
        self._sparse_emb = sparse_emb
        self._input_var_dict = input_var_dict
        self._emb_param_attr = emb_param_attr
        self._score_param_attr = score_param_attr
        self._score_bias_attr = score_bias_attr

        k = self._beam_size

        def _like(shape, value, dtype, out_dim=0):
            return layers.fill_constant_batch_size_like(
                input=init_ids, shape=shape, dtype=dtype, value=value,
                input_dim_idx=0, output_dim_idx=out_dim)

        # beam carries: ids [B, K] seeded from init_ids' first column;
        # scores [B, K] = init score on beam 0, -inf elsewhere so the
        # first expansion grows out of beam 0 only
        first_ids = layers.reshape(
            layers.slice(init_ids, axes=[1], starts=[0], ends=[1]),
            shape=[-1, 1])
        self._cur_ids = layers.elementwise_add(
            _like([-1, k], 0, 'int64'),
            layers.cast(first_ids, 'int64'))
        lane_penalty = np.zeros((1, k), dtype='float32')
        lane_penalty[0, 1:] = -1e9
        first_scores = layers.cast(
            layers.reshape(
                layers.slice(init_scores, axes=[1], starts=[0], ends=[1]),
                shape=[-1, 1]), 'float32')
        self._cur_scores = layers.elementwise_add(
            layers.elementwise_add(_like([-1, k], 0.0, 'float32'),
                                   layers.assign(lane_penalty)),
            first_scores)

        # step arrays preinitialized to a FROZEN tail: end_id tokens with
        # identity parents, so an early_stop() exit leaves every
        # unwritten step a valid no-op link for backtracking
        self._ids_array = _like([self._max_len, -1, k],
                                float(self._end_id), 'int64', out_dim=1)
        self._parents_array = layers.elementwise_add(
            _like([self._max_len, -1, k], 0, 'int64', out_dim=1),
            layers.assign(np.arange(k, dtype='int64').reshape(1, 1, k)))

        self._counter = layers.fill_constant(
            shape=[1], dtype='int64', value=0)
        self._counter.stop_gradient = True
        self._max_len_var = layers.fill_constant(
            shape=[1], dtype='int64', value=self._max_len)
        self._cond = layers.less_than(self._counter, self._max_len_var)
        self._while_op = layers.While(self._cond)

        self._array_dict = {}
        self._array_link = []
        self._parent_onehot = None
        self._state_cell._enter_decoder(self)

    @property
    def type(self):
        return self._type

    @property
    def state_cell(self):
        self._assert_in_decoder_block('state_cell')
        return self._state_cell

    @contextlib.contextmanager
    def block(self):
        """The per-step search block.  On exit: flush scheduled array
        writes at the current step index, advance the counter, and
        refresh the loop condition."""
        if self._status != BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER:
            raise ValueError('block() can only be invoked once.')
        self._status = BeamSearchDecoder.IN_BEAM_SEARCH_DECODER
        with self._while_op.block():
            yield
            for value, array in self._array_link:
                layers.assign(
                    layers.array_write(value, self._counter, array=array),
                    output=array)
            layers.increment(self._counter, value=1)
            refreshed = layers.less_than(self._counter, self._max_len_var)
            layers.assign(layers.logical_and(self._cond, refreshed),
                          output=self._cond)
        self._status = BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER
        self._state_cell._leave_decoder(self)

    def early_stop(self):
        """Terminate the search before ``max_len`` steps ("break")."""
        self._assert_in_decoder_block('early_stop')
        layers.assign(
            layers.fill_constant(shape=[1], dtype='bool', value=0),
            output=self._cond)

    def read_array(self, init, is_ids=False, is_scores=False):
        """Expose ``init`` as a per-step carried value; returns the
        current step's view.  The reference reads a LoD tensor array at
        the loop counter; with fixed beams the carry IS the value, so
        this returns the carried Variable directly (``update_array``
        writes the next step's value into it)."""
        self._assert_in_decoder_block('read_array')
        if is_ids and is_scores:
            raise ValueError('An array cannot be both ids and scores.')
        if not isinstance(init, Variable):
            raise TypeError('The input argument `init` must be a Variable.')
        if is_ids:
            read_value = self._cur_ids
        elif is_scores:
            read_value = self._cur_scores
        else:
            read_value = init
        self._array_dict[read_value.name] = read_value
        return read_value

    def update_array(self, array, value):
        """Carry ``value`` into the next step's ``read_array`` view."""
        self._assert_in_decoder_block('update_array')
        if not isinstance(array, Variable):
            raise TypeError('The input argument `array` must be a Variable.')
        if not isinstance(value, Variable):
            raise TypeError('The input argument `value` must be a Variable.')
        carried = self._array_dict.get(array.name, None)
        if carried is None:
            raise ValueError('Please invoke read_array before update_array.')
        layers.assign(value, output=carried)

    def search_step(self, log_probs):
        """Expand beams with this step's ``[B*K, V]`` (or ``[B, K, V]``)
        log-probabilities: runs the ``beam_search`` op, records
        ids/backpointers for decode-time backtracking, updates the
        ids/scores carries, and remembers the parent gather for
        ``update_states`` to commit hidden states.  Returns
        (selected_ids [B, K], selected_scores [B, K])."""
        self._assert_in_decoder_block('search_step')
        k = self._beam_size
        if len(log_probs.shape) == 2:
            log_probs = layers.reshape(
                log_probs, shape=[-1, k, int(log_probs.shape[-1])])
        sel_ids, sel_scores, parent = layers.beam_search(
            self._cur_ids, self._cur_scores, log_probs,
            beam_size=k, end_id=self._end_id)
        self._parent_onehot = layers.one_hot(
            layers.unsqueeze(parent, axes=[2]), depth=k)      # [B, K, K]
        self._array_link = [(sel_ids, self._ids_array),
                            (parent, self._parents_array)]
        layers.assign(sel_ids, output=self._cur_ids)
        layers.assign(sel_scores, output=self._cur_scores)
        # flush states updated BEFORE this search (custom decoders that
        # call update_states first); they gather by this step's parents
        for holder in self._state_cell._states_holder.values():
            state = holder.get(id(self))
            if state is not None and state._pending is not None:
                state.commit(self._parent_onehot)
        return sel_ids, sel_scores

    def commit_states(self):
        """Gather every pending hidden state by the beam parents chosen
        in ``search_step`` and write the loop carries."""
        self._assert_in_decoder_block('commit_states')
        if self._parent_onehot is None:
            raise ValueError('commit_states requires a prior search_step.')
        for holder in self._state_cell._states_holder.values():
            state = holder.get(id(self))
            if state is not None:
                state.commit(self._parent_onehot)

    def decode(self):
        """The standard search loop (override for a custom decoder):
        embed the previous tokens, advance the StateCell, score with a
        softmax projection, expand beams, stop early once every beam
        has emitted ``end_id``."""
        # tile per-sentence inputs across the K beam lanes OUTSIDE the
        # loop (they are step-invariant; outer vars are readable inside
        # the block): [B, d1, ..., dn] -> [B*K, d1, ..., dn] (e.g. an
        # encoder sequence [B, T, H] an attention cell reads).  Tiled by
        # a batch-index gather (row b repeats K times) rather than
        # expand+reshape: trailing dims of RNN outputs are unknown at
        # build time, and gather never needs them.
        feed_dict = {}
        k = self._beam_size
        idx = None   # one shared [B*K] index: every entry has batch B
        for name, var in self._input_var_dict.items():
            if name not in self._state_cell._inputs:
                raise ValueError(
                    'Variable %s not found in StateCell!' % name)
            if len(var.shape) < 2:
                raise ValueError(
                    'input_var_dict entries must be [batch, ...]; '
                    '%s has shape %s' % (name, (var.shape,)))
            if idx is None:
                ones = layers.fill_constant_batch_size_like(
                    var, shape=[-1, 1], dtype='int64', value=1)
                bidx = layers.elementwise_sub(
                    layers.cumsum(ones, axis=0), ones)       # [B,1] 0..B-1
                lanes = layers.fill_constant_batch_size_like(
                    var, shape=[-1, k], dtype='int64', value=0)
                idx = layers.reshape(
                    layers.elementwise_add(lanes, bidx), shape=[-1])
            feed_dict[name] = layers.gather(var, idx)

        with self.block():
            prev_ids = self.read_array(init=self._cur_ids, is_ids=True)
            self.read_array(init=self._cur_scores, is_scores=True)
            prev_ids_embedding = layers.embedding(
                layers.reshape(prev_ids, shape=[-1, 1]),
                size=[self._target_dict_dim, self._word_dim],
                dtype='float32', is_sparse=self._sparse_emb,
                param_attr=self._emb_param_attr)
            prev_ids_embedding = layers.reshape(
                prev_ids_embedding, shape=[-1, self._word_dim])

            for input_name in self._state_cell._inputs:
                if input_name not in feed_dict:
                    feed_dict[input_name] = prev_ids_embedding

            self.state_cell.compute_state(inputs=feed_dict)
            current_state = self.state_cell.out_state()
            scores = layers.fc(current_state, size=self._target_dict_dim,
                               act='softmax',
                               param_attr=self._score_param_attr,
                               bias_attr=self._score_bias_attr)
            sel_ids, _ = self.search_step(layers.log(scores))
            self.state_cell.update_states()
            self.commit_states()

            # all-finished => stop: every selected id is end_id
            end_fill = layers.fill_constant_batch_size_like(
                input=sel_ids, shape=[-1, k], dtype='int64',
                value=float(self._end_id))
            alive = layers.reduce_sum(
                layers.cast(layers.logical_not(
                    layers.equal(sel_ids, end_fill)), 'float32'))
            half = layers.fill_constant(shape=[1], dtype='float32',
                                        value=0.5)
            any_alive = layers.less_than(half,
                                         layers.reshape(alive, shape=[1]))
            layers.assign(layers.logical_and(self._cond, any_alive),
                          output=self._cond)

    def __call__(self):
        """Backtrack the recorded ids/parents into full sequences.
        Returns (sentence_ids [B, K, max_len], sentence_scores [B, K])."""
        if self._status != BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER:
            raise ValueError('Output of BeamSearchDecoder object can only be '
                             'visited outside the block.')
        return layers.beam_search_decode(
            self._ids_array, self._parents_array, self._cur_scores,
            beam_size=self._beam_size, end_id=self._end_id)

    def _assert_in_decoder_block(self, method):
        if self._status != BeamSearchDecoder.IN_BEAM_SEARCH_DECODER:
            raise ValueError('%s should be invoked inside block of '
                             'BeamSearchDecoder object.' % method)
