"""General seq2seq decoder API (reference
python/paddle/fluid/contrib/decoder/__init__.py:1)."""

from . import beam_search_decoder
from .beam_search_decoder import *  # noqa: F401,F403

__all__ = beam_search_decoder.__all__
