"""Quantization-aware-training program rewrite.

Parity: reference ``contrib/quantize/quantize_transpiler.py`` — insert
fake-quantization ops around quantizable ops so training learns
int8-robust weights, then freeze for inference and convert weights to
int8 storage.

TPU-first redesign: the reference transpiles a program that ALREADY has
gradient ops, so it must also rewire every grad op's inputs to the
quantized tensors.  Here ``training_transpile`` runs BEFORE
``append_backward`` (the same contract as ``transpiler.fuse_conv_bn``):
the framework's registry derives gradients from the rewritten forward —
the fake-quant ops' straight-through-estimator grads (ops/quantize.py)
flow automatically and no backward rewiring exists to get wrong.  The
``range_abs_max`` running scale is a persistable state var updated
in-graph via the executor's writeback contract (the reference's
window/global-step machinery collapses into a running max envelope).
"""

import numpy as np

from ...framework import (Operator, Parameter, default_main_program,
                          default_startup_program)
from ...registry import infer_op
from ...scope import global_scope
from ... import unique_name

__all__ = ["QuantizeTranspiler"]

_QUANTIZABLE_OP_TYPES = ("conv2d", "depthwise_conv2d", "mul", "matmul")
_QUANT_TYPES = ("abs_max", "range_abs_max")


class QuantizeTranspiler:
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000,
                 weight_quant_axis=None):
        if weight_quantize_type not in _QUANT_TYPES:
            raise ValueError(
                "Unknown weight_quantize_type: %r" % weight_quantize_type)
        if activation_quantize_type not in _QUANT_TYPES:
            raise ValueError(
                "Unknown activation_quantize_type: %r"
                % activation_quantize_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.weight_quantize_type = weight_quantize_type
        self.activation_quantize_type = activation_quantize_type
        self.window_size = window_size   # accepted for API parity
        # per-channel weight grids: "auto" picks the consumer's output-
        # channel axis (conv filters 0, mul/matmul weights their last
        # axis) so QAT trains against the SAME per-channel grid the
        # quantize_inference pass deploys; an int pins the axis; None
        # keeps the legacy per-tensor max (which over-clips wide FC
        # layers).  abs_max weights only — the range_abs_max running
        # scale is a scalar state var.
        if weight_quant_axis not in (None, "auto") and \
                not isinstance(weight_quant_axis, int):
            raise ValueError(
                "weight_quant_axis must be None, 'auto', or an int, "
                "got %r" % (weight_quant_axis,))
        self.weight_quant_axis = weight_quant_axis

    # ------------------------------------------------------------------
    def training_transpile(self, program=None, startup_program=None):
        """Insert fake quant-dequant ops on every input of every
        quantizable op.  MUST run before append_backward/minimize (the
        registry then derives STE gradients from the rewritten ops).
        Returns the number of fake-quant ops inserted."""
        program = program or default_main_program()
        startup = startup_program or default_startup_program()
        for blk in program.blocks:
            if any(op.type.endswith("_grad") for op in blk.ops):
                raise ValueError(
                    "training_transpile must run BEFORE append_backward: "
                    "gradients are derived from the rewritten forward")

        params = {p.name
                  for p in program.global_block().all_parameters()}
        inserted = 0
        # every block: quantizable ops inside While/conditional
        # sub-blocks must see quantization error too
        for block in program.blocks:
            quantized = {}   # var name -> fake-quantized var name
            new_ops = []
            for op in block.ops:
                if op.type in _QUANTIZABLE_OP_TYPES:
                    for slot, names in list(op.inputs.items()):
                        renamed = []
                        for name in names:
                            var = block._find_var_recursive(name)
                            if var is None or var.dtype is None or \
                                    "float" not in str(var.dtype):
                                renamed.append(name)
                                continue
                            if name not in quantized:
                                qname, qops = self._make_quant_ops(
                                    block, startup, name, name in params,
                                    consumer_type=op.type)
                                new_ops.extend(qops)
                                inserted += len(qops)
                                quantized[name] = qname
                            renamed.append(quantized[name])
                        op.inputs[slot] = renamed
                new_ops.append(op)
            block.ops = new_ops
        program._version += 1
        return inserted

    def _quant_axis_for(self, var, consumer_type):
        """The per-channel axis for a weight feeding ``consumer_type``
        (None = per-tensor)."""
        axis = self.weight_quant_axis
        if axis is None:
            return None
        if axis == "auto":
            if consumer_type in ("conv2d", "depthwise_conv2d"):
                return 0        # [O, C, H, W] filters: output channel
            return len(var.shape) - 1   # mul/matmul [K, N]: output axis
        # normalize negative axes: the op's quant_axis attr gates on
        # axis >= 0 (a raw -1 would silently degrade to per-tensor)
        return int(axis) % len(var.shape)

    def _make_quant_ops(self, block, startup, name, is_weight,
                        consumer_type=None):
        bits = self.weight_bits if is_weight else self.activation_bits
        qtype = self.weight_quantize_type if is_weight \
            else self.activation_quantize_type
        var = block._find_var_recursive(name)
        qname = name + ".quantized.dequantized"
        scale_name = name + ".scale"
        block.create_var(name=qname, shape=var.shape, dtype=var.dtype,
                         persistable=False)
        ops = []
        if qtype == "abs_max":
            attrs = {"bit_length": bits}
            scale_shape = (1,)
            if is_weight:
                axis = self._quant_axis_for(var, consumer_type)
                if axis is not None:
                    attrs["quant_axis"] = axis
                    scale_shape = (var.shape[axis],)
            block.create_var(name=scale_name, shape=scale_shape,
                             dtype=var.dtype, persistable=False)
            op = Operator(block, type="fake_quantize_abs_max",
                          inputs={"X": [name]},
                          outputs={"Out": [qname],
                                   "OutScale": [scale_name]},
                          attrs=attrs)
        else:
            # running-scale state: persistable, zero-initialized by the
            # startup program, updated in place every step (OutScale
            # writes back over InScale via the executor's writeback)
            block.create_var(name=scale_name, shape=(1,), dtype=var.dtype,
                             persistable=True)
            sblock = startup.global_block()
            sblock.create_var(name=scale_name, shape=(1,),
                              dtype=var.dtype, persistable=True)
            init = Operator(sblock, type="fill_constant", inputs={},
                            outputs={"Out": [scale_name]},
                            attrs={"shape": [1], "value": 0.0,
                                   "dtype": str(var.dtype),
                                   "force_cpu": False})
            infer_op(init, sblock)
            sblock.ops.append(init)
            startup._version += 1
            op = Operator(block, type="fake_quantize_range_abs_max",
                          inputs={"X": [name], "InScale": [scale_name]},
                          outputs={"Out": [qname],
                                   "OutScale": [scale_name]},
                          attrs={"bit_length": bits})
        infer_op(op, block)
        ops.append(op)
        return qname, ops

    # ------------------------------------------------------------------
    def freeze_program(self, program, place=None, fuse_bn=False,
                       scope=None):
        """Return the inference version of a quantize-transpiled
        program: ``clone(for_test=True)`` flips the fake-quant ops to
        test mode, where ``range_abs_max`` consumes its trained running
        scale as-is.  ``fuse_bn`` additionally folds frozen BN via
        InferenceTranspiler."""
        frozen = program.clone(for_test=True)
        if fuse_bn:
            from ...transpiler import InferenceTranspiler

            frozen = InferenceTranspiler().transpile(frozen, place,
                                                     scope)
        return frozen

    def convert_to_int8(self, program, place=None, scope=None):
        """Store every quantized weight as int8 in the scope
        (``<name>.int8`` plus ``<name>.int8_scale``) — the deployment
        size reduction; returns {weight name: (int8 name, scale)}."""
        scope = scope if scope is not None else global_scope()
        block = program.global_block()
        rng = float((1 << (self.weight_bits - 1)) - 1)
        out = {}
        for op in block.ops:
            if op.type not in ("fake_quantize_abs_max",
                               "fake_quantize_range_abs_max"):
                continue
            name = op.inputs["X"][0]
            var = block._find_var_recursive(name)
            if not isinstance(var, Parameter) or not scope.has_var(name):
                continue
            w = np.asarray(scope.var(name), dtype=np.float64)
            axis = op.attrs.get("quant_axis", -1)
            if op.type == "fake_quantize_range_abs_max" and \
                    scope.has_var(op.inputs["InScale"][0]):
                # the TRAINED running scale IS the grid QAT optimized
                # against — recomputing abs-max here would deploy a
                # different grid than the one the weights learned
                scale = max(float(np.asarray(
                    scope.var(op.inputs["InScale"][0])).ravel()[0]),
                    1e-12)
            elif axis is not None and axis >= 0:
                # per-channel grid, matching the op's quant_axis attr
                red = tuple(i for i in range(w.ndim) if i != axis)
                scale = np.maximum(np.max(np.abs(w), axis=red), 1e-12)
            else:
                scale = max(float(np.max(np.abs(w))), 1e-12)
            bshape = [1] * w.ndim
            if np.ndim(scale):
                bshape[axis] = -1
            q = np.clip(np.round(w / np.reshape(scale, bshape) * rng),
                        -rng, rng).astype(np.int8)
            scope.set_var(name + ".int8", q)
            scope.set_var(name + ".int8_scale",
                          np.asarray(scale, np.float32).reshape(-1))
            out[name] = (name + ".int8", scale)
        return out
