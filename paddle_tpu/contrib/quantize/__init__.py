from .quantize_transpiler import QuantizeTranspiler  # noqa: F401

__all__ = ["QuantizeTranspiler"]
