"""Op-frequency statistics over a Program.

Parity: reference ``contrib/op_frequence.py`` — same contract
(``op_freq_statistic(program) -> (uni_op_freq, adj_2_op_freq)``):
single-op counts plus adjacent-producer pair counts (which op feeds
which), both sorted most-frequent first.
"""

from collections import OrderedDict

from ..framework import Program

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Returns ``(uni_op_freq, adj_2_op_freq)`` OrderedDicts sorted by
    descending count; pair keys are ``"producer_type consumer_type"``."""
    if not isinstance(program, Program):
        raise TypeError("The input type should be Porgram."
                        "But you passed in %s" % (type(program)))

    block = program.global_block()
    params = {p.name for p in block.all_parameters()}

    uni = {}
    producer_of = {}
    pair = {}
    for op in block.ops:
        uni[op.type] = uni.get(op.type, 0) + 1
        for name in op.input_arg_names:
            if not name or name in params:
                continue
            src = producer_of.get(name)
            if src is not None:
                key = "%s %s" % (src, op.type)
                pair[key] = pair.get(key, 0) + 1
        for name in op.output_arg_names:
            if name:
                producer_of[name] = op.type

    uni_sorted = OrderedDict(
        sorted(uni.items(), key=lambda kv: kv[1], reverse=True))
    pair_sorted = OrderedDict(
        sorted(pair.items(), key=lambda kv: kv[1], reverse=True))
    return uni_sorted, pair_sorted
