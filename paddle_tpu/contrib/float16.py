"""Half-precision inference transpiler.

Parity: reference ``paddle/contrib/float16/float16_transpiler.py:21``
(Float16Transpiler) — rewrites a *trained fp32 inference program* so it
runs in half precision while the user still feeds and fetches fp32
tensors.  TPU-first redesign: the half type is **bfloat16** (the MXU's
native half format; fp16 on TPU buys nothing and loses exponent range),
and instead of swapping per-op kernels the rewrite only touches the
boundaries —

1. trained parameters in the scope are cast to bf16 in place (the
   reference creates ``@FP16`` twins; XLA consumes the converted arrays
   directly, so twins would just double scope memory),
2. a ``cast`` op is prepended per feed var (user feeds fp32, graph
   computes bf16),
3. each fetch target's producer is renamed to a ``@BF16`` twin and a
   ``cast`` back to fp32 is appended under the original name, so
   fetch dtypes are unchanged.

Numerically-sensitive ops keep fp32 compute exactly as training AMP
does (softmax & friends — ``contrib.mixed_precision`` black list): the
rewrite inserts a fp32 cast before each and returns to bf16 after,
mirroring the reference's "no fp16 kernel" fallback for such ops.
"""

import numpy as np

from .. import core
from ..framework import Program
from ..scope import global_scope

__all__ = ["Bfloat16Transpiler", "Float16Transpiler"]

# ops whose inputs must stay fp32: the AMP black list minus optimizer
# updates (which never appear in inference programs) — derived, so new
# sensitive ops added there are guarded here automatically
def _fp32_ops():
    from .mixed_precision import AutoMixedPrecisionLists

    # optimizer updates and gradient-infrastructure ops never appear in
    # (or must not widen) inference programs: `sum` is residual adds
    # here, not grad accumulation, and clip/norm/isfinite guards are
    # training machinery
    train_only = {
        "sgd", "momentum", "adam", "adamax", "adagrad", "adadelta",
        "rmsprop", "ftrl", "decayed_adagrad", "proximal_gd",
        "proximal_adagrad", "sum", "clip_by_norm", "squared_l2_norm",
        "isfinite",
    }
    return set(AutoMixedPrecisionLists.BLACK) - train_only

_SKIP_RENAME = {"cast", "feed", "fetch"}


class Bfloat16Transpiler:
    """Rewrite an inference program + scope for bf16 execution."""

    def transpile(self, program, place=None, scope=None, fetch_targets=None):
        """``fetch_targets``: Variables/names whose fetched dtype must
        remain fp32 (reference reads them off the fetch ops; this stack
        keeps fetch lists outside the program, so callers pass them —
        load_inference_model's fetch_targets slot in).
        """
        if not isinstance(program, Program):
            raise TypeError("program should be a Program")
        scope = scope if scope is not None else global_scope()
        block = program.global_block()
        self._block = block
        self._input_map = {}

        self._convert_params(block, scope)
        self._cast_feeds(block)
        self._adjust_inputs(block)
        self._repropagate(block)
        self._guard_fp32_ops(block)
        self._repropagate(block)
        self._cast_fetches(block, fetch_targets or [])
        self._repropagate(block)
        return program

    @staticmethod
    def _repropagate(block):
        """Re-run shape/dtype inference in op order so the var metadata
        reflects the rewritten boundaries (bf16 flows forward; fp32
        islands re-promote downstream exactly as the runtime will)."""
        from ..registry import infer_op

        for op in block.ops:
            infer_op(op, block)

    # -- 1. parameters ------------------------------------------------------

    def _convert_params(self, block, scope):
        """Scope cast delegates to AMP's cast_parameters_to_bf16; this
        pass then retypes the program vars to match."""
        from .mixed_precision import cast_parameters_to_bf16

        cast_parameters_to_bf16(block.program, scope)
        bf16 = core.convert_dtype("bfloat16")
        for var in list(block.vars.values()):
            if not getattr(var, "persistable", False):
                continue
            if core.convert_dtype(var.dtype) != np.dtype(np.float32):
                continue
            val = scope.find_var(var.name)
            if val is None:
                continue
            var.dtype = bf16

    # -- 2. feed boundary ---------------------------------------------------

    def _cast_feeds(self, block):
        # only data vars some op actually consumes: prune_feed_fetch
        # keeps orphan feed vars in the block, and casting one would
        # turn an optional input into a required one
        consumed = set()
        for op in block.ops:
            consumed.update(op.input_arg_names)
        idx = 0
        for var in list(block.vars.values()):
            if not getattr(var, "is_data", False) or \
                    var.name not in consumed:
                continue
            if core.convert_dtype(var.dtype) != np.dtype(np.float32):
                continue  # ids/labels stay integer
            twin_name = var.name + "@BF16"
            twin = block.create_var(
                name=twin_name, shape=var.shape, dtype="bfloat16",
                stop_gradient=True)
            if getattr(var, "_seq_len_name", None):
                twin._seq_len_name = var._seq_len_name
            block.insert_op(
                idx, type="cast",
                inputs={"X": [var.name]}, outputs={"Out": [twin_name]},
                attrs={"out_dtype": "bfloat16"})
            idx += 1
            self._input_map[var.name] = twin_name

    def _adjust_inputs(self, block):
        """Rewire consumers onto the cast twins (reference
        _adjust_input, skipping the cast ops themselves)."""
        for op in block.ops:
            if op.type in _SKIP_RENAME:
                continue
            for slot, names in op.inputs.items():
                op.inputs[slot] = [self._input_map.get(n, n) for n in names]

    # -- 3. fp32 islands ----------------------------------------------------

    def _guard_fp32_ops(self, block):
        """Insert bf16->fp32 casts before black-listed ops and retype
        their outputs fp32; the next bf16 consumer simply computes in
        fp32 inputs' promoted dtype, matching AMP's black-list rule."""
        fp32_ops = _fp32_ops()
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type in fp32_ops:
                for slot, names in list(op.inputs.items()):
                    new_names = []
                    for n in names:
                        v = block._find_var_recursive(n)
                        if v is not None and core.convert_dtype(v.dtype) == \
                                core.convert_dtype("bfloat16"):
                            cast_name = n + "@FP32"
                            if block._find_var_recursive(cast_name) is None:
                                block.create_var(
                                    name=cast_name, shape=v.shape,
                                    dtype="float32", stop_gradient=True)
                                block.insert_op(
                                    i, type="cast", inputs={"X": [n]},
                                    outputs={"Out": [cast_name]},
                                    attrs={"out_dtype": "float32"})
                                i += 1
                            new_names.append(cast_name)
                        else:
                            new_names.append(n)
                    op.inputs[slot] = new_names
            i += 1

    # -- 4. fetch boundary --------------------------------------------------

    def _cast_fetches(self, block, fetch_targets):
        for t in fetch_targets:
            name = t if isinstance(t, str) else t.name
            var = block._find_var_recursive(name)
            if var is None:
                raise KeyError("fetch target %r not in program" % name)
            if core.convert_dtype(var.dtype) == np.dtype(np.float32):
                continue  # already fp32 (e.g. a guarded softmax output)
            producer = None
            for op in block.ops:
                if name in op.output_arg_names:
                    producer = op
            if producer is None or producer.type == "cast":
                continue
            twin_name = name + "@BF16"
            twin = block.create_var(
                name=twin_name, shape=var.shape, dtype="bfloat16",
                stop_gradient=True)
            for slot, names in producer.outputs.items():
                producer.outputs[slot] = [
                    twin_name if n == name else n for n in names]
            # consumers between producer and fetch read the twin too
            for op in block.ops:
                if op is producer:
                    continue
                for slot, names in op.inputs.items():
                    op.inputs[slot] = [
                        twin_name if n == name else n for n in names]
            block.append_op(
                type="cast", inputs={"X": [twin_name]},
                outputs={"Out": [name]}, attrs={"out_dtype": "float32"})
            var.dtype = core.convert_dtype("float32")


# the reference name; on TPU "float16" means bfloat16
Float16Transpiler = Bfloat16Transpiler
