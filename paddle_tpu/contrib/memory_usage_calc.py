"""Estimate a Program's activation/parameter memory from var shapes.

Parity: reference ``contrib/memory_usage_calc.py`` — same contract
(``memory_usage(program, batch_size) -> (lower, upper, unit)``), with a
TPU-honest caveat: XLA's buffer assignment reuses dead buffers inside
the fused module, so the true step footprint is usually BELOW this
shape-sum estimate; the number is an upper-bound planning figure (the
reference's is too — it also ignores workspace reuse).
"""

import numpy as np

from ..framework import Program

__all__ = ["memory_usage"]

_DTYPE_SIZE = {
    "float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
    "int16": 2, "int32": 4, "int64": 8, "bool": 1, "uint8": 1, "int8": 1,
}


def memory_usage(program, batch_size):
    """Returns ``(min_total, max_total, unit_str)`` — the estimated
    memory of every op-produced LoD-tensor var in the global block, with
    ``-1`` dims filled by ``batch_size`` and the reference's 5-10%
    overhead band applied."""
    if not isinstance(program, Program):
        raise TypeError(
            "Calculating Memory Usage requires Program as its Parameter."
            "But you passed in %s" % (type(program)))
    if batch_size <= 0:
        raise ValueError("The batch size need to be positive.")

    # Every block var counts — op outputs (activations), parameters, and
    # feed/data vars.  (The reference loops op outputs only, which
    # omits parameters held by the startup program; including them makes
    # the estimate an honest whole-footprint upper bound.)
    total = 0.0
    block = program.global_block()
    for var in block.vars.values():
        if var.shape is None:
            continue
        count = 1
        neg = 0
        for d in var.shape:
            if d is None or d < 0:
                if neg >= 1:
                    raise ValueError(
                        "Var %s has more than one negtive dim." % var.name)
                neg += 1
                count *= batch_size * (1 if d is None else -d)
            else:
                count *= d
        total += count * _DTYPE_SIZE.get(str(var.dtype or "float32"), 4)

    unit = "B"
    if total > 1024:
        total /= 1024
        unit = "KB"
        if total > 1024:
            total /= 1024
            unit = "MB"
    return total * 1.05, total * 1.1, unit
