"""Inference companion to the Trainer (reference
``python/paddle/fluid/contrib/inferencer.py``: Inferencer builds the
network from ``infer_func``, loads parameters saved by
``Trainer.save_params``, and runs forward-only steps).

TPU notes: inference is just the forward program traced and jit-compiled
by the whole-program Executor; repeated ``infer`` calls at the same batch
shape hit the executor's program cache, so there is no separate predictor
engine to manage.
"""

import os


from .. import io as fluid_io
from .. import unique_name
from ..executor import Executor
from ..framework import Parameter, Program, program_guard
from ..scope import Scope, scope_guard
from .trainer import _default_place

__all__ = ["Inferencer"]


class Inferencer:
    """reference contrib/inferencer.py:25.

    ``infer_func`` builds the forward network and returns the prediction
    Variable (or a list of them); ``param_path`` is a directory written by
    ``Trainer.save_params`` / ``io.save_persistables``.
    """

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        self.param_path = param_path
        self.scope = Scope()
        if parallel:
            raise NotImplementedError(
                "parallel inference is served by the mesh ParallelExecutor "
                "(paddle_tpu.parallel); pass the program to it directly")
        self.parallel = parallel
        self.place = _default_place(place)

        if not os.path.isdir(param_path):
            raise ValueError("param_path %r is not a directory" % param_path)

        self.startup_program = Program()
        self.inference_program = Program()
        # fresh name generator: the rebuilt net must reproduce the parameter
        # names the Trainer saved, independent of what else this process
        # already built (reference contrib/inferencer.py wraps in
        # unique_name.guard() for the same reason)
        with unique_name.guard():
            with program_guard(self.inference_program, self.startup_program):
                outs = infer_func()
                self.predict_vars = outs if isinstance(outs, list) else [outs]

        with scope_guard(self.scope):
            self.exe = Executor(self.place)
            self.exe.run(self.startup_program)
            fluid_io.load_params(self.exe, param_path,
                                 main_program=self.inference_program)
        missing = [
            v.name for v in self.inference_program.list_vars()
            if isinstance(v, Parameter) and not os.path.exists(
                os.path.join(param_path, v.name + ".npy"))]
        if missing:
            raise RuntimeError(
                "param_path %r has no saved tensor for parameter(s) %s — "
                "was the model saved with Trainer.save_params/io.save_params "
                "(per-var layout, no filename=) from the same network "
                "definition?" % (param_path, missing))

    def infer(self, inputs, return_numpy=True):
        """Run one forward pass. ``inputs`` is a dict var_name -> ndarray."""
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        with scope_guard(self.scope):
            return self.exe.run(
                self.inference_program, feed=inputs,
                fetch_list=[v.name for v in self.predict_vars],
                return_numpy=return_numpy)
