"""High-level Trainer (reference ``python/paddle/fluid/contrib/trainer.py``:
Trainer:169 — build programs from train_func, optimizer_func; event-driven
train loop; CheckpointConfig:100 periodic save + auto-resume; cluster role
wiring via PADDLE_TRAINING_ROLE env).

TPU redesign notes: the executor is the whole-program jit Executor (or the
mesh ParallelExecutor with ``parallel=True``); the pserver training role
is subsumed by mesh sharding, so PADDLE_TRAINING_ROLE=PSERVER raises with
guidance instead of transpiling (SURVEY.md §2.4)."""

import os
import warnings

import numpy as np

from .. import flags as _flags
from .. import guardian as _guardian
from .. import io as fluid_io
from .. import monitor
from .. import unique_name
from ..data_feeder import DataFeeder
from ..executor import CPUPlace, Executor, TPUPlace
from ..framework import Program, default_main_program, \
    default_startup_program, program_guard
from ..optimizer import Optimizer
from ..parallel import ParallelExecutor
from ..profiler import RecordEvent
from ..scope import Scope, scope_guard

__all__ = [
    "Trainer", "CheckpointConfig",
    "BeginEpochEvent", "EndEpochEvent", "BeginStepEvent", "EndStepEvent",
]


def _default_place(place=None):
    """Pick TPU if one is attached, else CPU (shared by Trainer/Inferencer)."""
    if place is not None:
        return place
    import jax
    has_tpu = any(d.platform != "cpu" for d in jax.devices())
    return TPUPlace(0) if has_tpu else CPUPlace()


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference contrib/trainer.py:100 — grown into the exact-resume
    config: checkpoints are full ``TrainState`` artifacts (params +
    optimizer slots + LR/step counters + executor PRNG counters +
    reader position), written asynchronously under compute
    (``async_save``) and committed atomically with checksum manifests
    (``parallel.checkpoint.TrainStateCheckpointManager``).
    ``step_interval`` counts GLOBAL steps across epochs."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=None, async_save=True,
                 incremental=None, incremental_full_every=8):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            os.getcwd(), "checkpoints")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(int(epoch_interval), 1)
        # incremental table checkpoints (Check-N-Run): 'auto'/True delta-
        # encodes every is_sparse lookup table + its row-wise optimizer
        # slots; or pass an explicit var-name list.  See
        # TrainStateCheckpointManager(incremental=...).
        self.incremental = incremental
        self.incremental_full_every = int(incremental_full_every)
        # an EXPLICIT step_interval is a pin: the auto-tuner's
        # checkpoint-interval decision (Trainer(autotune=...)) never
        # overrides a cadence the user chose; None takes the historical
        # default of 10 and stays tunable
        self.step_interval_pinned = step_interval is not None
        self.step_interval = max(int(step_interval), 1) \
            if step_interval is not None else 10
        self.async_save = bool(async_save)
        self.epoch_id = 0
        self.step_id = 0
        # the restored global-step index after an auto-resume (kept
        # under the reference's name: scripts test it for truthiness)
        self.load_serial = None


class Trainer:
    """reference contrib/trainer.py:169.

    ``train_func`` builds the model and returns the loss Variable (or a
    list whose first element is the loss); ``optimizer_func`` returns an
    Optimizer.
    """

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None,
                 mesh=None, guardian_config=None, autotune=None,
                 cluster_member=None):
        """``guardian_config``: the recovery policy — a ``Guardian``
        instance, or a kwargs dict for ``guardian.Guardian`` (policy
        ladder, window, budgets...).  Passing one turns the guardian on
        (``FLAGS_guardian``) for the duration of ``train()``; with the
        flag already set the Trainer wires a default Guardian in by
        itself, so a flag-enabled run is guarded with no code
        changes.

        ``autotune``: a ``paddle_tpu.autotune.TunedConfig`` (or a path
        to its JSON artifact).  Flag-backed decisions apply through
        ``TunedConfig.apply`` (pinned flags win); a tuned
        ``checkpoint_interval`` re-gates the checkpoint manager unless
        the user pinned ``CheckpointConfig(step_interval=...)``
        explicitly.

        ``cluster_member``: a ``paddle_tpu.cluster.ClusterMember`` — the
        host's session against a ClusterMaster.  With one, multi-host
        sharded checkpoint commits go through the master's saver
        election, and — when a guardian is enabled (``FLAGS_guardian``
        or ``guardian_config``) — verdicts are cluster-arbitrated
        (``ClusterGuardian``: one host's rollback wins cluster-wide).
        A plain ``Guardian`` INSTANCE as ``guardian_config`` conflicts
        with that promise and raises; pass a kwargs dict or a
        ``ClusterGuardian``."""
        self.__stop = False
        self.parallel = parallel
        self.place = _default_place(place)
        self._mesh = mesh
        self._guardian_config = guardian_config
        self._cluster_member = cluster_member
        self._set_guardian_flag = False
        self._current_epoch = 0

        if checkpoint_config is not None and not isinstance(
                checkpoint_config, CheckpointConfig):
            raise TypeError(
                "checkpoint_config must be a CheckpointConfig instance")
        self.checkpoint_cfg = checkpoint_config

        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()

        # fresh name generator so parameter names (fc_0.w_0, ...) are
        # reproducible regardless of what this process built before —
        # Inferencer rebuilds the net under the same guard and must get
        # identical names to match the saved files
        with unique_name.guard(), \
                program_guard(self.train_program, self.startup_program):
            program_func_outs = train_func()
            self.train_func_outputs = (
                program_func_outs if isinstance(program_func_outs, list)
                else [program_func_outs])
            # test program: forward only, before optimizer ops
            self.test_program = self.train_program.clone(for_test=True)
            if not isinstance(optimizer_func, type(lambda: None)) and \
                    not callable(optimizer_func):
                raise TypeError("optimizer_func must be callable")
            optimizer = optimizer_func()
            if not isinstance(optimizer, Optimizer):
                raise TypeError(
                    "optimizer_func must return a paddle_tpu Optimizer")
            loss = self.train_func_outputs[0]
            optimizer.minimize(loss)
        self._loss_name = loss.name

        self._dist_transpile_if_necessary()

        with scope_guard(self.scope):
            exe = Executor(self.place)
            exe.run(self.startup_program)

        if param_path is not None:
            with scope_guard(self.scope):
                fluid_io.load_persistables(
                    Executor(self.place), param_path,
                    main_program=self.startup_program)

        self._autotune = None
        if autotune is not None:
            from .. import autotune as _at

            self._autotune = autotune if isinstance(
                autotune, _at.TunedConfig) else _at.TunedConfig.load(
                autotune)
            # flag-backed decisions (attention-kernel table install);
            # pinned flags win inside apply()
            self._autotune.apply()
            interval = self._autotune.value("checkpoint_interval")
            if interval and self.checkpoint_cfg is not None:
                if self.checkpoint_cfg.step_interval_pinned:
                    monitor.log_event({
                        "event": "autotune_applied",
                        "knob": "checkpoint_interval",
                        "outcome": "pinned",
                        "pinned_interval":
                            self.checkpoint_cfg.step_interval})
                else:
                    self.checkpoint_cfg.step_interval = max(
                        1, int(interval))
                    monitor.log_event({
                        "event": "autotune_applied",
                        "knob": "checkpoint_interval",
                        "outcome": "applied",
                        "interval": self.checkpoint_cfg.step_interval})

        self._ckpt_mgr = None
        self._global_step = 0
        self._resume_epoch = 0
        self._pending_resume = None
        if self.checkpoint_cfg is not None:
            from ..parallel.checkpoint import TrainStateCheckpointManager

            cfg = self.checkpoint_cfg
            member = self._cluster_member
            self._ckpt_mgr = TrainStateCheckpointManager(
                cfg.checkpoint_dir,
                max_to_keep=cfg.max_num_checkpoints,
                save_interval_steps=cfg.step_interval,
                async_save=cfg.async_save,
                incremental=getattr(cfg, "incremental", None),
                incremental_full_every=getattr(
                    cfg, "incremental_full_every", 8),
                # cluster runs elect exactly one manifest committer per
                # step through the master (sharded-mode saves only)
                saver_elect=member.request_save
                if member is not None else None)
            with scope_guard(self.scope):
                restored = self._ckpt_mgr.restore(
                    scope=self.scope, program=self.train_program)
            if restored is not None:
                cfg.load_serial = restored
                self._global_step = restored
                # consumed (once) by train()'s _apply_resume_state
                self._pending_resume = self._ckpt_mgr.last_restored.host
                self._resume_epoch = int(
                    self._pending_resume.get("extra", {}).get("epoch", 0))
            else:
                # a dir holding only the PREVIOUS Trainer's serial-based
                # format must not be silently abandoned: resume its
                # persistables (params-only legacy semantics) and say so
                serial = fluid_io.get_latest_checkpoint_serial(
                    cfg.checkpoint_dir)
                if serial >= 0:
                    import warnings

                    warnings.warn(
                        "resuming a LEGACY (serial-based, params-only) "
                        "checkpoint from %s; future saves use the "
                        "TrainState format" % cfg.checkpoint_dir)
                    cfg.load_serial = serial
                    with scope_guard(self.scope):
                        fluid_io.load_checkpoint(
                            Executor(self.place), cfg.checkpoint_dir,
                            main_program=self.train_program)

    # ------------------------------------------------------------------
    def _dist_transpile_if_necessary(self):
        role = os.getenv("PADDLE_TRAINING_ROLE")
        if role is None or role == "TRAINER":
            return
        if role == "PSERVER":
            raise RuntimeError(
                "parameter-server roles do not exist on the TPU runtime: "
                "parameters live sharded on the mesh (use parallel=True "
                "with a Mesh spanning your hosts via jax.distributed)")
        raise ValueError("unknown PADDLE_TRAINING_ROLE %r" % role)

    def stop(self):
        self.__stop = True

    # ------------------------------------------------------------------
    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        with scope_guard(self.scope):
            if self.parallel:
                executor = ParallelExecutor(
                    loss_name=self._loss_name,
                    main_program=self.train_program, mesh=self._mesh)
                run = lambda feed, fetch: executor.run(
                    feed=feed, fetch_list=fetch)
            else:
                executor = Executor(self.place)
                run = lambda feed, fetch: executor.run(
                    self.train_program, feed=feed, fetch_list=fetch)
            feeder = self._feeder(feed_order)
            epoch_id = self._apply_resume_state(executor, reader)
            try:
                # inside the try: a raising Guardian construction
                # (invalid config) must also restore the flag below
                g = self._make_guardian()
                with self._signal_guard(), _guardian.installed(g):
                    # detect -> decide -> recover loop: a
                    # GuardianRollback raised by the guardian (from
                    # inside executor.run) restores the newest clean
                    # TrainState and re-enters the epoch loop from the
                    # restored position; the rollback budget turns a
                    # persistent fault into a typed GuardianAbortError
                    # instead of recovering forever
                    while True:
                        try:
                            self._run_epochs(epoch_id, num_epochs,
                                             event_handler, reader,
                                             feeder, run, executor)
                            break
                        except _guardian.GuardianRollback as rb:
                            epoch_id = self._rollback_recover(
                                rb, executor, reader)
                            if self.__stop or self.__preempted:
                                break
                    if self.__preempted and self._ckpt_mgr is not None \
                            and self._global_step > 0:
                        # > 0: a preemption before any step completed
                        # has nothing worth flushing — and a step-0
                        # artifact would restore as load_serial=0,
                        # falsy under the documented
                        # `if cfg.load_serial:` resume check
                        # preemption: the step finished, now force a
                        # synchronous TrainState flush, then let the
                        # signal's default behavior proceed (SURVEY §5
                        # checkpoint-on-signal; reference analog:
                        # listen_and_serv_op.cc signal handler)
                        self._flush_checkpoint(executor, reader,
                                               self._current_epoch)
            finally:
                if self._set_guardian_flag:
                    # restore the flag this train() set: a later plain
                    # executor (or the next Trainer's startup program)
                    # must not run guarded with nobody deciding
                    self._set_guardian_flag = False
                    _flags.set_flags({"guardian": False})
                if monitor.enabled():
                    # stamp the run's wall-clock attribution into the
                    # JSONL at the boundary every post-mortem starts
                    # from — in the finally, because the runs that NEED
                    # a post-mortem (guardian abort, preemption) are
                    # the ones that don't return cleanly
                    try:
                        monitor.goodput_stamp()
                        # final per-layer model-health state next to it
                        # (no-op while FLAGS_health never published)
                        monitor.health.stamp()
                    except Exception:  # noqa: BLE001 — telemetry must
                        pass           # not mask the real exit
            if self._ckpt_mgr is not None:
                # a trailing async write must land before the process
                # can exit believing the state is durable
                self._ckpt_mgr.wait_until_finished()

    def _run_epochs(self, epoch_id, num_epochs, event_handler, reader,
                    feeder, run, executor):
        g = _guardian.active()
        for epoch_id in range(epoch_id, num_epochs):
            self._current_epoch = epoch_id
            if self.__stop:
                break
            event_handler(BeginEpochEvent(epoch_id))
            for step_id, data in enumerate(reader()):
                if self.__stop:
                    break
                begin = BeginStepEvent(epoch_id, step_id)
                event_handler(begin)
                fetch = [v.name for v in self.train_func_outputs] \
                    if begin.fetch_metrics else []
                with RecordEvent("trainer/step"):
                    metrics = run(feeder.feed(data), fetch)
                    metrics = [np.asarray(m) for m in metrics]
                self._global_step += 1
                event_handler(EndStepEvent(epoch_id, step_id,
                                           metrics))
                with RecordEvent("trainer/checkpoint"):
                    self._maybe_save_checkpoint(executor, reader,
                                                epoch_id)
                if self.__preempted:
                    break
            if g is not None:
                # epoch boundary: force every deferred guardian
                # observation through the ladder while the recovery
                # loop can still catch its decision
                g.flush()
            event_handler(EndEpochEvent(epoch_id))
            if self.__preempted:
                break
        if g is not None:
            g.flush()

    def _make_guardian(self):
        """The default wiring: a caller-installed guardian stays in
        charge (returns None so the Trainer neither re-installs nor
        uninstalls it); otherwise FLAGS_guardian / guardian_config
        build one, quarantining next to the checkpoints unless
        configured elsewhere."""
        if self._guardian_config is not None \
                and not _flags.flag("guardian"):
            # explicit config implies intent: enable the flag so the
            # executors lower the in-graph skip guard too.  Deferred to
            # train() (not __init__) and restored when train() returns:
            # programs run while no guardian is installed (this
            # Trainer's startup, a later plain executor) must not be
            # silently guarded
            _flags.set_flags({"guardian": True})
            self._set_guardian_flag = True
        if _guardian.active() is not None:
            return None
        cfg = self._guardian_config
        if cfg is None and not _flags.flag("guardian"):
            return None
        if isinstance(cfg, _guardian.Guardian):
            from ..cluster import ClusterGuardian

            if self._cluster_member is not None \
                    and not isinstance(cfg, ClusterGuardian):
                # a plain Guardian instance would decide ALONE while
                # cluster_member promises arbitration — silently
                # bypassing it is exactly the per-process-divergence
                # hole the bridge exists to close; make the conflict a
                # configuration error instead
                raise ValueError(
                    "Trainer(cluster_member=...) with a plain Guardian "
                    "instance: verdicts would not be cluster-"
                    "arbitrated.  Pass guardian_config as a kwargs "
                    "dict (the Trainer builds a ClusterGuardian), or "
                    "construct cluster.ClusterGuardian(member, ...) "
                    "yourself")
            g = cfg
            # budgets/history are per-run: a reused instance must not
            # carry a spent rollback budget into this train() (the
            # kwargs path below builds a fresh Guardian each time)
            g.reset_run_state()
        elif self._cluster_member is not None:
            # cluster runs arbitrate verdicts through the master: one
            # host's rollback/abort becomes the cluster's
            from ..cluster import ClusterGuardian

            g = ClusterGuardian(self._cluster_member, **dict(cfg or {}))
        else:
            g = _guardian.Guardian(**dict(cfg or {}))
        if not g.quarantine_dir \
                and not _flags.flag("guardian_quarantine_dir") \
                and self.checkpoint_cfg is not None:
            g.quarantine_dir = os.path.join(
                self.checkpoint_cfg.checkpoint_dir, "quarantine")
        return g

    def _rollback_recover(self, rb, executor, reader):
        """One rung of the recovery ladder: charge the rollback budget,
        restore the newest clean TrainState (skipping corrupt or
        NaN-poisoned artifacts), re-apply executor PRNG counter and
        reader position, and fast-forward the reader past a poisoned
        batch window.  Returns the epoch to re-enter the loop at."""
        g = _guardian.active()
        if g is None:
            raise rb
        if self._ckpt_mgr is None:
            raise _guardian.GuardianAbortError(
                "guardian requested a rollback at step %d (%s) but the "
                "Trainer has no CheckpointConfig — nothing to roll back "
                "to" % (rb.step, rb.reason)) from rb
        g.begin_rollback(rb)          # budget; raises when exhausted
        executor.sync()               # retire in-flight async steps
        readers = self._ckpt_readers(reader)
        if reader is not None and not readers:
            warnings.warn(
                "guardian rollback cannot rewind this reader (no "
                "state_dict — wrap it with reader.checkpointable()): "
                "the replay re-enters the epoch from the reader's "
                "current position, so the recovered trajectory will "
                "NOT exactly reproduce the clean run")
        restored = g.rollback_restore(
            self._ckpt_mgr, rb, scope=self.scope,
            program=self.train_program, executors={"train": executor},
            readers=readers)
        self._global_step = restored
        if self.checkpoint_cfg is not None:
            self.checkpoint_cfg.load_serial = restored
        ff = g.post_restore(rb, restored)
        if ff:
            if hasattr(reader, "fast_forward"):
                reader.fast_forward(ff)
                monitor.log_event({"event": "guardian_fast_forward",
                                   "batches": ff,
                                   "restored_step": restored})
            else:
                warnings.warn(
                    "guardian rollback wants to skip %d poisoned "
                    "batches but the reader has no fast_forward() — "
                    "wrap it with reader.checkpointable(); the replay "
                    "may re-trip the sentinel" % ff)
        if reader is not None and hasattr(reader, "state_dict"):
            try:
                return int(reader.state_dict().get(
                    "epoch", self._current_epoch))
            except Exception:  # noqa: BLE001 — epoch is best-effort
                pass
        return self._current_epoch

    def _apply_resume_state(self, executor, reader):
        """After an auto-resume, re-apply the non-scope legs of the
        restored TrainState to the objects that now exist: the
        executor's PRNG fold-in counter and the reader's position.
        Consumed once — a second train() call must not rewind the
        executor to the restore point (it starts a fresh epoch range).
        Returns the resume epoch."""
        host, self._pending_resume = self._pending_resume, None
        start, self._resume_epoch = self._resume_epoch, 0
        if host is None:
            return start
        ex_state = host.get("executors", {}).get("train")
        if ex_state is not None:
            executor.load_state_dict(ex_state)
        rd_state = host.get("readers", {}).get("train")
        if rd_state is not None and hasattr(reader, "load_state_dict"):
            reader.load_state_dict(rd_state)
            # the reader's own epoch counter is the precise resume
            # epoch (it rolls over exactly at source exhaustion)
            return int(rd_state.get("epoch", start))
        return start

    def _signal_guard(self):
        """While training, SIGTERM/SIGINT request a graceful stop: the
        current step finishes, a checkpoint is flushed, and the signal
        is re-raised with its original handler."""
        import contextlib
        import signal as _signal

        self.__preempted = None

        @contextlib.contextmanager
        def _ctx():
            prev = {}

            def handler(signum, frame):
                self.__preempted = signum
                self.__stop = True

            try:
                for s in (_signal.SIGTERM, _signal.SIGINT):
                    prev[s] = _signal.signal(s, handler)
            except ValueError:      # not the main thread
                yield
                return
            try:
                yield
            finally:
                for s, h in prev.items():
                    _signal.signal(s, h)
                if self.__preempted is not None:
                    _signal.raise_signal(self.__preempted)

        return _ctx()

    def _ckpt_readers(self, reader):
        if reader is not None and hasattr(reader, "state_dict"):
            return {"train": reader}
        return None

    def _flush_checkpoint(self, executor, reader, epoch_id):
        self._ckpt_mgr.save_now(
            self._global_step, scope=self.scope,
            program=self.train_program, executors={"train": executor},
            readers=self._ckpt_readers(reader),
            extra={"epoch": epoch_id, "preempted": True})

    def test(self, reader, feed_order=None):
        """Average the train_func outputs over the test reader."""
        with scope_guard(self.scope):
            executor = Executor(self.place)
            feeder = self._feeder(feed_order, program=self.test_program)
            accumulated = None
            count = 0
            for data in reader():
                outs = executor.run(
                    self.test_program, feed=feeder.feed(data),
                    fetch_list=[v.name for v in self.train_func_outputs])
                outs = [float(np.asarray(o).mean()) for o in outs]
                accumulated = outs if accumulated is None else [
                    a + o for a, o in zip(accumulated, outs)]
                count += 1
            if count == 0:
                return accumulated
            return [a / count for a in accumulated]

    def save_params(self, param_path):
        with scope_guard(self.scope):
            fluid_io.save_persistables(
                Executor(self.place), param_path,
                main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        with scope_guard(self.scope):
            fluid_io.save_inference_model(
                param_path, feeded_var_names,
                [self.train_func_outputs[i] for i in target_var_indexes],
                Executor(self.place), main_program=self.train_program)

    # ------------------------------------------------------------------
    def _feeder(self, feed_order, program=None):
        program = program or self.train_program
        if feed_order is None:
            feed_order = [
                v.name for v in program.global_block().vars.values()
                if getattr(v, "is_data", False)
                and not v.name.endswith("@LEN")
            ]
        feed_list = [
            program.global_block().var(name) for name in feed_order
        ]
        return DataFeeder(feed_list=feed_list, place=self.place,
                          program=program)

    def _maybe_save_checkpoint(self, executor, reader, epoch_id):
        cfg = self.checkpoint_cfg
        if cfg is None or epoch_id % cfg.epoch_interval != 0:
            return
        # the manager gates on the GLOBAL step interval; the snapshot is
        # synchronous (device->host), the write overlaps later compute
        self._ckpt_mgr.save(
            self._global_step, scope=self.scope,
            program=self.train_program, executors={"train": executor},
            readers=self._ckpt_readers(reader),
            extra={"epoch": epoch_id})
