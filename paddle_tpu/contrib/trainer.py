"""High-level Trainer (reference ``python/paddle/fluid/contrib/trainer.py``:
Trainer:169 — build programs from train_func, optimizer_func; event-driven
train loop; CheckpointConfig:100 periodic save + auto-resume; cluster role
wiring via PADDLE_TRAINING_ROLE env).

TPU redesign notes: the executor is the whole-program jit Executor (or the
mesh ParallelExecutor with ``parallel=True``); the pserver training role
is subsumed by mesh sharding, so PADDLE_TRAINING_ROLE=PSERVER raises with
guidance instead of transpiling (SURVEY.md §2.4)."""

import os

import numpy as np

from .. import io as fluid_io
from .. import unique_name
from ..data_feeder import DataFeeder
from ..executor import CPUPlace, Executor, TPUPlace
from ..framework import Program, default_main_program, \
    default_startup_program, program_guard
from ..optimizer import Optimizer
from ..parallel import ParallelExecutor
from ..profiler import RecordEvent
from ..scope import Scope, scope_guard

__all__ = [
    "Trainer", "CheckpointConfig",
    "BeginEpochEvent", "EndEpochEvent", "BeginStepEvent", "EndStepEvent",
]


def _default_place(place=None):
    """Pick TPU if one is attached, else CPU (shared by Trainer/Inferencer)."""
    if place is not None:
        return place
    import jax
    has_tpu = any(d.platform != "cpu" for d in jax.devices())
    return TPUPlace(0) if has_tpu else CPUPlace()


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference contrib/trainer.py:100"""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            os.getcwd(), "checkpoints")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(int(epoch_interval), 1)
        self.step_interval = max(int(step_interval), 1)
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial = None


class Trainer:
    """reference contrib/trainer.py:169.

    ``train_func`` builds the model and returns the loss Variable (or a
    list whose first element is the loss); ``optimizer_func`` returns an
    Optimizer.
    """

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None,
                 mesh=None):
        self.__stop = False
        self.parallel = parallel
        self.place = _default_place(place)
        self._mesh = mesh

        if checkpoint_config is not None and not isinstance(
                checkpoint_config, CheckpointConfig):
            raise TypeError(
                "checkpoint_config must be a CheckpointConfig instance")
        self.checkpoint_cfg = checkpoint_config

        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()

        # fresh name generator so parameter names (fc_0.w_0, ...) are
        # reproducible regardless of what this process built before —
        # Inferencer rebuilds the net under the same guard and must get
        # identical names to match the saved files
        with unique_name.guard(), \
                program_guard(self.train_program, self.startup_program):
            program_func_outs = train_func()
            self.train_func_outputs = (
                program_func_outs if isinstance(program_func_outs, list)
                else [program_func_outs])
            # test program: forward only, before optimizer ops
            self.test_program = self.train_program.clone(for_test=True)
            if not isinstance(optimizer_func, type(lambda: None)) and \
                    not callable(optimizer_func):
                raise TypeError("optimizer_func must be callable")
            optimizer = optimizer_func()
            if not isinstance(optimizer, Optimizer):
                raise TypeError(
                    "optimizer_func must return a paddle_tpu Optimizer")
            loss = self.train_func_outputs[0]
            optimizer.minimize(loss)
        self._loss_name = loss.name

        self._dist_transpile_if_necessary()

        with scope_guard(self.scope):
            exe = Executor(self.place)
            exe.run(self.startup_program)

        if param_path is not None:
            with scope_guard(self.scope):
                fluid_io.load_persistables(
                    Executor(self.place), param_path,
                    main_program=self.startup_program)

        if self.checkpoint_cfg is not None:
            with scope_guard(self.scope):
                serial = fluid_io.get_latest_checkpoint_serial(
                    self.checkpoint_cfg.checkpoint_dir)
                if serial >= 0:
                    self.checkpoint_cfg.load_serial = serial
                    fluid_io.load_checkpoint(
                        Executor(self.place),
                        self.checkpoint_cfg.checkpoint_dir,
                        main_program=self.train_program)

    # ------------------------------------------------------------------
    def _dist_transpile_if_necessary(self):
        role = os.getenv("PADDLE_TRAINING_ROLE")
        if role is None or role == "TRAINER":
            return
        if role == "PSERVER":
            raise RuntimeError(
                "parameter-server roles do not exist on the TPU runtime: "
                "parameters live sharded on the mesh (use parallel=True "
                "with a Mesh spanning your hosts via jax.distributed)")
        raise ValueError("unknown PADDLE_TRAINING_ROLE %r" % role)

    def stop(self):
        self.__stop = True

    # ------------------------------------------------------------------
    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        with scope_guard(self.scope):
            if self.parallel:
                executor = ParallelExecutor(
                    loss_name=self._loss_name,
                    main_program=self.train_program, mesh=self._mesh)
                run = lambda feed, fetch: executor.run(
                    feed=feed, fetch_list=fetch)
            else:
                executor = Executor(self.place)
                run = lambda feed, fetch: executor.run(
                    self.train_program, feed=feed, fetch_list=fetch)
            feeder = self._feeder(feed_order)
            ckpt_exe = Executor(self.place)
            with self._signal_guard():
                for epoch_id in range(num_epochs):
                    if self.__stop:
                        break
                    event_handler(BeginEpochEvent(epoch_id))
                    for step_id, data in enumerate(reader()):
                        if self.__stop:
                            break
                        begin = BeginStepEvent(epoch_id, step_id)
                        event_handler(begin)
                        fetch = [v.name for v in self.train_func_outputs] \
                            if begin.fetch_metrics else []
                        with RecordEvent("trainer/step"):
                            metrics = run(feeder.feed(data), fetch)
                            metrics = [np.asarray(m) for m in metrics]
                        event_handler(EndStepEvent(epoch_id, step_id,
                                                   metrics))
                        with RecordEvent("trainer/checkpoint"):
                            self._maybe_save_checkpoint(ckpt_exe, epoch_id,
                                                        step_id)
                        if self.__preempted:
                            break
                    event_handler(EndEpochEvent(epoch_id))
                    if self.__preempted:
                        break
                if self.__preempted and self.checkpoint_cfg is not None:
                    # flush at the step boundary, then let the signal's
                    # default behavior proceed (SURVEY §5
                    # checkpoint-on-signal; reference analog:
                    # listen_and_serv_op.cc signal handler)
                    self._flush_checkpoint(ckpt_exe, epoch_id)

    def _signal_guard(self):
        """While training, SIGTERM/SIGINT request a graceful stop: the
        current step finishes, a checkpoint is flushed, and the signal
        is re-raised with its original handler."""
        import contextlib
        import signal as _signal

        self.__preempted = None

        @contextlib.contextmanager
        def _ctx():
            prev = {}

            def handler(signum, frame):
                self.__preempted = signum
                self.__stop = True

            try:
                for s in (_signal.SIGTERM, _signal.SIGINT):
                    prev[s] = _signal.signal(s, handler)
            except ValueError:      # not the main thread
                yield
                return
            try:
                yield
            finally:
                for s, h in prev.items():
                    _signal.signal(s, h)
                if self.__preempted is not None:
                    _signal.raise_signal(self.__preempted)

        return _ctx()

    def _flush_checkpoint(self, exe, epoch_id):
        cfg = self.checkpoint_cfg
        # one past the periodic serial for this epoch, so resume picks
        # the preemption flush as latest
        serial = (cfg.load_serial or 0) + epoch_id + 2
        fluid_io.save_checkpoint(
            exe, cfg.checkpoint_dir, serial=serial,
            main_program=self.train_program,
            max_num_checkpoints=cfg.max_num_checkpoints)

    def test(self, reader, feed_order=None):
        """Average the train_func outputs over the test reader."""
        with scope_guard(self.scope):
            executor = Executor(self.place)
            feeder = self._feeder(feed_order, program=self.test_program)
            accumulated = None
            count = 0
            for data in reader():
                outs = executor.run(
                    self.test_program, feed=feeder.feed(data),
                    fetch_list=[v.name for v in self.train_func_outputs])
                outs = [float(np.asarray(o).mean()) for o in outs]
                accumulated = outs if accumulated is None else [
                    a + o for a, o in zip(accumulated, outs)]
                count += 1
            if count == 0:
                return accumulated
            return [a / count for a in accumulated]

    def save_params(self, param_path):
        with scope_guard(self.scope):
            fluid_io.save_persistables(
                Executor(self.place), param_path,
                main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        with scope_guard(self.scope):
            fluid_io.save_inference_model(
                param_path, feeded_var_names,
                [self.train_func_outputs[i] for i in target_var_indexes],
                Executor(self.place), main_program=self.train_program)

    # ------------------------------------------------------------------
    def _feeder(self, feed_order, program=None):
        program = program or self.train_program
        if feed_order is None:
            feed_order = [
                v.name for v in program.global_block().vars.values()
                if getattr(v, "is_data", False)
                and not v.name.endswith("@LEN")
            ]
        feed_list = [
            program.global_block().var(name) for name in feed_order
        ]
        return DataFeeder(feed_list=feed_list, place=self.place,
                          program=program)

    def _maybe_save_checkpoint(self, exe, epoch_id, step_id):
        cfg = self.checkpoint_cfg
        if cfg is None:
            return
        if epoch_id % cfg.epoch_interval == 0 and \
                step_id % cfg.step_interval == 0:
            serial = (cfg.load_serial or 0) + epoch_id + 1
            fluid_io.save_checkpoint(
                exe, cfg.checkpoint_dir, serial=serial,
                main_program=self.train_program,
                max_num_checkpoints=cfg.max_num_checkpoints)
